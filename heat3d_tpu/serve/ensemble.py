"""EnsembleSolver — the leading batch axis through the distributed step.

One compiled SPMD program advances B independent scenarios (distinct
initial conditions, Dirichlet boundary values, diffusivities/timesteps,
step budgets) over one structural config. The batch dimension threads
through the existing machinery: the local per-member update IS the
portable chain step (``parallel.step._local_step`` / ``_local_stepk``
semantics — exchange, tap chain, ghost-ring pinning), ``vmap``-mapped
over the members a device holds, inside one ``shard_map`` over a mesh
that can factorize over batch, space, or both.

Two coefficient-binding modes, because XLA treats constants and
parameters differently at codegen:

- ``bind='traced'`` (default — the serving mode): per-member taps,
  boundary values, and budgets are RUNTIME INPUTS of one compiled
  program, so a shape bucket compiles once and serves any member values
  (the compile-amortization the queue exists for). Member results are
  bitwise-invariant to batch packing (B=1 equals B=64 member-wise, and
  both equal the same parametric program with no batch axis at all), and
  match solo :class:`HeatSolver3D` runs to final-ulp rounding — NOT
  bitwise, because the solo program bakes its coefficients as XLA
  constants and constant-vs-parameter codegen may contract FMAs
  differently.
- ``bind='baked'``: per-member coefficients are compile-time constants,
  and each member runs ITS OWN executable — literally the solo
  ``make_multistep_fn`` program over the spatial mesh, driven through
  the ensemble's batched state layout. Bitwise-identical to B
  independent :class:`HeatSolver3D` runs BY CONSTRUCTION (the tier-1
  acceptance proof; stacking members into one XLA module was measured
  to perturb cross-member fusion by a final ulp on CPU, so the
  certification mode refuses to share a module), at the price of B
  compiles + B dispatches per call. Requires the batch axis unsharded
  (``batch_mesh == 1``: per-member constants cannot vary across the
  devices of one SPMD program).

Batch-aware sharding: ``batch_mesh = Pb`` builds the 4-axis mesh
``('b', 'x', 'y', 'z')`` over ``Pb * Px*Py*Pz`` devices — pure batch
parallel (Pb = ndev, spatial mesh (1,1,1): zero halo traffic), pure
spatial (Pb = 1), or hybrid. Halo collectives run over the spatial axes
only; members are independent, so the batch axis needs no communication
beyond the residual psum. The tune cache resolves ``auto`` knobs through
a batch-shape-bucketed key (``tune.cache.cache_key(batch_size=B)``).

Scope: the ensemble path runs the portable jnp chain compute on the
axis-ordered ppermute exchange. The Pallas kernel routes (direct,
streamk, DMA) bake taps into kernel constants and stay single-tenant —
the ensemble's win is packing + compile amortization, not kernel fusion.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from heat3d_tpu import obs
from heat3d_tpu.core import golden
from heat3d_tpu.core.config import SolverConfig
from heat3d_tpu.core.stencils import (
    decompose_mehrstellen,
    flat_taps,
    mehrstellen_enabled,
)
from heat3d_tpu.obs.trace import named_phase
from heat3d_tpu.ops.stencil_jnp import (
    _apply_mehrstellen_padded,
    apply_taps_padded,
    apply_taps_padded_params,
    emission_positions,
    residual_sumsq,
)
from heat3d_tpu.parallel.plan import exchange_with_plan
from heat3d_tpu.parallel.step import (
    _fill_mid_ghosts,
    _pin_padding,
    _solver_taps,
)
from heat3d_tpu.serve.scenario import ScenarioBatch
from heat3d_tpu.utils import checkpoint as ckpt
from heat3d_tpu.utils.compat import shard_map
from heat3d_tpu.utils.logging import get_logger

log = get_logger(__name__)

BATCH_AXIS = "b"


def _resolve_base(base: SolverConfig, batch_size: int) -> SolverConfig:
    """Auto-knob resolution for the ensemble. ``backend='auto'`` and
    ``halo='auto'`` are pinned to the chain/ppermute FIRST — the solo
    tune cache's winner for them is typically a single-tenant kernel
    route (pallas/dma), which the ensemble cannot run; letting the cache
    resolve them would turn a default config into a constructor error.
    Only ``time_blocking=0`` then resolves through the batch-bucketed
    cache key (the same belt-and-braces posture as HeatSolver3D's
    constructor: resolution is optional, and an unimportable tune
    package must not break serving)."""
    kw = {}
    if base.backend == "auto":
        kw["backend"] = "jnp"
    if base.halo == "auto":
        kw["halo"] = "ppermute"
    if kw:
        base = dataclasses.replace(base, **kw)
    try:
        from heat3d_tpu.tune.cache import resolve_config

        return resolve_config(base, batch_size=batch_size)
    except Exception:  # noqa: BLE001 - resolution is optional
        if base.time_blocking == 0:
            return dataclasses.replace(base, time_blocking=1)
        return base


class EnsembleSolver:
    """B scenarios, one compiled program. See the module docstring.

    Usage::

        batch = ScenarioBatch(SolverConfig(grid=GridConfig.cube(64),
                                           backend="jnp"),
                              [Scenario(alpha=0.3, bc_value=1.0),
                               Scenario(alpha=0.5, steps=200)])
        es = EnsembleSolver(batch)          # traced binding, batch_mesh=1
        u = es.init_state()                 # (B, *padded_shape), sharded
        u = es.run(u, es.budgets)           # per-member budgets (masked)
        fields = es.gather(u)               # (B, *grid) on host
    """

    def __init__(
        self,
        batch: ScenarioBatch,
        batch_mesh: int = 1,
        bind: str = "traced",
        devices=None,
    ):
        if bind not in ("traced", "baked"):
            raise ValueError(f"bind must be traced|baked, got {bind!r}")
        self.batch = batch
        self.B = len(batch)
        self.bind = bind
        self.batch_mesh = int(batch_mesh)
        cfg = _resolve_base(batch.base, self.B)
        if cfg.integrator != "explicit-euler":
            raise ValueError(
                f"integrator={cfg.integrator!r}: the ensemble packs the "
                "explicit sweep (and its variable-coefficient flux form); "
                "the leapfrog two-level carry and the CG solve are "
                "single-tenant — run them through HeatSolver3D "
                "(docs/INTEGRATORS.md)"
            )
        self._varcoef = bool(getattr(batch, "has_coef_fields", False))
        if self._varcoef and bind != "traced":
            raise ValueError(
                "coefficient-field members need bind='traced': the field "
                "arrays are runtime inputs of one shared program (the "
                "baked binding dispatches constant-coefficient solo "
                "executables)"
            )
        if cfg.backend in ("pallas", "conv"):
            # only an EXPLICIT kernel/conv request reaches here —
            # 'auto' was pinned to the chain before cache resolution
            raise ValueError(
                f"backend={cfg.backend!r} bakes its coefficients into the "
                "kernel/conv program; the ensemble threads per-member "
                "coefficients as runtime inputs — use backend 'jnp' (or "
                "'auto', which the ensemble pins to the chain)"
            )
        if cfg.halo != "ppermute":
            raise ValueError(
                f"halo={cfg.halo!r}: the ensemble path runs the portable "
                "axis-ordered ppermute exchange (the DMA kernels are "
                "single-tenant)"
            )
        if cfg.halo_order != "axis":
            raise ValueError(
                "halo_order='pairwise' is a single-tenant exchange A/B "
                "knob; the ensemble pins axis ordering"
            )
        if cfg.overlap:
            raise ValueError(
                "overlap=True splits the step for a single tenant; the "
                "ensemble's members already fill the schedule — drop it"
            )
        # the ensemble's compute route is the chain; record it concretely
        cfg = dataclasses.replace(cfg, backend="jnp")
        k = cfg.time_blocking
        if self._varcoef and k > 1:
            raise ValueError(
                f"time_blocking={k} with coefficient fields: the "
                "superstep ring recompute carries the solution only — "
                "the flux form needs the field's ghosts every update "
                "(tb=1; docs/INTEGRATORS.md)"
            )
        if k > 1 and min(cfg.local_shape) < max(3, k):
            raise ValueError(
                f"time_blocking={k} needs local extents >= {max(3, k)} "
                f"(k ghost layers plus the shrinking recompute rings), "
                f"got {cfg.local_shape}"
            )
        self.cfg = cfg
        self.k = max(1, k)

        if self.batch_mesh < 1 or self.B % self.batch_mesh:
            raise ValueError(
                f"batch_mesh={batch_mesh} must divide the batch size "
                f"{self.B}"
            )
        if bind == "baked" and self.batch_mesh != 1:
            raise ValueError(
                "bind='baked' needs batch_mesh=1: members sharded across "
                "devices would need per-device constants, which one SPMD "
                "program cannot carry — use bind='traced' to factorize "
                "the mesh over batch"
            )
        total = self.batch_mesh * cfg.mesh.num_devices
        avail = list(devices) if devices is not None else jax.devices()
        if len(avail) < total:
            raise ValueError(
                f"ensemble mesh b={self.batch_mesh} x space "
                f"{cfg.mesh.shape} needs {total} devices, only "
                f"{len(avail)} visible"
            )
        dev = np.asarray(avail[:total]).reshape(
            (self.batch_mesh,) + cfg.mesh.shape
        )
        self.mesh = Mesh(dev, (BATCH_AXIS,) + cfg.mesh.axis_names)
        self.spec = P(BATCH_AXIS, *cfg.mesh.axis_names)
        self.sharding = NamedSharding(self.mesh, self.spec)
        self._member_spec = NamedSharding(self.mesh, P(BATCH_AXIS))

        self._build_coefficients()
        self._build_programs()

    # ---- coefficient packing ---------------------------------------------

    def _build_coefficients(self) -> None:
        cfg = self.cfg
        compute_dtype = jnp.dtype(cfg.precision.compute)
        storage_dtype = jnp.dtype(cfg.precision.storage)
        self.budgets = np.asarray(
            [self.batch.member_steps(m) for m in range(self.B)],
            dtype=np.int32,
        )
        self._BCV = np.asarray(
            [m.bc_value for m in self.batch.members], dtype=np.float64
        ).astype(storage_dtype)
        self._BCV_dev = jax.device_put(
            jnp.asarray(self._BCV), self._member_spec
        )
        if self._varcoef:
            # per-member coefficient FIELDS, (B, *padded), sharded like
            # the solution. Storage padding stays ZERO: a=0 kills the
            # face flux out of the pad — the same Dirichlet rule the
            # ghost exchange applies (timeint.coeffield). Fields are
            # deterministic from the scenario spec tuples, so rebinds
            # and supervised restarts rebuild them — never checkpointed.
            A = np.zeros((self.B,) + tuple(cfg.padded_shape), np.float64)
            sl = tuple(slice(0, g) for g in cfg.grid.shape)
            for m in range(self.B):
                A[(m,) + sl] = self.batch.member_coef_field(m)
            self._A = A.astype(storage_dtype)
            self._DT = np.asarray(
                [self.batch.member_dt(m) for m in range(self.B)],
                dtype=np.float64,
            ).astype(compute_dtype)
            self._W = self._COEF = None
            self._mehrstellen = False
            self._A_dev = jax.device_put(jnp.asarray(self._A), self.sharding)
            self._DT_dev = jax.device_put(
                jnp.asarray(self._DT), self._member_spec
            )
            return
        nominal = _solver_taps(cfg)
        self._flat = flat_taps(nominal)
        positions = emission_positions(self._flat)
        member_taps = [self.batch.member_taps(m) for m in range(self.B)]
        # host-side double -> compute-dtype cast, ONE rounding — exactly
        # jnp.asarray(python_float, compute_dtype) on the baked path
        self._W = np.asarray(
            [
                [t[di + 1, dj + 1, dk + 1] for (di, dj, dk) in positions]
                for t in member_taps
            ],
            dtype=np.float64,
        ).astype(compute_dtype)
        # the separable S+F route follows the same env gate as the solo
        # apply; members share decomposability (same stencil kind, same
        # footprint), so the route is uniform across the batch
        coeffs = [decompose_mehrstellen(t) for t in member_taps]
        self._mehrstellen = mehrstellen_enabled() and all(
            c is not None for c in coeffs
        )
        self._COEF = (
            np.asarray(coeffs, dtype=np.float64).astype(compute_dtype)
            if self._mehrstellen
            else None
        )
        # upload ONCE per (re)bind: the arrays are fixed for the batch,
        # and run()/step calls may fire many times per bind (the queue's
        # snapshot loop, the bench's timed repeats)
        self._W_dev = jax.device_put(jnp.asarray(self._W), self._member_spec)
        self._C_dev = (
            jax.device_put(jnp.asarray(self._COEF), self._member_spec)
            if self._COEF is not None
            else jnp.zeros((self.B, 1), jnp.float32)  # placeholder, unused
        )

    @property
    def storage_dtype(self):
        return jnp.dtype(self.cfg.precision.storage)

    # ---- the member update (traced binding) ------------------------------

    def _member_apply(self, up, w, coef):
        cfg = self.cfg
        compute_dtype = jnp.dtype(cfg.precision.compute)
        out_dtype = jnp.dtype(cfg.precision.storage)
        if self._mehrstellen:
            return _apply_mehrstellen_padded(
                up.astype(compute_dtype), tuple(coef), compute_dtype
            ).astype(out_dtype)
        return apply_taps_padded_params(
            up, self._flat, w, compute_dtype=compute_dtype,
            out_dtype=out_dtype,
        )

    def _member_step(self, ul, w, coef, bcv):
        """One member's single update — the parametric mirror of
        ``parallel.step._local_step`` (same exchange, same chain emission,
        same padding pin; coefficients traced). The exchange rides the
        shared persistent plan (parallel.plan) with the member's TRACED
        boundary value as the apply-time argument, so one plan serves
        every member and every bucket of this mesh shape."""
        cfg = self.cfg
        with named_phase("halo_exchange"):
            up = exchange_with_plan(ul, cfg, 1, bcv)
        with named_phase("stencil"):
            out = self._member_apply(up, w, coef)
            return _pin_padding(out, cfg, bc_value=bcv)

    def _member_superstep(self, ul, w, coef, bcv):
        """One member's k-update superstep — the parametric mirror of
        ``parallel.step._local_stepk`` (width-k exchange, shrinking
        ghost-ring recompute, storage-dtype round trips)."""
        cfg, k = self.cfg, self.k
        with named_phase("halo_exchange"):
            cur = exchange_with_plan(ul, cfg, k, bcv)
        with named_phase("stencil"):
            for j in range(k):
                cur = self._member_apply(cur, w, coef)
                rings = k - 1 - j
                if rings > 0:
                    cur = _fill_mid_ghosts(cur, cfg, rings, bc_value=bcv)
            return _pin_padding(cur, cfg, bc_value=bcv)

    def _vmapped(self, member_fn):
        if self._mehrstellen:
            return lambda u_b, W_b, C_b, bc_b: jax.vmap(member_fn)(
                u_b, W_b, C_b, bc_b
            )
        # no coef array: close a None in per member (vmap cannot map None)
        return lambda u_b, W_b, C_b, bc_b: jax.vmap(
            lambda u, w, bc: member_fn(u, w, None, bc)
        )(u_b, W_b, bc_b)

    # ---- compiled programs ------------------------------------------------

    def _coef_args(self):
        """The coefficient-argument triple, uploaded once per (re)bind
        in _build_coefficients: ``(W, COEF, BCV)`` on the constant-
        coefficient route, ``(A, DT, BCV)`` on the variable-coefficient
        one — same arity, so the compiled-program plumbing (run /
        residual / IR / AOT) is route-agnostic."""
        if self._varcoef:
            return self._A_dev, self._DT_dev, self._BCV_dev
        return self._W_dev, self._C_dev, self._BCV_dev

    def _build_programs(self) -> None:
        cfg, k, B = self.cfg, self.k, self.B
        spec = self.spec
        mspec = P(BATCH_AXIS)
        res_dtype = jnp.dtype(cfg.precision.residual)
        spatial_axes = cfg.mesh.axis_names

        if self.bind == "traced":
            if self._varcoef:
                # variable-coefficient flux form: the member update is
                # timeint.coeffield's local step with the member's FIELD
                # shard, dt, and boundary value all traced; k==1 is
                # enforced at construction, so the superstep IS the step
                from heat3d_tpu.timeint.coeffield import _local_flux_update

                def member_vc(u, a, dtm, bcv):
                    return _local_flux_update(
                        u, a, cfg, dtm, exchange_with_plan, bc_value=bcv
                    )

                def step_v(u_b, A_b, DT_b, bc_b):
                    return jax.vmap(member_vc)(u_b, A_b, DT_b, bc_b)

                super_v = step_v
            else:
                step_v = self._vmapped(self._member_step)
                super_v = self._vmapped(self._member_superstep)

            def local_run(u_b, W_b, C_b, bc_b, budget_b):
                # loop bounds must be SPMD-uniform: a device's local
                # budget max would differ across the batch axis and
                # desynchronize the halo collectives — pmax makes the
                # trip count global, the per-member mask does the rest
                n_super = budget_b // k
                bound = lax.pmax(
                    jnp.max(n_super, initial=jnp.int32(0)), BATCH_AXIS
                )

                def body(i, ub):
                    stepped = super_v(ub, W_b, C_b, bc_b)
                    keep = (i < n_super)[:, None, None, None]
                    return jnp.where(keep, stepped, ub)

                u = lax.fori_loop(0, bound, body, u_b)
                if k > 1:
                    rem = budget_b % k
                    rbound = lax.pmax(
                        jnp.max(rem, initial=jnp.int32(0)), BATCH_AXIS
                    )

                    def rem_body(i, ub):
                        stepped = step_v(ub, W_b, C_b, bc_b)
                        keep = (i < rem)[:, None, None, None]
                        return jnp.where(keep, stepped, ub)

                    u = lax.fori_loop(0, rbound, rem_body, u)
                return u

            def local_step_res(u_b, W_b, C_b, bc_b):
                new = step_v(u_b, W_b, C_b, bc_b)
                r = jax.vmap(
                    lambda a, b: residual_sumsq(a, b, res_dtype)
                )(new, u_b)
                return new, lax.psum(r, spatial_axes)

            # the field array shards like the solution; scalar
            # per-member coefficients shard over the batch axis only
            coef_specs = (
                (spec, mspec, mspec)
                if self._varcoef
                else (mspec, mspec, mspec)
            )
            self._run_p = jax.jit(
                shard_map(
                    local_run,
                    mesh=self.mesh,
                    in_specs=(spec,) + coef_specs + (mspec,),
                    out_specs=spec,
                    check_vma=False,
                ),
                donate_argnums=0,
            )
            self._step_res_p = jax.jit(
                shard_map(
                    local_step_res,
                    mesh=self.mesh,
                    in_specs=(spec,) + coef_specs,
                    out_specs=(spec, P(BATCH_AXIS)),
                    check_vma=False,
                ),
                donate_argnums=0,
            )
            return

        # ---- baked binding: one SOLO executable per member --------------
        # The whole point of this binding is bitwise identity with B
        # independent HeatSolver3D runs, so each member gets the EXACT
        # solo program — make_multistep_fn over the spatial mesh, jitted
        # with the same donation — dispatched from the batched state
        # (slice member in, run, stack back out; pure data movement).
        from heat3d_tpu.parallel.step import make_multistep_fn, make_step_fn

        member_cfgs = [self.batch.member_config(m) for m in range(B)]
        space_dev = np.asarray(self.mesh.devices)[0]
        self._space_mesh = Mesh(space_dev, cfg.mesh.axis_names)
        self._space_sharding = NamedSharding(
            self._space_mesh, P(*cfg.mesh.axis_names)
        )
        self._member_run = [
            jax.jit(
                make_multistep_fn(c, self._space_mesh, apply_taps_padded),
                donate_argnums=0,
            )
            for c in member_cfgs
        ]
        self._member_step_res = [
            jax.jit(
                make_step_fn(
                    c, self._space_mesh, apply_taps_padded,
                    with_residual=True,
                ),
                donate_argnums=0,
            )
            for c in member_cfgs
        ]
        self._stack = jax.jit(
            lambda *xs: jnp.stack(xs), out_shardings=self.sharding
        )

    def ir_programs(self):
        """The traced-bind executables as ``(name, fn, example_args)``
        triples for the IR verifier (``heat3d lint --ir``,
        analysis/ir/programs.py): the run program (masked superstep +
        remainder loops under SPMD-uniform pmax bounds) and the residual
        probe. Abstract args only — nothing executes; the verifier
        traces these to closed jaxprs and certifies the collective
        topology / footprint / dtype flow the queue actually serves.
        Baked binding dispatches the solo executables, which the solver
        matrix already certifies — only the traced binding has an
        ensemble-specific program to verify."""
        if self.bind != "traced":
            return []
        u = jax.ShapeDtypeStruct(
            (self.B,) + tuple(self.cfg.padded_shape), self.storage_dtype
        )
        W, C, BCV = self._coef_args()
        budgets = jax.ShapeDtypeStruct((self.B,), jnp.int32)
        return self._program_triples(u, W, C, BCV, budgets)

    # ---- AOT export/adoption (serve/aot.py) -------------------------------

    # name -> dispatcher attribute: THE registry of the traced bind's
    # shared programs. ir_programs, aot_programs, and adopt_executables
    # all derive from it, so a new program (a superstep variant, say)
    # added here + in _program_triples' arg map is certified AND
    # AOT-cached — there is no third hand-kept list to miss.
    _PROGRAM_ATTRS = (("run", "_run_p"), ("step_residual", "_step_res_p"))

    def _program_triples(self, u, W, C, BCV, budgets):
        """(name, dispatcher, args) for every shared traced-bind
        program, given the caller's avals (the IR verifier passes plain
        shapes, the AOT cache sharding-annotated ones)."""
        args = {
            "run": (u, W, C, BCV, budgets),
            "step_residual": (u, W, C, BCV),
        }
        return [
            (name, getattr(self, attr), args[name])
            for name, attr in self._PROGRAM_ATTRS
        ]

    def aot_programs(self):
        """The traced-bind programs as ``(name, jit_fn, abstract_args)``
        for ahead-of-time compilation: ``fn.lower(*args).compile()``
        yields exactly the executable the first :meth:`run` /
        :meth:`step_with_member_residuals` call would have compiled.
        Args are sharding-annotated ``ShapeDtypeStruct``s (the compiled
        program is layout-strict, so the abstract avals must pin the
        same shardings the runtime inputs carry). Baked binding has no
        shared program to AOT — its solo executables are per-member."""
        if self.bind != "traced":
            return []

        def sds(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)

        u = jax.ShapeDtypeStruct(
            (self.B,) + tuple(self.cfg.padded_shape),
            self.storage_dtype,
            sharding=self.sharding,
        )
        W, C, BCV = (sds(a) for a in self._coef_args())
        budgets = jax.ShapeDtypeStruct(
            (self.B,), jnp.int32, sharding=self._member_spec
        )
        return self._program_triples(u, W, C, BCV, budgets)

    def adopt_executables(self, programs) -> None:
        """Swap AOT-compiled executables in for the jit dispatchers —
        the cold-start elimination hook (serve/aot.py): after adoption,
        the first request dispatches straight into the loaded PJRT
        executable with no trace and no compile. Coefficient REBINDS
        (the queue/engine's bucket reuse) survive adoption: rebinding
        replaces the uploaded arrays, not the programs."""
        if self.bind != "traced":
            raise ValueError(
                "adopt_executables: only the traced binding has shared "
                "programs (baked dispatches per-member solo executables)"
            )
        known = dict(self._PROGRAM_ATTRS)
        unknown = sorted(set(programs) - set(known))
        if unknown:
            raise ValueError(
                f"adopt_executables: unknown program name(s) {unknown} "
                f"(have {sorted(known)})"
            )
        for name, comp in programs.items():
            setattr(self, known[name], comp)

    # ---- stepping ---------------------------------------------------------

    def _budget_host(self, steps: Union[int, Sequence[int], None]):
        if steps is None:
            return self.budgets
        if np.isscalar(steps) or getattr(steps, "ndim", 1) == 0:
            return np.full((self.B,), int(steps), np.int32)
        b = np.asarray(steps, np.int32)
        if b.shape != (self.B,):
            raise ValueError(
                f"per-member steps must have shape ({self.B},), got "
                f"{b.shape}"
            )
        return b

    def run(self, u: jax.Array, steps: Union[int, Sequence[int], None] = None):
        """Advance every member by its budget. ``steps``: a scalar (all
        members), a per-member sequence, or ``None`` (each scenario's own
        budget). Members advance through supersteps for ``budget // k``
        then single steps for the remainder — the exact update sequence a
        solo run of that budget executes; finished members freeze bitwise
        while the rest run on."""
        budgets = self._budget_host(steps)
        if self.bind == "traced":
            W, C, BCV = self._coef_args()
            b_dev = jax.device_put(
                jnp.asarray(budgets, jnp.int32), self._member_spec
            )
            return self._run_p(u, W, C, BCV, b_dev)
        outs = []
        for m in range(self.B):
            um = jax.device_put(u[m], self._space_sharding)
            outs.append(self._member_run[m](um, jnp.int32(int(budgets[m]))))
        return self._stack(*outs)

    def step_with_residual(self, u: jax.Array):
        """One update for every member; returns ``(u_new, r2)`` where
        ``r2`` is the ENSEMBLE-AGGREGATE residual sum-of-squares (a
        scalar — the supervised loop's convergence/health number; use
        :meth:`step_with_member_residuals` for per-member values)."""
        u, r = self.step_with_member_residuals(u)
        return u, jnp.sum(r)

    def step_with_member_residuals(self, u: jax.Array):
        """One update for every member; returns ``(u_new, r2_members)``
        with ``r2_members`` shape (B,): each member's global residual
        sum-of-squares (psum over the spatial mesh only)."""
        if self.bind == "traced":
            W, C, BCV = self._coef_args()
            return self._step_res_p(u, W, C, BCV)
        outs, rs = [], []
        for m in range(self.B):
            um = jax.device_put(u[m], self._space_sharding)
            new, r = self._member_step_res[m](um)
            outs.append(new)
            rs.append(jnp.asarray(r))
        return self._stack(*outs), jnp.stack(rs)

    # ---- state ------------------------------------------------------------

    def init_state(self, init=None) -> jax.Array:
        """The sharded (B, *padded_shape) initial ensemble field. ``init``
        None or ``"scenario"`` builds each member's own IC from its
        scenario spec; a string or array overrides every member (the
        supervised-restart path). Built per-shard — no process ever holds
        the full batch."""
        with obs.get().span(
            "init_state",
            init="scenario" if init in (None, "scenario") else (
                init if isinstance(init, str) else "array"
            ),
            grid=list(self.cfg.grid.shape),
            members=self.B,
        ):
            return self._from_member_blocks(init)

    def _member_block(self, m: int, clipped, init_override):
        true_shape = self.cfg.grid.shape
        init = init_override
        if init in (None, "scenario"):
            init = self.batch.members[m].init
        if isinstance(init, np.ndarray):
            if init.shape != true_shape:
                raise ValueError(
                    f"scenario {m}: init shape {init.shape} != grid "
                    f"{true_shape}"
                )
            return init[clipped].astype(self.storage_dtype)
        return golden.make_init_block(
            init, true_shape, clipped, seed=self.batch.members[m].seed
        ).astype(self.storage_dtype)

    def _from_member_blocks(self, init_override=None) -> jax.Array:
        cfg = self.cfg
        true_shape = cfg.grid.shape
        storage_shape = cfg.padded_shape
        B = self.B

        def cb(idx):
            bsl, sp = idx[0], idx[1:]
            b0 = 0 if bsl.start is None else bsl.start
            b1 = B if bsl.stop is None else bsl.stop
            starts = [0 if s.start is None else s.start for s in sp]
            stops = [
                n if s.stop is None else s.stop
                for s, n in zip(sp, storage_shape)
            ]
            shape = tuple(b - a for a, b in zip(starts, stops))
            clipped = tuple(
                slice(a, min(b, g))
                for a, b, g in zip(starts, stops, true_shape)
            )
            local = tuple(slice(0, c.stop - c.start) for c in clipped)
            blocks = []
            for m in range(b0, b1):
                # uneven-decomposition padding pins at the MEMBER's bc
                block = np.full(
                    shape,
                    self.batch.members[m].bc_value,
                    self.storage_dtype,
                )
                if all(c.stop > c.start for c in clipped):
                    block[local] = self._member_block(m, clipped, init_override)
                blocks.append(block)
            return np.stack(blocks)

        return jax.make_array_from_callback(
            (B,) + storage_shape, self.sharding, cb
        )

    def zeros_state(self) -> jax.Array:
        """All-zero TRUE grids (padding at each member's bc) — cheap
        warmup input for the donated executables."""
        return self._from_member_blocks(np.zeros(self.cfg.grid.shape,
                                                 self.storage_dtype))

    # ---- IO ---------------------------------------------------------------

    def gather(self, u: jax.Array) -> np.ndarray:
        """All member fields on host, (B, *grid), storage padding
        stripped. Multi-host safe (collective when shards are remote)."""
        if u.is_fully_addressable:
            full = np.asarray(jax.device_get(u))
        else:
            from jax.experimental import multihost_utils

            full = np.asarray(multihost_utils.process_allgather(u, tiled=True))
        want = (self.B,) + self.cfg.grid.shape
        if full.shape != want:
            full = full[
                (slice(None),) + tuple(slice(0, g) for g in self.cfg.grid.shape)
            ]
        return full

    def gather_member(self, u: jax.Array, m: int) -> np.ndarray:
        """One member's field on host, (nx, ny, nz)."""
        if not 0 <= m < self.B:
            raise ValueError(f"member {m} outside batch of {self.B}")
        return self.gather(u)[m]

    def save_checkpoint(self, path: str, u: jax.Array, step: int) -> None:
        ckpt.save(
            path, u, step,
            extra={"config": repr(self.cfg), "members": self.B},
        )

    def load_checkpoint(self, path: str) -> Tuple[jax.Array, int]:
        u, step, _ = ckpt.load(path, self.sharding)
        want = (self.B,) + self.cfg.padded_shape
        if tuple(u.shape) != want:
            raise ValueError(
                f"checkpoint {path} holds a {tuple(u.shape)} field but this "
                f"ensemble's storage shape is {want} (B={self.B}, grid "
                f"{self.cfg.grid.shape} on mesh {self.cfg.mesh.shape}) — "
                "wrong checkpoint for this batch"
            )
        if u.dtype != self.storage_dtype:
            u = u.astype(self.storage_dtype)
        return u, step

    def run_supervised(
        self,
        total_steps: int,
        ckpt_root: str,
        checkpoint_every: int = 0,
        **kwargs,
    ):
        """Run the whole ensemble to global step ``total_steps`` under the
        resilience supervisor — generations carry the batch axis, so a
        supervised ensemble heals exactly like a single run (checkpoint
        every K steps, auto-resume from the newest good generation,
        quarantine corrupt ones). The ensemble advances in LOCKSTEP here
        (``total_steps`` for every member); per-member budgets are a
        :meth:`run` feature."""
        from heat3d_tpu.resilience.supervisor import run_supervised

        kwargs.setdefault(
            "make_solver",
            lambda: EnsembleSolver(
                self.batch, batch_mesh=self.batch_mesh, bind=self.bind
            ),
        )
        kwargs.setdefault("init", "scenario")
        return run_supervised(
            self, total_steps, ckpt_root, checkpoint_every, **kwargs
        )
