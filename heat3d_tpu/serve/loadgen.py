"""Open-loop sustained-traffic soak: seeded load generation + verdict.

``heat3d serve --loadgen SPEC.json`` replays a declarative scenario-mix
spec against the async engine the way real traffic arrives — OPEN LOOP
(arrivals keep coming whether or not the service keeps up; a closed-loop
generator would self-throttle and hide every overload bug this soak
exists to find), Poisson inter-arrivals per stream, an optional diurnal
ramp shaping the rate over the run, and per-stream adversarial bursts.
The whole schedule derives from ONE seed (spec ``seed``, else
``HEAT3D_LOADGEN_SEED``), so a soak run replays exactly: same arrival
times, same stream, same scenario per arrival.

Spec shape (docs/SERVING.md "Load, overload & soak")::

    {
      "duration_s": 60,
      "seed": 7,
      "rate_hz": 4.0,                     # aggregate peak, split by weight
      "ramp": {"kind": "diurnal", "period_s": 30, "min_frac": 0.25},
      "engine": {"max_batch": 4, "max_per_stream": 8, "workers": 2},
      "streams": [
        {"name": "tenant-a", "weight": 3,
         "scenarios": [{"grid": 16, "alpha": 0.5, "steps": 4}, ...]},
        {"name": "flood", "weight": 1,
         "burst": {"every_s": 10, "len_s": 2, "multiplier": 8},
         "scenarios": [...]}
      ],
      "slo": { ... inline SLO spec, optional ... }
    }

The run: (1) **warmup** — every bucket in the mix is prewarmed across
its full pow2 padded-size ladder (continuous batching makes the padded
member count — the executable key — depend on arrival timing, so zero
``compile_stall`` after warmup is only achievable by warming every size
a batch could pad to; soak specs keep ``max_batch`` small for exactly
this reason); (2) **replay** — arrivals submit open-loop, shed
submissions (typed ``Backpressure``) are counted, not retried, and the
engine's :meth:`~heat3d_tpu.serve.engine.AsyncServeEngine.
prewarm_forecast` runs between arrivals; a collector thread consumes
``results()`` concurrently, checking per-stream delivery order; (3)
**verdict** — accounting (admitted + shed == submitted), order, zero
failures, zero post-warmup compile stalls, and the SLO evaluation
(``serve_latency`` percentiles per bucket with computed p99, and the
``serve_degraded`` budget — the chaos leg injects partial-device-loss
mid-soak via ``HEAT3D_FAULTS`` and this objective judges the recovery)
fold into one machine-checked ``soak_verdict``.
"""

from __future__ import annotations

import dataclasses
import math
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from heat3d_tpu import obs
from heat3d_tpu.core.config import SolverConfig
from heat3d_tpu.serve.queue import Backpressure, _padded_size
from heat3d_tpu.serve.scenario import Scenario, solver_bucket_key
from heat3d_tpu.utils.logging import get_logger

log = get_logger(__name__)

ENV_LOADGEN_SEED = "HEAT3D_LOADGEN_SEED"

# the soak's default SLO when the spec carries none: generous latency
# bounds (CPU soak smokes must pass on loaded CI hosts) but a REAL
# degraded budget — the chaos leg is only meaningful if recovery is
# actually judged
DEFAULT_SOAK_SLO: Dict[str, Any] = {
    "default_spec": True,
    "objectives": [
        {"name": "soak-p95", "kind": "serve_latency",
         "percentile": 95, "max_s": 120.0},
        {"name": "soak-p99", "kind": "serve_latency",
         "percentile": 99, "max_s": 240.0},
        {"name": "soak-degraded", "kind": "serve_degraded",
         "max_s": 30.0},
    ],
}


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled request: fires ``t`` seconds into the soak on
    ``stream``, submitting that stream's ``record_index``-th scenario."""

    t: float
    stream: str
    record_index: int


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"loadgen spec: {msg}")


def validate_mix(mix: Dict[str, Any]) -> Dict[str, Any]:
    """Validate a scenario-mix spec (raises ValueError with the exact
    field at fault — a soak that dies an hour in on a typo\'d key is a
    wasted hour)."""
    _require(isinstance(mix, dict), "top level must be an object")
    known = {
        "duration_s", "seed", "rate_hz", "ramp", "engine", "streams", "slo",
        "monitor",
    }
    unknown = set(mix) - known
    _require(not unknown, f"unknown key(s) {sorted(unknown)}")
    dur = mix.get("duration_s")
    _require(
        isinstance(dur, (int, float)) and dur > 0,
        "duration_s must be a positive number",
    )
    rate = mix.get("rate_hz", 2.0)
    _require(
        isinstance(rate, (int, float)) and rate > 0,
        "rate_hz must be a positive number",
    )
    seed = mix.get("seed")
    _require(
        seed is None or isinstance(seed, int),
        "seed must be an integer",
    )
    ramp = mix.get("ramp")
    if ramp is not None:
        _require(isinstance(ramp, dict), "ramp must be an object")
        _require(
            ramp.get("kind", "diurnal") == "diurnal",
            f"ramp.kind {ramp.get('kind')!r} unknown (only 'diurnal')",
        )
        period = ramp.get("period_s", dur)
        _require(
            isinstance(period, (int, float)) and period > 0,
            "ramp.period_s must be a positive number",
        )
        frac = ramp.get("min_frac", 0.25)
        _require(
            isinstance(frac, (int, float)) and 0 <= frac <= 1,
            "ramp.min_frac must be in [0, 1]",
        )
    streams = mix.get("streams")
    _require(
        isinstance(streams, list) and streams,
        "streams must be a non-empty list",
    )
    names = set()
    for i, s in enumerate(streams):
        _require(isinstance(s, dict), f"streams[{i}] must be an object")
        name = s.get("name")
        _require(
            isinstance(name, str) and name,
            f"streams[{i}].name must be a non-empty string",
        )
        _require(name not in names, f"duplicate stream name {name!r}")
        names.add(name)
        w = s.get("weight", 1.0)
        _require(
            isinstance(w, (int, float)) and w > 0,
            f"streams[{i}].weight must be positive",
        )
        r = s.get("rate_hz")
        _require(
            r is None or (isinstance(r, (int, float)) and r > 0),
            f"streams[{i}].rate_hz must be positive when present",
        )
        burst = s.get("burst")
        if burst is not None:
            _require(
                isinstance(burst, dict),
                f"streams[{i}].burst must be an object",
            )
            for k in ("every_s", "len_s", "multiplier"):
                v = burst.get(k)
                _require(
                    isinstance(v, (int, float)) and v > 0,
                    f"streams[{i}].burst.{k} must be a positive number",
                )
        recs = s.get("scenarios")
        _require(
            isinstance(recs, list) and recs,
            f"streams[{i}].scenarios must be a non-empty list",
        )
        for j, rec in enumerate(recs):
            _require(
                isinstance(rec, dict),
                f"streams[{i}].scenarios[{j}] must be an object",
            )
    engine = mix.get("engine", {})
    _require(isinstance(engine, dict), "engine must be an object")
    monitor = mix.get("monitor")
    if monitor is not None:
        _require(isinstance(monitor, dict), "monitor must be an object")
        mon_known = {
            "interval_s", "fast_window_s", "slow_window_s", "threshold"
        }
        unknown_m = set(monitor) - mon_known
        _require(
            not unknown_m, f"monitor: unknown key(s) {sorted(unknown_m)}"
        )
        for k in mon_known:
            v = monitor.get(k)
            _require(
                v is None or (isinstance(v, (int, float)) and v > 0),
                f"monitor.{k} must be a positive number",
            )
    return mix


def _rate_factor(t: float, ramp: Optional[Dict[str, Any]], dur: float) -> float:
    """The diurnal shape: rate multiplier in [min_frac, 1] at soak time
    ``t`` — a raised cosine trough-to-peak over each period, the
    small-scale analog of a day's traffic curve."""
    if not ramp:
        return 1.0
    period = float(ramp.get("period_s", dur))
    frac = float(ramp.get("min_frac", 0.25))
    return frac + (1.0 - frac) * 0.5 * (
        1.0 - math.cos(2.0 * math.pi * t / period)
    )


def _burst_factor(t: float, burst: Optional[Dict[str, Any]]) -> float:
    """Adversarial bursts: ``multiplier`` x rate for ``len_s`` seconds
    every ``every_s`` — the pattern that wedges naive global-cap
    queues."""
    if not burst:
        return 1.0
    every = float(burst["every_s"])
    if t % every < float(burst["len_s"]):
        return float(burst["multiplier"])
    return 1.0


def generate_arrivals(mix: Dict[str, Any]) -> List[Arrival]:
    """The deterministic schedule: per-stream non-homogeneous Poisson
    arrivals by thinning (draw at the stream's PEAK rate, accept with
    probability rate(t)/peak), each stream seeded from
    ``f"{seed}:{name}"`` so adding a stream never perturbs another's
    schedule. Merged in time order."""
    dur = float(mix["duration_s"])
    ramp = mix.get("ramp")
    seed = mix.get("seed")
    if seed is None:
        seed = int(os.environ.get(ENV_LOADGEN_SEED, "0") or 0)
    total_rate = float(mix.get("rate_hz", 2.0))
    weights = {
        s["name"]: float(s.get("weight", 1.0)) for s in mix["streams"]
    }
    wsum = sum(weights.values())
    out: List[Arrival] = []
    for s in mix["streams"]:
        name = s["name"]
        base_rate = (
            float(s["rate_hz"]) if s.get("rate_hz") is not None
            else total_rate * weights[name] / wsum
        )
        burst = s.get("burst")
        peak = base_rate * (
            float(burst["multiplier"]) if burst else 1.0
        )
        rng = random.Random(f"{seed}:{name}")
        n_rec = len(s["scenarios"])
        t = 0.0
        while True:
            t += rng.expovariate(peak)
            if t >= dur:
                break
            rate_t = (
                base_rate * _rate_factor(t, ramp, dur) * _burst_factor(t, burst)
            )
            if rng.random() * peak <= rate_t:
                out.append(
                    Arrival(t=t, stream=name, record_index=rng.randrange(n_rec))
                )
    out.sort(key=lambda a: (a.t, a.stream))
    return out


def _pow2_ladder(max_batch: int) -> List[int]:
    """Every padded size continuous batching can produce up to
    ``max_batch``: 1, 2, 4, ... then max_batch itself."""
    sizes = []
    p = 1
    while p < max_batch:
        sizes.append(p)
        p *= 2
    sizes.append(max_batch)
    return sizes


def _warmup(engine, bases: List[SolverConfig]) -> Tuple[int, float]:
    """Prewarm every (bucket, padded-size) pair the mix can produce and
    WAIT for the builds — the post-warmup zero-``compile_stall``
    criterion starts counting after this returns. Returns (executables
    warmed, seconds)."""
    t0 = time.monotonic()
    seen = set()
    waits = []
    for base in bases:
        bucket = str(solver_bucket_key(base))
        for size in _pow2_ladder(engine.max_batch):
            padded = _padded_size(size, engine.max_batch, engine.batch_mesh)
            if (bucket, padded) in seen:
                continue
            seen.add((bucket, padded))
            ev = engine.prewarm(base, expected_members=size, forecast=size)
            if ev is not None:
                waits.append(ev)
    for ev in waits:
        ev.wait(timeout=600)
    return len(waits), time.monotonic() - t0


def _percentile(sorted_vals: List[float], pct: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(
        len(sorted_vals) - 1, max(0, int(math.ceil(pct / 100.0 * len(sorted_vals))) - 1)
    )
    return sorted_vals[idx]


class _Monitor:
    """The soak's live leg (``serve --loadgen --monitor``): a thread that
    tails the run's OWN ledger while the replay is in flight, re-judging
    the SLO spec as burn rate over sliding fast/slow windows
    (:class:`heat3d_tpu.obs.burn.BurnEvaluator`), landing one
    ``slo_burn_alert`` per objective RISING EDGE (enter-alerting, not
    every tick), and — under ``abort_on_burn`` — tripping the abort
    event the arrivals loop honors, so a soak that is already condemned
    dies in minutes with a machine-readable partial verdict instead of
    burning its full duration."""

    def __init__(self, engine, cfg: Dict[str, Any], ledger_path: str):
        from heat3d_tpu.obs.burn import BurnEvaluator
        from heat3d_tpu.obs.tailer import LedgerTailer

        self._engine = engine
        self._spec = cfg["spec"]
        self.abort_on_burn = bool(cfg.get("abort_on_burn"))
        self.interval_s = float(cfg.get("interval_s") or 2.0)
        self._be = BurnEvaluator(
            self._spec,
            fast_s=cfg.get("fast_window_s"),
            slow_s=cfg.get("slow_window_s"),
            threshold=cfg.get("threshold"),
        )
        self._tailer = LedgerTailer(ledger_path)
        self.abort = threading.Event()
        self.alerts = 0
        self.alerted: List[str] = []
        self._was_alerting: set = set()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="heat3d-soak-monitor", daemon=True
        )

    def start(self) -> None:
        obs.get().event(
            "monitor_start",
            interval_s=self.interval_s,
            fast_window_s=self._be.fast_s,
            slow_window_s=self._be.slow_s,
            threshold=self._be.threshold,
            abort_on_burn=self.abort_on_burn,
            objectives=[
                o.get("name", o.get("kind"))
                for o in self._spec.get("objectives", [])
            ],
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._tick()

    def _tick(self) -> None:
        # flush the engine's summary first (dirty-gated no-op when
        # clean) so the cumulative degraded/requeue budgets the
        # evaluator carries stay current between deliveries
        self._engine._emit_summary()
        self._be.consume(self._tailer.poll())
        rep = self._be.evaluate()
        now_alerting = set(rep["alerting"])
        for obj in rep["objectives"]:
            name = obj["name"]
            if name not in now_alerting or name in self._was_alerting:
                continue
            self.alerts += 1
            self.alerted.append(name)
            obs.get().event(
                "slo_burn_alert",
                objective=name,
                kind_=obj["kind"],
                fast_burn=obj["fast"]["burn"],
                slow_burn=obj["slow"]["burn"],
                fast_window_s=rep["fast_window_s"],
                slow_window_s=rep["slow_window_s"],
                threshold=rep["threshold"],
                value=obj["fast"]["value"],
                bucket=obj["fast"].get("bucket"),
            )
            log.warning(
                "SLO burn alert: %s fast=%.3gx slow=%.3gx (threshold "
                "%.3gx)",
                name, obj["fast"]["burn"] or 0.0,
                obj["slow"]["burn"] or 0.0, rep["threshold"],
            )
        self._was_alerting = now_alerting
        if now_alerting and self.abort_on_burn:
            self.abort.set()

    def finalize(self) -> Dict[str, Any]:
        """Stop the thread, drain the tail (the engine's final
        ``serve_metrics_summary`` landed at shutdown), and emit
        ``monitor_summary`` — the live evaluator's final state fed
        through the same shared core a post-hoc ``heat3d obs slo`` on
        this ledger uses, so the two agree by construction (the soak
        battery pins it). Returns the verdict's ``monitor`` block."""
        self._stop.set()
        self._thread.join(timeout=60)
        self._be.consume(self._tailer.poll())
        final = self._be.final_verdict()
        info = {
            "alerts": self.alerts,
            "alerted": self.alerted,
            "aborted": self.abort.is_set(),
            "fast_window_s": self._be.fast_s,
            "slow_window_s": self._be.slow_s,
            "threshold": self._be.threshold,
            "final": final["verdict"],
            "objectives": [
                {
                    "name": o["name"],
                    "status": o["status"],
                    "burn_rate": o["burn_rate"],
                }
                for o in final["objectives"]
            ],
        }
        obs.get().event("monitor_summary", **info)
        return info


def run_soak(
    mix: Dict[str, Any],
    base_for_record,
    scenario_for_record,
    slo_spec: Optional[Dict[str, Any]] = None,
    monitor: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Execute the soak: warmup, open-loop replay, collect, judge.

    ``base_for_record(record) -> SolverConfig`` and
    ``scenario_for_record(record) -> Scenario`` translate the spec's
    scenario records (the CLI passes its own request-record builders, so
    the spec grammar matches ``--requests`` exactly).

    Returns the verdict dict (also landed as a ``soak_verdict`` ledger
    event). SLO evaluation happens in the CALLER (the CLI owns the spec
    resolution + report printing); this returns the raw material —
    per-bucket latency percentiles merged into the engine summary."""
    from heat3d_tpu.serve.engine import AsyncServeEngine

    mix = validate_mix(mix)
    seed = mix.get("seed")
    if seed is None:
        seed = int(os.environ.get(ENV_LOADGEN_SEED, "0") or 0)
    arrivals = generate_arrivals(mix)
    dur = float(mix["duration_s"])
    eng_kw = dict(mix.get("engine", {}))
    engine = AsyncServeEngine(autostart=True, **eng_kw)

    # live monitoring leg: constructed BEFORE warmup so a misconfigured
    # monitor (no ledger to tail) fails at soak start, started after —
    # warmup emits no serve traffic worth judging
    mon: Optional[_Monitor] = None
    if monitor is not None:
        ledger_path = obs.get().path
        if not ledger_path:
            raise ValueError(
                "--monitor needs a run ledger (--ledger or "
                "$HEAT3D_LEDGER) — the live evaluator tails the run's "
                "own event stream"
            )
        mon = _Monitor(engine, monitor, ledger_path)

    # resolve every stream's records to (base, scenario) ONCE — a bad
    # record must fail at soak start, not minutes in
    resolved: Dict[str, List[Tuple[SolverConfig, Scenario]]] = {}
    for s in mix["streams"]:
        resolved[s["name"]] = [
            (base_for_record(rec), scenario_for_record(rec))
            for rec in s["scenarios"]
        ]
    bases = [b for recs in resolved.values() for b, _ in recs]

    obs.get().event(
        "loadgen_start",
        seed=seed,
        duration_s=dur,
        arrivals=len(arrivals),
        streams=[s["name"] for s in mix["streams"]],
        rate_hz=mix.get("rate_hz", 2.0),
    )
    warmed, warmup_s = _warmup(engine, bases)
    warm_stalls = engine.stats()["aot"]["stalls"]
    log.info(
        "soak warmup: %d executable(s) in %.1fs (%d stall(s) absorbed); "
        "replaying %d arrival(s) over %.0fs",
        warmed, warmup_s, warm_stalls, len(arrivals), dur,
    )

    # rid -> (stream, bucket, cells, submit_t); written by the submitter
    # BEFORE the engine can deliver the result, read by the collector
    meta: Dict[int, Tuple[str, str, int, float]] = {}
    meta_lock = threading.Lock()
    delivered_by_stream: Dict[str, List[int]] = {}
    bucket_lat: Dict[str, List[float]] = {}
    delivered_steps_cells = [0.0]
    order_ok = [True]

    stop_collect = threading.Event()

    def collect():
        # results() returns whenever nothing submitted remains
        # undelivered — which happens repeatedly in a soak whose service
        # keeps up with arrivals — so loop until the replay is over AND
        # the engine has drained
        while True:
            for res in engine.results():
                with meta_lock:
                    stream, bucket, cells, t_sub = meta[res.request_id]
                lst = delivered_by_stream.setdefault(stream, [])
                if lst and res.request_id <= lst[-1]:
                    order_ok[0] = False
                lst.append(res.request_id)
                bucket_lat.setdefault(bucket, []).append(
                    time.monotonic() - t_sub
                )
                delivered_steps_cells[0] += res.steps * cells
            if stop_collect.is_set():
                return
            time.sleep(0.02)

    collector = threading.Thread(
        target=collect, name="heat3d-soak-collect", daemon=True
    )
    collector.start()

    if mon is not None:
        mon.start()
    # the abort event doubles as the arrivals-loop sleep: an alert mid
    # inter-arrival gap wakes the loop immediately instead of after the
    # gap (an unmonitored soak keeps a plain never-set event — one code
    # path, zero behavior change)
    abort_ev = mon.abort if mon is not None else threading.Event()

    submitted = 0
    shed = 0
    t0 = time.monotonic()
    last_forecast = t0
    for a in arrivals:
        if abort_ev.is_set():
            break
        now = time.monotonic()
        target = t0 + a.t
        if target > now and abort_ev.wait(target - now):
            break
        base, scenario = resolved[a.stream][a.record_index]
        cells = int(
            base.grid.shape[0] * base.grid.shape[1] * base.grid.shape[2]
        )
        submitted += 1
        with meta_lock:
            try:
                rid = engine.submit(base, scenario, stream=a.stream)
            except Backpressure:
                shed += 1
                continue
            meta[rid] = (
                a.stream, str(solver_bucket_key(base)), cells,
                time.monotonic(),
            )
        if time.monotonic() - last_forecast >= 1.0:
            last_forecast = time.monotonic()
            engine.prewarm_forecast()

    engine.shutdown(wait=True)
    stop_collect.set()
    collector.join(timeout=600)
    elapsed = time.monotonic() - t0

    # finalize AFTER shutdown (the engine's final serve_metrics_summary
    # has landed) and BEFORE the soak_verdict event, so the ledger reads
    # monitor_summary -> soak_verdict in causal order
    aborted = abort_ev.is_set()
    mon_info = mon.finalize() if mon is not None else None

    stats = engine.stats()
    summary = engine.metrics_summary()
    # computed per-bucket percentiles (the engine summary's reservoir
    # carries p50/p95 — the soak verdict additionally wants p99, and
    # wants it from the FULL sample, not the reservoir)
    per_bucket: Dict[str, Dict[str, float]] = {}
    for bucket, lats in bucket_lat.items():
        lats.sort()
        per_bucket[bucket] = {
            "n": len(lats),
            "p50_s": round(_percentile(lats, 50), 6),
            "p95_s": round(_percentile(lats, 95), 6),
            "p99_s": round(_percentile(lats, 99), 6),
        }
    # merge p99 into the summary buckets so an SLO percentile-99
    # objective can read it through the normal path (the reservoir
    # carries p50/p95 only)
    for bucket_name, rec in summary.get("buckets", {}).items():
        pb = per_bucket.get(bucket_name)
        if pb:
            rec["p99_s"] = pb["p99_s"]

    stalls_after_warmup = stats["aot"]["stalls"] - warm_stalls
    accounting_ok = (
        submitted == stats["submitted"]
        and stats["admitted"] + stats["shed"] == stats["submitted"]
        and shed == stats["shed"]
    )
    delivered_all = (
        stats["delivered"] == stats["admitted"] - stats["cancelled"]
        and stats["failed"] == 0
    )
    sustained = (
        delivered_steps_cells[0] / 1e9 / elapsed if elapsed > 0 else 0.0
    )
    verdict = {
        "seed": seed,
        "duration_s": round(elapsed, 3),
        "planned_duration_s": dur,
        "arrivals": len(arrivals),
        "submitted": stats["submitted"],
        "admitted": stats["admitted"],
        "shed": stats["shed"],
        "shed_by_stream": stats["shed_by_stream"],
        "delivered": stats["delivered"],
        "failed": stats["failed"],
        "requeues": stats["requeues"],
        "degraded_s": stats["degraded_s"],
        "batches": stats["batches"],
        "scale_events": stats["scale_events"],
        "prewarmed": stats["prewarmed"],
        "warmup_s": round(warmup_s, 3),
        "compile_stall_after_warmup": stalls_after_warmup,
        "sustained_member_gcell_per_s": round(sustained, 6),
        "per_bucket": per_bucket,
        "order_ok": order_ok[0],
        "accounting_ok": accounting_ok,
        "aot": stats["aot"],
        # an aborted soak is judged on what it replayed: ``partial``
        # flags the truncated schedule, ``aborted`` condemns the verdict
        # (rc 1 in the CLI) — the early-termination contract
        "aborted": aborted,
        "partial": stats["submitted"] < len(arrivals),
        "ok": bool(
            accounting_ok
            and order_ok[0]
            and delivered_all
            and stalls_after_warmup == 0
            and not aborted
        ),
        "summary": summary,
    }
    if aborted:
        verdict["abort_reason"] = "slo_burn"
    if mon_info is not None:
        verdict["monitor"] = mon_info
    obs.get().event(
        "soak_verdict",
        ok=verdict["ok"],
        aborted=aborted,
        seed=seed,
        duration_s=verdict["duration_s"],
        submitted=verdict["submitted"],
        admitted=verdict["admitted"],
        shed=verdict["shed"],
        delivered=verdict["delivered"],
        failed=verdict["failed"],
        requeues=verdict["requeues"],
        degraded_s=verdict["degraded_s"],
        compile_stall_after_warmup=stalls_after_warmup,
        sustained_member_gcell_per_s=verdict["sustained_member_gcell_per_s"],
        order_ok=order_ok[0],
        accounting_ok=accounting_ok,
    )
    return verdict


def soak_row(
    verdict: Dict[str, Any], slo_verdict: str, ts: Optional[str] = None
) -> Dict[str, Any]:
    """The committed provenance row (``bench=soak``; checked by
    ``scripts/check_provenance.py`` — admitted + shed must equal
    submitted, the seed must replay the schedule, and the SLO verdict
    that judged the soak rides on the row)."""
    import datetime

    import jax

    return {
        "ts": ts or datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        ),
        "bench": "soak",
        "platform": jax.default_backend(),
        "devices": len(jax.devices()),
        "seed": verdict["seed"],
        "duration_s": verdict["duration_s"],
        "arrivals": verdict["arrivals"],
        "submitted": verdict["submitted"],
        "admitted": verdict["admitted"],
        "shed": verdict["shed"],
        "delivered": verdict["delivered"],
        "failed": verdict["failed"],
        "requeues": verdict["requeues"],
        "degraded_s": verdict["degraded_s"],
        "batches": verdict["batches"],
        "scale_events": verdict["scale_events"],
        "warmup_s": verdict["warmup_s"],
        "compile_stall_after_warmup": verdict["compile_stall_after_warmup"],
        "sustained_member_gcell_per_s": verdict[
            "sustained_member_gcell_per_s"
        ],
        "per_bucket": verdict["per_bucket"],
        "slo": slo_verdict,
        "ok": verdict["ok"],
    }
