"""Scenario specs: what varies per ensemble member, what must be shared.

A *scenario* is one independent heat problem: its initial condition, its
Dirichlet boundary value, its diffusivity/timestep, and its step budget.
A :class:`ScenarioBatch` packs B scenarios over ONE structural config —
grid, stencil kind, BC kind, mesh, precision, solver knobs — which is
exactly the set a single compiled SPMD program can serve with the
per-member values as runtime inputs (serve/ensemble.py). The queue
(serve/queue.py) buckets incoming requests by :meth:`ScenarioBatch
.bucket_key` so only compatible scenarios ever share a program.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple, Union

import numpy as np

from heat3d_tpu.core.config import SolverConfig


@dataclasses.dataclass(frozen=True)
class Scenario:
    """One ensemble member's independent problem data.

    ``init`` — named initializer (core.golden.INITIALIZERS) or an explicit
    array of the TRUE grid shape. ``alpha``/``dt`` — the member's
    diffusivity and timestep (``dt=None`` = 0.9x the member's stable dt,
    same rule as GridConfig). ``bc_value`` — the member's Dirichlet
    boundary value (ignored under periodic BCs). ``steps`` — the member's
    step budget (``None`` = the batch default); members of one batch may
    carry different budgets — finished members freeze bitwise while the
    rest run on.
    """

    init: Union[str, np.ndarray] = "hot-cube"
    alpha: float = 1.0
    dt: Optional[float] = None
    bc_value: float = 0.0
    steps: Optional[int] = None
    seed: int = 0
    # Per-member equation-parameter overrides ((name, value) pairs —
    # e.g. a member's own advection velocity) on top of the BASE config's
    # equation family + eq_params. The traced bind feeds the member's
    # lowered tap values into the shared parametric chain, so per-member
    # spec coefficients ride with zero recompilation (docs/SERVING.md
    # "Per-member spec binding"); the footprint guard still applies —
    # values that change which taps are nonzero fail loudly at batch
    # construction.
    eq_params: Tuple[Tuple[str, float], ...] = ()
    # Which time integrator this request wants (core.config.INTEGRATORS;
    # None = the batch base's). One compiled program runs ONE integrator,
    # so the queue buckets on it (solver_bucket_key) and ScenarioBatch
    # requires every member that states one to agree.
    integrator: Optional[str] = None
    # Per-member spatially-varying diffusivity: a coefficient-FIELD spec
    # tuple ``(name, seed, lo, hi)`` resolved by
    # ``timeint.coeffield.make_coef_field`` (name alone or a prefix is
    # accepted; defaults seed=0, lo=0.5, hi=1.5). The field replaces the
    # member's scalar alpha in the flux-form update and rides the traced
    # bind as a runtime input; all-or-none across a batch (the varcoef
    # program has a different input signature).
    coef_field: Optional[Tuple] = None

    def __post_init__(self):
        if self.integrator is not None:
            from heat3d_tpu.core.config import INTEGRATORS

            if self.integrator not in INTEGRATORS:
                raise ValueError(
                    f"scenario integrator {self.integrator!r} not in "
                    f"{INTEGRATORS}"
                )
        if self.coef_field is not None:
            cf = self.coef_field
            if isinstance(cf, str):
                cf = (cf,)
            cf = tuple(cf)
            if not 1 <= len(cf) <= 4:
                raise ValueError(
                    f"coef_field must be (name[, seed[, lo[, hi]]]), got "
                    f"{self.coef_field!r}"
                )
            name = str(cf[0])
            seed = int(cf[1]) if len(cf) > 1 else 0
            lo = float(cf[2]) if len(cf) > 2 else 0.5
            hi = float(cf[3]) if len(cf) > 3 else 1.5
            from heat3d_tpu.timeint.coeffield import COEF_FIELDS

            if name not in COEF_FIELDS:
                raise ValueError(
                    f"unknown coefficient field {name!r}; have "
                    f"{COEF_FIELDS}"
                )
            if not 0.0 < lo <= hi:
                raise ValueError(
                    f"coef_field needs 0 < lo <= hi, got lo={lo} hi={hi}"
                )
            object.__setattr__(self, "coef_field", (name, seed, lo, hi))
        if self.alpha <= 0.0:
            raise ValueError(
                f"scenario alpha must be > 0, got {self.alpha} (alpha*dt=0 "
                "degenerates the tap footprint the batch shares)"
            )
        if self.dt is not None and self.dt <= 0.0:
            raise ValueError(f"scenario dt must be > 0, got {self.dt}")
        if self.steps is not None and self.steps < 0:
            raise ValueError(f"scenario steps must be >= 0, got {self.steps}")
        if not isinstance(self.eq_params, tuple):
            object.__setattr__(
                self,
                "eq_params",
                tuple((str(k), float(v)) for k, v in self.eq_params),
            )


class ScenarioBatch:
    """B scenarios over one structural :class:`SolverConfig`.

    ``base`` supplies everything the members share: grid shape/spacing,
    stencil kind + BC kind, mesh, precision, and the solver knobs
    (backend/halo/time_blocking/...). Each member's ``alpha``/``dt``/
    ``bc_value``/``steps`` override the base's per-member. Construction
    validates that every member's update taps occupy the SAME footprint
    as the base's (they always do for alpha*dt > 0 — the guard exists so
    a degenerate member fails loudly instead of silently changing the
    shared chain structure).
    """

    def __init__(self, base: SolverConfig, members: Sequence[Scenario]):
        members = tuple(members)
        if not members:
            raise ValueError("a ScenarioBatch needs at least one scenario")
        # integrator consistency: one compiled program runs ONE
        # integrator, so members that state one must agree — and the
        # stated one becomes the batch's effective base integrator
        # (requests carrying an integrator bucket apart via
        # solver_bucket_key before they ever reach a batch)
        stated = {m.integrator for m in members if m.integrator is not None}
        if len(stated) > 1:
            raise ValueError(
                f"members of one batch state conflicting integrators "
                f"{sorted(stated)} — one compiled program runs one "
                "integrator (the queue buckets on it; these requests "
                "should never have shared a batch)"
            )
        if stated:
            ti = stated.pop()
            if ti != base.integrator:
                base = dataclasses.replace(base, integrator=ti)
        # coefficient fields: all-or-none (the varcoef program takes the
        # field array as an extra runtime input — a mixed batch has no
        # single program signature), and only on the explicit heat sweep
        with_cf = sum(1 for m in members if m.coef_field is not None)
        if with_cf not in (0, len(members)):
            raise ValueError(
                f"{with_cf}/{len(members)} members carry coef_field — "
                "coefficient fields are all-or-none across a batch (the "
                "varcoef program has a different input signature)"
            )
        self.has_coef_fields = with_cf == len(members)
        if self.has_coef_fields:
            if base.equation != "heat" or base.integrator != "explicit-euler":
                raise ValueError(
                    "coef_field members need the explicit-euler heat "
                    f"sweep, got equation={base.equation!r} "
                    f"integrator={base.integrator!r} (docs/INTEGRATORS.md)"
                )
        self.base = base
        self.members = members
        self._check_footprints()

    def __len__(self) -> int:
        return len(self.members)

    # ---- per-member config materialization --------------------------------

    def member_dt(self, i: int) -> float:
        m = self.members[i]
        if m.dt is not None:
            return m.dt
        if m.coef_field is not None:
            # flux-form explicit bound at the field's MAX (hi clip):
            # same 0.9x safety rule as GridConfig.effective_dt
            from heat3d_tpu.timeint.coeffield import varcoef_stable_dt

            return 0.9 * varcoef_stable_dt(
                m.coef_field[3], self.base.grid.spacing
            )
        g = dataclasses.replace(self.base.grid, alpha=m.alpha, dt=None)
        return g.effective_dt()

    def member_config(self, i: int) -> SolverConfig:
        """The full solo :class:`SolverConfig` member ``i`` describes —
        what a single-tenant :class:`HeatSolver3D` run of this scenario
        would be configured with (the bitwise reference the ensemble
        equivalence tests compare against). A member's ``eq_params``
        overlay the base's (member pairs win on name clashes)."""
        m = self.members[i]
        eq = dict(self.base.eq_params)
        eq.update(dict(m.eq_params))
        return dataclasses.replace(
            self.base,
            grid=dataclasses.replace(
                self.base.grid, alpha=m.alpha, dt=self.member_dt(i)
            ),
            stencil=dataclasses.replace(
                self.base.stencil, bc_value=m.bc_value
            ),
            run=dataclasses.replace(
                self.base.run, num_steps=self.member_steps(i), seed=m.seed
            ),
            eq_params=tuple(sorted(eq.items())),
        )

    def member_steps(self, i: int) -> int:
        m = self.members[i]
        return self.base.run.num_steps if m.steps is None else m.steps

    def member_coef_field(self, i: int) -> np.ndarray:
        """Member ``i``'s resolved fp64 coefficient field on the TRUE
        grid (deterministic from the spec tuple — rebuilt, never
        checkpointed)."""
        m = self.members[i]
        if m.coef_field is None:
            raise ValueError(f"scenario {i} carries no coef_field")
        from heat3d_tpu.timeint.coeffield import make_coef_field

        name, seed, lo, hi = m.coef_field
        return make_coef_field(
            name, self.base.grid.shape, seed=seed, lo=lo, hi=hi
        )

    def member_taps(self, i: int) -> np.ndarray:
        """Member ``i``'s lowered update taps, via the equation frontend
        on the member's solo config — for the heat family this is
        bit-identical to the old inline ``stencil_taps(kind, alpha, dt,
        spacing)`` call (the eqn bitwise contract), and for spec-built
        families it carries the member's own equation coefficients into
        the traced bind."""
        from heat3d_tpu import eqn

        return eqn.solver_taps(self.member_config(i))

    def _check_footprints(self) -> None:
        from heat3d_tpu.core.stencils import flat_taps
        from heat3d_tpu.parallel.step import _solver_taps

        want = tuple(
            (di, dj, dk) for di, dj, dk, _ in flat_taps(_solver_taps(self.base))
        )
        for i in range(len(self.members)):
            got = tuple(
                (di, dj, dk) for di, dj, dk, _ in flat_taps(self.member_taps(i))
            )
            if got != want:
                raise ValueError(
                    f"scenario {i}: its taps occupy footprint {got} but the "
                    f"batch's shared structure is {want} — members of one "
                    "batch must share the stencil footprint (alpha*dt > 0)"
                )

    # ---- queue bucketing ---------------------------------------------------

    def bucket_key(self) -> Tuple:
        """The structural compatibility key: scenarios whose batches share
        this key can be packed into ONE compiled ensemble program (the
        per-member values are runtime inputs; step budgets are traced, so
        they do NOT bucket). Coefficient-field batches run a different
        PROGRAM (the field array is an extra traced input), so the flag
        buckets — the field VALUES stay runtime inputs and do not."""
        key = solver_bucket_key(self.base)
        if self.has_coef_fields:
            key = key + ("coef-field",)
        return key


def request_bucket_key(base: SolverConfig, scenario: Scenario) -> Tuple:
    """The bucket key of ONE request: the base's structural key with the
    scenario's integrator override applied and the coef-field program
    flag appended — exactly what :meth:`ScenarioBatch.bucket_key` would
    say for a batch of such requests. Queues group by THIS key, so a
    request stating ``integrator='implicit-cg'`` (or carrying a
    coefficient field) can never pack with the plain explicit sweep of
    the same base."""
    ti = scenario.integrator
    if ti is not None and ti != base.integrator:
        base = dataclasses.replace(base, integrator=ti)
    key = solver_bucket_key(base)
    if scenario.coef_field is not None:
        key = key + ("coef-field",)
    return key


def solver_bucket_key(cfg: SolverConfig) -> Tuple:
    """The structural key of ``cfg``: everything that shapes the compiled
    ensemble program. Two requests sharing this key differ only in
    runtime inputs (IC, bc value, taps, budget)."""
    return (
        tuple(cfg.grid.shape),
        tuple(cfg.grid.spacing),
        cfg.stencil.kind,
        # equation family + base params shape the compiled chain (its
        # footprint and term structure) — requests of different families
        # must never pack into one program. Member-level eq_params stay
        # runtime inputs (the traced bind), so they deliberately do NOT
        # bucket.
        cfg.equation,
        tuple(cfg.eq_params),
        cfg.stencil.bc.value,
        tuple(cfg.mesh.shape),
        cfg.precision.storage,
        cfg.precision.compute,
        cfg.precision.residual,
        cfg.backend,
        cfg.halo,
        cfg.halo_order,
        cfg.overlap,
        cfg.time_blocking,
        # time integrator (PR 19): a leapfrog carry or a CG solve is a
        # structurally different program — requests of different
        # integrators must never pack into one bucket
        cfg.integrator,
    )
