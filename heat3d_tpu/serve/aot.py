"""AOT executable cache — cold-start elimination for the serving tier.

The serving engine's first request in a fresh process used to pay the
full trace + XLA-compile stall before a single member advanced (hundreds
of ms on CPU smoke shapes, tens of seconds for pod-scale ensembles).
This module makes that stall a *managed artifact*: the bucketed ensemble
executables are compiled ahead of time, serialized with
``jax.experimental.serialize_executable`` (the PJRT executable itself,
not a re-traceable staging of it — loading skips BOTH trace and
compile), and stored under a key that carries everything that could make
a stored program wrong to reuse:

- the **structural bucket** (:func:`~heat3d_tpu.serve.scenario
  .solver_bucket_key` + padded batch size + batch-mesh factorization) —
  what shapes the program;
- the **tune-cache key** (:func:`~heat3d_tpu.tune.cache.cache_key` at
  the batch bucket) — chip generation, process/device counts, per-device
  working-set bucket, equation fingerprint, dtype: the same context that
  decides which knobs win decides which executable is valid;
- **toolchain provenance** — jax version, platform, device kind/count.
  A serialized executable is a build artifact of one exact stack;
  anything else deserializes to undefined behavior, so a mismatch is
  ``stale`` and falls back to a fresh compile, never an error. The
  device count here is also what makes serve-tier elastic degradation
  free (docs/SERVING.md "Degraded-mode serving"): when the engine
  rebuilds a bucket after a backend loss shrank the mesh, the degraded
  warm-up keys (and staleness-checks) on the NEW device count — a
  full-mesh executable can never load into the survivor mesh, and the
  recompile is the ordinary miss path, not a special case.

Ledger contract (docs/OBSERVABILITY.md §6): every warm-up lands exactly
one of ``aot_cache_hit`` (with the measured ``load_s``) /
``aot_cache_miss`` / ``aot_cache_stale`` (with the reason), a paid
trace+compile lands a ``compile_stall`` event with its measured seconds
(absent on a hit — the acceptance criterion a warm restart is judged
by), and a store write lands ``aot_export``. Stall time is a measured
ledger quantity either way, never an invisible first-request tax.

``HEAT3D_AOT_CACHE`` points the store somewhere else (default
``~/.cache/heat3d/aot``); ``0``/``off`` disables it — the engine then
AOT-compiles at bucket creation (the stall is still measured and paid
OUTSIDE the first request's latency) but persists nothing. Store IO
fails soft: an unwritable directory or a torn payload degrades to
compile-and-serve, never to a dead bucket.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import tempfile
import time
from typing import Any, Dict, Optional

from heat3d_tpu import obs
from heat3d_tpu.utils.logging import get_logger

log = get_logger(__name__)

ENV_AOT = "HEAT3D_AOT_CACHE"
AOT_SCHEMA = 1


def aot_dir(explicit: Optional[str] = None) -> Optional[str]:
    """The store directory: explicit arg > ``$HEAT3D_AOT_CACHE`` > the
    per-user default. ``None`` when disabled (env set to ``0``/``off``)."""
    if explicit:
        return explicit
    env = os.environ.get(ENV_AOT)
    if env is not None:
        if env.strip().lower() in ("", "0", "off"):
            return None
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "heat3d", "aot")


def _toolchain() -> Dict[str, Any]:
    """The provenance legs a serialized executable is only valid under.
    Device kind + count pin the exact SPMD layout the payload was
    compiled for (a 4-device program must not load into a 2-device
    session)."""
    prov: Dict[str, Any] = {"schema": AOT_SCHEMA}
    try:
        import jax

        prov["jax_version"] = jax.__version__
        prov["platform"] = jax.default_backend()
        devs = jax.devices()
        prov["devices"] = len(devs)
        prov["device_kind"] = getattr(devs[0], "device_kind", devs[0].platform)
    except Exception:  # noqa: BLE001 - provenance derivation fails soft
        prov.update(
            jax_version=None, platform=None, devices=0, device_kind=None
        )
    return prov


def aot_key(solver) -> str:
    """The content key of ``solver``'s compiled programs: a hash over the
    structural bucket, the batch factorization, the tune-cache key at the
    batch bucket (chip/topology/working-set/equation/dtype context), and
    every resolved leg that shapes the TRACED program beyond the bucket:
    mehrstellen decomposability, time_blocking after auto-resolution,
    the EFFECTIVE exchange-plan mode + partition floor (halo_plan is not
    in ``solver_bucket_key`` but changes the ppermute schedule — a tuned
    partitioned winner must never warm-hit a monolithic executable), and
    the chain-factoring env gates (``_chain_accumulate`` emits under
    them)."""
    from heat3d_tpu.parallel.plan import effective_halo_plan
    from heat3d_tpu.serve.scenario import solver_bucket_key
    from heat3d_tpu.tune import cache as tcache

    tc = _toolchain()
    doc = {
        "bucket": [list(x) if isinstance(x, tuple) else x
                   for x in solver_bucket_key(solver.cfg)],
        "B": solver.B,
        "batch_mesh": solver.batch_mesh,
        "bind": solver.bind,
        "tune_key": tcache.cache_key(solver.cfg, batch_size=solver.B),
        "mehrstellen": bool(solver._mehrstellen),
        # variable-coefficient batches compile a different program
        # signature (the field array is a traced input) — never
        # warm-hit across the routes
        "coef_fields": bool(getattr(solver, "_varcoef", False)),
        "time_blocking": solver.cfg.time_blocking,
        # the exchange schedule legs: effective mode folds HEAT3D_NO_PLAN
        # in (parallel.plan's one rule); the floor changes which faces
        # genuinely sub-block under partitioned
        "halo_plan": effective_halo_plan(solver.cfg),
        "plan_floor": os.environ.get("HEAT3D_PLAN_PART_MIN_BYTES"),
        # chain-emission structure gates (docs/LOWERING.md factoring A/Bs)
        "factor_env": [
            os.environ.get("HEAT3D_FACTOR_7PT"),
            os.environ.get("HEAT3D_FACTOR_Y"),
        ],
        "jax": tc["jax_version"],
        "platform": tc["platform"],
        "devices": tc["devices"],
        "device_kind": tc["device_kind"],
        "schema": AOT_SCHEMA,
    }
    blob = json.dumps(doc, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


def _manifest_path(d: str, key: str) -> str:
    return os.path.join(d, f"{key}.json")


def _payload_path(d: str, key: str, name: str) -> str:
    return os.path.join(d, f"{key}.{name}.bin")


def _atomic_write(path: str, data: bytes) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".aot.", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _stale_reason(manifest: Dict[str, Any]) -> Optional[str]:
    """Why a stored manifest cannot serve this process, or None. The
    key already hashes the toolchain, so a mismatch here means a hash
    collision or a hand-edited store — checked anyway: loading a
    wrong-stack executable is undefined behavior, not a slow path."""
    tc = _toolchain()
    prov = manifest.get("provenance") or {}
    for leg in ("jax_version", "platform", "devices", "device_kind"):
        if prov.get(leg) != tc[leg]:
            return f"{leg} {prov.get(leg)!r} != {tc[leg]!r}"
    if manifest.get("schema") != AOT_SCHEMA:
        return f"schema {manifest.get('schema')!r} != {AOT_SCHEMA}"
    return None


def _load_programs(
    d: str, key: str, manifest: Dict[str, Any]
) -> Dict[str, Any]:
    """Deserialize every program payload the manifest names. Raises on
    any defect — the caller turns that into ``stale`` + recompile."""
    from jax.experimental.serialize_executable import deserialize_and_load

    out: Dict[str, Any] = {}
    for name in manifest.get("programs") or []:
        with open(_payload_path(d, key, name), "rb") as f:
            payload, in_tree, out_tree = pickle.load(f)
        out[name] = deserialize_and_load(payload, in_tree, out_tree)
    if not out:
        raise ValueError("manifest names no programs")
    return out


def _compile_now(solver, bucket: str):
    """AOT-compile the solver's programs, measuring the trace+compile
    stall into a ``compile_stall`` ledger event (the cost a cold process
    pays; adopting the compiled objects means the first REQUEST does
    not pay it again). Returns ``(compiled, stall_seconds)``."""
    compiled: Dict[str, Any] = {}
    t0 = time.monotonic()
    for name, fn, args in solver.aot_programs():
        compiled[name] = fn.lower(*args).compile()
    stall = time.monotonic() - t0
    obs.get().event(
        "compile_stall",
        bucket=bucket,
        programs=sorted(compiled),
        seconds=round(stall, 6),
    )
    return compiled, stall


def _export(solver, d: str, key: str, compiled: Dict[str, Any]) -> bool:
    """Serialize ``compiled`` into the store (manifest written LAST, so
    a torn export is an absent entry, not a corrupt one). Fails soft."""
    from jax.experimental.serialize_executable import serialize

    try:
        total = 0
        for name, comp in compiled.items():
            payload, in_tree, out_tree = serialize(comp)
            blob = pickle.dumps((payload, in_tree, out_tree))
            total += len(blob)
            _atomic_write(_payload_path(d, key, name), blob)
        manifest = {
            "schema": AOT_SCHEMA,
            "key": key,
            "programs": sorted(compiled),
            "bucket": repr(solver.batch.bucket_key()),
            "B": solver.B,
            "batch_mesh": solver.batch_mesh,
            "provenance": {
                **_toolchain(),
                "run_id": obs.get().run_id,
                "created": time.time(),
            },
        }
        _atomic_write(
            _manifest_path(d, key),
            (json.dumps(manifest, indent=1, sort_keys=True) + "\n").encode(),
        )
        obs.get().event(
            "aot_export",
            key=key,
            dir=d,
            programs=sorted(compiled),
            bytes=total,
        )
        return True
    except Exception as e:  # noqa: BLE001 - an unwritable store must
        # degrade to compile-and-serve, never kill the bucket being warmed
        log.warning("aot export failed (%s: %s) — serving uncached",
                    type(e).__name__, e)
        return False


def warm(solver, directory: Optional[str] = None) -> Dict[str, Any]:
    """Eliminate (or pay-and-measure) ``solver``'s compile stall.

    Load path: a valid store entry deserializes straight to executables
    (no trace, no compile) which are adopted into the solver —
    ``aot_cache_hit`` with the measured ``load_s``. Miss/stale/disabled
    path: AOT-compile NOW (``compile_stall`` event carries the measured
    seconds), adopt, and — when the store is enabled — serialize for the
    next process (``aot_export``). Returns a small report dict the
    engine aggregates into its stats. Never raises for store defects;
    only a genuinely uncompilable program propagates."""
    report: Dict[str, Any] = {
        "source": "jit", "outcome": None, "load_s": None,
        "compile_stall_s": None,
    }
    bucket = repr(solver.batch.bucket_key())
    d = aot_dir(directory)
    if d is None:
        compiled, stall = _compile_now(solver, bucket)
        solver.adopt_executables(compiled)
        report.update(
            source="disabled", outcome="disabled", compile_stall_s=stall
        )
        return report
    key = aot_key(solver)
    report["key"] = key
    mpath = _manifest_path(d, key)
    manifest = None
    try:
        with open(mpath) as f:
            manifest = json.load(f)
    except FileNotFoundError:
        obs.get().event("aot_cache_miss", key=key, dir=d, bucket=bucket)
        report["outcome"] = "miss"
    except (OSError, json.JSONDecodeError, ValueError) as e:
        obs.get().event(
            "aot_cache_stale", key=key, dir=d, bucket=bucket,
            reason=f"unreadable manifest: {type(e).__name__}: {e}",
        )
        report["outcome"] = "stale"
        manifest = None
    if manifest is not None:
        reason = _stale_reason(manifest)
        if reason is None:
            try:
                t0 = time.monotonic()
                programs = _load_programs(d, key, manifest)
                solver.adopt_executables(programs)
                load_s = time.monotonic() - t0
                obs.get().event(
                    "aot_cache_hit",
                    key=key,
                    dir=d,
                    bucket=bucket,
                    programs=sorted(programs),
                    load_s=round(load_s, 6),
                )
                report.update(source="aot", outcome="hit", load_s=load_s)
                return report
            except Exception as e:  # noqa: BLE001 - torn payload, pjrt
                # refusal, pickle drift: all degrade to recompile
                reason = f"payload load failed: {type(e).__name__}: {e}"
        obs.get().event(
            "aot_cache_stale", key=key, dir=d, bucket=bucket, reason=reason
        )
        report["outcome"] = "stale"
    compiled, stall = _compile_now(solver, bucket)
    solver.adopt_executables(compiled)
    report.update(source="compiled", compile_stall_s=stall)
    if report["outcome"] is None:
        report["outcome"] = "miss"
    report["exported"] = _export(solver, d, key, compiled)
    return report
