"""Batched scenario engine: the ensemble axis + the solver-as-a-service
front-end (docs/SERVING.md).

Layer 1 — :mod:`heat3d_tpu.serve.scenario` / :mod:`heat3d_tpu.serve.ensemble`:
a ``ScenarioBatch`` (per-member initial condition, boundary value,
diffusivity/dt, step budget over one shared structural config) and an
``EnsembleSolver`` that threads a leading batch dimension through the
distributed step — one compiled SPMD program advances every member.

Layer 2 — :mod:`heat3d_tpu.serve.queue` / ``heat3d serve``: a request
queue that packs compatible scenario submissions into shape-bucketed
batches, executes them through cached compiled ensembles, and streams
per-member results back with ledger spans and queue metrics.
"""

from heat3d_tpu.serve.scenario import Scenario, ScenarioBatch  # noqa: F401
from heat3d_tpu.serve.ensemble import EnsembleSolver  # noqa: F401
from heat3d_tpu.serve.queue import ScenarioQueue  # noqa: F401
