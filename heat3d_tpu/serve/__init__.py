"""Batched scenario engine: the ensemble axis + the solver-as-a-service
front-end (docs/SERVING.md).

Layer 1 — :mod:`heat3d_tpu.serve.scenario` / :mod:`heat3d_tpu.serve.ensemble`:
a ``ScenarioBatch`` (per-member initial condition, boundary value,
diffusivity/dt, step budget over one shared structural config) and an
``EnsembleSolver`` that threads a leading batch dimension through the
distributed step — one compiled SPMD program advances every member.

Layer 2 — :mod:`heat3d_tpu.serve.queue` / ``heat3d serve``: a request
queue that packs compatible scenario submissions into shape-bucketed
batches, executes them through cached compiled ensembles, and streams
per-member results back with ledger spans and queue metrics.

Layer 3 — :mod:`heat3d_tpu.serve.engine` / :mod:`heat3d_tpu.serve.aot`
/ ``heat3d serve --async``: the always-on posture — a continuously-
batching dispatcher/worker engine that accepts submissions while
batches are in flight, backed by an AOT executable cache that
eliminates the fresh-process trace+compile stall (docs/SERVING.md
"Async engine & cold start").
"""

from heat3d_tpu.serve.scenario import Scenario, ScenarioBatch  # noqa: F401
from heat3d_tpu.serve.ensemble import EnsembleSolver  # noqa: F401
from heat3d_tpu.serve.queue import ScenarioQueue  # noqa: F401


def __getattr__(name):
    # lazy: the engine pulls in threading machinery and serve/aot pulls
    # jax serialization — neither belongs on the import path of a caller
    # that only wants Scenario/ScenarioBatch
    if name == "AsyncServeEngine":
        from heat3d_tpu.serve.engine import AsyncServeEngine

        return AsyncServeEngine
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
