"""Always-on async serving engine (docs/SERVING.md "Async engine & cold
start").

The continuously-batching counterpart of :class:`~heat3d_tpu.serve.queue
.ScenarioQueue`: submissions are accepted WHILE batches execute — a
dispatcher loop packs pending requests into shape-bucketed batches and
hands them to per-bucket worker threads, each of which builds (and
AOT-warms, serve/aot.py) its bucket's compiled ensemble once and then
holds the device futures of one in-flight batch at a time. Results
deliver in submission order per request stream; per-bucket latency
stats, backpressure caps, and the drain-final ``serve_metrics_summary``
event are shared with the synchronous queue, so the PR 8 SLO layer
judges both front-ends identically.
"""

from heat3d_tpu.serve.engine.core import (  # noqa: F401
    AsyncServeEngine,
    DEFAULT_WORKERS,
    ENV_WORKERS,
)
