"""The continuously-batching async serving engine.

``ScenarioQueue`` (serve/queue.py) is submit-then-drain: ``drain()``
holds the caller while every batch executes, and nothing can be
submitted meanwhile — correct for certification, wrong for a service.
This engine is the always-on posture the ROADMAP's "millions of users"
axis needs (the GPU-aware-async-tasks paper's thesis: the scaling win is
overlapping dispatch with in-flight work):

- :meth:`AsyncServeEngine.submit` is thread-safe, returns immediately,
  and applies explicit ADMISSION CONTROL (docs/SERVING.md "Load,
  overload & soak"): the global ``HEAT3D_SERVE_QUEUE`` outstanding-
  request cap bounds engine memory, and a per-stream cap
  (``HEAT3D_SERVE_MAX_PER_STREAM``) bounds what any one stream/tenant
  may hold open — a rejected submission raises a typed
  :class:`~heat3d_tpu.serve.queue.Backpressure` carrying the per-stream
  occupancy and lands a ``serve_shed`` ledger event, so shed traffic is
  accounted, never silent. Packing interleaves streams round-robin
  within each bucket, so a flooding stream can neither wedge the queue
  against nor monopolize batch slots over a well-behaved one;
- a **dispatcher thread** packs whatever is pending into shape-bucketed
  chunks (the queue's own bucketing/padding helpers) and hands each to
  its bucket's worker the moment that worker is free — continuous
  batching: requests arriving while a batch flies ride the NEXT batch,
  not a global barrier;
- **per-bucket worker threads** own their bucket's compiled ensembles
  (AOT-warmed through serve/aot.py at first touch, so a fresh process
  with a warm store serves its first request with no trace+compile
  stall), execute one batch at a time, and block on the device futures
  (``gather`` / ``block_until_ready``) without stalling submission or
  other buckets. Total concurrent batches are capped by
  ``HEAT3D_SERVE_WORKERS`` execution slots — and the slot count SCALES
  with load: the dispatcher grows it toward ``max_workers`` when the
  pending backlog (sized in batches, weighted by the last measured
  batch-execute time) outruns the current slots, and shrinks back to
  the configured base when the queue drains, each move a
  ``worker_scale`` ledger event;
- **predictive AOT pre-warm**: the engine keeps a per-bucket arrival
  history; :meth:`AsyncServeEngine.prewarm_forecast` (the load
  generator calls it between arrivals) forecasts each hot bucket's
  near-term batch size and warms that executable on the bucket's own
  worker thread BEFORE traffic needs it (``aot_prewarm`` events —
  the soak's zero-``compile_stall``-after-warmup criterion);
- **delivery preserves submission order per request stream** (the
  ``stream`` tag at submit): within a stream, results yield strictly in
  submit order; across streams, a slow stream never blocks a fast one;
- a failed bucket (bad config, uncompilable route) fails ONLY its own
  requests — every other bucket's in-flight and future results still
  deliver, and the failures are surfaced explicitly
  (:attr:`AsyncServeEngine.failures`, and :meth:`drain` re-raises after
  streaming what landed — the queue's contract);
- **backend-loss failures are requeued, not failed** (serve-tier
  elastic degradation, docs/SERVING.md "Degraded-mode serving"):
  :func:`is_backend_loss` classifies a batch failure as device-runtime
  loss vs scenario error; a loss puts the chunk's requests back in the
  pending set IN ORDER, drops the worker's cached ensembles (the
  rebuild lands on whatever mesh now exists — the AOT store keys carry
  the device count, so a shrunken mesh is a clean stale→recompile, not
  a poisoned load), backs off through the shared
  :class:`~heat3d_tpu.resilience.retry.RetryPolicy` schedule, and
  opens the ``degraded`` window on :class:`ServeStats` (``degraded_s``
  in ``serve_metrics_summary`` — the budget the SLO layer's
  ``serve_degraded`` objective judges). Retries exhausted (or losses
  during shutdown run-down) fail the chunk exactly as before;
- :meth:`shutdown` is graceful: stop accepting, run down every
  dispatched batch, join the workers, close with ONE
  ``serve_metrics_summary`` event (the SLO layer's source, same shape
  as the queue's).

Ledger: ``serve_submit`` / ``serve_batch_start`` / ``serve_batch`` span /
``serve_result`` / ``serve_metrics_summary`` exactly as the queue emits
them, plus the engine's own ``serve_dispatch`` (dispatcher handed a
packed chunk to a worker) and ``serve_batch_ready`` (a batch's device
futures resolved — the dispatch→ready gap is the overlap window the
timeline shows) and the serve/aot.py events.
"""

from __future__ import annotations

import dataclasses
import queue as stdqueue
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from heat3d_tpu import obs
from heat3d_tpu.core.config import SolverConfig
from heat3d_tpu.serve.ensemble import EnsembleSolver
from heat3d_tpu.serve.queue import (
    DEFAULT_MAX_BATCH,
    DEFAULT_QUEUE_DEPTH,
    ENV_MAX_BATCH,
    ENV_QUEUE_DEPTH,
    Backpressure,
    ServeResult,
    ServeStats,
    _env_int,
    _padded_size,
    build_chunk_results,
    new_trace,
    pad_batch,
    run_packed_batch,
)
from heat3d_tpu.serve.scenario import (
    Scenario,
    request_bucket_key,
    solver_bucket_key,
)
from heat3d_tpu.utils.logging import get_logger

log = get_logger(__name__)

ENV_WORKERS = "HEAT3D_SERVE_WORKERS"
ENV_MAX_PER_STREAM = "HEAT3D_SERVE_MAX_PER_STREAM"
DEFAULT_WORKERS = 2
# worker-slot scaling: how far past the configured base the dispatcher
# may grow the execution slots, and the predicted-backlog-drain seconds
# above which the latency leg adds a slot beyond the pure depth need
DEFAULT_MAX_WORKERS_FACTOR = 4
SCALE_LATENCY_S = 2.0
# per-bucket arrival history (predictive prewarm): timestamps retained
ARRIVAL_HISTORY_CAP = 256

# Backend-loss requeue backoff (the ONE RetryPolicy implementation —
# resilience/retry.py): attempts-capped, no deadline — a service must
# bound retries per chunk, and the dispatcher owns global liveness.
DEFAULT_REQUEUE_POLICY_KW = dict(
    max_attempts=4, base_delay_s=0.5, multiplier=2.0, max_delay_s=10.0
)


def is_backend_loss(exc: BaseException) -> bool:
    """Device-runtime loss (requeue) vs scenario error (fail).

    Injected faults (:class:`~heat3d_tpu.resilience.faults.InjectedFault`)
    and jaxlib runtime errors (XlaRuntimeError and friends — the device
    runtime speaking, not the scenario) classify as loss; Python-level
    config/validation errors (ValueError, TypeError, ...) stay scenario
    errors and fail the chunk immediately — retrying a bad config
    forever would hide the bug behind backoff."""
    from heat3d_tpu.resilience.faults import InjectedFault

    if isinstance(exc, InjectedFault):
        return True
    mod = type(exc).__module__ or ""
    return mod.startswith("jaxlib")

# request lifecycle states
_PENDING = "pending"
_DISPATCHED = "dispatched"
_DONE = "done"
_FAILED = "failed"
_CANCELLED = "cancelled"


@dataclasses.dataclass
class _Tracked:
    request_id: int
    base: SolverConfig
    scenario: Scenario
    stream: str
    submitted_at: float
    state: str = _PENDING
    result: Optional[ServeResult] = None
    error: Optional[str] = None
    # backend-loss requeue count: the chunk fails for real once the
    # shared RetryPolicy's attempt cap is reached
    attempts: int = 0
    # per-request trace context (serve/queue.new_trace): the trace_id
    # survives requeues because the _Tracked object does
    trace: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class _Prewarm:
    """A predictive warm-up work item: build (or AOT-load) the bucket's
    executable for ``padded`` members on the bucket's OWN worker thread,
    before traffic needs it. ``done`` lets a warmup phase wait for the
    build without polling."""

    base: SolverConfig
    padded: int
    forecast_members: int
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )


class _BucketWorker(threading.Thread):
    """One bucket's executor: owns the bucket's solver cache (and its
    AOT warm-up) and runs one packed batch at a time off its own queue.
    ``None`` is the shutdown sentinel; a :class:`_Prewarm` item builds
    an executable without serving anything."""

    def __init__(self, engine: "AsyncServeEngine", bucket: str):
        super().__init__(name=f"heat3d-serve-{bucket[:24]}", daemon=True)
        self.engine = engine
        self.bucket = bucket
        self.q: "stdqueue.Queue[Any]" = stdqueue.Queue()
        self.solvers: Dict[Tuple, EnsembleSolver] = {}
        self.start()

    def run(self) -> None:
        while True:
            chunk = self.q.get()
            if chunk is None:
                return
            if isinstance(chunk, _Prewarm):
                self.engine._do_prewarm(self, chunk)
                continue
            # the global execution-slot cap (HEAT3D_SERVE_WORKERS): more
            # buckets than slots queue here rather than oversubscribing
            # the device
            with self.engine._slots:
                try:
                    self.engine._run_batch(self, chunk)
                except BaseException as e:  # noqa: BLE001 - a worker
                    # must never die silently: fail its chunk, keep
                    # serving later batches (a transient error must not
                    # wedge the bucket forever)
                    self.engine._fail_chunk(chunk, e)
            with self.engine._cond:
                self.engine._busy.discard(self.bucket)
                self.engine._cond.notify_all()

    def solver_for(self, batch, padded: int) -> EnsembleSolver:
        key = (batch.bucket_key(), padded, self.engine.batch_mesh)
        solver = self.solvers.get(key)
        if solver is None:
            solver = EnsembleSolver(
                batch, batch_mesh=self.engine.batch_mesh, bind="traced"
            )
            if self.engine._aot:
                from heat3d_tpu.serve import aot

                report = aot.warm(solver, self.engine._aot_dir)
                self.engine._note_aot(report)
            self.solvers[key] = solver
        else:
            # same structure, new member values: rebind coefficients;
            # the compiled (possibly AOT-loaded) programs are reused
            solver.batch = batch
            solver._build_coefficients()
        return solver


class AsyncServeEngine:
    """Submit scenarios from any thread; batches execute continuously.

    Usage::

        with AsyncServeEngine(batch_mesh=1) as eng:
            rid = eng.submit(base, Scenario(alpha=0.5), stream="tenant-a")
            ...                       # keep submitting — batches fly now
            for r in eng.results():   # per-stream submission order
                handle(r)
        # __exit__ -> shutdown(): graceful run-down + serve_metrics_summary

    ``before_execute`` is an instrumentation hook called in the worker
    thread immediately before a batch's device work ``(bucket,
    request_ids)`` — tests pin the submit-while-in-flight overlap with
    it; production leaves it None.
    """

    def __init__(
        self,
        max_batch: Optional[int] = None,
        max_depth: Optional[int] = None,
        batch_mesh: int = 1,
        workers: Optional[int] = None,
        max_per_stream: Optional[int] = None,
        max_workers: Optional[int] = None,
        snapshot_every: int = 0,
        with_residuals: bool = False,
        aot: Optional[bool] = None,
        aot_dir: Optional[str] = None,
        before_execute: Optional[Callable[[str, List[int]], None]] = None,
        autostart: bool = True,
        retry_policy=None,
        faults=None,
    ):
        self.max_batch = max_batch or _env_int(ENV_MAX_BATCH, DEFAULT_MAX_BATCH)
        self.max_depth = max_depth or _env_int(
            ENV_QUEUE_DEPTH, DEFAULT_QUEUE_DEPTH
        )
        self.batch_mesh = batch_mesh
        self.snapshot_every = snapshot_every
        self.with_residuals = with_residuals
        self.workers = workers or _env_int(ENV_WORKERS, DEFAULT_WORKERS)
        # per-stream admission cap: defaults to the global depth cap, so
        # a single-stream caller sees EXACTLY the old behavior; soak /
        # multi-tenant deployments set it lower to stop one stream from
        # consuming the whole queue
        self.max_per_stream = (
            max_per_stream
            or _env_int(ENV_MAX_PER_STREAM, 0)
            or self.max_depth
        )
        # worker-slot scaling bounds: the semaphore starts at the
        # configured base and the dispatcher moves it in
        # [base, max_workers] as backlog demands
        self.base_workers = self.workers
        self.max_workers = max_workers or (
            self.workers * DEFAULT_MAX_WORKERS_FACTOR
        )
        self.scale_latency_s = SCALE_LATENCY_S
        self._aot_dir = aot_dir
        # aot=None: enabled (serve/aot.py decides store-vs-measure-only
        # from HEAT3D_AOT_CACHE — an env-disabled store still warms with
        # the stall measured, just persists nothing). aot=False: raw jit
        # dispatch — the debugging escape where the first request pays a
        # hidden stall.
        self._aot = True if aot is None else bool(aot)
        self.before_execute = before_execute
        # backend-loss requeue: the shared RetryPolicy supplies the
        # attempt cap + backoff schedule (tests inject a millisecond
        # policy); the fault plan supplies the deterministic serve-tier
        # injection point (partial-device-loss:batch=N)
        from heat3d_tpu.resilience.faults import FaultPlan
        from heat3d_tpu.resilience.retry import RetryPolicy

        self._retry = retry_policy or RetryPolicy(
            **DEFAULT_REQUEUE_POLICY_KW
        )
        self._faults = faults if faults is not None else FaultPlan.from_env()
        self._batch_seq = 0

        self._cond = threading.Condition()
        self._req: Dict[int, _Tracked] = {}
        # open = everything the engine still holds memory for — pending,
        # in flight, AND completed-but-undelivered results (each of those
        # is a gathered full-grid field). Maintained incrementally (an
        # always-on service must not scan its request history per
        # submit), decremented only at delivery/failure/cancel, so the
        # HEAT3D_SERVE_QUEUE cap bounds engine memory even when the
        # results() consumer is slower than batch throughput.
        self._open = 0
        self._next_id = 0
        self._streams: Dict[str, List[int]] = {}
        # admission-control bookkeeping: per-stream open counts (the cap
        # the Backpressure error reports), shed totals, and the streams
        # whose serve_admission event already landed
        self._stream_open: Dict[str, int] = {}
        self._stream_shed: Dict[str, int] = {}
        self._shed = 0
        self._admission_noted: set = set()
        # predictive-prewarm state: per-bucket arrival timestamps (the
        # forecast input), a representative base config per bucket (to
        # build the dummy warm batch), and the (bucket, padded) sizes
        # already warm — whether by prewarm or by live traffic
        self._arrival_history: Dict[str, List[float]] = {}
        self._bucket_base: Dict[str, SolverConfig] = {}
        self._prewarmed: set = set()
        self._workers: Dict[str, _BucketWorker] = {}
        self._busy: set = set()
        self._slots = threading.Semaphore(self.workers)
        self._slot_count = self.workers
        self._scale_events = 0
        self._last_execute_s = 0.0
        self._stop = False
        self._joined = False
        self._stats = ServeStats()
        self.failures: List[Dict[str, Any]] = []
        self._unraised_failures: List[Dict[str, Any]] = []
        self._summary_dirty = False
        # overlap/in-flight accounting (stats() + the acceptance tests)
        self._in_flight = 0
        self._max_in_flight = 0
        self._accepted_in_flight = 0
        self._cancelled = 0
        self._aot_stats = {
            "hits": 0, "misses": 0, "stale": 0, "disabled": 0,
            "exports": 0, "stalls": 0,
            "compile_stall_s": 0.0, "load_s": 0.0,
        }
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="heat3d-serve-dispatch",
            daemon=True,
        )
        self._started = False
        # autostart=False defers dispatching until start() (or the first
        # results()/drain()/shutdown() call): a caller enqueueing an
        # initial burst gets one optimally-packed batch per bucket
        # instead of a timing-dependent split — which also makes the
        # batch composition (and therefore the AOT store's padded-size
        # keys) deterministic for a fixed request set.
        if autostart:
            self.start()

    def start(self) -> None:
        """Begin dispatching (idempotent; no-op after autostart)."""
        with self._cond:
            if self._started:
                return
            self._started = True
        self._dispatcher.start()

    # ---- context manager ---------------------------------------------------

    def __enter__(self) -> "AsyncServeEngine":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.shutdown(wait=exc_type is None)
        return False

    # ---- submission --------------------------------------------------------

    def __len__(self) -> int:
        with self._cond:
            return self._open

    def submit(
        self,
        base: SolverConfig,
        scenario: Scenario,
        stream: str = "",
    ) -> int:
        """Enqueue one scenario; returns the request id. Thread-safe and
        non-blocking: batches already in flight keep flying. Raises a
        typed :class:`~heat3d_tpu.serve.queue.Backpressure` (carrying
        per-stream occupancy, with a ``serve_shed`` ledger event) when
        the engine holds ``HEAT3D_SERVE_QUEUE`` requests (pending +
        in-flight + completed-but-undelivered — the cap bounds engine
        MEMORY, so a slow results() consumer backpressures submitters)
        or when this ``stream`` already holds
        ``HEAT3D_SERVE_MAX_PER_STREAM`` open requests — and a plain
        RuntimeError after :meth:`shutdown`."""
        if scenario.steps is None:
            # materialize the budget at SUBMIT time (the queue's rule):
            # budgets are traced inputs, not bucket structure, so a
            # default-budget scenario must not inherit another base's
            # step count at packing time
            scenario = dataclasses.replace(scenario, steps=base.run.num_steps)
        shed: Optional[Backpressure] = None
        shed_reason = ""
        first_on_stream = False
        with self._cond:
            if self._stop:
                raise RuntimeError(
                    "engine is shut down — no further submissions"
                )
            s_open = self._stream_open.get(stream, 0)
            if self._open >= self.max_depth:
                shed_reason = "depth"
                shed = Backpressure(
                    f"serve queue full ({self.max_depth} outstanding; "
                    f"{ENV_QUEUE_DEPTH} raises the cap) — wait for "
                    "deliveries before submitting more",
                    depth=self._open, max_depth=self.max_depth,
                    stream=stream, stream_depth=s_open,
                    stream_cap=self.max_per_stream,
                    per_stream=dict(self._stream_open),
                )
            elif s_open >= self.max_per_stream:
                shed_reason = "stream_cap"
                shed = Backpressure(
                    f"stream {stream or '(default)'} at its admission "
                    f"cap ({s_open} open; {ENV_MAX_PER_STREAM} raises "
                    "it) — other streams keep flowing",
                    depth=self._open, max_depth=self.max_depth,
                    stream=stream, stream_depth=s_open,
                    stream_cap=self.max_per_stream,
                    per_stream=dict(self._stream_open),
                )
            if shed is not None:
                # shed accounting: the rejection is explicit state, not
                # just an exception — admitted + shed == submitted is
                # the soak's conservation law
                self._shed += 1
                self._stream_shed[stream] = (
                    self._stream_shed.get(stream, 0) + 1
                )
            else:
                rid = self._next_id
                self._next_id += 1
                self._open += 1
                self._stream_open[stream] = s_open + 1
                first_on_stream = stream not in self._admission_noted
                self._admission_noted.add(stream)
                trace = new_trace()
                trace["stream"] = stream or None
                self._req[rid] = _Tracked(
                    request_id=rid,
                    base=base,
                    scenario=scenario,
                    stream=stream,
                    submitted_at=trace["t_submit"],
                    trace=trace,
                )
                self._streams.setdefault(stream, []).append(rid)
                bucket = str(request_bucket_key(base, scenario))
                self._bucket_base.setdefault(bucket, base)
                hist = self._arrival_history.setdefault(bucket, [])
                hist.append(time.monotonic())
                if len(hist) > ARRIVAL_HISTORY_CAP:
                    del hist[: len(hist) - ARRIVAL_HISTORY_CAP]
                if self._in_flight > 0:
                    # the overlap the engine exists for: this submission
                    # was accepted while a batch executed (test-pinned)
                    self._accepted_in_flight += 1
                depth = self._open
            self._cond.notify_all()
        if shed is not None:
            obs.get().event(
                "serve_shed",
                stream=stream or None,
                reason=shed_reason,
                depth=shed.depth,
                max_depth=shed.max_depth,
                stream_depth=shed.stream_depth,
                stream_cap=shed.stream_cap,
                per_stream={
                    (k or "(default)"): v for k, v in shed.per_stream.items()
                },
            )
            raise shed
        if first_on_stream:
            obs.get().event(
                "serve_admission",
                stream=stream or None,
                stream_cap=self.max_per_stream,
                max_depth=self.max_depth,
            )
        self._stats.observe_depth(depth)
        obs.get().event(
            "serve_submit",
            request_id=rid,
            trace_id=trace["id"],
            grid=list(base.grid.shape),
            stencil=base.stencil.kind,
            steps=scenario.steps,
            queue_depth=depth,
            stream=stream or None,
            in_flight=self._in_flight,
        )
        return rid

    def cancel(self, rid: int) -> bool:
        """Cancel a not-yet-dispatched request. True when cancelled;
        False when unknown, already dispatched (in flight — results are
        coming), or already resolved. Cancelled requests never deliver
        and never count as failures."""
        with self._cond:
            r = self._req.get(rid)
            if r is None or r.state != _PENDING:
                return False
            r.state = _CANCELLED
            self._cancelled += 1
            self._open -= 1
            self._release_stream(r.stream)
            self._cond.notify_all()
            return True

    def _release_stream(self, stream: str) -> None:
        """Under the lock: one open request on ``stream`` left the
        engine (delivered, failed, or cancelled) — free its admission
        slot."""
        n = self._stream_open.get(stream, 0) - 1
        if n > 0:
            self._stream_open[stream] = n
        else:
            self._stream_open.pop(stream, None)

    # ---- the dispatcher loop ----------------------------------------------

    def _undispatched(self) -> List[_Tracked]:
        return [r for r in self._req.values() if r.state == _PENDING]

    def _pack(self) -> List[Tuple[_BucketWorker, List[_Tracked]]]:
        """Under the lock: one chunk per idle-bucket. Within a bucket,
        streams share batch slots ROUND-ROBIN (each stream's own
        requests stay in submission order — delivery order needs that),
        so a flooding stream cannot monopolize a batch over a
        well-behaved one. A single stream degenerates to exactly the
        old take-the-first-``max_batch`` packing, which keeps batch
        composition — and the AOT store's padded-size keys —
        deterministic for the single-stream acceptance runs."""
        by_bucket: Dict[str, List[_Tracked]] = {}
        for r in self._undispatched():
            # request-level key: integrator/coef-field requests must
            # never pack with the plain sweep of the same base
            by_bucket.setdefault(
                str(request_bucket_key(r.base, r.scenario)), []
            ).append(r)
        out: List[Tuple[_BucketWorker, List[_Tracked]]] = []
        for bucket, reqs in by_bucket.items():
            if bucket in self._busy:
                # continuous batching: this bucket's worker is flying a
                # batch; everything pending for it packs the NEXT one
                continue
            worker = self._workers.get(bucket)
            if worker is None:
                worker = _BucketWorker(self, bucket)
                self._workers[bucket] = worker
            lanes: Dict[str, List[_Tracked]] = {}
            for r in reqs:  # reqs are in submission order already
                lanes.setdefault(r.stream, []).append(r)
            chunk: List[_Tracked] = []
            while len(chunk) < self.max_batch and lanes:
                for stream in list(lanes):
                    chunk.append(lanes[stream].pop(0))
                    if not lanes[stream]:
                        del lanes[stream]
                    if len(chunk) >= self.max_batch:
                        break
            t_pack = time.monotonic()
            for r in chunk:
                r.state = _DISPATCHED
                if r.trace is not None:
                    r.trace["packs"].append(t_pack)
            self._busy.add(bucket)
            out.append((worker, chunk))
        return out

    def _maybe_scale(self) -> Optional[Dict[str, Any]]:
        """Under the lock: move the execution-slot count toward what the
        backlog needs — grow toward ``max_workers`` when more batches
        are waiting than slots can fly (the latency leg adds one more
        when the predicted drain time, backlog-batches x the last
        measured execute time, exceeds ``scale_latency_s``), shrink back
        to the configured base once the queue is empty and nothing
        flies. Returns the ``worker_scale`` event payload (emitted by
        the caller OUTSIDE the lock) or None when the count stands."""
        backlog = sum(1 for r in self._req.values() if r.state == _PENDING)
        need = -(-backlog // self.max_batch) if backlog else 0  # ceil
        if (
            backlog
            and self._last_execute_s > 0
            and need * self._last_execute_s > self.scale_latency_s
        ):
            need += 1
        desired = min(self.max_workers, max(self.base_workers, need))
        if backlog == 0 and self._in_flight == 0:
            desired = self.base_workers
        elif desired < self._slot_count:
            # never shrink while loaded: reclaiming a slot can only block
            # on an acquire the backlog is about to need
            return None
        if desired == self._slot_count:
            return None
        before = self._slot_count
        if desired > self._slot_count:
            for _ in range(desired - self._slot_count):
                self._slots.release()
            self._slot_count = desired
        else:
            # reclaim only idle slots (non-blocking): a slot held by an
            # in-flight batch is returned by its worker and reclaimed on
            # a later pass
            while self._slot_count > desired and self._slots.acquire(
                blocking=False
            ):
                self._slot_count -= 1
            if self._slot_count == before:
                return None
        self._scale_events += 1
        return {
            "direction": "up" if self._slot_count > before else "down",
            "slots_from": before,
            "slots_to": self._slot_count,
            "backlog": backlog,
            "last_execute_s": round(self._last_execute_s, 6),
        }

    def _dispatch_loop(self) -> None:
        while True:
            with self._cond:
                while True:
                    scale = self._maybe_scale()
                    assignments = self._pack()
                    if assignments or scale:
                        break
                    if self._stop and not self._undispatched():
                        return
                    self._cond.wait()
            if scale:
                obs.get().event("worker_scale", **scale)
            for worker, chunk in assignments:
                obs.get().event(
                    "serve_dispatch",
                    bucket=worker.bucket,
                    members=len(chunk),
                    request_ids=[r.request_id for r in chunk],
                    in_flight=self._in_flight,
                )
                worker.q.put(chunk)

    # ---- batch execution (worker threads) ---------------------------------

    def _run_batch(self, worker: _BucketWorker, chunk: List[_Tracked]) -> None:
        with self._cond:
            self._in_flight += 1
            self._max_in_flight = max(self._max_in_flight, self._in_flight)
            batch_seq = self._batch_seq
            self._batch_seq += 1
        try:
            base = chunk[0].base
            members = [r.scenario for r in chunk]
            padded = _padded_size(
                len(members), self.max_batch, self.batch_mesh
            )
            batch = pad_batch(base, members, padded)
            solver = worker.solver_for(batch, padded)
            self._stats.observe_batch(len(chunk))
            bucket_s = str(batch.bucket_key())
            obs.get().event(
                "serve_batch_start",
                members=len(chunk),
                padded=padded,
                request_ids=[r.request_id for r in chunk],
                bucket=bucket_s,
                mesh=list(solver.cfg.mesh.shape),
                batch_mesh=solver.batch_mesh,
                time_blocking=solver.cfg.time_blocking,
            )
            budgets = np.asarray(
                [batch.member_steps(m) for m in range(len(batch))], np.int32
            )
            if self.before_execute is not None:
                self.before_execute(
                    bucket_s, [r.request_id for r in chunk]
                )
            # the serve-tier fault-injection point: a declared
            # partial-device-loss:batch=N fires here, lands in the
            # except below, classifies as backend loss, and requeues —
            # exactly the path a real mid-batch device loss takes
            self._faults.on_serve_batch(batch_seq)
            t_ex0 = time.monotonic()
            with obs.get().span(
                "serve_batch", members=len(chunk), padded=padded
            ) as span:
                fields, residuals, snapshots = run_packed_batch(
                    solver, budgets,
                    snapshot_every=self.snapshot_every,
                    with_residuals=self.with_residuals,
                )
                span.add(steps_total=int(budgets.sum()))
            # the device futures this worker held just resolved — the
            # dispatch->ready window is where submission overlapped
            obs.get().event(
                "serve_batch_ready",
                bucket=bucket_s,
                members=len(chunk),
                execute_s=round(span.dur_s or 0.0, 6),
                in_flight=self._in_flight,
            )
            with self._cond:
                # the scaling signal: how long the LAST batch took to
                # execute weights the backlog into a drain-time estimate
                self._last_execute_s = span.dur_s or 0.0
                # live traffic built this executable: the padded size is
                # warm now — prewarm must not rebuild it
                self._prewarmed.add((bucket_s, padded))
        except BaseException as e:  # noqa: BLE001 - fail THIS chunk only
            if self._maybe_requeue(worker, chunk, e):
                return
            self._fail_chunk(chunk, e)
            return
        finally:
            with self._cond:
                self._in_flight -= 1
        t_ex1 = time.monotonic()
        for r in chunk:
            if r.trace is not None:
                r.trace["exec"].append((t_ex0, t_ex1))
        results = build_chunk_results(
            [(r.request_id, r.submitted_at, r.trace) for r in chunk],
            bucket_s, budgets, fields, residuals, snapshots, self._stats,
        )
        # a REQUEUED chunk finally succeeding closes the degraded window
        # (cumulative seconds retained for the SLO budget). Other
        # buckets' healthy batches don't: while a lost chunk is still
        # backing off, the service IS degraded, and letting unaffected
        # traffic close the window would undercount the very budget the
        # serve_degraded objective meters.
        if any(r.attempts for r in chunk):
            self._stats.clear_degraded()
        with self._cond:
            for r, res in zip(chunk, results):
                r.result = res
                r.state = _DONE
            self._summary_dirty = True
            self._cond.notify_all()
        self._stats.observe_depth(len(self))

    def _maybe_requeue(
        self, worker: _BucketWorker, chunk: List[_Tracked], exc: BaseException
    ) -> bool:
        """Backend-loss triage for a failed batch: requeue the chunk with
        backoff (True) or let it fail (False — scenario errors, retries
        exhausted, or shutdown run-down, where retry-forever would hang
        the join)."""
        if not is_backend_loss(exc):
            return False
        attempt = max(r.attempts for r in chunk) + 1
        cap = self._retry.max_attempts or 1
        if attempt >= cap:
            log.warning(
                "serve batch lost its backend %d time(s); retries "
                "exhausted — failing the chunk", attempt,
            )
            return False
        delay = self._retry.delay_for(attempt)
        t_rq = time.monotonic()
        with self._cond:
            if self._stop:
                return False
            for r in chunk:
                r.state = _PENDING
                r.attempts = attempt
                if r.trace is not None:
                    r.trace["requeues"].append(
                        {"t": t_rq, "attempt": attempt, "backoff_s": delay}
                    )
        # rebuild, don't reuse: the cached ensembles hold programs
        # compiled for the pre-loss device set; dropping them makes the
        # next dispatch rebuild on whatever mesh NOW exists (the AOT
        # store keys carry the device count — stale→recompile, never a
        # wrong-mesh load)
        worker.solvers.clear()
        # attempt 1 = this chunk's first loss: it takes its own reference
        # on the degraded window (refcounted — another chunk recovering
        # must not stop the clock while this one still backs off)
        self._stats.mark_degraded(new=attempt == 1)
        obs.get().event(
            "serve_requeue",
            bucket=worker.bucket,
            request_ids=[r.request_id for r in chunk],
            attempt=attempt,
            backoff_s=round(delay, 6),
            error=f"{type(exc).__name__}: {str(exc)[:200]}",
        )
        log.warning(
            "serve batch backend loss (%s request(s), attempt %d): "
            "requeued with %.2fs backoff",
            len(chunk), attempt, delay,
        )
        # backoff INSIDE the worker thread, while the bucket is still
        # marked busy: the dispatcher cannot re-dispatch this bucket
        # until the worker frees it, so the sleep IS the backoff —
        # submission and other buckets keep flowing meanwhile
        if delay > 0:
            time.sleep(delay)
        with self._cond:
            self._cond.notify_all()
        return True

    def _fail_chunk(self, chunk: List[_Tracked], exc: BaseException) -> None:
        err = f"{type(exc).__name__}: {str(exc)[:300]}"
        log.warning("serve batch failed (%s request(s)): %s", len(chunk), err)
        if any(r.attempts for r in chunk):
            # a requeued chunk finally failing RESOLVES its degraded
            # window (seconds retained for the SLO budget): the requests
            # are failed, not pending — leaving the clock running would
            # count every healthy hour after this failure as degraded
            self._stats.clear_degraded()
        with self._cond:
            for r in chunk:
                if r.state in (_DONE, _FAILED):
                    continue
                r.state = _FAILED
                r.error = err
                self._open -= 1
                self._release_stream(r.stream)
                rec = {
                    "request_id": r.request_id,
                    "stream": r.stream,
                    "error": err,
                }
                self.failures.append(rec)
                self._unraised_failures.append(rec)
            self._summary_dirty = True
            self._cond.notify_all()

    def _note_aot(self, report: Dict[str, Any]) -> None:
        with self._cond:
            st = self._aot_stats
            outcome = report.get("outcome")
            if outcome == "hit":
                st["hits"] += 1
            elif outcome == "miss":
                st["misses"] += 1
            elif outcome == "stale":
                st["stale"] += 1
            elif outcome == "disabled":
                st["disabled"] += 1
            if report.get("exported"):
                st["exports"] += 1
            if report.get("compile_stall_s"):
                st["stalls"] += 1
                st["compile_stall_s"] += float(report["compile_stall_s"])
            if report.get("load_s"):
                st["load_s"] += float(report["load_s"])

    # ---- predictive AOT pre-warm -------------------------------------------

    def _do_prewarm(self, worker: _BucketWorker, item: _Prewarm) -> None:
        """In the worker thread: build (or AOT-load) the executable for
        ``item.padded`` members with a dummy member batch. The solver
        cache key is member-INDEPENDENT (bucket, padded, batch_mesh), so
        the first real request of that shape rebinds coefficients on the
        prewarmed programs instead of tracing. Fail-soft: a prewarm
        failure only costs the prediction — live traffic still builds on
        demand."""
        t0 = time.monotonic()
        try:
            dummy = [
                Scenario(steps=item.base.run.num_steps)
            ] * min(item.forecast_members, item.padded)
            batch = pad_batch(item.base, dummy, item.padded)
            worker.solver_for(batch, item.padded)
            obs.get().event(
                "aot_prewarm",
                bucket=worker.bucket,
                padded=item.padded,
                forecast_members=item.forecast_members,
                seconds=round(time.monotonic() - t0, 6),
            )
        except BaseException as e:  # noqa: BLE001 - prediction only
            log.warning(
                "prewarm failed for bucket %s padded=%d: %s",
                worker.bucket, item.padded, e,
            )
            with self._cond:
                self._prewarmed.discard((worker.bucket, item.padded))
        finally:
            item.done.set()

    def prewarm(
        self,
        base: SolverConfig,
        expected_members: int = 1,
        forecast: Optional[int] = None,
    ) -> Optional[threading.Event]:
        """Queue a warm-up of ``base``'s bucket for ``expected_members``
        (padded to the executable size traffic of that count would use)
        on the bucket's own worker. Returns an Event that sets when the
        build finishes, or None when that (bucket, padded) is already
        warm. Thread-safe; never blocks on the build itself."""
        bucket = str(solver_bucket_key(base))
        padded = _padded_size(
            max(1, expected_members), self.max_batch, self.batch_mesh
        )
        with self._cond:
            if self._stop:
                return None
            key = (bucket, padded)
            if key in self._prewarmed:
                return None
            self._prewarmed.add(key)
            worker = self._workers.get(bucket)
            if worker is None:
                worker = _BucketWorker(self, bucket)
                self._workers[bucket] = worker
        item = _Prewarm(
            base=base, padded=padded,
            forecast_members=forecast or expected_members,
        )
        worker.q.put(item)
        return item.done

    def prewarm_forecast(
        self,
        horizon_s: float = 5.0,
        window_s: float = 30.0,
        max_buckets: int = 4,
    ) -> List[threading.Event]:
        """Forecast each hot bucket's near-term batch size from its
        arrival history (arrivals in the trailing ``window_s``, scaled
        to ``horizon_s``) and queue prewarms for the executables that
        forecast implies. The load generator calls this between
        arrivals; each build emits ``aot_prewarm``. Returns the pending
        build Events (already-warm forecasts return nothing)."""
        now = time.monotonic()
        plans: List[Tuple[SolverConfig, int]] = []
        with self._cond:
            rates = []
            for bucket, hist in self._arrival_history.items():
                recent = [t for t in hist if now - t <= window_s]
                if not recent:
                    continue
                rates.append((len(recent), bucket))
            rates.sort(reverse=True)
            for n, bucket in rates[:max_buckets]:
                base = self._bucket_base.get(bucket)
                if base is None:
                    continue
                expect = max(1, int(n * horizon_s / window_s))
                plans.append((base, min(expect, self.max_batch)))
        events = []
        for base, expect in plans:
            ev = self.prewarm(base, expected_members=expect, forecast=expect)
            if ev is not None:
                events.append(ev)
        return events

    # ---- delivery ----------------------------------------------------------

    def _pop_next(self) -> Optional[ServeResult]:
        """Under the lock: the single NEXT deliverable result across
        streams (submission order within each stream; FAILED/CANCELLED
        requests are skipped — they surface via :attr:`failures` /
        :meth:`drain` and never block the stream behind them), pruning
        the consumed prefix as it goes. One at a time BY DESIGN: a
        result leaves the engine's bookkeeping only at the moment it is
        handed to the consumer, so an abandoned ``results()`` iterator
        cannot strand already-popped results — and the prune keeps an
        always-on engine from retaining every request it ever served
        (each _Tracked holds the scenario, possibly a full-grid init
        array; each DONE result a gathered field)."""
        for stream, rids in list(self._streams.items()):
            i = 0
            res: Optional[ServeResult] = None
            while i < len(rids):
                r = self._req[rids[i]]
                if r.state in (_FAILED, _CANCELLED):
                    i += 1
                    continue
                if r.state == _DONE:
                    res = r.result
                    self._open -= 1
                    self._release_stream(stream)
                    i += 1
                break
            if i:
                for rid in rids[:i]:
                    self._req.pop(rid, None)
                del rids[:i]
            if not rids:
                # a drained stream tag must not live forever: per-tenant
                # stream names would otherwise leak one entry each and
                # put every delivery at O(streams ever seen)
                del self._streams[stream]
            if res is not None:
                return res
        return None

    def _outstanding(self) -> bool:
        return any(
            r.state in (_PENDING, _DISPATCHED, _DONE)
            for r in self._req.values()
        )

    def results(self, timeout: Optional[float] = None) -> Iterator[ServeResult]:
        """Yield results as they become deliverable — submission order
        within each stream, streams interleaved by completion. Returns
        when nothing submitted remains undelivered (new submissions
        while iterating extend the iteration). ``timeout`` bounds the
        TOTAL wait; expiry raises ``TimeoutError``."""
        self.start()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cond:
                res = self._pop_next()
                if res is None:
                    if not self._outstanding():
                        return
                    left = (
                        None if deadline is None
                        else deadline - time.monotonic()
                    )
                    if left is not None and left <= 0:
                        raise TimeoutError(
                            f"serve results: {len(self)} request(s) still "
                            f"outstanding after {timeout}s"
                        )
                    self._cond.wait(left)
                    continue
            yield res

    def drain(self, timeout: Optional[float] = None) -> Iterator[ServeResult]:
        """The queue-compatible collector: wait for everything submitted,
        yield it (per-stream submission order), close with ONE
        ``serve_metrics_summary`` event, then — like
        ``ScenarioQueue.drain`` — re-raise if any bucket failed (after
        streaming everything that landed; the failed requests are listed
        in :attr:`failures`). Unlike the queue, submission stays open
        while draining: batches keep executing underneath."""
        yield from self.results(timeout=timeout)
        self._emit_summary()
        with self._cond:
            unraised, self._unraised_failures = self._unraised_failures, []
        if unraised:
            raise RuntimeError(
                f"{len(unraised)} request(s) failed "
                f"(first: request {unraised[0]['request_id']}: "
                f"{unraised[0]['error']}); delivered results already "
                "streamed — failed requests were NOT delivered"
            )

    # ---- summary / stats / shutdown ---------------------------------------

    def metrics_summary(self) -> Dict[str, Any]:
        """The live SLO source (``serve --async --slo``): same shape as
        ``ScenarioQueue.metrics_summary`` — the SLO layer cannot tell
        which front-end produced it."""
        return self._stats.summary(pending=len(self))

    def _emit_summary(self) -> None:
        with self._cond:
            if not self._summary_dirty:
                return
            self._summary_dirty = False
        obs.get().event("serve_metrics_summary", **self.metrics_summary())

    def stats(self) -> Dict[str, Any]:
        """Engine-side counters (the CLI verdict's payload): submission /
        delivery / failure totals, the in-flight high-water mark, how
        many submissions were accepted while batches flew (the overlap
        proof), and the AOT warm-up aggregate."""
        with self._cond:
            return {
                # submitted = every submit() ATTEMPT; admitted + shed ==
                # submitted is the soak verdict's conservation law
                "submitted": self._next_id + self._shed,
                "admitted": self._next_id,
                "shed": self._shed,
                "shed_by_stream": {
                    (k or "(default)"): v
                    for k, v in self._stream_shed.items()
                },
                "delivered": self._stats.delivered,
                "failed": len(self.failures),
                "cancelled": self._cancelled,
                "batches": self._stats.batches,
                "buckets": len(self._workers),
                "workers": self.workers,
                "slots": self._slot_count,
                "scale_events": self._scale_events,
                "prewarmed": len(self._prewarmed),
                "streams": {
                    (k or "(default)"): v
                    for k, v in self._stream_open.items()
                },
                "max_in_flight": self._max_in_flight,
                "accepted_in_flight": self._accepted_in_flight,
                "requeues": self._stats.requeues,
                "degraded_s": round(self._stats.degraded_seconds(), 6),
                "aot": dict(self._aot_stats),
            }

    def shutdown(self, wait: bool = True, cancel_pending: bool = False) -> None:
        """Graceful stop: refuse new submissions, run down everything
        dispatched (and, unless ``cancel_pending``, everything pending),
        join the workers, and close with the drain-final
        ``serve_metrics_summary`` if anything executed since the last
        one. Idempotent. ``wait=False`` abandons pending work (requests
        stay undelivered; in-flight device work still completes in the
        daemon workers but is not waited for)."""
        self.start()  # an unstarted engine still runs down its pending
        with self._cond:
            if self._joined:
                return
            self._stop = True
            if cancel_pending or not wait:
                for r in self._undispatched():
                    r.state = _CANCELLED
                    self._cancelled += 1
                    self._open -= 1
                    self._release_stream(r.stream)
            self._cond.notify_all()
        if wait:
            self._dispatcher.join()
            workers = list(self._workers.values())
            for w in workers:
                w.q.put(None)
            for w in workers:
                w.join()
            with self._cond:
                self._joined = True
        self._emit_summary()
