"""The request queue: scenario submissions -> shape-bucketed batches ->
streamed per-member results.

The serving posture (ROADMAP "solver-as-a-service"): many small
independent requests amortize ONE compiled program per shape bucket
instead of paying a compile each. ``submit()`` enqueues a scenario;
``drain()`` packs compatible pending requests (same
:func:`~heat3d_tpu.serve.scenario.solver_bucket_key`) into batches,
pads each batch up to a power-of-two member count (so the compiled-
program cache is hit by ANY request count, not just repeats of one), and
executes them through cached :class:`~heat3d_tpu.serve.ensemble
.EnsembleSolver` instances. Results stream back in SUBMISSION order.

Observability: every submission lands a ``serve_submit`` ledger event,
every executed batch a ``serve_batch_start`` point + a ``serve_batch``
span, every delivered result a ``serve_result`` event with the
request's queue latency; the metrics registry carries queue depth,
batch-size and per-request latency histograms (bucket-labelled). Every
``drain()`` additionally closes with ONE ``serve_metrics_summary``
event — per-bucket latency p50/p95/max plus the depth high-water mark —
so post-hoc SLO evaluation (``heat3d obs slo``; obs/perf/slo.py) works
from the ledger alone, never the live registry. Knobs:
``HEAT3D_SERVE_QUEUE`` caps the pending depth (submit raises when
full), ``HEAT3D_SERVE_MAX_BATCH`` caps members per packed batch.
"""

from __future__ import annotations

import dataclasses
import os
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from heat3d_tpu import obs
from heat3d_tpu.core.config import SolverConfig
from heat3d_tpu.obs.metrics import HISTOGRAM_SAMPLE_CAP
from heat3d_tpu.serve.ensemble import EnsembleSolver
from heat3d_tpu.serve.scenario import (
    Scenario,
    ScenarioBatch,
    request_bucket_key,
    solver_bucket_key,
)
from heat3d_tpu.utils.logging import get_logger

log = get_logger(__name__)

ENV_QUEUE_DEPTH = "HEAT3D_SERVE_QUEUE"
ENV_MAX_BATCH = "HEAT3D_SERVE_MAX_BATCH"
DEFAULT_QUEUE_DEPTH = 1024
DEFAULT_MAX_BATCH = 64


class Backpressure(RuntimeError):
    """Typed admission-control rejection (docs/SERVING.md "Load,
    overload & soak"): submit() refused a request, and the exception
    carries the occupancy state a caller needs to ACT — back off, shed
    to another replica, retry after deliveries — instead of parsing a
    message. Subclasses RuntimeError so pre-existing submit() error
    handling keeps working unchanged.

    ``depth``/``max_depth`` are the global occupancy and cap at the
    rejection; ``stream``/``stream_depth``/``stream_cap`` identify a
    PER-STREAM rejection (``stream_cap`` is None when the global depth
    cap rejected); ``per_stream`` maps every live stream tag to the
    requests it still holds open — the whole point: the caller can see
    WHO is occupying the queue, not just that it is full.
    """

    def __init__(
        self,
        message: str,
        *,
        depth: int,
        max_depth: int,
        stream: Optional[str] = None,
        stream_depth: Optional[int] = None,
        stream_cap: Optional[int] = None,
        per_stream: Optional[Dict[str, int]] = None,
    ):
        super().__init__(message)
        self.depth = depth
        self.max_depth = max_depth
        self.stream = stream
        self.stream_depth = stream_depth
        self.stream_cap = stream_cap
        self.per_stream = dict(per_stream or {})


def _env_int(name: str, default: int) -> int:
    try:
        v = int(os.environ.get(name, ""))
        return v if v > 0 else default
    except ValueError:
        return default


def _pad_pow2(n: int, cap: int) -> int:
    """The bucketed batch size: the next power of two >= n, capped. One
    compiled program per (shape bucket, padded size) then serves every
    request count up to the cap."""
    p = 1
    while p < n:
        p *= 2
    return min(p, cap)


def _padded_size(n: int, cap: int, batch_mesh: int) -> int:
    """The executed batch size for ``n`` live members: pow2-bucketed,
    then rounded up to a multiple of ``batch_mesh`` — the ensemble
    shards members across the batch axis, so a padded size the mesh
    cannot divide would fail EVERY drain of that chunk (the cap may be
    exceeded by the rounding; padding members cost 0 steps)."""
    padded = _pad_pow2(n, cap)
    if padded % batch_mesh:
        padded = -(-padded // batch_mesh) * batch_mesh
    return padded


@dataclasses.dataclass
class ServeResult:
    """One request's streamed result."""

    request_id: int
    field: np.ndarray  # final (nx, ny, nz) member field
    steps: int
    residual_sumsq: Optional[float]
    batch_size: int  # members packed in the executing batch (pre-pad)
    queue_latency_s: float  # submit -> result delivery
    snapshots: Optional[List[np.ndarray]] = None  # per snapshot_every chunk


@dataclasses.dataclass
class _Pending:
    request_id: int
    base: SolverConfig
    scenario: Scenario
    submitted_at: float
    trace: Optional[Dict[str, Any]] = None


def new_trace() -> Dict[str, Any]:
    """Per-request trace context, minted at ``submit()`` (both
    front-ends) and carried on the request through pack, dispatch,
    execution, requeue, and delivery. Milestones are ``time.monotonic``
    (immune to wall steps; one process, so comparable):

    - ``t_submit`` — admission;
    - ``packs`` — each time the request left the queue into a chunk
      (one entry per attempt);
    - ``exec`` — each successful device-execution window ``(t0, t1)``;
    - ``requeues`` — each backend-loss requeue ``{t, attempt,
      backoff_s}``.

    At delivery :func:`build_chunk_results` folds the milestones into
    causally-linked ``serve_span`` ledger events (the queue / pack /
    compute / deliver decomposition ``heat3d obs trace`` prints and the
    timeline's waterfall track renders)."""
    return {
        "id": uuid.uuid4().hex[:12],
        "t_submit": time.monotonic(),
        "packs": [],
        "exec": [],
        "requeues": [],
    }


def _emit_trace_spans(
    trace: Dict[str, Any],
    rid: int,
    bucket: str,
    stream: Optional[str],
    now_mono: float,
) -> None:
    """One request's ``serve_span`` events, written at delivery. These
    are POINT events carrying explicit wall-clock ``t0_wall``/``t1_wall``
    bounds — per-request phases from concurrent bucket workers interleave
    freely, which the ledger's span-nesting lint (correctly) rejects for
    real ``kind=span`` records, so the waterfall gets its own field
    contract instead."""
    # one wall/monotonic offset for the whole request so phases butt
    # exactly (each t_wall = t_mono + offset with the same offset)
    off = time.time() - time.monotonic()

    def phase(name, m0, m1, parent="request", **extra):
        obs.get().event(
            "serve_span",
            trace_id=trace["id"],
            request_id=rid,
            span=name,
            parent=parent,
            bucket=bucket,
            stream=stream,
            t0_wall=round(m0 + off, 6),
            t1_wall=round(m1 + off, 6),
            span_dur_s=round(max(m1 - m0, 0.0), 6),
            **extra,
        )

    t_sub = trace["t_submit"]
    packs = trace["packs"]
    execs = trace["exec"]
    requeues = trace["requeues"]
    phase(
        "request", t_sub, now_mono, parent=None,
        attempts=len(requeues) + 1,
    )
    first_pack = packs[0] if packs else now_mono
    phase("queue", t_sub, first_pack)
    if execs:
        t_ex0, t_ex1 = execs[-1]
        last_pack = packs[-1] if packs else t_ex0
        phase("pack", last_pack, t_ex0)
        phase("compute", t_ex0, t_ex1)
        phase("deliver", t_ex1, now_mono)
    for rq in requeues:
        # the gap a backend loss cost this request: requeue -> the next
        # time it left the queue (or delivery, if it never re-packed)
        t_rq = rq["t"]
        t_next = next((t for t in packs if t > t_rq), now_mono)
        phase(
            "requeue_gap", t_rq, t_next,
            attempt=rq.get("attempt"),
            backoff_s=rq.get("backoff_s"),
        )


def pad_batch(
    base: SolverConfig, members: List[Scenario], padded: int
) -> ScenarioBatch:
    """The executed :class:`ScenarioBatch`: ``members`` plus 0-step dummy
    copies of the first member up to ``padded`` (masked out after the
    first bound computation, never delivered). Shared by the synchronous
    queue and the async engine so both execute the identical program."""
    fill = padded - len(members)
    if fill > 0:
        members = members + [
            dataclasses.replace(members[0], steps=0) for _ in range(fill)
        ]
    return ScenarioBatch(base, members)


def run_packed_batch(
    solver: EnsembleSolver,
    budgets: np.ndarray,
    snapshot_every: int = 0,
    with_residuals: bool = False,
):
    """One packed batch's device work — init, (chunked) run, gather,
    optional residual probe — returning ``(fields, residuals,
    snapshots)``. This is THE execution body both the synchronous
    queue and the async engine (serve/engine) drive: byte-identical
    results between the two are a consequence of sharing it, not a
    test-maintained coincidence."""
    u = solver.init_state()
    snapshots: Optional[List[np.ndarray]] = None
    if snapshot_every > 0:
        snapshots = []
        done = np.zeros_like(budgets)
        while (done < budgets).any():
            stride = np.minimum(budgets - done, snapshot_every).astype(
                np.int32
            )
            u = solver.run(u, stride)
            done = done + stride
            snapshots.append(solver.gather(u))
    else:
        u = solver.run(u, budgets)
    # the last snapshot already gathered the final state — don't pay a
    # second full-batch device-to-host transfer for it
    fields = snapshots[-1] if snapshots else solver.gather(u)
    residuals = None
    if with_residuals:
        # the residual costs one probe update per member — a health
        # signal measured FROM the delivered state. Fields are gathered
        # first (the probe donates u), so delivered results stay at
        # exactly the budgeted step either way.
        u, r2 = solver.step_with_member_residuals(u)
        residuals = np.asarray(r2)
    return fields, residuals, snapshots


def build_chunk_results(
    requests: List[Tuple],
    bucket: str,
    budgets: np.ndarray,
    fields,
    residuals,
    snapshots,
    stats: "ServeStats",
    stream: Optional[str] = None,
) -> List[ServeResult]:
    """``(request_id, submitted_at[, trace])`` tuples → delivered
    :class:`ServeResult`s: the per-request latency observation,
    ``serve_result`` ledger event, the request's ``serve_span`` trace
    decomposition (when a trace context rode along), and result assembly
    (snapshot slicing, residual conversion). Shared by the synchronous
    queue and the async engine for the same reason as
    :func:`run_packed_batch` — the delivered payload cannot diverge
    between front-ends if there is only one assembler."""
    out: List[ServeResult] = []
    now = time.monotonic()
    for i, req in enumerate(requests):
        rid, submitted_at = req[0], req[1]
        trace = req[2] if len(req) > 2 else None
        latency = now - submitted_at
        stats.observe_result(bucket, latency)
        obs.get().event(
            "serve_result",
            request_id=rid,
            steps=int(budgets[i]),
            batch_members=len(requests),
            queue_latency_s=round(latency, 6),
            bucket=bucket,
            trace_id=trace["id"] if trace else None,
        )
        if trace is not None:
            _emit_trace_spans(
                trace, rid, bucket, trace.get("stream") or stream, now
            )
        out.append(
            ServeResult(
                request_id=rid,
                field=fields[i],
                steps=int(budgets[i]),
                residual_sumsq=(
                    float(residuals[i]) if residuals is not None else None
                ),
                batch_size=len(requests),
                queue_latency_s=latency,
                snapshots=(
                    [s[i] for s in snapshots]
                    if snapshots is not None
                    else None
                ),
            )
        )
    return out


class ServeStats:
    """Cumulative serve-health tracking shared by the synchronous queue
    and the async engine: per-bucket queue-latency reservoirs (bounded by
    the metrics layer's ``HISTOGRAM_SAMPLE_CAP`` — count/max stay exact
    past it, percentiles note ``clipped``), the pending-depth high-water
    mark, batch/delivery counters, and the live metrics-registry mirrors
    (queue-depth gauge, latency/batch-size histograms). Thread-safe: the
    engine's bucket workers observe concurrently."""

    def __init__(self) -> None:
        import threading

        self._lock = threading.Lock()
        self._bucket_stats: Dict[str, Dict] = {}
        self.depth_max = 0
        self.batches = 0
        self.delivered = 0
        # degraded-mode accounting (serve-tier elastic degradation,
        # docs/SERVING.md "Degraded-mode serving"): a backend-loss
        # requeue opens a degraded window, the next successful batch
        # closes it; cumulative seconds + the live flag ride the
        # serve_metrics_summary the SLO layer judges (`serve_degraded`
        # objective: degraded-seconds budget)
        self.requeues = 0
        # refcounted: each DISTINCT degraded chunk holds one reference;
        # the window closes only when the last one resolves — chunk A's
        # quick recovery must not stop the clock while chunk B is still
        # backing off
        self._degraded_open = 0
        self._degraded_since: Optional[float] = None
        self._degraded_s_total = 0.0
        self._depth_gauge = obs.REGISTRY.gauge(
            "serve_queue_depth", "pending scenario requests"
        )
        self._latency_hist = obs.REGISTRY.histogram(
            "serve_request_latency_seconds",
            "submit -> result delivery per request",
        )
        self._batch_hist = obs.REGISTRY.histogram(
            "serve_batch_members", "members packed per executed batch"
        )

    def observe_depth(self, depth: int) -> None:
        self._depth_gauge.set(depth)
        with self._lock:
            self.depth_max = max(self.depth_max, depth)

    def observe_batch(self, members: int) -> None:
        self._batch_hist.observe(members)
        with self._lock:
            self.batches += 1

    def mark_degraded(self, new: bool = True) -> None:
        """A backend-loss requeue happened: count it, and — when this is
        the chunk's FIRST requeue (``new``) — take one reference on the
        degraded window (a chunk re-requeued on a later attempt already
        holds its reference)."""
        with self._lock:
            self.requeues += 1
            if new:
                self._degraded_open += 1
            if self._degraded_since is None:
                self._degraded_since = time.monotonic()
        obs.REGISTRY.counter(
            "serve_requeues_total", "backend-loss batch requeues"
        ).inc()

    def clear_degraded(self) -> None:
        """Drop one degraded-chunk reference — the engine calls this when
        a REQUEUED chunk resolves (success or final failure). The window
        closes (cumulative seconds retained for the SLO budget) only when
        the LAST open chunk resolves."""
        with self._lock:
            if self._degraded_open > 0:
                self._degraded_open -= 1
            if self._degraded_open == 0 and self._degraded_since is not None:
                self._degraded_s_total += (
                    time.monotonic() - self._degraded_since
                )
                self._degraded_since = None

    def degraded_seconds(self) -> float:
        with self._lock:
            live = (
                0.0
                if self._degraded_since is None
                else time.monotonic() - self._degraded_since
            )
            return self._degraded_s_total + live

    def observe_result(self, bucket: str, latency_s: float) -> None:
        # bucket-labelled: the SLO layer judges latency PER BUCKET (a
        # big-grid bucket legitimately runs slower than a small one)
        self._latency_hist.observe(latency_s, bucket=bucket)
        with self._lock:
            st = self._bucket_stats.setdefault(
                bucket,
                {"count": 0, "max": 0.0, "samples": [], "clipped": False},
            )
            st["count"] += 1
            st["max"] = max(st["max"], latency_s)
            if len(st["samples"]) < HISTOGRAM_SAMPLE_CAP:
                st["samples"].append(latency_s)
            else:
                st["clipped"] = True
            self.delivered += 1

    def summary(self, pending: int) -> Dict[str, object]:
        """The ``serve_metrics_summary`` payload: per-bucket latency
        count/p50/p95/max, depth high-water mark, batch/delivery
        counters — the dict the SLO layer evaluates (obs/perf/slo.py),
        identical in shape whichever front-end produced it."""
        from heat3d_tpu.obs.metrics import percentile

        with self._lock:
            buckets = {}
            for bucket, st in sorted(self._bucket_stats.items()):
                rec = {
                    "count": st["count"],
                    "p50_s": round(percentile(st["samples"], 50), 6),
                    "p95_s": round(percentile(st["samples"], 95), 6),
                    "max_s": round(st["max"], 6),
                }
                if st["clipped"]:
                    # percentiles cover the stored reservoir only, never
                    # to be mistaken for exact (count/max stay exact)
                    rec["clipped"] = True
                buckets[bucket] = rec
            live_degraded = self._degraded_since is not None
            degraded_s = self._degraded_s_total + (
                0.0
                if self._degraded_since is None
                else time.monotonic() - self._degraded_since
            )
            return {
                "buckets": buckets,
                "depth_max": self.depth_max,
                "batches": self.batches,
                "delivered": self.delivered,
                "pending": pending,
                # degraded-mode serving provenance: ALWAYS present (0.0
                # on a healthy drain) so the SLO serve_degraded
                # objective reads a value, never no_data, from any
                # summary this code produced
                "degraded": live_degraded,
                "degraded_s": round(degraded_s, 6),
                "requeues": self.requeues,
            }


class ScenarioQueue:
    """Submit scenarios, drain shape-bucketed batches, stream results.

    Single-controller, synchronous: ``drain()`` (or ``serve_pending()``)
    executes everything pending and yields results. The compiled-program
    amortization lives in ``self._solvers`` — an :class:`EnsembleSolver`
    (traced binding: coefficients are runtime inputs) per
    (bucket key, padded batch size), reused across drains.
    """

    def __init__(
        self,
        max_batch: Optional[int] = None,
        max_depth: Optional[int] = None,
        batch_mesh: int = 1,
        snapshot_every: int = 0,
        with_residuals: bool = False,
    ):
        self.max_batch = max_batch or _env_int(ENV_MAX_BATCH, DEFAULT_MAX_BATCH)
        self.max_depth = max_depth or _env_int(
            ENV_QUEUE_DEPTH, DEFAULT_QUEUE_DEPTH
        )
        self.batch_mesh = batch_mesh
        self.snapshot_every = snapshot_every
        self.with_residuals = with_residuals
        self._pending: "OrderedDict[int, _Pending]" = OrderedDict()
        self._next_id = 0
        self._solvers: Dict[Tuple, EnsembleSolver] = {}
        # cumulative per-bucket latency stats + queue-depth high-water
        # mark: the drain-final serve_metrics_summary event reports these
        # so post-hoc SLO evaluation (obs/perf/slo.py) never needs the
        # live registry (ServeStats — shared with the async engine so the
        # SLO layer judges both front-ends from one summary shape).
        self._stats = ServeStats()

    # ---- submission -------------------------------------------------------

    def __len__(self) -> int:
        return len(self._pending)

    @property
    def _bucket_stats(self) -> Dict[str, Dict]:
        # introspection view of the shared stats (tests assert the
        # reservoir bound here)
        return self._stats._bucket_stats

    def submit(self, base: SolverConfig, scenario: Scenario) -> int:
        """Enqueue one scenario over structural config ``base``; returns
        the request id results are keyed by. Raises :class:`Backpressure`
        (a RuntimeError carrying the occupancy) when the queue is at
        ``HEAT3D_SERVE_QUEUE`` depth — backpressure must be explicit AND
        actionable; a silently unbounded queue is how a service dies,
        and a bare depth error gives the caller nothing to act on. The
        rejection also lands a ``serve_shed`` ledger event so shed
        traffic is accounted, never invisible."""
        if len(self._pending) >= self.max_depth:
            obs.get().event(
                "serve_shed",
                stream=None,
                reason="depth",
                depth=len(self._pending),
                max_depth=self.max_depth,
            )
            raise Backpressure(
                f"serve queue full ({self.max_depth} pending; "
                f"{ENV_QUEUE_DEPTH} raises the cap) — drain before "
                "submitting more",
                depth=len(self._pending),
                max_depth=self.max_depth,
                per_stream={"": len(self._pending)},
            )
        if scenario.steps is None:
            # materialize the budget NOW: num_steps is not part of the
            # structural bucket key (budgets are traced inputs), so a
            # default-budget scenario packed with requests from another
            # base must not silently inherit that base's step count
            scenario = dataclasses.replace(
                scenario, steps=base.run.num_steps
            )
        rid = self._next_id
        self._next_id += 1
        trace = new_trace()
        self._pending[rid] = _Pending(
            request_id=rid,
            base=base,
            scenario=scenario,
            submitted_at=trace["t_submit"],
            trace=trace,
        )
        self._stats.observe_depth(len(self._pending))
        obs.get().event(
            "serve_submit",
            request_id=rid,
            trace_id=trace["id"],
            grid=list(base.grid.shape),
            stencil=base.stencil.kind,
            steps=scenario.steps,  # materialized above — never None here
            queue_depth=len(self._pending),
        )
        return rid

    # ---- batching ---------------------------------------------------------

    def _buckets(self) -> "OrderedDict[Tuple, List[_Pending]]":
        out: "OrderedDict[Tuple, List[_Pending]]" = OrderedDict()
        for p in self._pending.values():
            # the request-level key: a scenario stating its own
            # integrator (or carrying a coefficient field) must never
            # share a batch with the base's plain explicit sweep
            out.setdefault(request_bucket_key(p.base, p.scenario), []).append(p)
        return out

    def _solver_for(
        self, batch: ScenarioBatch, padded: int
    ) -> EnsembleSolver:
        key = (batch.bucket_key(), padded, self.batch_mesh)
        solver = self._solvers.get(key)
        if solver is None:
            solver = EnsembleSolver(
                batch, batch_mesh=self.batch_mesh, bind="traced"
            )
            self._solvers[key] = solver
        else:
            # same structure, new member values: rebind the coefficient
            # arrays; the compiled programs (keyed on shapes only — the
            # traced binding's whole point) are reused as-is
            solver.batch = batch
            solver._build_coefficients()
        return solver

    # ---- execution --------------------------------------------------------

    def drain(self) -> Iterator[ServeResult]:
        """Execute everything pending, yielding results in SUBMISSION
        order (requests are only delivered once every batch of this drain
        has executed — ordering beats latency at this layer; callers that
        want per-batch streaming use :meth:`serve_batches`)."""
        results: Dict[int, ServeResult] = {}
        order = list(self._pending.keys())
        err: Optional[BaseException] = None
        try:
            for batch_results in self.serve_batches():
                for r in batch_results:
                    results[r.request_id] = r
        except Exception as e:  # noqa: BLE001 - deliver, then surface
            # one bucket failing (e.g. its config can't build) must not
            # destroy the batches that already executed: stream what
            # landed, then re-raise. The failed bucket's requests are
            # still pending (they pop only on successful execution), so
            # a caller can fix the config and drain again.
            err = e
        for rid in order:
            if rid in results:
                yield results[rid]
        # drain-final summary (even on a partial drain — the batches that
        # executed are real): per-bucket p50/p95/max queue latency and the
        # depth high-water mark, as one ledger event, so SLO evaluation
        # works from the ledger alone (docs/SERVING.md "SLOs")
        obs.get().event("serve_metrics_summary", **self.metrics_summary())
        if err is not None:
            raise err

    def metrics_summary(self) -> Dict[str, object]:
        """Cumulative serve health over this queue's lifetime: per-bucket
        queue-latency count/p50/p95/max, the pending-depth high-water
        mark, and batch/delivery counters — the dict the drain-final
        ``serve_metrics_summary`` ledger event carries and ``heat3d serve
        --slo`` evaluates live (obs/perf/slo.py)."""
        return self._stats.summary(pending=len(self._pending))

    def serve_batches(self) -> Iterator[List[ServeResult]]:
        """Pack and execute pending requests bucket by bucket, yielding
        each executed batch's results as they land."""
        for bucket_key_, group in self._buckets().items():
            while group:
                chunk = group[: self.max_batch]
                group = group[len(chunk):]
                yield self._execute(chunk)

    def _execute(self, chunk: List[_Pending]) -> List[ServeResult]:
        base = chunk[0].base
        t_pack = time.monotonic()
        for p in chunk:
            if p.trace is not None:
                p.trace["packs"].append(t_pack)
        members = [p.scenario for p in chunk]
        padded = _padded_size(len(members), self.max_batch, self.batch_mesh)
        batch = pad_batch(base, members, padded)
        solver = self._solver_for(batch, padded)
        self._stats.observe_batch(len(chunk))
        bucket_s = str(batch.bucket_key())
        obs.get().event(
            "serve_batch_start",
            members=len(chunk),
            padded=padded,
            request_ids=[p.request_id for p in chunk],
            bucket=bucket_s,
            mesh=list(solver.cfg.mesh.shape),
            batch_mesh=solver.batch_mesh,
            time_blocking=solver.cfg.time_blocking,
        )
        budgets = np.asarray(
            [batch.member_steps(m) for m in range(len(batch))], np.int32
        )
        t_ex0 = time.monotonic()
        with obs.get().span(
            "serve_batch", members=len(chunk), padded=padded
        ) as span:
            fields, residuals, snapshots = run_packed_batch(
                solver, budgets,
                snapshot_every=self.snapshot_every,
                with_residuals=self.with_residuals,
            )
            span.add(steps_total=int(budgets.sum()))
        t_ex1 = time.monotonic()

        for p in chunk:
            self._pending.pop(p.request_id, None)
            if p.trace is not None:
                p.trace["exec"].append((t_ex0, t_ex1))
        out = build_chunk_results(
            [(p.request_id, p.submitted_at, p.trace) for p in chunk],
            bucket_s, budgets, fields, residuals, snapshots, self._stats,
        )
        self._stats.observe_depth(len(self._pending))
        return out
