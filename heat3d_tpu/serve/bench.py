"""Ensemble throughput measurement — the batched counterpart of
``bench.harness.bench_throughput``, sharing its provenance discipline.

The row is a normal ``bench: throughput`` record (same ledger mirror,
same ``check_provenance.py`` contract) whose ``batch_shape`` /
``members_per_step`` fields carry the ensemble workload: ``gcell_per_sec``
counts EVERY member's cell updates, so the per-member effective rate is
``gcell_per_sec / members_per_step`` — ``heat3d obs summary`` and
``obs regress`` report that split so an ensemble win can never masquerade
as (or hide) a single-run regression.
"""

from __future__ import annotations

import time
from typing import Dict

import jax
import jax.numpy as jnp

from heat3d_tpu import obs
from heat3d_tpu.parallel.plan import effective_halo_plan
from heat3d_tpu.serve.ensemble import EnsembleSolver
from heat3d_tpu.serve.scenario import ScenarioBatch
from heat3d_tpu.utils.timing import (
    calibrate_trip_count,
    force_sync,
    honest_time,
    sync_overhead,
)


def bench_ensemble_throughput(
    batch: ScenarioBatch,
    steps: int = 50,
    warmup: int = 2,
    repeats: int = 3,
    batch_mesh: int = 1,
) -> Dict:
    """Gcell-updates/sec of the compiled ensemble loop (ALL members'
    updates counted; per-member effective rate = total / members). Same
    methodology as the solo bench: device-side loop, RTT-honest timing,
    best-of-repeats, auto-calibrated step count."""
    from heat3d_tpu.bench.harness import (
        _chain_ops,
        _ledger_bench_row,
        _utc_now,
    )
    from heat3d_tpu.parallel.step import redundant_flops_frac

    solver = EnsembleSolver(batch, batch_mesh=batch_mesh, bind="traced")
    cfg = solver.cfg
    B = solver.B
    u = solver.init_state()

    for _ in range(warmup):
        u = solver.run(u, steps)
        force_sync(u)
    rtt = sync_overhead(probe=jnp.zeros((8, 128)))

    def _timed(n):
        nonlocal u
        t0 = time.perf_counter()
        u = solver.run(u, int(n))
        force_sync(u)
        return time.perf_counter() - t0

    steps_requested = steps
    steps, raw = calibrate_trip_count(_timed, rtt, start=steps)
    raw_times = [raw] + [_timed(steps) for _ in range(repeats - 1)]
    times = [honest_time(t, rtt) for t in raw_times]
    best = min(times)
    rtt_dominated = min(raw_times) < 2 * rtt
    updates = B * cfg.grid.num_cells * steps
    gcells = updates / best / 1e9
    n_dev = solver.batch_mesh * cfg.mesh.num_devices
    row = {
        "bench": "throughput",
        "ts": _utc_now(),
        "platform": jax.default_backend(),
        "grid": list(cfg.grid.shape),
        "stencil": cfg.stencil.kind,
        # equation-family provenance, same contract as the solo harness
        # rows (check_provenance requires it; regress keys on it)
        "equation": cfg.equation,
        # integrator provenance (REQUIRED by check_provenance.py on every
        # throughput row): the ensemble packs the explicit sweep only,
        # but the row says so explicitly rather than by omission
        "integrator": cfg.integrator,
        "mesh": list(cfg.mesh.shape),
        "dtype": cfg.precision.storage,
        "compute_dtype": cfg.precision.compute,
        "backend": cfg.backend,
        "time_blocking": cfg.time_blocking,
        "overlap": cfg.overlap,
        "halo": cfg.halo,
        "halo_order": cfg.halo_order,
        # the EFFECTIVE plan mode (HEAT3D_NO_PLAN degrades partitioned
        # to the ad-hoc monolithic schedule — the solo harness's rule,
        # one source: parallel.plan.effective_halo_plan)
        "halo_plan": effective_halo_plan(cfg),
        # the fused in-kernel RDMA route never dispatches on the batched
        # ensemble path (vmapped members; no shard_map kernel) — rows
        # record what ran, so the knob keys to off here
        "fused_rdma": "off",
        "steps": steps,
        "steps_requested": steps_requested,
        "seconds_best": best,
        "seconds_all": times,
        "sync_rtt": rtt,
        "sync_rtt_s": rtt,
        "rtt_dominated": rtt_dominated,
        "gcell_per_sec": gcells,
        "gcell_per_sec_per_chip": gcells / n_dev,
        # the ensemble workload axis: total rate / members_per_step is the
        # per-member effective rate the obs reports print
        "batch_shape": [B],
        "members_per_step": B,
        "batch_mesh": solver.batch_mesh,
        # route provenance (check_provenance ROUTE_FIELDS): the ensemble
        # path is the parametric chain — no kernel route ever resolves
        "chain_ops": _chain_ops(cfg, mehrstellen=solver._mehrstellen),
        "mehrstellen_route": solver._mehrstellen,
        "direct_path": False,
        "fused_dma_path": False,
        "fused_dma_emulated": False,
        "streamk_path": False,
        "streamk_emulated": False,
        "fused_rdma_path": False,
        "fused_rdma_emulated": False,
        "cost_redundant_flops_frac": redundant_flops_frac(cfg),
        "cost_flops_per_step": None,
        "cost_bytes_per_step": None,
    }
    _ledger_bench_row(row)
    obs.REGISTRY.histogram(
        "bench_step_latency_seconds", "bench throughput per-step latency"
    ).observe(best / steps)
    return row
