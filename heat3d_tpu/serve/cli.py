"""``heat3d serve`` — the solver-as-a-service front-end (docs/SERVING.md).

Modes::

    heat3d serve --smoke                 # tiny 2-scenario CPU-safe batch
    heat3d serve --requests FILE.jsonl   # submit scenarios, stream results
    heat3d serve --bench [--members B]   # one ensemble throughput row

``--requests`` reads one scenario per JSONL line::

    {"grid": 64, "stencil": "7pt", "bc": "dirichlet", "bc_value": 1.0,
     "alpha": 0.5, "dt": null, "steps": 100, "init": "hot-cube",
     "seed": 0, "mesh": [1, 1, 1], "dtype": "fp32",
     "time_blocking": 1}

Requests sharing a structural bucket (grid/stencil/bc/mesh/dtype/knobs)
pack into one compiled batch; results stream to stdout as JSON lines in
submission order (``--out DIR`` additionally saves each final field as
``req-<id>.npy``). Per-request ledger spans (``serve_submit`` /
``serve_batch_start`` / ``serve_result``) and queue metrics land in the
run ledger / metrics registry like every other entry point.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

import numpy as np

from heat3d_tpu import obs
from heat3d_tpu.utils.logging import get_logger

log = get_logger("heat3d.serve")


def _base_from_record(rec: dict):
    from heat3d_tpu.core.config import (
        BoundaryCondition,
        GridConfig,
        MeshConfig,
        Precision,
        RunConfig,
        SolverConfig,
        StencilConfig,
    )

    grid = rec.get("grid", 64)
    shape = tuple(grid if isinstance(grid, list) else [grid] * 3)
    mesh = rec.get("mesh")
    if mesh is None:
        mesh_cfg = MeshConfig()
    elif isinstance(mesh, list) and len(mesh) == 3:
        mesh_cfg = MeshConfig(shape=tuple(mesh))
    else:
        raise ValueError(f"request mesh must be [Px, Py, Pz], got {mesh!r}")
    dtype = rec.get("dtype", "fp32")
    return SolverConfig(
        grid=GridConfig(
            shape=shape, spacing=tuple(rec.get("spacing", (1.0, 1.0, 1.0)))
        ),
        stencil=StencilConfig(
            kind=rec.get("stencil", "7pt"),
            bc=BoundaryCondition(rec.get("bc", "dirichlet")),
        ),
        mesh=mesh_cfg,
        precision=Precision.bf16() if dtype == "bf16" else Precision.fp32(),
        run=RunConfig(num_steps=int(rec.get("steps", 100))),
        backend="jnp",
        halo="ppermute",
        time_blocking=int(rec.get("time_blocking", 1)),
        # equation family is STRUCTURAL (it shapes the compiled chain, so
        # it buckets); the member-level eq_params overrides below stay
        # runtime inputs of the shared program (docs/SERVING.md)
        equation=rec.get("equation", "heat"),
    )


def _eq_pairs(rec: dict) -> tuple:
    ep = rec.get("eq_params") or {}
    if not isinstance(ep, dict):
        raise ValueError(
            f"request eq_params must be an object of name -> value, got "
            f"{ep!r}"
        )
    return tuple(sorted((str(k), float(v)) for k, v in ep.items()))


def _scenario_from_record(rec: dict):
    from heat3d_tpu.serve.scenario import Scenario

    # coef_field arrives as ["name", seed, lo, hi] (prefixes allowed) or a
    # bare "name"; integrator as a string. Both bucket the request apart
    # from plain ones (scenario.request_bucket_key), so passing them
    # through here is what keeps a varcoef request from silently packing
    # with — and being served as — a constant-coefficient member.
    cf = rec.get("coef_field")
    if isinstance(cf, str):
        cf = (cf,)
    elif cf is not None:
        cf = tuple(cf)
    return Scenario(
        init=rec.get("init", "hot-cube"),
        alpha=float(rec.get("alpha", 1.0)),
        dt=rec.get("dt"),
        bc_value=float(rec.get("bc_value", 0.0)),
        steps=rec.get("steps"),
        seed=int(rec.get("seed", 0)),
        eq_params=_eq_pairs(rec),
        integrator=rec.get("integrator"),
        coef_field=cf,
    )


def _result_line(r, out_dir: Optional[str]) -> dict:
    line = {
        "request_id": r.request_id,
        "steps": r.steps,
        "batch_members": r.batch_size,
        "queue_latency_s": round(r.queue_latency_s, 6),
        "field_mean": float(np.mean(np.asarray(r.field, np.float64))),
        "field_max": float(np.max(np.asarray(r.field, np.float64))),
    }
    if r.residual_sumsq is not None:
        line["residual_sumsq"] = r.residual_sumsq
    if r.snapshots is not None:
        line["snapshots"] = len(r.snapshots)
    if out_dir:
        import os

        path = os.path.join(out_dir, f"req-{r.request_id}.npy")
        np.save(path, r.field)
        line["field_path"] = path
    return line


def _smoke_requests() -> List[dict]:
    # two heterogeneous scenarios in one bucket + one in a second bucket:
    # exercises packing AND bucket separation in under a second on CPU
    return [
        {"grid": 16, "steps": 4, "alpha": 0.5, "bc_value": 1.0, "seed": 1},
        {"grid": 16, "steps": 6, "alpha": 0.8, "init": "gaussian", "seed": 2},
        {"grid": 12, "steps": 3, "alpha": 0.3},
    ]


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="heat3d serve",
        description="batched scenario engine: queue scenario requests, "
        "pack shape-bucketed batches, stream per-member results "
        "(docs/SERVING.md)",
    )
    p.add_argument("--requests", default=None, metavar="FILE.jsonl",
                   help="scenario submissions, one JSON object per line")
    p.add_argument("--smoke", action="store_true",
                   help="run a built-in tiny multi-bucket batch (CI wiring; "
                   "CPU-safe, sub-second)")
    p.add_argument("--bench", action="store_true",
                   help="measure one ensemble throughput row "
                   "(batch_shape/members_per_step provenance) and print it")
    p.add_argument("--loadgen", default=None, metavar="SPEC.json",
                   help="sustained-traffic soak: replay a seeded open-"
                   "loop scenario-mix spec against the async engine "
                   "(Poisson arrivals, ramps, bursts, per-stream "
                   "admission control; serve/loadgen.py — docs/"
                   "SERVING.md \"Load, overload & soak\"); with "
                   "--verdict the machine-checked soak verdict prints "
                   "to stdout, exit 0 only when it passes")
    p.add_argument("--duration", type=float, default=None,
                   help="(--loadgen) override the spec's duration_s")
    p.add_argument("--monitor", action="store_true",
                   help="(--loadgen) live SLO burn-rate monitoring: a "
                   "thread tails the run's own ledger during the soak, "
                   "re-judging the SLO spec over sliding fast/slow "
                   "windows (obs/burn.py) and landing slo_burn_alert "
                   "events on each objective's rising edge; needs "
                   "--ledger/$HEAT3D_LEDGER; watch live with "
                   "`heat3d obs watch LEDGER`")
    p.add_argument("--abort-on-burn", action="store_true",
                   help="(--loadgen, implies --monitor) terminate the "
                   "replay early when any objective alerts on both "
                   "windows — the soak exits 1 with a machine-readable "
                   "partial verdict instead of burning its full "
                   "duration")
    p.add_argument("--row", default=None, metavar="FILE.jsonl",
                   help="(--loadgen) append the soak's provenance row "
                   "(bench=soak; check_provenance.py-checked) to this "
                   "JSONL file")
    p.add_argument("--members", type=int, default=4,
                   help="(--bench) ensemble members")
    p.add_argument("--grid", type=int, default=32,
                   help="(--bench) grid edge")
    p.add_argument("--steps", type=int, default=20,
                   help="(--bench) step floor per trial")
    p.add_argument("--batch-mesh", type=int, default=1,
                   help="devices along the batch axis (the mesh factorizes "
                   "b x space; 1 = all devices spatial)")
    p.add_argument("--async", dest="async_", action="store_true",
                   help="serve through the always-on async engine "
                   "(serve/engine): submissions are accepted while "
                   "batches are in flight, buckets execute on worker "
                   "threads, and the AOT executable cache eliminates the "
                   "cold-start compile stall (docs/SERVING.md \"Async "
                   "engine & cold start\")")
    p.add_argument("--workers", type=int, default=None,
                   help="(--async) concurrent batch-execution slots "
                   "(default $HEAT3D_SERVE_WORKERS or 2)")
    p.add_argument("--no-aot", action="store_true",
                   help="(--async) raw jit dispatch — skip the AOT "
                   "warm-up/cache entirely (the first request pays an "
                   "unmeasured compile stall; debugging escape)")
    p.add_argument("--verdict", action="store_true",
                   help="(--async) print a one-line JSON verdict to "
                   "stdout after the results: delivery counts, engine "
                   "stats, AOT hit/miss/stall figures (CI smoke wiring)")
    p.add_argument("--max-batch", type=int, default=None,
                   help="members per packed batch cap "
                   "(default $HEAT3D_SERVE_MAX_BATCH or 64)")
    p.add_argument("--snapshot-every", type=int, default=0,
                   help="stream per-member field snapshots every K steps "
                   "(0 = final field only)")
    p.add_argument("--residuals", action="store_true",
                   help="report each member's residual sum-of-squares "
                   "(one extra update per member)")
    p.add_argument("--out", default=None, metavar="DIR",
                   help="save each final field as DIR/req-<id>.npy")
    p.add_argument("--ledger", default=None,
                   help="run ledger path (default $HEAT3D_LEDGER)")
    p.add_argument("--slo", default=None, metavar="SPEC.json",
                   help="evaluate service-level objectives against this "
                   "drain (default $HEAT3D_SLO_SPEC when set; "
                   "obs/perf/slo.py) — verdict prints to stderr, an "
                   "objective BREACH exits 1 even when every result "
                   "delivered")
    args = p.parse_args(argv)

    obs.activate(args.ledger, meta={"entry": "serve"})
    try:
        rc = _main(args)
    except (ValueError, TypeError, NotImplementedError, RuntimeError) as e:
        # TypeError covers malformed request VALUES (e.g. a JSON null
        # where a number belongs: int(None)) — same clean exit as any
        # other bad request, not a traceback
        print(f"heat3d serve: error: {e}", file=sys.stderr)
        obs.deactivate(rc=2, error=f"{type(e).__name__}: {str(e)[:200]}")
        return 2
    except BaseException as e:
        obs.deactivate(rc=1, error=f"{type(e).__name__}: {str(e)[:200]}")
        raise
    obs.export_at_exit()
    obs.deactivate(rc=rc)
    return rc


def _main(args) -> int:
    if args.loadgen:
        if args.requests or args.smoke or args.bench:
            raise ValueError(
                "--loadgen is its own mode — it cannot combine with "
                "--requests/--smoke/--bench"
            )
        return _serve_loadgen(args)
    if args.bench:
        from heat3d_tpu.core.config import GridConfig, SolverConfig
        from heat3d_tpu.serve.bench import bench_ensemble_throughput
        from heat3d_tpu.serve.scenario import Scenario, ScenarioBatch

        base = SolverConfig(
            grid=GridConfig.cube(args.grid), backend="jnp",
        )
        members = [
            Scenario(alpha=0.3 + 0.4 * (m + 1) / args.members, seed=m)
            for m in range(args.members)
        ]
        row = bench_ensemble_throughput(
            ScenarioBatch(base, members),
            steps=args.steps,
            batch_mesh=args.batch_mesh,
        )
        print(json.dumps(row))
        return 0

    if args.smoke:
        records = _smoke_requests()
    elif args.requests:
        records = []
        with open(args.requests) as f:
            for i, line in enumerate(f, start=1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    raise ValueError(
                        f"{args.requests}:{i}: unparseable request: {e}"
                    ) from None
                if not isinstance(rec, dict):
                    raise ValueError(
                        f"{args.requests}:{i}: request must be a JSON object"
                    )
                records.append(rec)
        if not records:
            raise ValueError(f"{args.requests}: no requests")
    else:
        print(
            "heat3d serve: need --requests FILE, --smoke, or --bench",
            file=sys.stderr,
        )
        return 2

    import os

    if args.out:
        os.makedirs(args.out, exist_ok=True)

    # SLO spec validates BEFORE the drain: a typo'd objective file must
    # not surface only after the batches already executed
    slo_spec = None
    if args.slo or os.environ.get("HEAT3D_SLO_SPEC"):
        from heat3d_tpu.obs.perf import slo as slo_mod

        try:
            slo_spec = slo_mod.load_spec(args.slo)
        except OSError as e:
            # the same clean rc-2 exit every other bad input takes (the
            # outer handler catches ValueError)
            raise ValueError(f"--slo: {e}") from None

    if args.async_:
        summary, n, engine_stats = _serve_async(args, records)
    else:
        summary, n, engine_stats = _serve_sync(args, records)
    rc = 0
    if n != len(records):
        print(
            f"heat3d serve: delivered {n} of {len(records)} requests",
            file=sys.stderr,
        )
        rc = 1
    else:
        log.info("serve: %d result(s) streamed", n)
    if args.verdict:
        # the machine verdict line CI smokes parse (stdout, AFTER the
        # result stream): delivery counts + engine/AOT figures — the
        # cold/warm A/B in run_bench_suite.sh compares compile_stall_s
        # across two of these. The sync path has no engine: the key is
        # OMITTED (not null) so consumers of the documented async shape
        # fail loudly on the wrong mode instead of dereferencing null.
        verdict = {
            "requests": len(records),
            "delivered": n,
            "ok": rc == 0,
        }
        if engine_stats is not None:
            verdict["engine"] = engine_stats
        print(json.dumps({"serve_verdict": verdict}), flush=True)

    # SLO wiring (docs/SERVING.md "SLOs"): judge THIS drain against the
    # declarative objectives — evaluated from the front-end's own summary
    # (the same dict the drain-final serve_metrics_summary event carried;
    # queue and async engine produce the identical shape), so the verdict
    # is live, not a ledger re-read. Verdict goes to stderr (stdout is
    # the result stream); a breach is rc 1.
    if slo_spec is not None:
        from heat3d_tpu.obs.perf import slo as slo_mod

        report = slo_mod.evaluate(
            [], slo_spec, serve_summary={
                **summary,
                "source": "live engine" if args.async_ else "live queue",
            },
        )
        slo_mod.record_verdict(report)
        slo_mod.print_report(report, out=sys.stderr)
        # only serve-side objectives (latency, degraded budget) are
        # judgeable from a drain (the queue has no step spans or device
        # profile) — say so, so a mixed spec's step/halo ceilings don't
        # read as enforced here
        other = [
            o["name"]
            for o in report["objectives"]
            if o["kind"] not in ("serve_latency", "serve_degraded")
        ]
        if other:
            print(
                f"heat3d serve: note: {', '.join(other)} not evaluable "
                "at drain time — run `heat3d obs slo <ledger>` post-hoc",
                file=sys.stderr,
            )
        if report["verdict"] == "breach":
            return 1
    return rc


def _serve_loadgen(args) -> int:
    """The sustained-traffic soak (serve/loadgen.py): seeded open-loop
    replay against the async engine, SLO-judged, machine-verdicted.
    rc 0 only when the soak's own checks pass AND no SLO objective
    breached; rc 1 otherwise (the test-pinned contract)."""
    import os

    from heat3d_tpu.obs.perf import slo as slo_mod
    from heat3d_tpu.serve import loadgen

    with open(args.loadgen) as f:
        try:
            mix = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"{args.loadgen}: unparseable loadgen spec: {e}"
            ) from None
    if not isinstance(mix, dict):
        raise ValueError(f"{args.loadgen}: loadgen spec must be an object")
    if args.duration is not None:
        mix["duration_s"] = args.duration

    # SLO resolution, validated BEFORE the soak burns its duration:
    # --slo / $HEAT3D_SLO_SPEC file > the mix's inline "slo" block > the
    # soak default (generous latency, a real degraded budget)
    if args.slo or os.environ.get("HEAT3D_SLO_SPEC"):
        try:
            slo_spec = slo_mod.load_spec(args.slo)
        except OSError as e:
            raise ValueError(f"--slo: {e}") from None
    elif isinstance(mix.get("slo"), dict):
        slo_spec = slo_mod.validate_spec(
            dict(mix["slo"]), origin=f"{args.loadgen}: slo"
        )
    else:
        slo_spec = dict(loadgen.DEFAULT_SOAK_SLO)

    # live-monitor resolution: the mix's "monitor" block tunes windows /
    # threshold / cadence; the FLAGS enable it (a committed spec should
    # not silently grow a monitoring thread). --abort-on-burn implies
    # --monitor. Monitoring without a ledger is a config error (rc 2,
    # validated before the soak burns its duration).
    monitor_cfg = None
    if args.monitor or args.abort_on_burn:
        if not obs.get().active:
            raise ValueError(
                "--monitor needs a run ledger (--ledger or "
                "$HEAT3D_LEDGER) — the live evaluator tails the run's "
                "own event stream"
            )
        mblock = mix.get("monitor")
        mblock = mblock if isinstance(mblock, dict) else {}
        monitor_cfg = {
            "spec": slo_spec,
            "abort_on_burn": bool(args.abort_on_burn),
            "interval_s": mblock.get("interval_s"),
            "fast_window_s": mblock.get("fast_window_s"),
            "slow_window_s": mblock.get("slow_window_s"),
            "threshold": mblock.get("threshold"),
        }

    verdict = loadgen.run_soak(
        mix, _base_from_record, _scenario_from_record,
        monitor=monitor_cfg,
    )
    report = slo_mod.evaluate(
        [], slo_spec,
        serve_summary={**verdict["summary"], "source": "soak"},
    )
    slo_mod.record_verdict(report)
    slo_mod.print_report(report, out=sys.stderr)
    ok = verdict["ok"] and report["verdict"] != "breach"
    if args.row:
        row = loadgen.soak_row(verdict, report["verdict"])
        with open(args.row, "a") as f:
            f.write(json.dumps(row) + "\n")
        log.info("soak row appended to %s", args.row)
    if args.verdict:
        out = {k: v for k, v in verdict.items() if k != "summary"}
        out["slo"] = report["verdict"]
        out["ok"] = ok
        print(json.dumps({"soak_verdict": out}), flush=True)
    if verdict.get("aborted"):
        mon_info = verdict.get("monitor") or {}
        print(
            "heat3d serve: soak ABORTED early on SLO burn "
            f"(alerted: {', '.join(mon_info.get('alerted', [])) or '?'}; "
            f"replayed {verdict['submitted']} of {verdict['arrivals']} "
            "arrivals) — partial verdict above",
            file=sys.stderr,
        )
    elif not verdict["ok"]:
        print(
            "heat3d serve: soak failed its own checks "
            f"(accounting_ok={verdict['accounting_ok']}, "
            f"order_ok={verdict['order_ok']}, "
            f"failed={verdict['failed']}, "
            f"compile_stall_after_warmup="
            f"{verdict['compile_stall_after_warmup']})",
            file=sys.stderr,
        )
    return 0 if ok else 1


def _serve_sync(args, records):
    """The submit-then-drain path (``ScenarioQueue``)."""
    from heat3d_tpu.serve.queue import ScenarioQueue

    queue = ScenarioQueue(
        max_batch=args.max_batch,
        batch_mesh=args.batch_mesh,
        snapshot_every=args.snapshot_every,
        with_residuals=args.residuals,
    )
    for rec in records:
        queue.submit(_base_from_record(rec), _scenario_from_record(rec))
    log.info("serve: %d request(s) queued", len(queue))
    n = 0
    for r in queue.drain():
        print(json.dumps(_result_line(r, args.out)), flush=True)
        n += 1
    return queue.metrics_summary(), n, None


def _serve_async(args, records):
    """The always-on path (serve/engine): submissions land on the live
    engine, the result stream follows per-stream submission order, and a
    failed bucket costs only its own requests (the shortfall is the rc-1
    'delivered n of m' path, with the errors on stderr). The CLI knows
    its whole request set up front, so it defers dispatch until every
    submission landed (``autostart=False``): one optimally-packed batch
    per bucket, and DETERMINISTIC padded sizes — the property the AOT
    cold/warm A/B keys on (run_bench_suite.sh)."""
    from heat3d_tpu.serve.engine import AsyncServeEngine

    engine = AsyncServeEngine(
        max_batch=args.max_batch,
        batch_mesh=args.batch_mesh,
        snapshot_every=args.snapshot_every,
        with_residuals=args.residuals,
        workers=args.workers,
        aot=False if args.no_aot else None,
        autostart=False,
    )
    n = 0
    try:
        for rec in records:
            engine.submit(
                _base_from_record(rec),
                _scenario_from_record(rec),
                # `or ""`: a JSON null stream means "no stream", not a
                # stream literally named "None"
                stream=str(rec.get("stream") or ""),
            )
        try:
            for r in engine.drain():
                print(json.dumps(_result_line(r, args.out)), flush=True)
                n += 1
        except RuntimeError as e:
            # failed bucket(s): everything deliverable already streamed —
            # the shortfall surfaces as 'delivered n of m' + rc 1
            print(f"heat3d serve: {e}", file=sys.stderr)
    finally:
        engine.shutdown()
    return engine.metrics_summary(), n, engine.stats()


if __name__ == "__main__":
    sys.exit(main())
