"""heat3d_tpu.timeint — the time-integrator registry (docs/INTEGRATORS.md).

Generalizes the step carry beyond explicit Euler's single field:

- ``explicit-euler`` — the default; HeatSolver3D keeps its existing
  (bit-identical) parallel.step route and never enters this package.
- ``leapfrog`` — the wave family's two-level carry ``(u, u_prev)``
  (heat3d_tpu.timeint.leapfrog): one tap sweep + subtraction per update,
  superstep ring recompute included.
- ``implicit-cg`` — matrix-free conjugate-gradient backward Euler
  (heat3d_tpu.timeint.cg): unconditionally stable, dt far above the
  explicit CFL bound, keep-masked SPMD-uniform iteration.

``heat3d_tpu.timeint.coeffield`` carries the sibling generalization —
spatially-varying coefficient FIELDS as a second sharded array — which
is a serve/test surface (Scenario.coef_field), not a SolverConfig knob.

The builders here mirror parallel.step's contracts: shard_map over the
(x, y, z) mesh, P('x','y','z') field specs, psum-replicated scalars,
the shared ExchangePlan for every ghost ring, and the heat3d.step named
scope for profile attribution.
"""

from __future__ import annotations

from jax.sharding import Mesh

from heat3d_tpu.core.config import (  # noqa: F401
    DEFAULT_INTEGRATOR,
    INTEGRATORS,
    SolverConfig,
)
from heat3d_tpu.timeint import cg, coeffield, leapfrog  # noqa: F401


class MultiLevelCheckpointError(ValueError):
    """A checkpoint's level structure does not match the integrator's
    carry: missing level manifest, wrong ``levels`` count, or a
    per-level shard shape mismatch. Subclasses ValueError so the
    supervisor treats it as skip-this-generation, never quarantine
    (the shards are not corrupt — they are the wrong SHAPE of state)."""


def carry_levels(integrator: str) -> int:
    """Field levels in the step carry: 2 for leapfrog, else 1."""
    return 2 if integrator == "leapfrog" else 1


def pin_config(cfg: SolverConfig) -> SolverConfig:
    """Resolve 'auto' knobs for a non-default integrator the way the
    serve tier's _resolve_base does: the multi-level/implicit builders
    are jnp + ppermute programs, so auto pins there instead of running
    the explicit-route tuner (whose cached knobs describe a different
    program family), and tb=0 (auto) pins to 1."""
    import dataclasses

    kw = {}
    if cfg.backend == "auto":
        kw["backend"] = "jnp"
    if cfg.halo == "auto":
        kw["halo"] = "ppermute"
    if cfg.time_blocking == 0:
        kw["time_blocking"] = 1
    return dataclasses.replace(cfg, **kw) if kw else cfg


def validate_config(cfg: SolverConfig) -> None:
    """Structural validation for the non-default integrator builders
    (the family coupling itself — wave<->leapfrog, CG symmetry — is
    config-time: eqn._validate_integrator). Raises ValueError listing
    every violation at once."""
    problems = []
    if cfg.integrator not in INTEGRATORS:
        problems.append(f"unknown integrator {cfg.integrator!r}")
    if cfg.backend != "jnp":
        problems.append(
            f"backend must be 'jnp' (got {cfg.backend!r}): the kernel "
            "routes fuse the single-level explicit update only"
        )
    if cfg.halo != "ppermute":
        problems.append(
            f"halo must be 'ppermute' (got {cfg.halo!r}): the DMA slab "
            "kernels are explicit-step-shaped"
        )
    if cfg.halo_order != "axis":
        problems.append(
            f"halo_order must be 'axis' (got {cfg.halo_order!r})"
        )
    if cfg.overlap:
        problems.append(
            "overlap=True unsupported (the interior/boundary split is "
            "explicit-step-shaped)"
        )
    if cfg.integrator == "implicit-cg" and cfg.time_blocking != 1:
        problems.append(
            f"implicit-cg needs time_blocking=1 (got {cfg.time_blocking}): "
            "each solve already amortizes many matvecs per exchange"
        )
    if cfg.integrator == "leapfrog" and cfg.time_blocking < 1:
        problems.append(
            f"leapfrog needs time_blocking >= 1, got {cfg.time_blocking}"
        )
    if problems:
        raise ValueError(
            f"integrator {cfg.integrator!r} unsupported for this config: "
            + "; ".join(problems)
            + " (docs/INTEGRATORS.md)"
        )


def make_step_fn(cfg: SolverConfig, mesh: Mesh, with_residual: bool = False):
    """The integrator's sharded one-step builder. Leapfrog maps the
    two-level carry ``(u, u_prev) -> (u_new, u)``; implicit-cg maps
    ``u -> u_new``. ``with_residual`` appends the psum'd global change
    residual in both cases (the supervisor health contract)."""
    validate_config(cfg)
    if cfg.integrator == "leapfrog":
        return leapfrog.make_step_fn(cfg, mesh, with_residual=with_residual)
    if cfg.integrator == "implicit-cg":
        return cg.make_step_fn(cfg, mesh, with_residual=with_residual)
    raise ValueError(
        f"integrator {cfg.integrator!r} has no timeint builder "
        "(explicit-euler rides parallel.step)"
    )


def make_multistep_fn(cfg: SolverConfig, mesh: Mesh):
    """The integrator's device-side-loop builder. Leapfrog:
    ``(carry, n) -> carry``. implicit-cg: ``(u, n) -> (u, cg_iters,
    cg_relres)`` — the trailing stats feed the ``cg_solve`` event."""
    validate_config(cfg)
    if cfg.integrator == "leapfrog":
        return leapfrog.make_multistep_fn(cfg, mesh)
    if cfg.integrator == "implicit-cg":
        return cg.make_multistep_fn(cfg, mesh)
    raise ValueError(
        f"integrator {cfg.integrator!r} has no timeint builder "
        "(explicit-euler rides parallel.step)"
    )
