"""Leapfrog (Stormer-Verlet) integration of the wave family.

The wave equation u_tt = c^2 Lap u is second order in time, so its state
is TWO field levels. The classic leapfrog update

    u^{n+1} = 2 u^n - u^{n-1} + dt^2 c^2 Lap u^n

maps exactly onto the existing single-sweep tap machinery: lower the wave
spec at a *squared* timestep (giving I + dt^2 c^2 Lap), bump the center
tap by one (giving 2I + dt^2 c^2 Lap), and the whole update is one
``apply_taps_padded`` sweep of u^n followed by an elementwise subtraction
of u^{n-1} — the same chain emission, halo ``ExchangePlan``, and
shrinking-ring superstep recompute as the explicit-Euler step, with the
carry generalized to the tuple ``(u, u_prev)``.

The carry rotation ``(u_new, u)`` is naturally copy-free under
``lax.fori_loop`` (each buffer is written exactly when its old contents
die), so the multistep loop needs no ping-pong scratch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from heat3d_tpu.core.config import SolverConfig
from heat3d_tpu.obs.trace import named_phase, scoped
from heat3d_tpu.ops.stencil_jnp import apply_taps_padded, residual_sumsq
from heat3d_tpu.parallel.step import (
    PHASE_STEP,
    _fill_mid_ghosts,
    _pin_padding,
    exchange,
)
from heat3d_tpu.utils.compat import shard_map


def leapfrog_taps(cfg: SolverConfig) -> np.ndarray:
    """The 3x3x3 leapfrog update taps ``2I + dt^2 c^2 Lap``: the wave
    spec lowered at dt^2 (one generic ``lower_taps`` call — I + dt^2
    c^2 Lap) with the center bumped by 1. One sweep of these taps over
    u^n, minus u^{n-1}, IS the leapfrog update."""
    from heat3d_tpu import eqn
    from heat3d_tpu.eqn.spec import lower_taps

    dt = cfg.grid.effective_dt()
    taps = np.array(
        lower_taps(eqn.build_spec(cfg), dt * dt, cfg.grid.spacing),
        copy=True,
    )
    taps[1, 1, 1] += 1.0
    return taps


def stable_dt(cfg: SolverConfig) -> float:
    """The leapfrog CFL bound for the wave family at this grid:
    dt <= 1 / (c sqrt(sum 1/h_i^2)) (from dt^2 lambda_max <= 4 with
    lambda_max = c^2 sum 4/h_i^2 for the 7pt Laplacian)."""
    from heat3d_tpu import eqn

    c = float(eqn.resolved_params(cfg)["c"])
    return 1.0 / (c * np.sqrt(sum(1.0 / h**2 for h in cfg.grid.spacing)))


def _crop(a: jax.Array, r: int) -> jax.Array:
    return a[r:-r, r:-r, r:-r]


def make_step_fn(
    cfg: SolverConfig, mesh: Mesh, with_residual: bool = False
):
    """Build the sharded one-leapfrog-step function over the two-level
    carry: ``(u, u_prev) -> (u_new, u)`` (or ``-> ((u_new, u), r2)``
    with the global change residual psum'd in the residual dtype). Both
    levels ride P('x','y','z'); the residual out_spec is replicated by
    its psum, exactly the explicit step's contract."""
    taps = leapfrog_taps(cfg)
    spec = P(*cfg.mesh.axis_names)
    axes = cfg.mesh.axis_names
    cd = jnp.dtype(cfg.precision.compute)
    sd = jnp.dtype(cfg.precision.storage)

    def local_step(u_local, up_local):
        upad = exchange(u_local, cfg)
        with named_phase("stencil"):
            t = apply_taps_padded(upad, taps, compute_dtype=cd, out_dtype=cd)
            u_new = (t - up_local.astype(cd)).astype(sd)
            return _pin_padding(u_new, cfg)

    if with_residual:

        def local_res(carry):
            u_local, up_local = carry
            u_new = local_step(u_local, up_local)
            with named_phase("residual"):
                r = residual_sumsq(
                    u_new, u_local, jnp.dtype(cfg.precision.residual)
                )
                r = lax.psum(r, axes)
            return (u_new, u_local), r

        return scoped(
            PHASE_STEP,
            shard_map(
                local_res,
                mesh=mesh,
                in_specs=((spec, spec),),
                out_specs=((spec, spec), P()),
                check_vma=False,
            ),
        )

    def local(carry):
        u_local, up_local = carry
        return local_step(u_local, up_local), u_local

    return scoped(
        PHASE_STEP,
        shard_map(
            local,
            mesh=mesh,
            in_specs=((spec, spec),),
            out_specs=(spec, spec),
            check_vma=False,
        ),
    )


def make_superstep_fn(cfg: SolverConfig, mesh: Mesh):
    """Build the temporally-blocked leapfrog superstep: k updates per
    exchange pair. Level 0 exchanges width-k ghosts and level 1 width
    k-1 (the subtrahend of application j needs exactly the ring depth
    application j produces); the shrinking-ring recompute then mirrors
    ``parallel.step._local_stepk``, with the PREVIOUS level of the next
    application obtained by cropping two rings off the current level —
    its interior-domain ghost cells are genuine by the same recompute
    argument that makes the explicit superstep bitwise."""
    k = cfg.time_blocking
    if k < 2:
        raise ValueError(f"superstep needs time_blocking >= 2, got {k}")
    min_extent = max(3, k)
    if min(cfg.local_shape) < min_extent:
        raise ValueError(
            f"time_blocking={k} needs local extents >= {min_extent} "
            f"(k ghost layers plus the shrinking recompute rings), got "
            f"{cfg.local_shape}"
        )
    taps = leapfrog_taps(cfg)
    spec = P(*cfg.mesh.axis_names)
    cd = jnp.dtype(cfg.precision.compute)
    sd = jnp.dtype(cfg.precision.storage)

    def local(carry):
        u_local, up_local = carry
        cur = exchange(u_local, cfg, width=k)  # rings k
        prv = exchange(up_local, cfg, width=k - 1)  # rings k-1
        with named_phase("stencil"):
            new = None
            for j in range(k):
                rings_new = k - j - 1  # rings carried by this update
                t = apply_taps_padded(
                    cur, taps, compute_dtype=cd, out_dtype=cd
                )
                new = (t - prv.astype(cd)).astype(sd)
                if rings_new > 0:
                    new = _fill_mid_ghosts(new, cfg, rings_new)
                else:
                    new = _pin_padding(new, cfg)
                if j < k - 1:
                    prv = _crop(cur, 2)  # rings k-j-2
                    cur = new
            # cur still carries one ghost ring of u^{k-1}: crop it and
            # re-pin the storage padding to recover the level-1 state
            return new, _pin_padding(_crop(cur, 1), cfg)

    return scoped(
        PHASE_STEP,
        shard_map(
            local,
            mesh=mesh,
            in_specs=((spec, spec),),
            out_specs=(spec, spec),
            check_vma=False,
        ),
    )


def make_multistep_fn(cfg: SolverConfig, mesh: Mesh):
    """Build ``(carry, num_steps) -> carry`` with the device-side
    fori_loop. With time_blocking k > 1 the loop advances in k-update
    supersteps plus trailing single steps. The two-level rotation makes
    the loop copy-free without a ping-pong scratch: each trip writes
    u_new into the buffer u_prev just vacated."""
    step = make_step_fn(cfg, mesh)

    if cfg.time_blocking > 1:
        k = cfg.time_blocking
        superstep = make_superstep_fn(cfg, mesh)

        def runk(carry, num_steps):
            carry = lax.fori_loop(
                0, num_steps // k, lambda _, c: superstep(c), carry
            )
            return lax.fori_loop(
                0, num_steps % k, lambda _, c: step(c), carry
            )

        return runk

    def run(carry, num_steps):
        return lax.fori_loop(0, num_steps, lambda _, c: step(c), carry)

    return run


# ---- numpy reference (tests) -------------------------------------------------


def reference_step(
    u: np.ndarray,
    u_prev: np.ndarray,
    taps: np.ndarray,
    periodic: bool = True,
    bc_value: float = 0.0,
) -> np.ndarray:
    """One fp64 leapfrog update on the full (unsharded) grid: pad, apply
    the 27 taps, subtract the previous level. The oracle the distributed
    builders are checked against."""
    mode = "wrap" if periodic else "constant"
    kw = {} if periodic else {"constant_values": bc_value}
    up = np.pad(u.astype(np.float64), 1, mode=mode, **kw)
    out = np.zeros_like(u, dtype=np.float64)
    n = u.shape
    for di in range(3):
        for dj in range(3):
            for dk in range(3):
                w = float(taps[di, dj, dk])
                if w == 0.0:
                    continue
                out += w * up[di:di + n[0], dj:dj + n[1], dk:dk + n[2]]
    return out - u_prev.astype(np.float64)
