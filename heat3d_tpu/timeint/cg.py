"""Matrix-free conjugate-gradient backward Euler (integrator='implicit-cg').

Backward Euler for u_t = L u solves (I - dt L) u^{n+1} = u^n each step —
unconditionally stable, so dt can sit far above the explicit CFL bound.
The system matrix is never formed: with T = I + dt L the EXPLICIT update
taps already lowered by the eqn frontend, the SPD operator is

    A v = 2 v - T v

and one matvec is exactly one existing halo exchange + tap sweep — with
the ghosts filled HOMOGENEOUSLY (bc_value=0.0 through the same
ExchangePlan) because Krylov vectors live in the zero-boundary subspace.
The inhomogeneous Dirichlet data enters through the right-hand side via
the zero-field trick: T applied to the field that is zero on the interior
and bc_value on the padding/ghosts yields exactly the dt * (boundary
inflow) term, so b = u^n + T z.

The iteration is a keep-masked ``lax.fori_loop`` to a fixed trip count
(SPMD-uniform: every device runs identical traces; convergence is
decided by psum-replicated scalars, and converged state is frozen via
``jnp.where(keep, ...)``) — the same budget-loop idiom as the serve
tier's ensemble. All reductions accumulate in ``cfg.precision.residual``
and psum over the full (x, y, z) mesh, the residual dtype/replication
contract of the explicit step.

Env knobs (read at build time, not config fields — they tune the solve,
not the problem): ``HEAT3D_CG_MAX_ITERS`` (default 64) and
``HEAT3D_CG_TOL`` (relative residual, default 1e-6).
"""

from __future__ import annotations

import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from heat3d_tpu.core.config import SolverConfig
from heat3d_tpu.obs.trace import named_phase, scoped
from heat3d_tpu.ops.stencil_jnp import apply_taps_padded, residual_sumsq
from heat3d_tpu.parallel.step import PHASE_STEP, _pin_padding, _solver_taps
from heat3d_tpu.utils.compat import shard_map

ENV_MAX_ITERS = "HEAT3D_CG_MAX_ITERS"
ENV_TOL = "HEAT3D_CG_TOL"
DEFAULT_MAX_ITERS = 64
DEFAULT_TOL = 1e-6


def cg_settings() -> Tuple[int, float]:
    """(max_iters, rel_tol) from the env knobs, defaults when unset."""
    iters = int(os.environ.get(ENV_MAX_ITERS) or DEFAULT_MAX_ITERS)
    tol = float(os.environ.get(ENV_TOL) or DEFAULT_TOL)
    if iters < 1:
        raise ValueError(f"{ENV_MAX_ITERS} must be >= 1, got {iters}")
    if not (0.0 < tol < 1.0):
        raise ValueError(f"{ENV_TOL} must be in (0, 1), got {tol}")
    return iters, tol


def make_step_fn(
    cfg: SolverConfig,
    mesh: Mesh,
    with_residual: bool = False,
    with_stats: bool = False,
):
    """Build the sharded one-backward-Euler-step function ``u -> u_new``.

    ``with_residual`` appends the global change residual (psum'd sumsq of
    u_new - u in the residual dtype — the explicit step's supervisor
    health contract). ``with_stats`` appends the CG iteration count and
    final relative residual instead (both psum-derived, hence replicated
    by construction — the ledger's ``cg_solve`` event payload).
    """
    taps = _solver_taps(cfg)  # T = I + dt L, the explicit update taps
    spec = P(*cfg.mesh.axis_names)
    axes = cfg.mesh.axis_names
    cd = jnp.dtype(cfg.precision.compute)
    sd = jnp.dtype(cfg.precision.storage)
    rd = jnp.dtype(cfg.precision.residual)
    max_iters, tol = cg_settings()

    from heat3d_tpu.parallel.plan import exchange_with_plan

    def _mask0(a):
        # Krylov vectors are zero on the storage padding (the padded
        # cells are boundary data, not unknowns)
        return _pin_padding(a, cfg, bc_value=0.0)

    def _psum_sum(a):
        return lax.psum(jnp.sum(a, dtype=rd), axes)

    def local(u_local):
        def matvec(v):
            with named_phase("halo_exchange"):
                vp = exchange_with_plan(v, cfg, 1, bc_value=0.0)
            tv = apply_taps_padded(vp, taps, compute_dtype=cd, out_dtype=cd)
            return _mask0(2.0 * v - tv)

        with named_phase("stencil"):
            # zero-field trick: z is 0 on the interior and bc_value on
            # padding/ghosts, so (T z) interior == dt * (boundary inflow)
            z = _pin_padding(jnp.zeros(u_local.shape, cd), cfg)
            with named_phase("halo_exchange"):
                zp = exchange_with_plan(z, cfg, 1)
            tz = apply_taps_padded(zp, taps, compute_dtype=cd, out_dtype=cd)
            b = _mask0(u_local.astype(cd) + tz)

            b2 = _psum_sum(b.astype(rd) ** 2)
            tol2 = jnp.asarray(tol * tol, rd) * b2
            x = b  # warm start: b == u^n in the homogeneous subspace
            r = _mask0(b - matvec(x))
            p = r
            rs = _psum_sum(r.astype(rd) ** 2)

            def body(_, state):
                x, r, p, rs, iters = state
                keep = rs > tol2
                ap = matvec(p)
                pap = _psum_sum(p.astype(rd) * ap.astype(rd))
                alpha = jnp.where(pap > 0, rs / jnp.where(pap > 0, pap, 1), 0)
                xn = x + alpha.astype(cd) * p
                rn = r - alpha.astype(cd) * ap
                rsn = _psum_sum(rn.astype(rd) ** 2)
                beta = jnp.where(rs > 0, rsn / jnp.where(rs > 0, rs, 1), 0)
                pn = rn + beta.astype(cd) * p
                return (
                    jnp.where(keep, xn, x),
                    jnp.where(keep, rn, r),
                    jnp.where(keep, pn, p),
                    jnp.where(keep, rsn, rs),
                    iters + keep.astype(jnp.int32),
                )

            state = (x, r, p, rs, jnp.zeros((), jnp.int32))
            x, _, _, rs, iters = lax.fori_loop(0, max_iters, body, state)
            # restore the REAL boundary value on the storage padding
            u_new = _pin_padding(x.astype(sd), cfg)
            relres = jnp.sqrt(rs / jnp.where(b2 > 0, b2, 1))
        return u_new, iters, relres

    if with_stats:
        return scoped(
            PHASE_STEP,
            shard_map(
                local,
                mesh=mesh,
                in_specs=spec,
                out_specs=(spec, P(), P()),
                check_vma=False,
            ),
        )

    if with_residual:

        def local_res(u_local):
            u_new, _, _ = local(u_local)
            with named_phase("residual"):
                r = residual_sumsq(u_new, u_local, rd)
                r = lax.psum(r, axes)
            return u_new, r

        return scoped(
            PHASE_STEP,
            shard_map(
                local_res,
                mesh=mesh,
                in_specs=spec,
                out_specs=(spec, P()),
                check_vma=False,
            ),
        )

    def local_plain(u_local):
        return local(u_local)[0]

    return scoped(
        PHASE_STEP,
        shard_map(
            local_plain,
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
            check_vma=False,
        ),
    )


def make_multistep_fn(cfg: SolverConfig, mesh: Mesh):
    """Build ``(u, num_steps) -> (u, iters_last, relres_last)``: the
    device-side fori_loop over backward-Euler solves, carrying the LAST
    solve's CG statistics out for the host-side ``cg_solve`` ledger
    event (models.heat3d.HeatSolver3D.run)."""
    step = make_step_fn(cfg, mesh, with_stats=True)
    rd = jnp.dtype(cfg.precision.residual)

    def run(u, num_steps):
        def body(_, c):
            u, _, _ = c
            return step(u)

        init = (u, jnp.zeros((), jnp.int32), jnp.zeros((), rd))
        return lax.fori_loop(0, num_steps, body, init)

    return run


# ---- numpy reference (tests) -------------------------------------------------


def reference_apply_T(
    v: np.ndarray,
    taps: np.ndarray,
    periodic: bool = True,
    bc_value: float = 0.0,
) -> np.ndarray:
    """fp64 full-grid sweep of the explicit taps T (pad + 27-tap apply)."""
    mode = "wrap" if periodic else "constant"
    kw = {} if periodic else {"constant_values": bc_value}
    vp = np.pad(v.astype(np.float64), 1, mode=mode, **kw)
    out = np.zeros_like(v, dtype=np.float64)
    n = v.shape
    for di in range(3):
        for dj in range(3):
            for dk in range(3):
                w = float(taps[di, dj, dk])
                if w == 0.0:
                    continue
                out += w * vp[di:di + n[0], dj:dj + n[1], dk:dk + n[2]]
    return out


def reference_solve(
    u0: np.ndarray,
    taps: np.ndarray,
    periodic: bool = True,
    bc_value: float = 0.0,
    tol: float = 1e-12,
    max_iters: int = 500,
) -> np.ndarray:
    """fp64 full-grid CG solve of (2I - T) u1 = u0 + T z — the oracle
    the distributed keep-masked solve is checked against."""

    def matvec(v):
        return 2.0 * v - reference_apply_T(v, taps, periodic, 0.0)

    if periodic:
        b = u0.astype(np.float64)
    else:
        z = np.zeros_like(u0, dtype=np.float64)
        b = u0.astype(np.float64) + reference_apply_T(
            z, taps, periodic, bc_value
        )
    x = b.copy()
    r = b - matvec(x)
    p = r.copy()
    rs = float(np.sum(r * r))
    b2 = float(np.sum(b * b)) or 1.0
    for _ in range(max_iters):
        if rs <= tol * tol * b2:
            break
        ap = matvec(p)
        alpha = rs / float(np.sum(p * ap))
        x += alpha * p
        r -= alpha * ap
        rs_new = float(np.sum(r * r))
        p = r + (rs_new / rs) * p
        rs = rs_new
    return x
