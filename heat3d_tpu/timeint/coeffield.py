"""Spatially-varying coefficient fields: a(x) as a SECOND sharded array.

div(a grad u) discretized in flux form on the 7-point footprint:

    u' = u + dt/h_ax^2 * sum_ax [ (a_c+a_p)/2 (u_p - u_c)
                                 - (a_m+a_c)/2 (u_c - u_m) ]

The coefficient field rides the SAME machinery as the solution: sharded
P('x','y','z'), ghost-exchanged through the config's persistent
:class:`ExchangePlan` (``exchange_with_plan`` — so its sends show up in
the plan audit ledger exactly like the solution's), and pinned on
storage padding. At a physical Dirichlet boundary the coefficient ghosts
are zero-filled, which zeroes the boundary-face flux contribution from
outside; periodic ghosts wrap genuinely. The field REPLACES grid.alpha
(uniform a == alpha reproduces the constant-coefficient operator up to
fp association).

Named initializers (fp64 numpy, seeded — the serve tier's
``Scenario.coef_field`` spec tuples resolve here) cover the test and
serve surfaces: uniform (iid U[lo,hi]), layered (smooth z-gradient),
checker (lo/hi block checkerboard), lognormal (clipped).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from heat3d_tpu.core.config import SolverConfig
from heat3d_tpu.obs.trace import named_phase, scoped
from heat3d_tpu.parallel.step import PHASE_STEP, _pin_padding
from heat3d_tpu.utils.compat import shard_map

COEF_FIELDS = ("uniform", "layered", "checker", "lognormal")


def make_coef_field(
    name: str,
    shape: Tuple[int, int, int],
    seed: int = 0,
    lo: float = 0.5,
    hi: float = 1.5,
) -> np.ndarray:
    """The named fp64 coefficient field on the GLOBAL grid. Every field
    is strictly positive (lo > 0 enforced) so the operator stays
    elliptic and the explicit bound dt <= h^2/(6 max a) holds."""
    if name not in COEF_FIELDS:
        raise ValueError(
            f"unknown coefficient field {name!r}; have {COEF_FIELDS}"
        )
    if not (0.0 < lo <= hi):
        raise ValueError(f"need 0 < lo <= hi, got lo={lo} hi={hi}")
    rng = np.random.default_rng(seed)
    if name == "uniform":
        return rng.uniform(lo, hi, size=shape)
    if name == "layered":
        z = (np.arange(shape[2], dtype=np.float64) + 0.5) / shape[2]
        prof = lo + (hi - lo) * 0.5 * (1.0 + np.sin(2.0 * np.pi * z))
        return np.broadcast_to(prof[None, None, :], shape).copy()
    if name == "checker":
        idx = np.add.outer(
            np.add.outer(np.arange(shape[0]) // 2, np.arange(shape[1]) // 2),
            np.arange(shape[2]) // 2,
        )
        return np.where(idx % 2 == 0, lo, hi).astype(np.float64)
    # lognormal: median sqrt(lo*hi), clipped into [lo, hi]
    mid = np.sqrt(lo * hi)
    sigma = 0.25 * np.log(hi / lo) if hi > lo else 0.0
    return np.clip(
        mid * np.exp(sigma * rng.standard_normal(shape)), lo, hi
    )


def varcoef_stable_dt(
    a_max: float, spacing: Tuple[float, float, float]
) -> float:
    """Explicit stability bound for the flux-form operator: dt <=
    1 / (2 a_max sum 1/h_i^2)."""
    return 1.0 / (2.0 * float(a_max) * sum(1.0 / h**2 for h in spacing))


def _slab(ap: jax.Array, axis: int, off: int) -> jax.Array:
    """Interior-shaped slice of a 1-ring-padded array shifted ``off``
    along ``axis``."""
    sl = []
    for ax in range(3):
        o = off if ax == axis else 0
        sl.append(slice(1 + o, ap.shape[ax] - 1 + o))
    return ap[tuple(sl)]


def _local_flux_update(
    u_local, a_local, cfg, dt, exchange_with_plan, bc_value=None
):
    """One flux-form update on a local shard: both arrays ghost-padded
    through the plan, per-axis face-averaged fluxes, compute-dtype
    accumulation, storage-dtype result with padding re-pinned.
    ``bc_value=None`` uses the config's (the solo route); the serve
    tier passes each member's TRACED boundary value — ``dt`` may be a
    traced per-member scalar for the same reason."""
    cd = jnp.dtype(cfg.precision.compute)
    sd = jnp.dtype(cfg.precision.storage)
    with named_phase("halo_exchange"):
        if bc_value is None:
            up = exchange_with_plan(u_local, cfg, 1)
        else:
            up = exchange_with_plan(u_local, cfg, 1, bc_value)
        apad = exchange_with_plan(a_local, cfg, 1, bc_value=0.0)
    with named_phase("stencil"):
        up = up.astype(cd)
        apad = apad.astype(cd)
        uc = _slab(up, 0, 0)
        ac = _slab(apad, 0, 0)
        acc = uc
        for axis in range(3):
            h2 = cfg.grid.spacing[axis] ** 2
            u_p, u_m = _slab(up, axis, 1), _slab(up, axis, -1)
            a_p, a_m = _slab(apad, axis, 1), _slab(apad, axis, -1)
            flux = 0.5 * (ac + a_p) * (u_p - uc) - 0.5 * (a_m + ac) * (
                uc - u_m
            )
            # dt/h2 stays a host-side fp64 divide when dt is concrete
            # (solo route, bitwise vs the oracle) and a traced divide
            # when the serve tier feeds a per-member dt
            acc = acc + jnp.asarray(dt / h2, cd) * flux
        out = acc.astype(sd)
        if bc_value is None:
            return _pin_padding(out, cfg)
        return _pin_padding(out, cfg, bc_value=bc_value)


def validate_config(cfg: SolverConfig) -> None:
    """Coefficient fields compose with the plain jnp explicit route
    only: heat family, explicit-euler, tb=1, no overlap, jnp backend
    (pinned by the caller), ppermute halo."""
    problems = []
    if cfg.equation != "heat":
        problems.append(f"equation must be 'heat', got {cfg.equation!r}")
    if cfg.integrator != "explicit-euler":
        problems.append(
            f"integrator must be 'explicit-euler', got {cfg.integrator!r}"
        )
    if cfg.time_blocking > 1:
        problems.append(
            f"time_blocking must be 1, got {cfg.time_blocking} (the "
            "superstep ring recompute does not carry the second array)"
        )
    if cfg.overlap:
        problems.append("overlap=True unsupported")
    if cfg.backend not in ("jnp", "auto"):
        problems.append(f"backend must be 'jnp', got {cfg.backend!r}")
    if cfg.halo not in ("ppermute", "auto"):
        problems.append(f"halo must be 'ppermute', got {cfg.halo!r}")
    if problems:
        raise ValueError(
            "coefficient-field step unsupported for this config: "
            + "; ".join(problems)
            + " (docs/INTEGRATORS.md)"
        )


def make_varcoef_step_fn(cfg: SolverConfig, mesh: Mesh):
    """Build the sharded variable-coefficient step ``(u, a) -> u_new``:
    both arrays P('x','y','z'), both ghost-exchanged through the one
    ExchangePlan, the coefficient passing through unchanged."""
    validate_config(cfg)
    from heat3d_tpu.parallel.plan import exchange_with_plan

    spec = P(*cfg.mesh.axis_names)
    dt = cfg.grid.effective_dt()

    def local(u_local, a_local):
        return _local_flux_update(u_local, a_local, cfg, dt, exchange_with_plan)

    return scoped(
        PHASE_STEP,
        shard_map(
            local,
            mesh=mesh,
            in_specs=(spec, spec),
            out_specs=spec,
            check_vma=False,
        ),
    )


def make_varcoef_multistep_fn(cfg: SolverConfig, mesh: Mesh):
    """Build ``(u, a, num_steps) -> u_after`` with the device-side
    fori_loop (coefficient loop-invariant)."""
    step = make_varcoef_step_fn(cfg, mesh)
    from jax import lax

    def run(u, a, num_steps):
        return lax.fori_loop(0, num_steps, lambda _, v: step(v, a), u)

    return run


# ---- numpy reference (tests) -------------------------------------------------


def reference_varcoef_step(
    u: np.ndarray,
    a: np.ndarray,
    dt: float,
    spacing: Tuple[float, float, float],
    periodic: bool = True,
    bc_value: float = 0.0,
) -> np.ndarray:
    """fp64 full-grid flux-form update — the oracle for the sharded
    builder (solution ghosts bc_value, coefficient ghosts zero)."""
    if periodic:
        up = np.pad(u.astype(np.float64), 1, mode="wrap")
        apd = np.pad(a.astype(np.float64), 1, mode="wrap")
    else:
        up = np.pad(
            u.astype(np.float64), 1, mode="constant",
            constant_values=bc_value,
        )
        apd = np.pad(
            a.astype(np.float64), 1, mode="constant", constant_values=0.0
        )
    n = u.shape

    def slab(arr, axis, off):
        sl = []
        for ax in range(3):
            o = off if ax == axis else 0
            sl.append(slice(1 + o, 1 + o + n[ax]))
        return arr[tuple(sl)]

    uc, ac = slab(up, 0, 0), slab(apd, 0, 0)
    acc = uc.copy()
    for axis in range(3):
        h2 = spacing[axis] ** 2
        u_p, u_m = slab(up, axis, 1), slab(up, axis, -1)
        a_p, a_m = slab(apd, axis, 1), slab(apd, axis, -1)
        acc += (dt / h2) * (
            0.5 * (ac + a_p) * (u_p - uc) - 0.5 * (a_m + ac) * (uc - u_m)
        )
    return acc
