"""Declarative stencil/equation specs and the compiler that lowers them.

The Cerebras/Tenstorrent stencil papers (PAPERS.md) treat a stencil as
*data* — coefficients + footprint in, optimized schedule out. This module
is that authoring surface for the repo: an :class:`EquationSpec` is a sum
of spatial-operator terms (:class:`StencilSpec` + coefficient), and
:func:`lower_taps` lowers it to the ONE artifact every downstream layer
already consumes — the 3x3x3 explicit-Euler *update* tap array ``T`` with
``u_new[c] = sum_d T[d] u[c+d-1]``. Everything past the taps (the
``_chain_accumulate`` emission, halo plans, supersteps, the tuner, the
serve traced-bind, IR certification) is untouched by construction: a
spec-built program IS a tap-chain program.

Bitwise contract: the heat family's diffusion term lowers through
:func:`core.stencils.scaled_laplacian` — the SAME float arithmetic body
``stencil_taps`` runs — and a single-diffusion-term spec multiplies
``(dt * coeff) * lap`` exactly as the legacy path does, so spec-compiled
heat taps are bit-identical to the hardcoded path (test-pinned, and
proven e2e on a 4-device CPU mesh in tests/multidevice_checks.py).

Scope: linear, constant-coefficient operators on the 3x3x3 footprint —
one time level, explicit Euler. Per-cell coefficient *values* still vary
per ensemble member at runtime (the serve traced-bind feeds each member's
lowered tap values into one compiled parametric chain); spatially-varying
coefficient FIELDS and multi-level schemes (wave) are future families
(docs/EQUATIONS.md "Authoring guide").
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Mapping, Tuple

import numpy as np

from heat3d_tpu.core.stencils import scaled_laplacian

# how a term's unit-spacing weights pick up the grid spacing
SCALINGS = (
    # per-axis 1/h^2 on the axis taps, center rebalanced — the exact
    # stencil_taps separable arithmetic (7pt Laplacian, anisotropic taps)
    "laplacian-separable",
    # uniform-spacing w / h^2 (the 27pt isotropic Laplacian)
    "laplacian-uniform",
    # first-derivative taps: each axis tap scaled by 1/(2*h_axis) — the
    # central-difference gradient (advection terms)
    "gradient",
    # raw weights, no spacing (zeroth-order/reaction terms)
    "none",
)


@dataclasses.dataclass(frozen=True)
class StencilSpec:
    """One spatial operator: 3x3x3 unit-spacing weights + spacing law.

    ``weights[di+1, dj+1, dk+1]`` multiplies ``u[c + (di,dj,dk)]``.
    Laplacian scalings require the weights to sum to 0 (a consistent
    second-difference operator); the gradient scaling requires
    axis-antisymmetric face taps and no off-axis entries.
    """

    weights: np.ndarray  # (3,3,3) float64
    scaling: str = "laplacian-separable"

    def __post_init__(self):
        w = np.asarray(self.weights, dtype=np.float64)
        if w.shape != (3, 3, 3):
            raise ValueError(f"spec weights must be (3,3,3), got {w.shape}")
        w = w.copy()
        w.setflags(write=False)
        object.__setattr__(self, "weights", w)
        if self.scaling not in SCALINGS:
            raise ValueError(
                f"unknown scaling {self.scaling!r}; have {SCALINGS}"
            )
        if self.scaling.startswith("laplacian") and abs(w.sum()) > 1e-12:
            raise ValueError(
                f"{self.scaling} weights must sum to 0, got {w.sum()}"
            )
        if self.scaling == "gradient":
            off_axis = w.copy()
            off_axis[0, 1, 1] = off_axis[2, 1, 1] = 0.0
            off_axis[1, 0, 1] = off_axis[1, 2, 1] = 0.0
            off_axis[1, 1, 0] = off_axis[1, 1, 2] = 0.0
            if np.any(off_axis != 0.0):
                raise ValueError(
                    "gradient weights must live on the six face taps only"
                )
            for lo, hi in (
                ((0, 1, 1), (2, 1, 1)),
                ((1, 0, 1), (1, 2, 1)),
                ((1, 1, 0), (1, 1, 2)),
            ):
                if w[lo] != -w[hi]:
                    raise ValueError(
                        "gradient weights must be axis-antisymmetric "
                        f"(w{lo} == -w{hi}), got {w[lo]} vs {w[hi]}"
                    )

    def scaled(self, spacing: Tuple[float, float, float]) -> np.ndarray:
        """The spacing-scaled spatial operator (float64)."""
        if self.scaling == "laplacian-separable":
            return scaled_laplacian(self.weights, spacing, True)
        if self.scaling == "laplacian-uniform":
            return scaled_laplacian(self.weights, spacing, False)
        if self.scaling == "gradient":
            hx, hy, hz = spacing
            out = np.zeros((3, 3, 3))
            out[0, 1, 1] = self.weights[0, 1, 1] / (2.0 * hx)
            out[2, 1, 1] = self.weights[2, 1, 1] / (2.0 * hx)
            out[1, 0, 1] = self.weights[1, 0, 1] / (2.0 * hy)
            out[1, 2, 1] = self.weights[1, 2, 1] / (2.0 * hy)
            out[1, 1, 0] = self.weights[1, 1, 0] / (2.0 * hz)
            out[1, 1, 2] = self.weights[1, 1, 2] / (2.0 * hz)
            return out
        return np.array(self.weights, dtype=np.float64)


@dataclasses.dataclass(frozen=True)
class Term:
    """``coeff * op`` — one named addend of the spatial operator."""

    name: str
    coeff: float
    op: StencilSpec


@dataclasses.dataclass(frozen=True)
class EquationSpec:
    """du/dt = sum_i coeff_i * op_i(u), discretized explicit-Euler.

    ``terms`` order is load-bearing: lowering accumulates term
    contributions in spec order (deterministic float summation), so two
    specs with the same terms in the same order lower bit-identically.
    BC family, dtype contract, and mesh/plan knobs stay on SolverConfig —
    the spec describes the OPERATOR, the config describes the run.
    """

    family: str
    terms: Tuple[Term, ...]

    def __post_init__(self):
        if not self.terms:
            raise ValueError("an EquationSpec needs at least one term")
        names = [t.name for t in self.terms]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate term names: {names}")

    def footprint(
        self, dt: float, spacing: Tuple[float, float, float]
    ) -> Tuple[Tuple[int, int, int], ...]:
        """Nonzero tap offsets of the lowered update taps (sorted)."""
        from heat3d_tpu.core.stencils import nonzero_taps

        taps = lower_taps(self, dt, spacing)
        return tuple(sorted(off for off, _ in nonzero_taps(taps)))


def lower_taps(
    spec: EquationSpec, dt: float, spacing: Tuple[float, float, float]
) -> np.ndarray:
    """Lower ``spec`` to explicit-Euler update taps:
    ``T = I + dt * sum_i coeff_i * scaled(op_i)``.

    Each term contributes ``(dt * coeff) * scaled`` — the scalar product
    formed FIRST, exactly the legacy ``dt * alpha * lap`` association —
    then contributions add in term order. A single-diffusion-term spec is
    therefore bit-identical to ``core.stencils.stencil_taps``.
    """
    taps = None
    for t in spec.terms:
        contrib = (dt * t.coeff) * t.op.scaled(spacing)
        taps = contrib if taps is None else taps + contrib
    taps[1, 1, 1] += 1.0
    return taps


def spec_fingerprint(spec: EquationSpec) -> str:
    """Deterministic short content hash of the spec structure + values —
    the tune-cache key leg for non-heat families (the heat family keys on
    the bare stencil kind so every committed entry stays addressable)."""
    h = hashlib.sha1()
    for t in spec.terms:
        h.update(
            f"{t.name}|{t.coeff!r}|{t.op.scaling}|".encode()
        )
        h.update(np.ascontiguousarray(t.op.weights).tobytes())
    return h.hexdigest()[:10]


def resolve_params(
    defaults: Mapping[str, float], overrides: Tuple[Tuple[str, float], ...]
) -> dict:
    """Family defaults merged with config overrides; unknown names raise
    (the config-validation surface — a typo'd --eq-param must fail in ms,
    not silently run the default equation)."""
    params = dict(defaults)
    for name, value in overrides:
        if name not in params:
            raise ValueError(
                f"unknown equation parameter {name!r}; this family has "
                f"{sorted(params)}"
            )
        v = float(value)
        if not np.isfinite(v):
            raise ValueError(f"equation parameter {name!r} must be finite")
        params[name] = v
    return params
