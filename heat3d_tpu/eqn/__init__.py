"""heat3d_tpu.eqn — the declarative equation frontend (docs/EQUATIONS.md).

One entry point matters to the rest of the framework:
:func:`solver_taps` — SolverConfig in, 3x3x3 explicit-Euler update taps
out. ``parallel.step._solver_taps`` routes every step/superstep/phase
program through it, so a registered family (heat, aniso-diffusion,
advection-diffusion, reaction-diffusion, ...) rides the unchanged
halo/ExchangePlan/tune/serve/obs machinery: the spec compiles to taps,
the taps feed the one shared chain emission.

``HEAT3D_EQN_LEGACY=1`` routes the heat family through the pre-spec
hardcoded derivation kept verbatim — the bitwise parity reference arm
(tests/multidevice_checks.py "eqn"), same escape-hatch pattern as
``HEAT3D_NO_PLAN``.
"""

from __future__ import annotations

import os
from typing import Tuple

import numpy as np

from heat3d_tpu.eqn.families import (  # noqa: F401
    DEFAULT_FAMILY,
    FAMILIES,
    EquationFamily,
    heat7,
    heat27,
)
from heat3d_tpu.eqn.spec import (  # noqa: F401
    EquationSpec,
    StencilSpec,
    Term,
    lower_taps,
    resolve_params,
    spec_fingerprint,
)

ENV_LEGACY = "HEAT3D_EQN_LEGACY"


def family_for(cfg) -> EquationFamily:
    """The registered family of ``cfg.equation`` (KeyError-free: config
    validation already rejected unknown names; this is the one lookup)."""
    fam = FAMILIES.get(cfg.equation)
    if fam is None:
        raise ValueError(
            f"unknown equation family {cfg.equation!r}; have "
            f"{sorted(FAMILIES)}"
        )
    return fam


def resolved_params(cfg) -> dict:
    """The family defaults merged with ``cfg.eq_params`` overrides."""
    fam = family_for(cfg)
    return resolve_params(dict(fam.defaults), tuple(cfg.eq_params))


def build_spec(cfg) -> EquationSpec:
    """Compile ``cfg`` (family + params + stencil kind + grid.alpha) to
    its :class:`EquationSpec`."""
    fam = family_for(cfg)
    if cfg.stencil.kind not in fam.kinds:
        raise ValueError(
            f"equation {fam.name!r} supports stencil kinds {fam.kinds}, "
            f"got {cfg.stencil.kind!r}"
        )
    return fam.build(cfg.stencil.kind, resolved_params(cfg), cfg.grid.alpha)


# Families whose explicit update operator is SYMMETRIC (no advection /
# odd-derivative term): the matrix-free CG solve (integrator=implicit-cg,
# heat3d_tpu.timeint.cg) requires a symmetric positive-definite system
# A = 2I - T, which an asymmetric T cannot provide.
CG_FAMILIES = ("heat", "aniso-diffusion", "reaction-diffusion")


def _validate_integrator(cfg, fam) -> None:
    """Integrator/family coupling (docs/INTEGRATORS.md): the wave family
    is second order in time and exists only under the two-level leapfrog
    carry; conversely leapfrog integrates nothing else. implicit-cg is
    restricted to symmetric operators (CG_FAMILIES) — and is the one
    integrator for which a dt above the family's explicit bound is the
    POINT, so the default-dt stability check stands down for it."""
    ti = getattr(cfg, "integrator", "explicit-euler")
    if fam.name == "wave" and ti != "leapfrog":
        raise ValueError(
            f"equation 'wave' is second order in time: it needs the "
            f"two-level leapfrog carry (integrator='leapfrog'), got "
            f"integrator={ti!r} (docs/INTEGRATORS.md)"
        )
    if ti == "leapfrog" and fam.name != "wave":
        raise ValueError(
            f"integrator='leapfrog' integrates the wave family's "
            f"second-order-in-time operator; {fam.name!r} is first order "
            "in time — use explicit-euler or implicit-cg "
            "(docs/INTEGRATORS.md)"
        )
    if ti == "implicit-cg" and fam.name not in CG_FAMILIES:
        raise ValueError(
            f"integrator='implicit-cg' needs a symmetric operator "
            f"(families {CG_FAMILIES}); {fam.name!r} breaks the "
            "conjugate-gradient symmetry contract (docs/INTEGRATORS.md)"
        )


def validate_config(cfg) -> None:
    """Config-time validation: family known, stencil kind supported,
    params resolvable, integrator/family coupling sound — and, for
    non-heat families with a DEFAULT (dt=None) timestep, the derived dt
    must respect the family's own explicit-Euler stability bound.
    ``GridConfig.effective_dt`` only knows the diffusion operator, so a
    strong reaction/advection term would otherwise let a default-dt run
    diverge silently (residual inf, rc 0); an EXPLICIT dt stays the
    author's contract (docs/EQUATIONS.md "Authoring guide"). The
    implicit-cg integrator is unconditionally stable, so the bound check
    stands down for it. Raises ValueError with the production message —
    SolverConfig.__post_init__ calls this so a bad --equation fails in
    ms, not at step-build time."""
    build_spec(cfg)
    fam = family_for(cfg)
    _validate_integrator(cfg, fam)
    if getattr(cfg, "integrator", "explicit-euler") == "implicit-cg":
        return
    if cfg.equation != "heat" and cfg.grid.dt is None and callable(
        fam.stable_dt
    ):
        bound = fam.stable_dt(
            resolved_params(cfg), cfg.grid.alpha, cfg.grid.spacing
        )
        dt = cfg.grid.effective_dt()
        if dt > bound * (1.0 + 1e-12):
            raise ValueError(
                f"equation {fam.name!r}: the default-derived dt "
                f"{dt:.4g} (0.9x the DIFFUSION stability bound) exceeds "
                f"this family's explicit-Euler bound {bound:.4g} at "
                f"these parameters — the run would diverge. Pass an "
                f"explicit dt <= {bound:.4g} (docs/EQUATIONS.md)"
            )


def solver_taps(cfg) -> np.ndarray:
    """THE tap derivation for a config: lower its equation spec at the
    grid's dt/spacing. Heat lowers bit-identically to the legacy
    ``stencil_taps`` path (the spec's diffusion term shares the
    ``scaled_laplacian`` body and the ``(dt*alpha)*lap`` association)."""
    if os.environ.get(ENV_LEGACY):
        if cfg.equation != "heat":
            raise ValueError(
                f"{ENV_LEGACY}=1 covers only the heat family (the legacy "
                f"hardcoded path never solved {cfg.equation!r})"
            )
        from heat3d_tpu.core.stencils import STENCILS, stencil_taps

        return stencil_taps(
            STENCILS[cfg.stencil.kind],
            cfg.grid.alpha,
            cfg.grid.effective_dt(),
            cfg.grid.spacing,
        )
    return lower_taps(
        build_spec(cfg), cfg.grid.effective_dt(), cfg.grid.spacing
    )


def fingerprint(cfg) -> str:
    """The tune-cache key leg for this config's equation: the bare
    stencil kind for heat (so every committed cache entry predating the
    eqn subsystem stays byte-identical and addressable), else
    ``<family>:<kind>:<spec content hash>``."""
    if cfg.equation == "heat":
        return cfg.stencil.kind
    return (
        f"{cfg.equation}:{cfg.stencil.kind}:"
        f"{spec_fingerprint(build_spec(cfg))}"
    )


def mms_rates(cfg, k: Tuple[float, float, float]) -> Tuple[float, float]:
    """(mu, omega) plane-wave rates of ``cfg``'s equation at physical
    wavevector ``k`` — the analytic reference the convergence tests
    compare against (core.golden.plane_wave evaluates the solution)."""
    return family_for(cfg).mms_rates(resolved_params(cfg), cfg.grid.alpha, k)
