"""The equation-family registry: named, parameterized PDE families.

Each family is a builder from ``(stencil kind, params, alpha)`` to an
:class:`~heat3d_tpu.eqn.spec.EquationSpec`, plus the metadata the lint
cross-checks (docs table row, CLI choice) and the fp64 manufactured-
solution reference the convergence tests drive (``mms_rates``: the decay
rate mu and phase rate omega of the periodic plane-wave solution
``u(x, t) = exp(-mu t) * sin(k . x - omega t)`` — every shipped family
is linear with constant coefficients, so a single plane wave is an exact
continuous solution; see core.golden.plane_wave).

``heat`` is the legacy 7pt/27pt heat equation re-authored as a spec —
the canonical surface now (``heat7()`` / ``heat27()`` return its specs
directly); its lowered taps are BIT-identical to the hardcoded
``stencil_taps`` path (tests/test_eqn.py pins it; the 4-device CPU-mesh
battery proves it e2e). The new families ride the same machinery:

- ``aniso-diffusion``   du/dt = alpha * div(D grad u), D = diag(dx,dy,dz)
- ``advection-diffusion`` du/dt = alpha * lap(u) - v . grad(u)
- ``reaction-diffusion``  du/dt = alpha * lap(u) + rate * u   (linear)
- ``wave``              d2u/dt2 = c^2 * lap(u)   (leapfrog two-level carry)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Mapping, Tuple

import numpy as np

from heat3d_tpu.core.stencils import STENCILS
from heat3d_tpu.eqn.spec import EquationSpec, StencilSpec, Term


@dataclasses.dataclass(frozen=True)
class EquationFamily:
    """One registered PDE family (see module docstring).

    ``defaults`` are (name, value) pairs — the full parameter schema; a
    config's ``eq_params`` may override any subset (unknown names raise
    at config validation). ``kinds`` are the stencil footprints the
    family's diffusion leg supports. ``mms_rates(params, alpha, k)``
    returns the (mu, omega) plane-wave rates for physical wavevector
    ``k`` — the analytic reference every family must carry (the
    eqn-registry lint flags a family without one).
    ``stable_dt(params, alpha, spacing)`` is the family's explicit-Euler
    stability bound: ``GridConfig.stable_dt`` only knows the diffusion
    operator, so a default-derived dt can silently diverge under strong
    reaction/advection terms — config validation rejects a DEFAULT dt
    above this bound (an explicit --dt stays the author's contract,
    docs/EQUATIONS.md)."""

    name: str
    description: str
    kinds: Tuple[str, ...]
    defaults: Tuple[Tuple[str, float], ...]
    build: Callable[[str, Mapping[str, float], float], EquationSpec]
    mms_rates: Callable[
        [Mapping[str, float], float, Tuple[float, float, float]],
        Tuple[float, float],
    ]
    stable_dt: Callable[
        [Mapping[str, float], float, Tuple[float, float, float]], float
    ] = None


def _diffusion_bound(alpha, spacing, d=(1.0, 1.0, 1.0)):
    """dt <= 1 / (2 * sum_a alpha*d_a/h_a^2) — the classic forward-Euler
    diffusion bound (GridConfig.stable_dt at d = 1)."""
    return 1.0 / (
        2.0 * alpha * sum(di / h**2 for di, h in zip(d, spacing))
    )


def _diffusion_term(kind: str, alpha: float) -> Term:
    s = STENCILS[kind]
    return Term(
        name="diffusion",
        coeff=alpha,
        op=StencilSpec(
            weights=s.weights,
            scaling=(
                "laplacian-separable" if s.separable else "laplacian-uniform"
            ),
        ),
    )


# ---- heat (the legacy equation, spec-authored) ------------------------------


def _build_heat(kind, params, alpha) -> EquationSpec:
    return EquationSpec(family="heat", terms=(_diffusion_term(kind, alpha),))


def _heat_rates(params, alpha, k):
    return alpha * float(sum(kk * kk for kk in k)), 0.0


def _heat_stable_dt(params, alpha, spacing):
    return _diffusion_bound(alpha, spacing)


def _aniso_stable_dt(params, alpha, spacing):
    return _diffusion_bound(
        alpha, spacing, (params["dx"], params["dy"], params["dz"])
    )


def _advdiff_stable_dt(params, alpha, spacing):
    # central advection + diffusion, forward Euler: the diffusion bound
    # AND dt <= 2*alpha / sum(v_a^2) (the cell-Reynolds-composed
    # sufficient condition; v = 0 leaves the diffusion bound alone)
    bound = _diffusion_bound(alpha, spacing)
    v2 = sum(params[p] ** 2 for p in ("vx", "vy", "vz"))
    if v2 > 0.0:
        bound = min(bound, 2.0 * alpha / v2)
    return bound


def _reactdiff_stable_dt(params, alpha, spacing):
    # lambda(s) = 1 + dt*(rate - alpha*s), s in [0, 4*sum 1/h^2]:
    # a DECAY rate tightens the |lambda| >= -1 corner to
    # dt <= 2 / (alpha*s_max + |rate|); growth (rate > 0) amplifies the
    # k=0 mode physically, so it never loosens the bound
    s_max = 4.0 * sum(1.0 / h**2 for h in spacing)
    return 2.0 / (alpha * s_max + max(-params["rate"], 0.0))


def heat7() -> EquationSpec:
    """The 7-point heat spec at unit diffusivity — the canonical
    authoring form of the legacy hardcoded kernel."""
    return _build_heat("7pt", {}, 1.0)


def heat27() -> EquationSpec:
    """The isotropic 27-point heat spec at unit diffusivity."""
    return _build_heat("27pt", {}, 1.0)


# ---- anisotropic (per-axis) diffusion ---------------------------------------


def _build_aniso(kind, params, alpha) -> EquationSpec:
    w = np.zeros((3, 3, 3))
    dx, dy, dz = params["dx"], params["dy"], params["dz"]
    if min(dx, dy, dz) <= 0.0:
        raise ValueError(
            f"aniso-diffusion needs positive per-axis diffusivities, got "
            f"dx={dx} dy={dy} dz={dz}"
        )
    w[0, 1, 1] = w[2, 1, 1] = dx
    w[1, 0, 1] = w[1, 2, 1] = dy
    w[1, 1, 0] = w[1, 1, 2] = dz
    w[1, 1, 1] = -2.0 * (dx + dy + dz)
    return EquationSpec(
        family="aniso-diffusion",
        terms=(
            Term(
                name="diffusion",
                coeff=alpha,
                op=StencilSpec(weights=w, scaling="laplacian-separable"),
            ),
        ),
    )


def _aniso_rates(params, alpha, k):
    d = (params["dx"], params["dy"], params["dz"])
    return alpha * float(sum(di * ki * ki for di, ki in zip(d, k))), 0.0


# ---- advection-diffusion ----------------------------------------------------


def _build_advdiff(kind, params, alpha) -> EquationSpec:
    v = (params["vx"], params["vy"], params["vz"])
    w = np.zeros((3, 3, 3))
    # -v . grad(u), central difference: tap at +1 along axis a is
    # -v_a/(2 h_a), at -1 it is +v_a/(2 h_a) (the gradient scaling
    # supplies the 1/(2h))
    w[0, 1, 1], w[2, 1, 1] = v[0], -v[0]
    w[1, 0, 1], w[1, 2, 1] = v[1], -v[1]
    w[1, 1, 0], w[1, 1, 2] = v[2], -v[2]
    return EquationSpec(
        family="advection-diffusion",
        terms=(
            _diffusion_term(kind, alpha),
            Term(
                name="advection",
                coeff=1.0,
                op=StencilSpec(weights=w, scaling="gradient"),
            ),
        ),
    )


def _advdiff_rates(params, alpha, k):
    v = (params["vx"], params["vy"], params["vz"])
    mu = alpha * float(sum(kk * kk for kk in k))
    omega = float(sum(vi * ki for vi, ki in zip(v, k)))
    return mu, omega


# ---- wave (second order in time; leapfrog-integrated) -----------------------


def _build_wave(kind, params, alpha) -> EquationSpec:
    c = params["c"]
    if c <= 0.0:
        raise ValueError(f"wave needs a positive speed c, got c={c}")
    s = STENCILS[kind]
    # the spatial operator is c^2 * lap(u); grid.alpha is a DIFFUSION
    # knob and deliberately does not enter (the wave speed is the
    # family's own parameter, like advection's velocity)
    return EquationSpec(
        family="wave",
        terms=(
            Term(
                name="wave-laplacian",
                coeff=c * c,
                op=StencilSpec(
                    weights=s.weights,
                    scaling=(
                        "laplacian-separable"
                        if s.separable
                        else "laplacian-uniform"
                    ),
                ),
            ),
        ),
    )


def _wave_rates(params, alpha, k):
    # d2u/dt2 = c^2 lap(u): sin(k.x - omega t) is exact at omega = c|k|,
    # with zero decay — the leapfrog MMS reference
    return 0.0, params["c"] * float(np.sqrt(sum(kk * kk for kk in k)))


def _wave_stable_dt(params, alpha, spacing):
    # leapfrog CFL: dt^2 * lambda_max <= 4 with
    # lambda_max(-c^2 lap_h) = c^2 * sum_a 4/h_a^2
    return 1.0 / (
        params["c"] * float(np.sqrt(sum(1.0 / h**2 for h in spacing)))
    )


# ---- reaction-diffusion (linear reaction) -----------------------------------


def _build_reactdiff(kind, params, alpha) -> EquationSpec:
    w = np.zeros((3, 3, 3))
    w[1, 1, 1] = 1.0
    return EquationSpec(
        family="reaction-diffusion",
        terms=(
            _diffusion_term(kind, alpha),
            Term(
                name="reaction",
                coeff=params["rate"],
                op=StencilSpec(weights=w, scaling="none"),
            ),
        ),
    )


def _reactdiff_rates(params, alpha, k):
    return alpha * float(sum(kk * kk for kk in k)) - params["rate"], 0.0


# ---- registry ---------------------------------------------------------------

FAMILIES: Dict[str, EquationFamily] = {
    f.name: f
    for f in (
        EquationFamily(
            name="heat",
            description="explicit-Euler heat diffusion (the legacy "
            "hardcoded 7pt/27pt path, spec-authored; alpha from the grid)",
            kinds=("7pt", "27pt"),
            defaults=(),
            build=_build_heat,
            mms_rates=_heat_rates,
            stable_dt=_heat_stable_dt,
        ),
        EquationFamily(
            name="aniso-diffusion",
            description="anisotropic diffusion du/dt = alpha*div(D grad u) "
            "with per-axis diffusivities D = diag(dx, dy, dz)",
            kinds=("7pt",),
            defaults=(("dx", 1.0), ("dy", 0.5), ("dz", 0.25)),
            build=_build_aniso,
            mms_rates=_aniso_rates,
            stable_dt=_aniso_stable_dt,
        ),
        EquationFamily(
            name="advection-diffusion",
            description="advection-diffusion du/dt = alpha*lap(u) - "
            "v.grad(u), central-difference transport v = (vx, vy, vz)",
            kinds=("7pt",),
            defaults=(("vx", 1.0), ("vy", 0.0), ("vz", 0.0)),
            build=_build_advdiff,
            mms_rates=_advdiff_rates,
            stable_dt=_advdiff_stable_dt,
        ),
        EquationFamily(
            name="wave",
            description="second-order wave equation d2u/dt2 = c^2*lap(u), "
            "leapfrog-integrated over the two-level (u, u_prev) carry "
            "(integrator='leapfrog'; docs/INTEGRATORS.md)",
            kinds=("7pt", "27pt"),
            defaults=(("c", 1.0),),
            build=_build_wave,
            mms_rates=_wave_rates,
            stable_dt=_wave_stable_dt,
        ),
        EquationFamily(
            name="reaction-diffusion",
            description="linear reaction-diffusion du/dt = alpha*lap(u) + "
            "rate*u (rate < 0 decays, rate > 0 grows)",
            kinds=("7pt", "27pt"),
            defaults=(("rate", -1.0),),
            build=_build_reactdiff,
            mms_rates=_reactdiff_rates,
            stable_dt=_reactdiff_stable_dt,
        ),
    )
}

DEFAULT_FAMILY = "heat"
