"""``heat3d eqn`` — inspect the declarative equation registry.

    heat3d eqn list [--json]          # family table (name, kinds, params)
    heat3d eqn show FAMILY [--json]   # spec detail + nominal lowered taps
                 [--stencil 7pt|27pt] [--eq-param NAME=VALUE ...]
                 [--alpha A] [--dt DT] [--spacing HX HY HZ]

``show`` compiles the family at the given (or nominal) coefficients and
prints the spec terms, the lowered 3x3x3 update taps, the tap footprint,
and the tune-cache fingerprint leg — the authoring feedback loop for new
families (docs/EQUATIONS.md "Authoring guide").
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

import numpy as np


def parse_eq_params(pairs: List[str]) -> tuple:
    """``NAME=VALUE`` strings -> the canonical eq_params tuple (shared by
    the solver CLI's --eq-param and this one)."""
    out = []
    for s in pairs or []:
        name, sep, val = s.partition("=")
        if not sep or not name:
            raise ValueError(
                f"--eq-param wants NAME=VALUE, got {s!r}"
            )
        try:
            out.append((name, float(val)))
        except ValueError:
            raise ValueError(
                f"--eq-param {name}: value {val!r} is not a number"
            ) from None
    return tuple(out)


def _family_record(fam) -> dict:
    return {
        "name": fam.name,
        "kinds": list(fam.kinds),
        "params": {k: v for k, v in fam.defaults},
        "description": fam.description,
    }


def cmd_list(args) -> int:
    from heat3d_tpu.eqn import FAMILIES

    if args.json:
        print(json.dumps([_family_record(f) for f in FAMILIES.values()]))
        return 0
    print(f"{len(FAMILIES)} equation families (docs/EQUATIONS.md):")
    for fam in FAMILIES.values():
        params = (
            ", ".join(f"{k}={v:g}" for k, v in fam.defaults) or "(none)"
        )
        print(f"  {fam.name:<20} kinds={'/'.join(fam.kinds):<9} {params}")
        print(f"  {'':<20} {fam.description}")
    return 0


def cmd_show(args) -> int:
    from heat3d_tpu.core.config import (
        GridConfig,
        SolverConfig,
        StencilConfig,
    )
    from heat3d_tpu.eqn import FAMILIES, build_spec, fingerprint

    fam = FAMILIES.get(args.family)
    if fam is None:
        print(
            f"heat3d eqn: unknown family {args.family!r}; have "
            f"{sorted(FAMILIES)}",
            file=sys.stderr,
        )
        return 2
    kind = args.stencil or fam.kinds[0]
    cfg = SolverConfig(
        grid=GridConfig.cube(
            16, alpha=args.alpha, dt=args.dt, spacing=tuple(args.spacing)
        ),
        stencil=StencilConfig(kind=kind),
        equation=fam.name,
        eq_params=parse_eq_params(args.eq_param),
    )
    spec = build_spec(cfg)
    from heat3d_tpu import eqn

    taps = eqn.solver_taps(cfg)
    merged = eqn.resolved_params(cfg)
    from heat3d_tpu.core.stencils import nonzero_taps

    taps_list = [
        {"offset": list(off), "weight": w} for off, w in nonzero_taps(taps)
    ]
    record = {
        **_family_record(fam),
        "stencil": kind,
        "alpha": args.alpha,
        "dt": cfg.grid.effective_dt(),
        "spacing": list(cfg.grid.spacing),
        # the EFFECTIVE parameter set (defaults + overrides, the one
        # resolution rule — eqn.resolved_params), plus the raw overrides
        # for callers reconstructing the command line
        "eq_params": merged,
        "eq_param_overrides": {k: v for k, v in cfg.eq_params},
        "terms": [
            {
                "name": t.name,
                "coeff": t.coeff,
                "scaling": t.op.scaling,
                "num_taps": int(np.count_nonzero(t.op.weights)),
            }
            for t in spec.terms
        ],
        "taps": taps_list,
        "num_taps": len(taps_list),
        "fingerprint": fingerprint(cfg),
    }
    if args.json:
        print(json.dumps(record))
        return 0
    print(f"{fam.name} ({kind}): {fam.description}")
    print(
        f"  alpha={args.alpha:g} dt={record['dt']:g} "
        f"spacing={tuple(cfg.grid.spacing)}"
    )
    if merged:
        print(
            "  params: "
            + " ".join(f"{k}={v:g}" for k, v in sorted(merged.items()))
        )
    for t in record["terms"]:
        print(
            f"  term {t['name']:<12} coeff={t['coeff']:g} "
            f"scaling={t['scaling']} taps={t['num_taps']}"
        )
    print(f"  lowered update taps ({record['num_taps']} nonzero):")
    for t in taps_list:
        off = tuple(t["offset"])
        print(f"    {off!s:<12} {t['weight']: .12g}")
    print(f"  tune-cache fingerprint leg: {record['fingerprint']}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="heat3d eqn",
        description="inspect the declarative equation registry "
        "(docs/EQUATIONS.md)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    pl = sub.add_parser("list", help="the family table")
    pl.add_argument("--json", action="store_true")
    pl.set_defaults(fn=cmd_list)
    ps = sub.add_parser("show", help="one family's spec + lowered taps")
    ps.add_argument("family")
    ps.add_argument("--stencil", choices=["7pt", "27pt"], default=None)
    ps.add_argument("--eq-param", action="append", default=[],
                    metavar="NAME=VALUE")
    ps.add_argument("--alpha", type=float, default=1.0)
    ps.add_argument("--dt", type=float, default=None)
    ps.add_argument("--spacing", type=float, nargs=3,
                    default=[1.0, 1.0, 1.0])
    ps.add_argument("--json", action="store_true")
    ps.set_defaults(fn=cmd_show)
    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except ValueError as e:
        print(f"heat3d eqn: error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
