"""Command-line driver — the reference's main() + mpirun surface.

Reference parity (SURVEY.md §2 C4/C12, §3.1): the reference is launched as
``mpirun -np P ./heat3d NX NY NZ NITER [Px Py Pz]``. Equivalent here::

    heat3d --grid 1024 --steps 1000 --mesh 8 1 1           # config 2 (slab)
    heat3d --grid 2048 --mesh 2 2 2                        # config 3
    heat3d --grid 4096 --stencil 27pt --mesh 4 4 4         # config 4
    heat3d --grid 4096 --dtype bf16 --mesh 8 4 4           # config 5
    heat3d --grid 128 --golden-check                       # config 1

One process per host on a pod slice; ``jax.distributed`` replaces mpirun
(BASELINE.json north star). All output is JSON on stdout, human logs on
stderr.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional

import jax
import numpy as np

from heat3d_tpu.core.config import (
    BoundaryCondition,
    GridConfig,
    MeshConfig,
    Precision,
    RunConfig,
    SolverConfig,
    StencilConfig,
)
from heat3d_tpu import obs
from heat3d_tpu.parallel import distributed
from heat3d_tpu.utils.logging import emit_json, get_logger
from heat3d_tpu.utils.timing import force_sync, maybe_profile

log = get_logger("heat3d.cli")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="heat3d",
        description="TPU-native 3D heat-equation solver "
        "(capabilities of the CUDA-aware-MPI reference, re-designed for TPU)",
    )
    p.add_argument(
        "--grid", type=int, nargs="+", default=[128],
        help="global interior grid: one int (cube) or three (NX NY NZ)",
    )
    p.add_argument("--spacing", type=float, nargs=3, default=[1.0, 1.0, 1.0])
    p.add_argument("--alpha", type=float, default=1.0, help="thermal diffusivity")
    p.add_argument("--dt", type=float, default=None, help="time step (default 0.9x stable)")
    p.add_argument("--stencil", choices=["7pt", "27pt"], default="7pt")
    # equation-family choices come from the LIVE registry (heat3d_tpu.eqn)
    # — the eqn-registry lint (ANL521) cross-checks this stays true
    from heat3d_tpu.eqn import FAMILIES

    p.add_argument(
        "--equation", choices=sorted(FAMILIES), default="heat",
        help="equation family the tap compiler lowers onto the stencil "
        "footprint (heat3d eqn list; docs/EQUATIONS.md). 'heat' is the "
        "legacy path, spec-authored",
    )
    p.add_argument(
        "--eq-param", action="append", default=[], metavar="NAME=VALUE",
        help="equation-family parameter override (repeatable), e.g. "
        "--eq-param vx=2.0; defaults per `heat3d eqn show FAMILY`",
    )
    from heat3d_tpu.core.config import INTEGRATORS

    p.add_argument(
        "--integrator", choices=list(INTEGRATORS), default="explicit-euler",
        help="time integrator (docs/INTEGRATORS.md): 'explicit-euler' "
        "(default, the tuned explicit route), 'leapfrog' (the wave "
        "family's two-level carry), 'implicit-cg' (matrix-free CG "
        "backward Euler — unconditionally stable, dt may exceed the "
        "explicit CFL bound; HEAT3D_CG_MAX_ITERS/HEAT3D_CG_TOL tune "
        "the solve)",
    )
    p.add_argument("--bc", choices=["dirichlet", "periodic"], default="dirichlet")
    p.add_argument("--bc-value", type=float, default=0.0)
    p.add_argument(
        "--mesh", type=int, nargs="+", default=None,
        help="device mesh Px Py Pz (one int = 1D slab; default: all devices, balanced 3D)",
    )
    p.add_argument("--dtype", choices=["fp32", "bf16"], default="fp32",
                   help="field storage dtype; residual always accumulates fp32")
    p.add_argument("--compute-dtype", choices=["fp32", "bf16"], default="fp32",
                   help="stencil compute dtype (bf16 halves VPU op width; "
                   "A/B knob for whether bf16 throughput is VPU- or "
                   "assembly-bound); residual still accumulates fp32")
    p.add_argument("--backend", choices=["auto", "jnp", "pallas", "conv"], default="auto")
    p.add_argument(
        "--dump-slice", nargs=3, metavar=("AXIS", "INDEX", "PATH"),
        default=None,
        help="after the run, save one global 2D plane as .npy: axis x|y|z "
        "(or 0|1|2), global index along it, output path — the reference "
        "class's visualization dump",
    )
    p.add_argument(
        "--dump-vtk", default=None, metavar="PATH",
        help="after the run, write the final field as legacy binary VTK "
        "STRUCTURED_POINTS (ParaView/VisIt — the reference class's "
        "visualization dump). Gathers the full field to the coordinator: "
        "meant for inspection-sized grids; use --dump-slice for planes of "
        "pod-scale fields",
    )
    p.add_argument("--overlap", action="store_true",
                   help="overlap halo exchange with interior compute "
                   "(interior/boundary split step)")
    p.add_argument("--halo", choices=["ppermute", "dma", "auto"],
                   default="ppermute",
                   help="ghost-exchange transport: XLA collective-permute, "
                   "Pallas remote-DMA kernels (TPU only), or 'auto' — "
                   "resolve through the tuning cache (heat3d tune; "
                   "docs/TUNING.md) with a ppermute fallback")
    p.add_argument("--halo-order", choices=["axis", "pairwise"],
                   default="axis",
                   help="halo-exchange ordering: 'axis' (x->y->z, "
                   "corner-propagating — required by 27pt) or 'pairwise' "
                   "(all six face permutes concurrent, stagger-tolerant; "
                   "7pt only — the tuner A/Bs the two)")
    p.add_argument("--halo-plan", choices=["monolithic", "partitioned", "auto"],
                   default="monolithic",
                   help="exchange-plan mode (parallel/plan.py): "
                   "'monolithic' (one collective per face), 'partitioned' "
                   "(each face ships as early-bird sub-blocks — more, "
                   "smaller messages overlapped with compute; "
                   "value-identical, pins the exchange path), or 'auto' "
                   "(resolve through the tuning cache; docs/TUNING.md)")
    p.add_argument("--fused-rdma", choices=["off", "on", "auto"],
                   default="off",
                   help="fused in-kernel RDMA superstep "
                   "(ops/stencil_fused_rdma): 'on' runs the halo "
                   "transfers INSIDE the stencil kernel — face remote "
                   "copies issued at grid step 0 on the ExchangePlan "
                   "schedule (--halo-plan partitioned splits the sends "
                   "into sub-block descriptors), interior swept while "
                   "they fly, skin planes after the semaphore waits; "
                   "value-identical to the unfused route, x-slab meshes "
                   "+ time-blocking <= 2 only (jnp path elsewhere); "
                   "'auto' resolves through the tuning cache")
    p.add_argument("--time-blocking", type=int, default=1,
                   help="stencil updates per ghost exchange in the "
                   "fixed-step loop (k>1 = temporal blocking: width-k "
                   "halos, 1/k the messages; k=2 also fuses both updates "
                   "into one HBM sweep; k=0 = auto via the tuning cache; "
                   "convergence mode --tol checks the "
                   "residual every step and always runs single updates)")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--init", default="hot-cube", help="hot-cube | gaussian | random")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--tol", type=float, default=None,
                   help="run to convergence at this L2 residual instead of fixed steps")
    p.add_argument("--residual-every", type=int, default=0,
                   help="report residual every K steps (0 = only at end)")
    p.add_argument("--golden-check", action="store_true",
                   help="compare against the NumPy golden model (config 1 oracle)")
    p.add_argument("--checkpoint", default=None, help="checkpoint directory")
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--resume", action="store_true", help="resume from --checkpoint")
    p.add_argument(
        "--supervise", action="store_true",
        help="run under the resilience supervisor: checkpoint generations "
        "every --checkpoint-every steps into --checkpoint, watchdog the "
        "backend, auto-resume from the last good generation (quarantining "
        "corrupt ones). --steps is then the TARGET GLOBAL step: relaunching "
        "the same command after a kill finishes the run. See "
        "docs/RESILIENCE.md",
    )
    p.add_argument(
        "--watchdog", type=float, default=None, metavar="SECONDS",
        help="(with --supervise) per-chunk wall-clock budget; an overrun "
        "triggers a backend probe and, if it fails, checkpoint-resume "
        "recovery",
    )
    p.add_argument(
        "--max-recoveries", type=int, default=3,
        help="(with --supervise) give up after this many survived failures",
    )
    p.add_argument(
        "--heal-mode", choices=["wait", "elastic", "auto"], default=None,
        help="(with --supervise) what a confirmed backend loss means: "
        "'wait' = probe until the ORIGINAL backend returns (PR 1 "
        "behavior; the HEAT3D_HEAL_DEADLINE_S deadline re-raises), "
        "'elastic' = probe the DEVICE SET and re-plan the moment any "
        "survivors answer (never waits out a platform heal): "
        "re-factorize the mesh over the survivors, re-stitch the newest "
        "generation onto the degraded mesh, continue "
        "(docs/RESILIENCE.md \"Elastic degradation\"), 'auto' = wait "
        "first, degrade when the heal deadline expires or the backend "
        "heals smaller. Default $HEAT3D_HEAL_MODE, else wait",
    )
    p.add_argument(
        "--reexpand", action="store_true",
        help="(with --supervise --heal-mode elastic|auto) opt-in "
        "re-expand: while degraded, probe at each checkpoint boundary "
        "and re-factorize back onto the original mesh when full "
        "capacity returns (degraded_mode_exit ledger event)",
    )
    p.add_argument("--profile", "--profile-dir", dest="profile_dir",
                   default=None, metavar="DIR",
                   help="capture a jax.profiler trace (TensorBoard/"
                   "Perfetto) of the timed region into DIR; the artifact "
                   "path and the capture overhead are recorded into the "
                   "run ledger as a profile_capture event "
                   "(docs/OBSERVABILITY.md). --profile-dir is the legacy "
                   "spelling")
    p.add_argument(
        "--ledger", default=None, metavar="PATH",
        help="append the run ledger (JSONL span/event stream) here; "
        "defaults to $HEAT3D_LEDGER; inspect with `heat3d obs summary "
        "PATH` (docs/OBSERVABILITY.md)",
    )
    p.add_argument("--coordinator", default=None, help="multi-host coordinator addr:port")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    return p


def config_from_args(args) -> SolverConfig:
    grid_shape = tuple(args.grid * 3 if len(args.grid) == 1 else args.grid)
    if len(grid_shape) != 3:
        raise SystemExit("--grid takes 1 or 3 ints")
    if args.mesh is None:
        mesh = MeshConfig.for_devices(len(jax.devices()))
    elif len(args.mesh) == 1:
        mesh = MeshConfig.slab(args.mesh[0])
    elif len(args.mesh) == 3:
        mesh = MeshConfig(shape=tuple(args.mesh))
    else:
        raise SystemExit("--mesh takes 1 or 3 ints")
    return SolverConfig(
        grid=GridConfig(
            shape=grid_shape,
            spacing=tuple(args.spacing),
            alpha=args.alpha,
            dt=args.dt,
        ),
        stencil=StencilConfig(
            kind=args.stencil,
            bc=BoundaryCondition(args.bc),
            bc_value=args.bc_value,
        ),
        mesh=mesh,
        precision=Precision(
            storage="bfloat16" if args.dtype == "bf16" else "float32",
            compute="bfloat16"
            if getattr(args, "compute_dtype", "fp32") == "bf16"
            else "float32",
        ),
        run=RunConfig(
            num_steps=args.steps,
            tolerance=args.tol,
            seed=args.seed,
            checkpoint_path=args.checkpoint,
            checkpoint_every=args.checkpoint_every,
            residual_every=args.residual_every,
            profile_dir=args.profile_dir,
        ),
        backend=args.backend,
        overlap=args.overlap,
        halo=args.halo,
        time_blocking=args.time_blocking,
        halo_order=args.halo_order,
        halo_plan=args.halo_plan,
        fused_rdma=getattr(args, "fused_rdma", "off"),
        equation=getattr(args, "equation", "heat"),
        eq_params=_parse_eq_params(getattr(args, "eq_param", [])),
        integrator=getattr(args, "integrator", "explicit-euler"),
    )


def _parse_eq_params(pairs) -> tuple:
    from heat3d_tpu.eqn.cli import parse_eq_params

    return parse_eq_params(pairs)


def main(argv: Optional[List[str]] = None) -> int:
    # `heat3d obs ...` — the ledger-inspection surface (summary/tail/check)
    # lives in its own subcommand parser, dispatched before the solver
    # parser ever sees the argv
    argv_l = list(sys.argv[1:] if argv is None else argv)
    if argv_l and argv_l[0] == "obs":
        from heat3d_tpu.obs.cli import main as obs_main

        return obs_main(argv_l[1:])
    # `heat3d tune ...` — the autotuner surface (run/show/apply/clear/lint),
    # dispatched the same way as `obs` (docs/TUNING.md)
    if argv_l and argv_l[0] == "tune":
        from heat3d_tpu.tune.cli import main as tune_main

        return tune_main(argv_l[1:])
    # `heat3d lint ...` — the static-analysis surface (docs/ANALYSIS.md):
    # SPMD-safety + invariant checkers, rc 1 only on error severity
    if argv_l and argv_l[0] == "lint":
        from heat3d_tpu.analysis.cli import main as lint_main

        return lint_main(argv_l[1:])
    # `heat3d serve ...` — the batched scenario engine's front-end
    # (queue scenario requests -> shape-bucketed batches -> streamed
    # results; docs/SERVING.md), dispatched like `obs`/`tune`
    if argv_l and argv_l[0] == "serve":
        from heat3d_tpu.serve.cli import main as serve_main

        return serve_main(argv_l[1:])
    # `heat3d eqn ...` — the declarative equation registry's inspection
    # surface (list/show; docs/EQUATIONS.md), dispatched like `obs`/`tune`
    if argv_l and argv_l[0] == "eqn":
        from heat3d_tpu.eqn.cli import main as eqn_main

        return eqn_main(argv_l[1:])
    # A measurement script stopping this run with `timeout` (SIGTERM) must
    # release the axon pool's chip claim on the way out, not die holding it.
    from heat3d_tpu.utils.backendprobe import install_sigterm_exit

    install_sigterm_exit()
    try:
        rc = _main(argv_l)
    except (ValueError, NotImplementedError) as e:
        # Config/capability errors (indivisible periodic meshes, halo='dma'
        # off-TPU, time_blocking constraints, ...) exit cleanly instead of
        # dumping a traceback — the reference's argv validation, done right.
        print(f"heat3d: error: {e}", file=sys.stderr)
        obs.deactivate(rc=2, error=f"{type(e).__name__}: {str(e)[:200]}")
        return 2
    except BaseException as e:
        # the ledger must record HOW the run ended even on crashes and
        # SIGTERM (SystemExit): close-with-error, then re-raise
        obs.deactivate(rc=1, error=f"{type(e).__name__}: {str(e)[:200]}")
        raise
    obs.export_at_exit()
    obs.deactivate(rc=rc)
    return rc


def _main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    distributed.initialize(args.coordinator, args.num_processes, args.process_id)
    # activation order: after distributed.initialize (so the ledger pins
    # the real process index), before config validation (so a run dying
    # on a bad config still leaves a ledger_open + rc=2 close)
    ledger = obs.activate(args.ledger, meta={"entry": "solve"})
    cfg = config_from_args(args)
    # tuning-cache resolution of the auto knobs (backend='auto',
    # halo='auto', time_blocking=0) BEFORE run_start, so the ledger
    # records the config that actually runs; the hit/miss/stale event
    # lands just above it (heat3d_tpu.tune.cache — fails soft to the
    # static defaults, never the run)
    from heat3d_tpu.tune.cache import resolve_config

    cfg = resolve_config(cfg)
    if args.supervise:
        # resolve the env default NOW so run_start records the heal mode
        # that will actually govern (the same rule as the auto knobs
        # above); a bad HEAT3D_HEAL_MODE fails here, in ms, not mid-outage
        from heat3d_tpu.resilience.elastic import resolve_heal_mode

        args.heal_mode = resolve_heal_mode(args.heal_mode)
    ledger.event(
        "run_start",
        grid=list(cfg.grid.shape),
        stencil=cfg.stencil.kind,
        equation=cfg.equation,
        integrator=cfg.integrator,
        mesh=list(cfg.mesh.shape),
        dtype=cfg.precision.storage,
        backend=cfg.backend,
        halo=cfg.halo,
        halo_order=cfg.halo_order,
        halo_plan=cfg.halo_plan,
        fused_rdma=cfg.fused_rdma,
        overlap=cfg.overlap,
        time_blocking=cfg.time_blocking,
        steps=cfg.run.num_steps,
        supervise=bool(args.supervise),
        heal_mode=args.heal_mode,
    )

    dump_slice = None
    if args.dump_slice:
        # validate BEFORE the run so a bad flag fails in ms, not hours
        axis_s, index_s, dump_path = args.dump_slice
        axis = {"x": 0, "y": 1, "z": 2}.get(axis_s.lower())
        if axis is None:
            try:
                axis = int(axis_s)
            except ValueError:
                raise ValueError(
                    f"--dump-slice axis must be x|y|z or 0|1|2, got {axis_s!r}"
                ) from None
        if not 0 <= axis <= 2:
            raise ValueError(f"--dump-slice axis must be 0..2, got {axis}")
        try:
            index = int(index_s)
        except ValueError:
            raise ValueError(
                f"--dump-slice index must be an int, got {index_s!r}"
            ) from None
        if not 0 <= index < cfg.grid.shape[axis]:
            raise ValueError(
                f"--dump-slice index {index} outside grid extent "
                f"{cfg.grid.shape[axis]} on axis {axis}"
            )
        dump_slice = (axis, index, dump_path)

    if args.dump_vtk and distributed.is_coordinator():
        # validate writability BEFORE the run (same rule as --dump-slice):
        # a bad path must fail in ms, not after hours + a pod-wide gather
        try:
            with open(args.dump_vtk, "ab"):
                pass
        except OSError as e:
            raise ValueError(f"--dump-vtk path not writable: {e}") from None

    from heat3d_tpu.models.heat3d import HeatSolver3D

    log.info(
        "grid=%s stencil=%s mesh=%s dtype=%s backend=%s devices=%d",
        cfg.grid.shape, cfg.stencil.kind, cfg.mesh.shape,
        cfg.precision.storage, cfg.backend, len(jax.devices()),
    )
    if (
        cfg.run.tolerance is not None
        and cfg.time_blocking != 1
        and cfg.run.residual_every <= 1
    ):
        log.warning(
            "--time-blocking is inactive in convergence mode without "
            "--residual-every K>1: a per-step residual check forces single "
            "updates. Pass --residual-every K with K-1 a multiple of the "
            "blocking factor (the K-1 updates between residual checks run "
            "as supersteps) to recover temporal blocking + the copy-free "
            "carry"
        )
    solver = HeatSolver3D(cfg)

    # cost-analysis provenance: one step_cost ledger event (XLA-counted
    # FLOPs/bytes of the step executable) so `obs summary` can print the
    # run's achieved-vs-peak line. Telemetry fails soft, never the run —
    # the guard covers import-time drift in the perf package too (the
    # same posture bench.harness takes on its row cost fields).
    try:
        from heat3d_tpu.obs.perf.roofline import record_step_cost

        record_step_cost(solver)
    except Exception as e:  # noqa: BLE001 - telemetry fails soft
        log.warning("step_cost telemetry unavailable: %s", e)

    if args.supervise:
        return _main_supervised(args, cfg, solver, dump_slice)

    start_step = 0
    if args.resume and args.checkpoint:
        u, start_step = solver.load_checkpoint(args.checkpoint)
        log.info("resumed from %s at step %d", args.checkpoint, start_step)
    else:
        u = solver.init_state(args.init)

    profile_cm = maybe_profile(cfg.run.profile_dir)
    profile_cm.__enter__()
    try:
        u, elapsed, steps_done, residual = _timed_run(
            args, cfg, solver, u, start_step
        )
    finally:
        # exception-safe: a failed run must still close (and flush) the
        # profiler trace instead of losing it; the bracket covers exactly
        # warmup + the timed loop, as before (checkpoint/report IO stays
        # out of the trace)
        profile_cm.__exit__(None, None, None)

    if args.checkpoint:
        solver.save_checkpoint(args.checkpoint, u, steps_done)

    return _finish(
        args, cfg, solver, u, elapsed, steps_done, start_step, residual,
        dump_slice,
    )


def _timed_run(args, cfg, solver, u, start_step):
    """Warmup + the timed stepping loop; returns
    ``(u, elapsed, steps_done, residual)``."""
    # Warm up the executables this mode will use, outside the timed window
    # (SURVEY.md §3.5: warmup iterations excluded). The dummy field is built
    # per-shard (zeros callback) so no process ever materializes the full
    # global array — same rule as init_state.
    with obs.get().span("warmup"), obs.annotate("warmup"):
        _dummy = solver.zeros_state

        if cfg.run.tolerance is not None:
            # while_loop cond is false at max_steps=0: compiles without
            # advancing
            solver.run_to_convergence(_dummy(), tol=1.0, max_steps=0)
        else:
            u = solver.run(u, 0)
            jax.block_until_ready(solver.step_with_residual(_dummy()))
        # force_sync, not block_until_ready: the latter returns before
        # execution finishes under the axon remote tunnel (utils.timing
        # docstring)
        force_sync(u)

    residual = None
    # One span for the whole timed region ("run_loop", with a `steps`
    # field): the plain loop syncs the device only at the END, so per-chunk
    # sub-spans would record async dispatch time, not execution — the
    # honest per-step latency here is elapsed/steps, observed once. (The
    # SUPERVISED loop force_syncs every chunk and gets real per-chunk
    # spans — see resilience.supervisor.)
    with obs.get().span("run_loop", step_start=start_step) as run_span:
        t0 = time.perf_counter()
        if cfg.run.tolerance is not None:
            result = solver.run_to_convergence(
                u, tol=cfg.run.tolerance, max_steps=cfg.run.num_steps
            )
            u, residual = result.u, result.residual
            done = result.steps
        else:
            total = cfg.run.num_steps
            done = 0
            while done < total:
                # Advance to the next reporting boundary: a residual point,
                # a checkpoint point, or the end. The final step is always a
                # residual step, so exactly `total` updates run — no
                # overshoot.
                boundaries = [total]
                if args.residual_every:
                    boundaries.append(
                        (done // args.residual_every + 1) * args.residual_every
                    )
                if args.checkpoint and args.checkpoint_every:
                    boundaries.append(
                        (done // args.checkpoint_every + 1)
                        * args.checkpoint_every
                    )
                nxt = min(min(boundaries), total)
                n = nxt - done
                want_residual = nxt == total or (
                    args.residual_every and nxt % args.residual_every == 0
                )
                if want_residual:
                    if n > 1:
                        u = solver.run(u, n - 1)
                    u, r2 = solver.step_with_residual(u)
                    residual = float(np.sqrt(np.float64(r2)))
                    log.info(
                        "step %d residual %.6e", start_step + nxt, residual
                    )
                    obs.get().event(
                        "residual",
                        step=start_step + nxt,
                        residual_l2=residual,
                    )
                else:
                    u = solver.run(u, n)
                done = nxt
                if (
                    args.checkpoint
                    and args.checkpoint_every
                    and done % args.checkpoint_every == 0
                    and done < total  # final checkpoint written below
                ):
                    solver.save_checkpoint(
                        args.checkpoint, u, start_step + done
                    )
        force_sync(u)
        elapsed = time.perf_counter() - t0
        run_span.add(steps=done, elapsed_s=elapsed)
    if done:
        obs.REGISTRY.histogram(
            "step_latency_seconds",
            "per-step wall latency (chunk dur / steps)",
        ).observe(elapsed / done)
    return u, elapsed, start_step + done, residual


def _main_supervised(args, cfg, solver, dump_slice) -> int:
    """The --supervise path: the supervisor owns init/resume, checkpoint
    cadence, and recovery; this wrapper owns arg plumbing + reporting."""
    if not args.checkpoint:
        raise ValueError("--supervise requires --checkpoint DIR")
    import os

    from heat3d_tpu.resilience.supervisor import generation_dirs
    from heat3d_tpu.utils import checkpoint as ckpt

    if os.path.exists(
        os.path.join(args.checkpoint, ckpt.MANIFEST)
    ) and not generation_dirs(args.checkpoint):
        # a plain (flat) checkpoint lives here; the supervisor only scans
        # gen-* generations, so proceeding would silently restart at step
        # 0 and orphan the user's progress
        raise ValueError(
            f"--checkpoint {args.checkpoint} holds a plain checkpoint, "
            "not supervised generations — finish it with --resume "
            "(without --supervise), or point --supervise at a fresh "
            "directory"
        )
    if args.resume:
        log.info(
            "--resume is implied by --supervise (auto-resumes from the "
            "newest good generation)"
        )
    if jax.process_count() > 1:
        # single-controller only (supervisor.py docstring): per-process
        # supervisors would race quarantine renames and generation prunes,
        # and desynchronize the collective step loop on recovery
        raise ValueError(
            "--supervise is single-controller: multi-host launches must "
            "supervise from the launcher (relaunch-on-exit resumes from "
            "the shared generations) — drop --supervise here"
        )
    if cfg.run.tolerance is not None:
        raise ValueError(
            "--supervise drives the fixed-step loop; convergence mode "
            "(--tol) is not supervised yet — drop one of the two flags"
        )
    if cfg.run.residual_every:
        # don't silently eat a flag the plain loop honors: supervised
        # chunks land on checkpoint boundaries only; the run still
        # reports its final residual
        log.warning(
            "--residual-every is not supported under --supervise yet; "
            "only the final residual is reported"
        )
    if not args.checkpoint_every:
        # legal (auto-resume + final-checkpoint quarantine still work),
        # but the whole run is then ONE chunk: a mid-run kill restarts
        # from step 0 and any --watchdog budget covers the full run
        log.warning(
            "--supervise without --checkpoint-every K writes no mid-run "
            "generations: an outage loses the whole run, not K steps"
        )
    t0 = time.perf_counter()
    with maybe_profile(cfg.run.profile_dir):
        result = solver.run_supervised(
            total_steps=cfg.run.num_steps,
            ckpt_root=args.checkpoint,
            checkpoint_every=args.checkpoint_every,
            watchdog_s=args.watchdog,
            max_recoveries=args.max_recoveries,
            init=args.init,
            heal_mode=args.heal_mode,
            reexpand=args.reexpand,
            # the platform this run STARTED on: without it, a probe child
            # whose jax silently falls back to CPU would classify a real
            # TPU outage as "backend alive" (re-raise instead of recover)
            # and a heal-wait would accept CPU instantly. In-process
            # recovery stays same-platform; the TPU->CPU cross-mesh
            # resume is the RELAUNCH path (generations on disk).
            want_platform=jax.default_backend(),
        )
    elapsed = time.perf_counter() - t0
    # Honest timing: heal waits are SLEEP, not work — leave them out of
    # the throughput denominator (each recovery's wait is itemized in the
    # supervised record). What remains still includes compile and any
    # redone steps: supervised runs are a resilience surface, not a
    # benchmark — the flag below keeps the number from being mistaken
    # for a calibrated bench row downstream.
    heal_s = sum(r.heal_wait_s for r in result.recoveries)
    busy = max(elapsed - heal_s, 1e-9)
    if result.residual is not None:
        log.info(
            "step %d residual %.6e", result.steps_done, result.residual
        )
    supervised_record = result.to_record()
    supervised_record["timing_note"] = (
        "seconds excludes heal waits but includes compile and redone "
        "steps; not comparable to bench rows"
    )
    # report through the solver that PRODUCED u: a recovery may have
    # rebuilt it (cross-mesh heal), and gather/slice on the stale
    # instance would bind the dead mesh. Same rule for the CONFIG: an
    # elastic re-factorization changed the mesh, and the summary's
    # mesh/provenance must describe the run that finished, not the one
    # that was requested (degraded throughput labeled at the source —
    # the supervised record carries degraded/mesh_shape/refactors too)
    final_solver = result.solver or solver
    final_cfg = getattr(final_solver, "cfg", cfg)
    return _finish(
        args, final_cfg, final_solver, result.u, busy,
        result.steps_done, result.start_step, result.residual, dump_slice,
        extra_summary={"supervised": supervised_record},
    )


def _finish(
    args, cfg, solver, u, elapsed, steps_done, start_step, residual,
    dump_slice, extra_summary=None,
) -> int:
    """Post-run reporting shared by the plain and supervised paths:
    dumps, throughput summary, golden check, coordinator JSON."""
    slice_path = None
    if dump_slice is not None:
        axis, index, slice_path = dump_slice
        plane = solver.gather_slice(u, axis, index)  # all processes join
        if distributed.is_coordinator():
            np.save(slice_path, plane)
            log.info(
                "dumped slice axis=%d index=%d shape=%s -> %s",
                axis, index, plane.shape, slice_path,
            )

    vtk_path = None
    if args.dump_vtk:
        from heat3d_tpu.utils.vtkio import write_structured_points

        full = solver.gather(u)  # collective: all processes join
        if distributed.is_coordinator():
            write_structured_points(
                args.dump_vtk, full, spacing=cfg.grid.spacing
            )
            vtk_path = args.dump_vtk
            log.info("dumped VTK field %s -> %s", full.shape, vtk_path)

    cells = cfg.grid.num_cells
    updates = cells * max(steps_done - start_step, 1)
    n_dev = cfg.mesh.num_devices
    summary = {
        "grid": list(cfg.grid.shape),
        "stencil": cfg.stencil.kind,
        "equation": cfg.equation,
        "integrator": cfg.integrator,
        "mesh": list(cfg.mesh.shape),
        "dtype": cfg.precision.storage,
        "backend": cfg.backend,
        # platform provenance (same contract as bench rows): a CPU-fallback
        # line must be distinguishable from an on-chip one downstream
        "platform": jax.default_backend(),
        "steps": steps_done - start_step,
        "seconds": elapsed,
        "residual_l2": residual,
        "gcell_updates_per_sec": updates / elapsed / 1e9,
        "gcell_updates_per_sec_per_chip": updates / elapsed / 1e9 / n_dev,
    }
    if slice_path is not None:
        summary["slice_path"] = slice_path
    if vtk_path is not None:
        summary["vtk_path"] = vtk_path
    if extra_summary:
        summary.update(extra_summary)

    if args.golden_check:
        if cfg.integrator != "explicit-euler":
            raise SystemExit(
                f"--golden-check covers the explicit-Euler oracle only "
                f"(integrator={cfg.integrator!r}); the per-integrator "
                "accuracy gates live in tests/test_timeint.py "
                "(docs/INTEGRATORS.md)"
            )
        from heat3d_tpu.core import golden

        # steps_done counts from t=0 even on --resume: the golden model must
        # advance the original init by the run's TOTAL step count, not just
        # the resumed segment.
        # the fp64 oracle steps the SPEC-compiled taps (identical to the
        # legacy derivation for heat), so --golden-check covers every
        # equation family, not just heat (docs/EQUATIONS.md)
        from heat3d_tpu import eqn

        g = golden.run(
            golden.make_init(args.init, cfg.grid.shape, seed=cfg.run.seed),
            cfg.grid, cfg.stencil, steps_done,
            taps=eqn.solver_taps(cfg),
        )
        got = solver.gather(u).astype(np.float64)
        err = float(np.max(np.abs(got - g)))
        rel = err / max(float(np.max(np.abs(g))), 1e-300)
        summary["golden_max_abs_err"] = err
        summary["golden_rel_err"] = rel
        # tolerance follows the loosest dtype in the chain: bf16 anywhere
        # (storage OR stencil compute) caps accuracy at bf16's ~3
        # decimal digits regardless of how the field is stored
        fp32_chain = (
            cfg.precision.storage == "float32"
            and cfg.precision.compute == "float32"
        )
        tol = 1e-5 if fp32_chain else 5e-2
        summary["golden_pass"] = bool(rel < tol)

    # the ledger's run_summary is the machine-readable mirror of the
    # stdout JSON (every process writes its own ledger; stdout stays
    # coordinator-only), followed by the final per-run metrics record
    obs.get().event("run_summary", **summary)
    obs.get().event("metrics_summary", metrics=obs.REGISTRY.snapshot())
    if distributed.is_coordinator():
        emit_json(summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
