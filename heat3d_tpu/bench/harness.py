"""Benchmark implementations.

Reference parity (SURVEY.md §3.5): the reference times warmup-excluded
iterations between barriers and prints Gcell/s; halo latency is the p50 of
a separately timed exchange-only program (the MPI_Waitall cost the
CUDA-aware path exists to minimize). Here both are separately jitted XLA
programs timed with block_until_ready.
"""

from __future__ import annotations

import dataclasses
import json
import sys
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from heat3d_tpu.core.config import SolverConfig
from heat3d_tpu.models.heat3d import HeatSolver3D
from heat3d_tpu.parallel.step import exchange
from heat3d_tpu.parallel.topology import build_mesh, field_sharding
from heat3d_tpu.utils.timing import (
    force_sync,
    percentile,
    sync_overhead,
    time_fn_batched,
)


def bench_throughput(
    cfg: SolverConfig,
    steps: int = 50,
    warmup: int = 2,
    repeats: int = 3,
) -> Dict:
    """Gcell-updates/sec (total and per chip) of the compiled time loop.

    ``repeats`` timed runs of a ``steps``-iteration device-side loop; the
    best run is reported (matching how the reference class reports its
    timing: minimum over repetitions cancels host jitter)."""
    solver = HeatSolver3D(cfg)
    u = solver.init_state("hot-cube")
    n = jnp.int32(steps)

    # The multistep executable donates its input, so thread the field through
    # successive calls (physically: the run just keeps time-stepping).
    # force_sync (not block_until_ready — a no-op under the axon tunnel) plus
    # subtraction of the measured host round trip gives honest device time.
    import time as _time

    for _ in range(warmup):
        u = solver.run(u, n)
        force_sync(u)
    rtt = sync_overhead(probe=jnp.zeros((8, 128)))
    times = []
    raw_times = []
    for _ in range(repeats):
        t0 = _time.perf_counter()
        u = solver.run(u, n)
        force_sync(u)
        raw = _time.perf_counter() - t0
        raw_times.append(raw)
        # never let RTT subtraction remove >95% of a sample: a measurement
        # that small is RTT-dominated and flagged invalid below, not
        # fabricated into an absurd throughput
        times.append(max(raw - rtt, 0.05 * raw))
    best = min(times)
    rtt_dominated = min(raw_times) < 2 * rtt
    updates = cfg.grid.num_cells * steps
    gcells = updates / best / 1e9
    return {
        "bench": "throughput",
        "grid": list(cfg.grid.shape),
        "stencil": cfg.stencil.kind,
        "mesh": list(cfg.mesh.shape),
        "dtype": cfg.precision.storage,
        "backend": cfg.backend,
        "time_blocking": cfg.time_blocking,
        "overlap": cfg.overlap,
        "halo": cfg.halo,
        "steps": steps,
        "seconds_best": best,
        "seconds_all": times,
        "sync_rtt": rtt,
        "rtt_dominated": rtt_dominated,
        "gcell_per_sec": gcells,
        "gcell_per_sec_per_chip": gcells / cfg.mesh.num_devices,
    }


def bench_halo(
    cfg: SolverConfig,
    iters: int = 30,
    warmup: int = 3,
    batch: int = 10,
) -> Dict:
    """p50/p95 latency of one full 3D ghost exchange (6 faces via 3
    axis-ordered ppermute pairs) as its own XLA program — the judged
    halo-exchange latency metric.

    Each sample amortizes ``batch`` asynchronously dispatched exchanges
    per device sync (time_fn_batched), so the host round trip — ~75 ms
    over the axon tunnel, which dwarfs a single exchange — contributes
    rtt/batch per call instead of rtt, and the reported percentiles
    measure device-side exchange latency."""
    mesh = build_mesh(cfg.mesh)
    sharding = field_sharding(mesh, cfg.mesh)
    spec = P(*cfg.mesh.axis_names)

    # exchange routes through the configured transport (ppermute or the
    # Pallas remote-DMA kernels), so the judged halo p50 covers both tiers.
    ex = jax.jit(
        jax.shard_map(
            lambda x: exchange(x, cfg),
            mesh=mesh,
            in_specs=spec,
            out_specs=spec,
            check_vma=False,
        )
    )
    u = jax.device_put(
        jnp.zeros(cfg.padded_shape, jnp.dtype(cfg.precision.storage)), sharding
    )
    rtt = sync_overhead(probe=jnp.zeros((8, 128)))
    # all `batch` in-flight outputs stay live on device until the sync;
    # cap their total at ~1/4 of a 16 GB chip so large grids don't OOM a
    # benchmark that used to run (padded field bytes per call)
    out_bytes = u.size * u.dtype.itemsize
    batch = max(1, min(batch, int(4e9 // max(out_bytes, 1))))
    raw = time_fn_batched(ex, u, warmup=warmup, iters=iters, batch=batch)
    # each per-call sample carries rtt/batch of host round trip; the
    # honesty guard still refuses to fabricate sub-5% residuals
    times = [max(t - rtt / batch, 0.05 * t) for t in raw]
    rtt_dominated = percentile(raw, 50) * batch < 2 * rtt
    face_cells = (
        cfg.local_shape[1] * cfg.local_shape[2]
        + cfg.local_shape[0] * cfg.local_shape[2]
        + cfg.local_shape[0] * cfg.local_shape[1]
    )
    bytes_per_dev = 2 * face_cells * jnp.dtype(cfg.precision.storage).itemsize
    return {
        "bench": "halo",
        "grid": list(cfg.grid.shape),
        "mesh": list(cfg.mesh.shape),
        "dtype": cfg.precision.storage,
        "iters": iters,
        "batch": batch,
        "p50_us": percentile(times, 50) * 1e6,
        "p95_us": percentile(times, 95) * 1e6,
        "min_us": min(times) * 1e6,
        "sync_rtt_us": rtt * 1e6,
        "rtt_dominated": rtt_dominated,
        "halo_bytes_per_device": bytes_per_dev,
    }


def run_suite(configs: List[SolverConfig], steps: int = 50, out=None) -> List[Dict]:
    """Run throughput + halo for each config; emit one JSON line per result."""
    out = out or sys.stdout
    results = []
    for cfg in configs:
        for fn, kw in ((bench_throughput, {"steps": steps}), (bench_halo, {})):
            r = fn(cfg, **kw)
            results.append(r)
            print(json.dumps(r), file=out, flush=True)
    return results
