"""Benchmark implementations.

Reference parity (SURVEY.md §3.5): the reference times warmup-excluded
iterations between barriers and prints Gcell/s; halo latency is the p50 of
a separately timed exchange-only program (the MPI_Waitall cost the
CUDA-aware path exists to minimize). Here both are separately jitted XLA
programs timed with block_until_ready.
"""

from __future__ import annotations

import dataclasses
import json
import sys
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from heat3d_tpu import obs
from heat3d_tpu.core.config import SolverConfig
from heat3d_tpu.models.heat3d import HeatSolver3D
from heat3d_tpu.parallel.step import exchange
from heat3d_tpu.parallel.topology import build_mesh, field_sharding
from heat3d_tpu.utils.compat import shard_map
from heat3d_tpu.utils.timing import (
    calibrate_trip_count,
    force_sync,
    honest_time,
    percentile,
    sync_overhead,
)


def _ledger_bench_row(row: Dict) -> None:
    """Mirror a measured row into the run ledger. The row's ``ts`` (UTC
    measurement-time string, the provenance key check_provenance.py
    requires) collides with the ledger envelope's ``ts`` (unix float at
    write time) — respell it ``ts_`` (the documented trailing-underscore
    rule) so a consumer can still join ledger events to
    bench_results.jsonl rows by timestamp."""
    obs.get().event(
        "bench_row", **{("ts_" if k == "ts" else k): v for k, v in row.items()}
    )


def _utc_now() -> str:
    import datetime

    return (
        datetime.datetime.now(datetime.timezone.utc)
        .strftime("%Y-%m-%dT%H:%M:%SZ")
    )


def bench_throughput(
    cfg: SolverConfig,
    steps: int = 50,
    warmup: int = 2,
    repeats: int = 3,
) -> Dict:
    """Gcell-updates/sec (total and per chip) of the compiled time loop.

    ``repeats`` timed runs of a device-side loop; the best run is reported
    (matching how the reference class reports its timing: minimum over
    repetitions cancels host jitter). ``steps`` is a floor: the step count
    is auto-calibrated UP until the program's device time swamps the host
    round trip (the multistep executable takes the trip count dynamically,
    so calibration costs no recompiles) — without this, small grids finish
    in single-digit ms under a ~75 ms tunnel RTT and every row is
    RTT-dominated no matter how the arithmetic subtracts it."""
    solver = HeatSolver3D(cfg)
    u = solver.init_state("hot-cube")

    # The multistep executable donates its input, so thread the field through
    # successive calls (physically: the run just keeps time-stepping).
    # force_sync (not block_until_ready — a no-op under the axon tunnel) plus
    # subtraction of the measured host round trip gives honest device time.
    import time as _time

    for _ in range(warmup):
        u = solver.run(u, jnp.int32(steps))
        force_sync(u)
    rtt = sync_overhead(probe=jnp.zeros((8, 128)))

    def _timed(n):
        nonlocal u
        t0 = _time.perf_counter()
        u = solver.run(u, jnp.int32(n))
        force_sync(u)
        return _time.perf_counter() - t0

    steps_requested = steps
    steps, raw = calibrate_trip_count(_timed, rtt, start=steps)
    raw_times = [raw] + [_timed(steps) for _ in range(repeats - 1)]
    times = [honest_time(t, rtt) for t in raw_times]
    best = min(times)
    rtt_dominated = min(raw_times) < 2 * rtt
    updates = cfg.grid.num_cells * steps
    gcells = updates / best / 1e9
    # one consistent evaluation of the env-dependent route/selector state
    # for all the provenance fields (each walks the real dispatch)
    mehrstellen = _mehrstellen_route(cfg)
    # the fused RDMA route wins the dispatch when it resolves
    # (make_step_fn / make_superstep_fn try it ahead of the direct and
    # streamk families), so the other route fields must mirror that order
    fused_rdma = _resolved_fused_rdma(cfg)
    direct = False if fused_rdma else _resolved_direct(cfg)
    fused = _resolved_fused_dma(cfg)
    streamk = (
        False if fused_rdma else _resolved_streamk(cfg, direct=direct)
    )
    from heat3d_tpu.parallel.step import _kernel_env_gate

    # the fused routes have an off-TPU emulation tier (interpret mode /
    # the pure-XLA reference contracts under HEAT3D_DIRECT_INTERPRET):
    # record it EXPLICITLY so A/B tooling cannot mistake an emulated row
    # for a real Mosaic-kernel row without cross-checking the platform
    fused_emulated = bool(fused and _kernel_env_gate(cfg)[1])
    streamk_emulated = bool(streamk and _kernel_env_gate(cfg)[1])
    fused_rdma_emulated = bool(
        fused_rdma
        and _kernel_env_gate(cfg, allow_partitioned_plan=True)[1]
    )
    # cost-analysis provenance (obs/perf/roofline): XLA's own FLOPs/bytes
    # for ONE step of this config, so a row's achieved-vs-peak is
    # computable from the row alone (`obs summary` roofline section,
    # `obs roofline`). One extra step-program compile per row
    # (HEAT3D_COST_ANALYSIS=0 skips); failures leave the fields null with
    # the error recorded — telemetry never fails the row.
    cost_fields = {"cost_flops_per_step": None, "cost_bytes_per_step": None}
    try:
        from heat3d_tpu.obs.perf.roofline import (
            cost_analysis_enabled,
            step_cost_fields,
        )

        if cost_analysis_enabled():
            cost_fields.update(step_cost_fields(solver))
    except Exception as e:  # noqa: BLE001 - telemetry fails soft, incl.
        # import-time drift in the perf package: the measured row lands
        # with null cost fields + the error, never dies
        cost_fields["cost_analysis_error"] = (
            f"{type(e).__name__}: {str(e)[:120]}"
        )
    row = {
        "bench": "throughput",
        # measurement time (UTC): lets a later outage round's fallback
        # prove WHICH session a carried committed row came from
        "ts": _utc_now(),
        # platform provenance: bench_results.jsonl is the on-chip record
        # by convention, but only this field makes a stray CPU row
        # detectable (bench.py's fallback filters on it)
        "platform": jax.default_backend(),
        "grid": list(cfg.grid.shape),
        "stencil": cfg.stencil.kind,
        # equation-family provenance (REQUIRED by check_provenance.py on
        # every throughput row): families share footprints but not
        # chains/stability envelopes, so a reaction-diffusion rate must
        # never baseline against — or masquerade as — a heat rate
        # (obs regress keys on it; legacy rows key to heat)
        "equation": cfg.equation,
        # integrator provenance (REQUIRED by check_provenance.py on every
        # throughput row): a CG solve's step does many matvecs and a
        # leapfrog step carries two levels — their Gcell/s must never
        # baseline against (or masquerade as) the explicit sweep (obs
        # regress keys on it; legacy rows key to explicit-euler)
        "integrator": cfg.integrator,
        "mesh": list(cfg.mesh.shape),
        "dtype": cfg.precision.storage,
        "compute_dtype": cfg.precision.compute,
        "backend": cfg.backend,
        "time_blocking": cfg.time_blocking,
        "overlap": cfg.overlap,
        "halo": cfg.halo,
        "halo_order": cfg.halo_order,
        # exchange-plan provenance (knob-drift + ROUTE_FIELDS contract):
        # a partitioned row's traffic is byte-identical to monolithic but
        # its message schedule is not — the A/B must be keyable from the
        # row alone. The EFFECTIVE mode is recorded (HEAT3D_NO_PLAN
        # degrades partitioned to the ad-hoc monolithic schedule; the
        # row must say what ran — docs/TUNING.md "Persistent exchange
        # plans")
        "halo_plan": _effective_halo_plan(cfg),
        # fused-RDMA knob provenance (the five-surface knob contract):
        # the EFFECTIVE value — HEAT3D_FUSED_RDMA override included,
        # 'auto' resolved — so an env-forced A/B row is keyable from the
        # row alone (obs regress/sweepstate key on it; legacy rows key
        # to off)
        "fused_rdma": _effective_fused_rdma(cfg),
        "steps": steps,
        "steps_requested": steps_requested,
        # ensemble-workload provenance (REQUIRED by check_provenance.py on
        # every throughput row): the solo bench advances one member per
        # step call. Ensemble rows (serve.bench.bench_ensemble_throughput)
        # carry [B]/B here, and gcell_per_sec counts every member's
        # updates — per-member effective rate = gcell_per_sec /
        # members_per_step, which obs summary/regress report so a packed
        # batch's total can never masquerade as a single-run rate.
        "batch_shape": [1],
        "members_per_step": 1,
        "seconds_best": best,
        "seconds_all": times,
        "sync_rtt": rtt,
        # canonical RTT provenance field (seconds) — REQUIRED by
        # scripts/check_provenance.py on every bench row, so an
        # RTT-dominated sample is auditable from the row alone
        "sync_rtt_s": rtt,
        "rtt_dominated": rtt_dominated,
        "gcell_per_sec": gcells,
        "gcell_per_sec_per_chip": gcells / cfg.mesh.num_devices,
        # Emitted-chain provenance: the factoring knobs are env vars, so
        # without this a HEAT3D_FACTOR_Y=0 A/B row is indistinguishable
        # from a default suite row, and analysis tools re-deriving the op
        # count later (under a different env) would mislabel it.
        "chain_ops": _chain_ops(cfg, mehrstellen=mehrstellen),
        "mehrstellen_route": mehrstellen,
        # Same provenance need for the transport knob: HEAT3D_NO_DIRECT=1
        # A/B rows carry identical config fields to direct rows but run
        # the exchange path at ~2x the HBM traffic — record the RESOLVED
        # selection (the real selector, not the env) so the traffic model
        # can't mislabel them.
        "direct_path": direct,
        # overlap+halo='dma' rows: whether the fused DMA-overlap kernel
        # (vs an error'd/jnp fallback elsewhere) actually resolved —
        # the pod A/B vs faces-direct needs the RESOLVED route on record
        "fused_dma_path": fused,
        # ... and whether that resolution was the XLA reference EMULATION
        # tier rather than the Mosaic kernel (ADVICE r5 item 2)
        "fused_dma_emulated": fused_emulated,
        # deep-tb route provenance: whether the fused k-sweep streaming
        # kernel resolved (tb=3..4); without it a tb=3 row's traffic model
        # can't distinguish one fused sweep from k plain sweeps. The
        # _emulated twin marks interpret-tier resolutions (same contract
        # as fused_dma_emulated).
        "streamk_path": streamk,
        "streamk_emulated": streamk_emulated,
        # fused in-kernel RDMA route: whether the plan-scheduled fused
        # superstep actually resolved (vs the jnp plan-exchange fallback
        # elsewhere) — the fused-vs-unfused A/B needs the RESOLVED route
        # on record, and the _emulated twin marks reference-contract
        # resolutions (same contract as fused_dma_emulated)
        "fused_rdma_path": fused_rdma,
        "fused_rdma_emulated": fused_rdma_emulated,
        # redundant-compute honesty (required by check_provenance.py on
        # tb>1 rows): fraction of the superstep's executed stencil flops
        # that are ghost-ring recompute — the discount between this row's
        # measured Gcell/s and what the chip actually sustained
        "cost_redundant_flops_frac": _redundant_frac(cfg),
        **cost_fields,
    }
    _ledger_bench_row(row)
    obs.REGISTRY.histogram(
        "bench_step_latency_seconds", "bench throughput per-step latency"
    ).observe(best / steps)
    return row


def _effective_halo_plan(cfg: SolverConfig) -> str:
    """The ONE effective-mode rule (parallel.plan.effective_halo_plan):
    what the rows record is what executed, incl. the HEAT3D_NO_PLAN
    degradation. No fail-soft wrapper: the function is pure env+config
    inspection, and if parallel.plan itself cannot import, the solver
    that produced the measurement could not have run either."""
    from heat3d_tpu.parallel.plan import effective_halo_plan

    return effective_halo_plan(cfg)


def _resolved_streamk(cfg: SolverConfig, direct: bool = None) -> bool:
    """Whether this config's superstep resolves to the fused k-sweep
    streaming kernel (parallel.step._fused_streamk_fn — tb=2..4, TPU or
    the interpret env, VMEM-feasible slab). Mirrors make_superstep_fn's
    dispatch ORDER: at tb=2 the no-padded-copy direct2 kernel is
    preferred, so a row it takes must not be labeled streamk (the two
    routes have different traffic shapes in the roofline row model).
    Pass ``direct`` when _resolved_direct was already evaluated — the
    feasibility walk (env gate + VMEM/tap-stack math) is not free."""
    from heat3d_tpu.parallel.step import _fused_streamk_fn

    if _fused_streamk_fn(cfg) is None:
        return False
    if cfg.time_blocking != 2:
        return True
    if direct is None:
        direct = _resolved_direct(cfg)
    return not direct


def _redundant_frac(cfg: SolverConfig) -> float:
    """parallel.step.redundant_flops_frac, fail-open to 0.0 only for
    tb<=1 (where no superstep exists); tb>1 derivation is pure local
    arithmetic and cannot fail."""
    from heat3d_tpu.parallel.step import redundant_flops_frac

    return redundant_flops_frac(cfg)


def _resolved_fused_dma(cfg: SolverConfig) -> bool:
    """Whether this config's hot path resolves to a fused DMA-overlap
    kernel (parallel.step._fused_dma_fn / _fused_dma_3d_fn /
    _fused_dma2_fn — overlap+halo='dma'; slab scope, the x-sharded block
    generalization, or the tb=2 superstep form, matching what the time
    loop runs)."""
    from heat3d_tpu.parallel.step import (
        _fused_dma2_fn,
        _fused_dma_3d_fn,
        _fused_dma_fn,
    )

    if cfg.time_blocking == 2:
        return _fused_dma2_fn(cfg) is not None
    if cfg.time_blocking == 1:
        return (
            _fused_dma_fn(cfg) is not None
            or _fused_dma_3d_fn(cfg) is not None
        )
    return False


def _resolved_fused_rdma(cfg: SolverConfig) -> bool:
    """Whether this config's hot path resolves to the fused in-kernel
    RDMA superstep (parallel.step._fused_rdma_fn / _fused_rdma2_fn —
    fused_rdma='on' / HEAT3D_FUSED_RDMA, 1D x-slab scope, plan-scheduled
    sends, tb <= 2), matching what the time loop runs."""
    from heat3d_tpu.parallel.step import _fused_rdma2_fn, _fused_rdma_fn

    if cfg.time_blocking == 2:
        return _fused_rdma2_fn(cfg) is not None
    if cfg.time_blocking <= 1:
        return _fused_rdma_fn(cfg) is not None
    return False


def _effective_fused_rdma(cfg: SolverConfig) -> str:
    """The ONE effective-knob rule (parallel.step.resolve_fused_rdma):
    rows record what the dispatcher saw — HEAT3D_FUSED_RDMA override
    included, 'auto' resolved to its static fallback — mirroring the
    halo_plan effective-mode posture."""
    from heat3d_tpu.parallel.step import resolve_fused_rdma

    return resolve_fused_rdma(cfg)


def _resolved_direct(cfg: SolverConfig) -> bool:
    """Whether this config's step resolves to the BC-fused direct kernels
    (parallel.step._direct_kernel_fn — honors HEAT3D_NO_DIRECT, VMEM
    feasibility, dtype support, and the faces-direct multichip tier)."""
    from heat3d_tpu.parallel.step import _direct_kernel_fn

    if cfg.halo != "ppermute" or cfg.time_blocking not in (1, 2):
        return False
    # multichip=True verbatim like both step builders (step.py's tb=1 and
    # tb=2 call sites); _direct_kernel_fn itself owns the mesh gating
    return _direct_kernel_fn(
        cfg, cfg.time_blocking, multichip=True
    ) is not None


def _chain_ops(cfg: SolverConfig, mehrstellen: bool = None) -> int:
    """Vector ops/cell/update of the local compute this config runs under
    the CURRENT env: the mehrstellen separable route's canonical count
    when that route is what executes (knob on + taps decompose + the
    resolved local compute implements it — the jnp apply, or the q-ring
    direct kernels at tb=1/tb=2), else the tap chain's
    effective_num_taps.
    Recorded per row; scripts/roofline_check.py prefers this over
    re-derivation. ``mehrstellen`` takes a precomputed _mehrstellen_route
    result so one env evaluation feeds every provenance field."""
    from heat3d_tpu.core.stencils import MEHRSTELLEN_OPS, chain_ops_for

    if cfg.backend == "conv":
        return None  # one conv op, not a tap chain — op count n/a
    if mehrstellen is None:
        mehrstellen = _mehrstellen_route(cfg)
    if mehrstellen:
        return MEHRSTELLEN_OPS
    if cfg.equation != "heat":
        # spec-built families: count the ACTUAL lowered chain (asymmetric
        # taps — e.g. advection — defeat the x/y factoring, so the heat
        # kind's nominal count would misstate the emitted ops)
        from heat3d_tpu.core.stencils import effective_num_taps
        from heat3d_tpu.parallel.step import _solver_taps

        return effective_num_taps(_solver_taps(cfg))
    return chain_ops_for(cfg.stencil.kind)


def _mehrstellen_route(cfg: SolverConfig) -> bool:
    """Whether the separable S+F route actually executes for this config:
    knob on, taps decompose, and the local compute implements it — the
    jnp apply (explicit --backend jnp, or auto off-TPU) or the q-ring
    direct kernels (tb=1 single step, tb=2 fused superstep). The windowed
    exchange-path kernels keep the tap chain."""
    from heat3d_tpu.core.stencils import (
        decompose_mehrstellen,
        mehrstellen_enabled,
    )
    from heat3d_tpu.parallel.step import _solver_taps

    if not mehrstellen_enabled():
        return False
    # the solver's own tap construction, so route provenance can't diverge
    # from what executes
    if decompose_mehrstellen(_solver_taps(cfg)) is None:
        return False
    # the solver's own resolution (models.heat3d.resolved_backend_name):
    # auto falls back to the jnp apply whenever the Pallas kernels
    # can't run this config — in which case the route DOES execute
    from heat3d_tpu.models.heat3d import resolved_backend_name

    backend = resolved_backend_name(cfg)
    if backend == "jnp":
        return True
    return cfg.time_blocking in (1, 2) and _resolved_direct(cfg)


def bench_halo(
    cfg: SolverConfig,
    iters: int = 10,
    warmup: int = 2,
    k: Optional[int] = None,
) -> Dict:
    """p50/p95 latency of one full 3D ghost exchange (6 faces via 3
    axis-ordered ppermute pairs) — the judged halo-exchange latency metric.

    Methodology (the same trick ``bench_throughput`` uses): a DEVICE-SIDE
    ``fori_loop`` of ``k`` back-to-back exchanges is compiled as one XLA
    program, the whole program is timed with one sync, and the per-exchange
    latency is (wall - rtt) / k. The loop carry is the local block with
    each of its six boundary faces overwritten by the received ghost face
    on that side — ALL six ppermutes are data-live every iteration so XLA
    cannot DCE any of them, the carry shape stays fixed, and the
    non-exchange work charged per iteration is six FACE-sized in-place
    updates, not a volume reduction (which would inflate the judged p50
    by a volume's worth of HBM traffic). ``k`` is
    auto-scaled until device time swamps the host round trip (the ~75 ms
    axon-tunnel RTT that made every host-dispatched sample RTT-dominated in
    round 2), so ``rtt_dominated`` rows should only appear for
    micro-exchanges on extreme-RTT links.

    Tail honesty: averaging k exchanges per sample necessarily dilutes
    per-exchange latency spikes, so the tail field is named
    ``p95_mean_us`` — the 95th percentile of per-PROGRAM means — and must
    not be read as per-exchange tail latency (which is unobservable
    through a high-RTT host link; the judged metric is the p50).

    On a (1,1,1) mesh no collective executes (size-1 axes short-circuit to
    self-wrap / BC fill): such rows measure the local pad/crop cost only
    and are labeled ``ici: false``.
    """
    mesh = build_mesh(cfg.mesh)
    sharding = field_sharding(mesh, cfg.mesh)
    spec = P(*cfg.mesh.axis_names)
    local = cfg.local_shape

    # exchange routes through the configured transport (ppermute or the
    # Pallas remote-DMA kernels), so the judged halo p50 covers both tiers.
    nx, ny, nz = local

    def _loop(u_local, n):
        def body(_, u):
            p = exchange(u, cfg)  # (nx+2, ny+2, nz+2), ghosts filled
            # fold each received ghost face onto the carry's boundary face
            # (in-place DUS on the loop carry: face-sized writes only)
            out = u
            out = out.at[0].set(p[0, 1 : 1 + ny, 1 : 1 + nz])
            out = out.at[nx - 1].set(p[nx + 1, 1 : 1 + ny, 1 : 1 + nz])
            out = out.at[:, 0].set(p[1 : 1 + nx, 0, 1 : 1 + nz])
            out = out.at[:, ny - 1].set(p[1 : 1 + nx, ny + 1, 1 : 1 + nz])
            out = out.at[:, :, 0].set(p[1 : 1 + nx, 1 : 1 + ny, 0])
            out = out.at[:, :, nz - 1].set(p[1 : 1 + nx, 1 : 1 + ny, nz + 1])
            return out

        return jax.lax.fori_loop(0, n, body, u_local)

    run_n = jax.jit(
        shard_map(
            _loop,
            mesh=mesh,
            in_specs=(spec, P()),
            out_specs=spec,
            check_vma=False,
        )
    )
    u = jax.device_put(
        jnp.zeros(cfg.padded_shape, jnp.dtype(cfg.precision.storage)), sharding
    )
    import time as _time

    for _ in range(warmup):
        force_sync(run_n(u, jnp.int32(1)))
    rtt = sync_overhead(probe=jnp.zeros((8, 128)))

    def _timed(n):
        t0 = _time.perf_counter()
        force_sync(run_n(u, jnp.int32(n)))
        return _time.perf_counter() - t0

    if k is None:
        k, _ = calibrate_trip_count(_timed, rtt, start=25)
    raws = [_timed(k) for _ in range(iters)]
    times = [honest_time(t, rtt) / k for t in raws]
    rtt_dominated = min(raws) < 2 * rtt
    face_cells = (
        cfg.local_shape[1] * cfg.local_shape[2]
        + cfg.local_shape[0] * cfg.local_shape[2]
        + cfg.local_shape[0] * cfg.local_shape[1]
    )
    bytes_per_dev = 2 * face_cells * jnp.dtype(cfg.precision.storage).itemsize
    halo_hist = obs.REGISTRY.histogram(
        "halo_exchange_latency_seconds",
        "per-exchange halo latency (program mean)",
    )
    for t in times:
        halo_hist.observe(t)
    # cost-analysis provenance for halo rows (ROADMAP open item): XLA's
    # bytes for ONE exchange via the `halo_exchange` phase program, so the
    # halo p50 gets its own achieved-vs-peak fraction in `obs roofline` /
    # `obs summary` without joining against a throughput row. Same
    # fail-soft posture as the throughput cost fields.
    halo_cost = {"cost_bytes_per_step": None}
    try:
        from heat3d_tpu.obs.perf.roofline import (
            cost_analysis_enabled,
            halo_cost_fields,
        )

        if cost_analysis_enabled():
            halo_cost.update(halo_cost_fields(cfg))
    except Exception as e:  # noqa: BLE001 - telemetry fails soft
        halo_cost["cost_analysis_error"] = (
            f"{type(e).__name__}: {str(e)[:120]}"
        )
    # planned-exchange provenance + the plan's own transport model
    # (messages and boundary bytes per device per exchange) beside XLA's
    # cost bytes — the roofline's planned-exchange arm reads these. The
    # model prices the EFFECTIVE schedule (see _effective_halo_plan).
    # Fail-soft like every other telemetry field on the row.
    eff_hp = _effective_halo_plan(cfg)
    plan_fields = {}
    try:
        from heat3d_tpu.parallel.plan import plan_for

        t = plan_for(
            dataclasses.replace(cfg, halo_plan=eff_hp)
        ).traffic(
            cfg.local_shape, jnp.dtype(cfg.precision.storage).itemsize
        )
        plan_fields = {
            "plan_messages_per_exchange": t["messages"],
            "plan_bytes_per_device": t["bytes_per_device"],
        }
    except Exception as e:  # noqa: BLE001 - telemetry fails soft
        plan_fields = {
            "plan_model_error": f"{type(e).__name__}: {str(e)[:120]}"
        }
    row = {
        "bench": "halo",
        "ts": _utc_now(),
        "platform": jax.default_backend(),
        "grid": list(cfg.grid.shape),
        "mesh": list(cfg.mesh.shape),
        "dtype": cfg.precision.storage,
        "halo_order": cfg.halo_order,
        "halo_plan": eff_hp,
        **plan_fields,
        "iters": iters,
        "exchanges_per_program": k,
        "p50_us": percentile(times, 50) * 1e6,
        "p95_mean_us": percentile(times, 95) * 1e6,
        "min_us": min(times) * 1e6,
        "sync_rtt_us": rtt * 1e6,
        # canonical RTT provenance field, same contract as throughput rows
        "sync_rtt_s": rtt,
        "rtt_dominated": rtt_dominated,
        "ici": cfg.mesh.num_devices > 1,
        "halo_bytes_per_device": bytes_per_dev,
        **halo_cost,
    }
    _ledger_bench_row(row)
    # opt-in per-link probe (HEAT3D_COMM_PROBE): time each (axis,
    # direction, sub-block) collective as its own micro-program and emit
    # comm_probe rows beside this bench row — predicted-vs-achieved GB/s
    # per link (docs/OBSERVABILITY.md §9). maybe_probe is env-gated and
    # fails soft; the import guard covers torn installs the same way the
    # other telemetry on this row does.
    try:
        from heat3d_tpu.obs.comm.probe import maybe_probe

        maybe_probe(cfg)
    except Exception as e:  # noqa: BLE001 - telemetry fails soft
        print(f"bench: comm probe skipped ({e})", file=sys.stderr)
    return row


def run_suite(
    configs: List[SolverConfig],
    steps: int = 50,
    out=None,
    state_path: Optional[str] = None,
) -> List[Dict]:
    """Run throughput for each config + halo once per distinct exchange
    shape; emit one JSON line per result.

    The halo latency depends only on (grid, mesh, storage dtype, transport)
    — not on tb/backend/stencil — so configs differing only in those knobs
    share one halo row instead of re-measuring it (the duplicate-row noise
    in the round-2 tables).

    With ``state_path``, every landed row is journaled in a
    :class:`~heat3d_tpu.resilience.sweepstate.SweepState` and an
    interrupted sweep (SIGTERM, backend death) RESUMES AT THE FIRST
    MISSING ROW on the next invocation — completed rows are re-emitted
    from the journal, not re-measured. Fault hooks
    (``HEAT3D_FAULTS=sigterm:row=K``) fire per row so the resume path is
    testable on CPU."""
    from heat3d_tpu.resilience.faults import FaultPlan
    from heat3d_tpu.resilience.sweepstate import SweepState, row_key

    import os

    out = out or sys.stdout
    state = SweepState(state_path) if state_path else None
    plan = FaultPlan.from_env()
    # On an axon TPU session, only ON-CHIP rows may retire a journal
    # entry: a silent jax CPU fallback still prints a row, and journaling
    # it would freeze a CPU number into the A/B record forever (same rule
    # as tpu_measure_all.sh's row_landed gate). Off the axon env (CPU
    # smoke/test sweeps) every row journals.
    want_platform = (
        "tpu"
        if os.environ.get("PALLAS_AXON_POOL_IPS")
        and os.environ.get("JAX_PLATFORMS") != "cpu"
        else None
    )
    results = []
    halo_seen = set()
    row_index = 0

    def one_row(key: str, measure) -> Dict:
        nonlocal row_index
        if state is not None:
            done = state.record(key)
            if done is not None and done.get("record") is not None:
                r = done["record"]
                results.append(r)
                print(json.dumps(r), file=out, flush=True)
                # re-emitted from the journal, NOT re-measured: the ledger
                # must distinguish the two or a resumed A/B session reads
                # as having measured rows it merely replayed
                obs.get().event("bench_row_replayed", key=key)
                return r
        plan.on_sweep_row(row_index)
        row_index += 1
        with obs.get().span("bench_row_measure", key=key):
            r = measure()
        results.append(r)
        print(json.dumps(r), file=out, flush=True)
        if state is not None:
            if want_platform is None or r.get("platform") == want_platform:
                state.mark_done(key, r)
            else:
                obs.get().event(
                    "bench_row_pending",
                    key=key,
                    platform=r.get("platform"),
                    want_platform=want_platform,
                )
                print(
                    f"suite: row {key} measured on "
                    f"{r.get('platform')!r}, not {want_platform!r} — left "
                    "pending for the next healthy window",
                    file=sys.stderr,
                )
        return r

    for cfg in configs:
        one_row(
            row_key(cfg, "throughput"),
            lambda cfg=cfg: bench_throughput(cfg, steps=steps),
        )
        halo_key = (
            cfg.grid.shape, cfg.mesh.shape, cfg.precision.storage,
            cfg.halo, cfg.halo_order, _effective_halo_plan(cfg),
        )
        if halo_key not in halo_seen:
            halo_seen.add(halo_key)
            one_row(row_key(cfg, "halo"), lambda cfg=cfg: bench_halo(cfg))
    return results
