"""CLI: ``python -m heat3d_tpu.bench`` — run the judged benchmark matrix.

Each BASELINE.md matrix row is expressible: --grid/--mesh/--stencil/--dtype
mirror the solver CLI; --profile-dir wraps the run in a jax.profiler trace
(SURVEY.md §5 'Tracing / profiling').
"""

from __future__ import annotations

import argparse
import sys

import jax

from heat3d_tpu.cli import build_parser, config_from_args
from heat3d_tpu.bench.harness import bench_halo, bench_throughput, run_suite


def main(argv=None) -> int:
    # Suite rows are stopped with `timeout` (SIGTERM) when they overrun;
    # the dying row must release the axon pool's chip claim on the way out.
    from heat3d_tpu.utils.backendprobe import install_sigterm_exit

    install_sigterm_exit()
    base = build_parser()
    p = argparse.ArgumentParser(
        prog="heat3d-bench", parents=[base], add_help=False, conflict_handler="resolve"
    )
    p.add_argument("--bench", choices=["all", "throughput", "halo"], default="all")
    p.add_argument("--repeats", type=int, default=3)
    p.add_argument("--iters", type=int, default=30, help="halo timing iterations")
    p.add_argument(
        "--sweep-state", default=None, metavar="FILE",
        help="journal landed rows here (resilience.sweepstate); an "
        "interrupted --bench all sweep resumes at the first missing row",
    )
    args = p.parse_args(argv)

    from heat3d_tpu import obs
    from heat3d_tpu.utils.timing import maybe_profile

    # --ledger comes in through the inherited solver parser; the env
    # fallback ($HEAT3D_LEDGER) is how run_bench_suite.sh threads ONE
    # ledger through every row's subprocess. Activated BEFORE config
    # validation, so a row dying on a bad config still leaves a record.
    obs.activate(args.ledger, meta={"entry": "bench", "bench": args.bench})
    try:
        cfg = config_from_args(args)
        # tuning-cache resolution of the auto knobs, HERE at the entry
        # point: measured rows must record the CONCRETE route (a row with
        # halo='auto' would corrupt the regression gate's config keys and
        # the roofline traffic model), and bench_halo exercises the
        # transport without ever building a solver
        from heat3d_tpu.tune.cache import resolve_config

        cfg = resolve_config(cfg)
        profile_cm = maybe_profile(args.profile_dir)
        profile_cm.__enter__()
        try:
            if args.bench == "throughput":
                import json

                print(json.dumps(bench_throughput(cfg, steps=args.steps,
                                                  repeats=args.repeats)))
            elif args.bench == "halo":
                import json

                print(json.dumps(bench_halo(cfg, iters=args.iters)))
            else:
                run_suite([cfg], steps=args.steps,
                          state_path=args.sweep_state)
        finally:
            # the profiler trace flushes whatever happened; its own
            # failure falls through to the outer handler, which records
            # it — never masking a clean row as rc=0
            profile_cm.__exit__(None, None, None)
    except BaseException as e:
        # the ledger must record HOW the row ended: a SIGTERM'd
        # (SystemExit via install_sigterm_exit) or crashed row closing
        # with rc=0 would read as a clean run in the post-mortem — the
        # misattribution the ledger exists to prevent
        obs.deactivate(rc=1, error=f"{type(e).__name__}: {str(e)[:200]}")
        raise
    obs.get().event("metrics_summary", metrics=obs.REGISTRY.snapshot())
    obs.export_at_exit()
    obs.deactivate(rc=0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
