"""Render benchmark JSONL into the BASELINE.md measured tables.

Reference parity (SURVEY.md §5 "Metrics / logging"): the reference prints
its timing from rank 0; here benchmark runs emit one JSON line per result
(bench.harness) and this module turns a results file into the markdown
tables in BASELINE.md, between the ``<!-- measured:begin/end -->`` markers,
so the scaling tables regenerate mechanically instead of being hand-edited.

Usage::

    python -m heat3d_tpu.bench --grid 512 ... >> bench_results.jsonl
    python -m heat3d_tpu.bench.report bench_results.jsonl [BASELINE.md]
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List

BEGIN = "<!-- measured:begin -->"
END = "<!-- measured:end -->"


def load_results(path: str) -> List[Dict]:
    out = []
    try:
        f = open(path)
    except OSError:
        # an APPEND session whose every row skipped never creates the
        # record file; that's the zero-row case, not an error
        return out
    with f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(r, dict) and r.get("bench") in ("throughput", "halo"):
                out.append(r)
    return out


def _fmt_grid(grid) -> str:
    if len(set(grid)) == 1:
        return f"{grid[0]}³"
    return "×".join(str(g) for g in grid)


def _fmt_mesh(mesh) -> str:
    return "×".join(str(m) for m in mesh)


def _fmt_route(r: Dict) -> str:
    """Compact route provenance for a throughput row: transport tier
    (direct = BC-fused one-sweep kernel, exch = pad-exchange path), local
    compute route (mehrstellen vs tap chain) and its emitted op count —
    so a committed table row is self-describing without consulting the
    env knobs that were live when it was measured. Rows predating the
    provenance fields (the archived r2 record) render an em dash."""
    if r.get("backend") == "conv":
        return "conv"  # one XLA conv op — neither transport tier applies
    if "direct_path" not in r and "chain_ops" not in r:
        return "—"
    if r.get("fused_dma_path"):
        # RDMA issued inside the sweep kernel; "(emu)" marks rows that ran
        # the XLA reference contract, not the Mosaic kernel — never let an
        # emulated row read as a real fused-kernel number
        transport = (
            "fused-dma(emu)" if r.get("fused_dma_emulated") else "fused-dma"
        )
    elif r.get("direct_path"):
        transport = "direct"
    else:
        transport = "exch"
    parts = [transport]
    route = "mehr" if r.get("mehrstellen_route") else "chain"
    ops = r.get("chain_ops")
    parts.append(f"{route}({ops})" if ops is not None else route)
    return " ".join(parts)


def scaling_rows(results: List[Dict]) -> List[Dict]:
    """Compute weak/strong-scaling efficiency for multi-chip throughput rows
    against the matching 1-chip baseline in the same result set.

    Efficiency = per-chip rate / baseline per-chip rate (the BASELINE.json
    north-star metric: >= 0.90 weak-scaling on the pod). Baselines match on
    (stencil, dtype, backend, time_blocking); strong scaling pairs rows with
    the SAME global grid, weak scaling pairs a multi-chip row with the
    1-chip run of its per-chip LOCAL grid. Rows without a baseline are
    skipped (the sweep script always emits the 1-chip runs first)."""
    thr = [r for r in results if r["bench"] == "throughput"]

    def key(r):
        return (
            r["stencil"],
            r["dtype"],
            r.get("compute_dtype", "float32"),
            r["backend"],
            r.get("time_blocking", 1),
        )

    def nchips(r):
        n = 1
        for m in r["mesh"]:
            n *= m
        return n

    base = {}
    for r in thr:
        if nchips(r) == 1:
            base[(key(r), tuple(r["grid"]))] = r["gcell_per_sec_per_chip"]
    rows = []
    for r in thr:
        n = nchips(r)
        if n == 1:
            continue
        local = tuple(g // m for g, m in zip(r["grid"], r["mesh"]))
        for mode, ref_grid in (("strong", tuple(r["grid"])), ("weak", local)):
            b = base.get((key(r), ref_grid))
            if b is None or b <= 0:
                # fail loudly, not silently: a pod-day sweep missing its
                # 1-chip baselines must not render an empty table unnoticed
                print(
                    f"scaling_rows: skipping {mode} efficiency for "
                    f"grid={r['grid']} mesh={r['mesh']} — no 1-chip "
                    f"baseline at grid={list(ref_grid)} with (stencil, "
                    f"dtype, compute_dtype, backend, tb)={key(r)}",
                    file=sys.stderr,
                )
                continue
            rows.append(
                {
                    "mode": mode,
                    "grid": r["grid"],
                    "mesh": r["mesh"],
                    "chips": n,
                    "stencil": r["stencil"],
                    "dtype": r["dtype"],
                    "time_blocking": r.get("time_blocking", 1),
                    "gcell_per_sec_per_chip": r["gcell_per_sec_per_chip"],
                    "baseline_per_chip": b,
                    "efficiency": r["gcell_per_sec_per_chip"] / b,
                }
            )
    return rows


def render(results: List[Dict]) -> str:
    lines = []
    thr = [r for r in results if r["bench"] == "throughput"]
    halo = [r for r in results if r["bench"] == "halo"]
    if thr:
        lines += [
            "### Throughput (measured)",
            "",
            "| Grid | Stencil | Mesh | Dtype | Backend | tb | Route | Steps | Gcell/s | Gcell/s/chip | RTT-dominated |",
            "|---|---|---|---|---|---|---|---|---|---|---|",
        ]
        for r in thr:
            dtype = r["dtype"]
            compute = r.get("compute_dtype", "float32")
            if compute != "float32":
                dtype += f" (c={compute})"
            lines.append(
                f"| {_fmt_grid(r['grid'])} | {r['stencil']} | "
                f"{_fmt_mesh(r['mesh'])} | {dtype} | {r['backend']} | "
                f"{r.get('time_blocking', 1)} | {_fmt_route(r)} | "
                f"{r['steps']} | {r['gcell_per_sec']:.2f} | "
                f"{r['gcell_per_sec_per_chip']:.2f} | "
                f"{'yes' if r.get('rtt_dominated') else 'no'} |"
            )
        lines.append("")
    scal = scaling_rows(results)
    if scal:
        lines += [
            "### Scaling efficiency (measured, vs 1-chip baseline)",
            "",
            "| Mode | Grid | Mesh | Chips | Stencil | Dtype | tb | Gcell/s/chip | 1-chip | Efficiency |",
            "|---|---|---|---|---|---|---|---|---|---|",
        ]
        for r in scal:
            lines.append(
                f"| {r['mode']} | {_fmt_grid(r['grid'])} | "
                f"{_fmt_mesh(r['mesh'])} | {r['chips']} | {r['stencil']} | "
                f"{r['dtype']} | {r['time_blocking']} | "
                f"{r['gcell_per_sec_per_chip']:.2f} | "
                f"{r['baseline_per_chip']:.2f} | "
                f"{100 * r['efficiency']:.1f}% |"
            )
        lines.append("")
    if halo:
        lines += [
            "### Halo exchange (measured)",
            "",
            "| Grid | Mesh | Dtype | p50 µs | p95(mean) µs | min µs | bytes/device | ICI | RTT-dominated |",
            "|---|---|---|---|---|---|---|---|---|",
        ]
        for r in halo:
            # rows on a (1,1,1) mesh execute no collective — they measure
            # the local pad/crop cost only, flagged in the ICI column
            ici = r.get("ici", any(m > 1 for m in r["mesh"]))
            # p95(mean): 95th pct of per-program MEANS (device-side loop
            # samples), not per-exchange tail; p95_us is the legacy key
            p95 = r.get("p95_mean_us", r.get("p95_us", 0.0))
            lines.append(
                f"| {_fmt_grid(r['grid'])} | {_fmt_mesh(r['mesh'])} | "
                f"{r['dtype']} | {r['p50_us']:.1f} | {p95:.1f} | "
                f"{r['min_us']:.1f} | {r['halo_bytes_per_device']} | "
                f"{'yes' if ici else 'no (local only)'} | "
                f"{'yes' if r.get('rtt_dominated') else 'no'} |"
            )
        lines.append("")
    if not lines:
        lines = ["(no benchmark results found)", ""]
    return "\n".join(lines)


def update_baseline_md(results: List[Dict], baseline_path: str) -> bool:
    """Rewrite the measured block; returns False when it was left alone.

    The block always mirrors the LIVE result record — a partial session's
    rows legitimately replace older tables (prior records live in git and
    the bench_results_r2.jsonl archive). The only refused rewrite is the
    zero-row one: a session whose every row skipped (wedged tunnel)
    carries no data at all, so erasing real tables for a placeholder
    would be pure loss."""
    with open(baseline_path) as f:
        text = f.read()
    if not results and BEGIN in text and END in text:
        existing = text.split(BEGIN)[1].split(END)[0]
        if existing.strip() and "(no benchmark results found)" not in existing:
            print(
                f"report: no results — keeping {baseline_path}'s existing "
                "measured block",
                file=sys.stderr,
            )
            return False
    block = f"{BEGIN}\n\n{render(results)}{END}"
    if BEGIN in text and END in text:
        pre = text.split(BEGIN)[0]
        post = text.split(END)[1]
        text = pre + block + post
    else:
        text = text.rstrip() + "\n\n## Measured results\n\n" + block + "\n"
    with open(baseline_path, "w") as f:
        f.write(text)
    return True


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    results_path = argv[0]
    baseline = argv[1] if len(argv) > 1 else "BASELINE.md"
    results = load_results(results_path)
    updated = update_baseline_md(results, baseline)
    verb = "updated" if updated else "kept (no results)"
    print(
        f"{verb} {baseline}: {len(results)} results "
        f"({sum(r['bench'] == 'throughput' for r in results)} throughput, "
        f"{sum(r['bench'] == 'halo' for r in results)} halo)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
