"""Benchmark harness — the judged-metric producer (SURVEY.md §2 C9, §3.5).

Two microbenchmarks mirror the reference's headline numbers:
  * throughput: Gcell-updates/sec/chip of the full time loop
  * halo: p50/p95 latency of a jitted exchange-only program
"""

from heat3d_tpu.bench.harness import (  # noqa: F401
    bench_halo,
    bench_throughput,
    run_suite,
)
