"""Solver models: user-facing facades that assemble ops + parallel layers
into runnable simulations (the reference's main()/driver layer, re-shaped
as a library API — SURVEY.md §2 C4).
"""

from heat3d_tpu.models.heat3d import HeatSolver3D
