"""HeatSolver3D — the flagship model: explicit 3D heat diffusion, any judged
configuration (grid size, 7/27-point stencil, mesh decomposition, mixed
precision), one API.

Reference parity (SURVEY.md §2 C4, §3.1-3.3): everything the reference's
main() does — topology setup, allocation, init, the time loop, residual
checks, final report — except re-shaped as a library class whose hot path
is a single compiled XLA program per run, launched once from Python.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from heat3d_tpu import obs
from heat3d_tpu.core import golden
from heat3d_tpu.core.config import Precision, SolverConfig
from heat3d_tpu.parallel.step import (
    make_converge_fn,
    make_multistep_fn,
    make_step_fn,
)
from heat3d_tpu.parallel.topology import build_mesh, field_sharding
from heat3d_tpu.utils import checkpoint as ckpt
from heat3d_tpu.utils.compat import shard_map
from heat3d_tpu.utils.logging import get_logger

log = get_logger(__name__)


def _device_init_enabled() -> bool:
    import os

    return os.environ.get("HEAT3D_DEVICE_INIT", "1").lower() not in (
        "0",
        "false",
    )


def resolved_backend_name(cfg: SolverConfig) -> str:
    """The concrete backend NAME this config's compute resolves to —
    ``_select_backend``'s 'auto' rule (pallas where supported, else jnp)
    as a name instead of a callable, so consumers that must RECORD the
    route (the tuner's cache entries, provenance fields) share the one
    rule instead of re-implementing it."""
    if cfg.backend != "auto":
        return cfg.backend
    try:
        from heat3d_tpu.ops.stencil_pallas import pallas_supported

        return "pallas" if pallas_supported(cfg)[0] else "jnp"
    except ImportError:
        return "jnp"


def _select_backend(cfg: SolverConfig):
    """Resolve the compute backend to a padded-block compute callable.

    'jnp'    — portable shifted-slice path (ops.stencil_jnp).
    'pallas' — the Pallas TPU kernel (ops.stencil_pallas).
    'conv'   — one XLA conv_general_dilated (MXU on TPU) — the measured
               A/B reference for what the chains/kernels buy.
    'auto'   — pallas on TPU when the local block meets the kernel's layout
               constraints, else jnp (``resolved_backend_name`` is the
               name-returning form of this rule).
    """
    from heat3d_tpu.ops.stencil_jnp import apply_taps_conv_padded, apply_taps_padded

    if cfg.backend == "jnp":
        return apply_taps_padded
    if cfg.backend == "conv":
        return apply_taps_conv_padded
    if cfg.backend not in ("pallas", "auto"):
        raise ValueError(
            f"unknown backend {cfg.backend!r} (want auto|jnp|pallas|conv)"
        )
    try:
        from heat3d_tpu.ops.stencil_pallas import (
            make_pallas_compute,
            pallas_supported,
        )

        ok, why = pallas_supported(cfg)
        if ok:
            return make_pallas_compute(cfg)
        if cfg.backend == "pallas":
            raise ValueError(f"pallas backend unsupported here: {why}")
        log.info("auto backend: falling back to jnp (%s)", why)
    except ImportError as e:
        if cfg.backend == "pallas":
            raise ValueError(
                "pallas backend requested but the Pallas kernel module "
                f"could not be imported: {e}"
            ) from e
    return apply_taps_padded


@dataclasses.dataclass
class RunResult:
    u: jax.Array
    steps: int
    residual: Optional[float] = None


class HeatSolver3D:
    """Assembles mesh + sharded step functions for one SolverConfig.

    Usage::

        cfg = SolverConfig(grid=GridConfig.cube(128))
        solver = HeatSolver3D(cfg)
        u = solver.init_state("hot-cube")
        u = solver.run(u, num_steps=100)

    Construction-time vs step-build-time checks: the constructor validates
    only platform/emulation availability for ``halo='dma'``; the fused
    DMA routes' SCOPE gates (x-sharded mesh, unpadded shards, local-extent
    minima) are enforced at step-build time inside
    ``parallel.step.make_step_fn`` — an out-of-scope config constructs
    fine and raises its precise ValueError when the step is built.
    """

    def __init__(self, cfg: SolverConfig, devices=None):
        # Auto knobs (backend='auto', halo='auto', time_blocking=0)
        # resolve through the tuning cache — the safety net for library
        # users; the CLIs resolve at their entry points so their rows and
        # run_start events record concrete routes. resolve_config fails
        # soft; the belt-and-braces fallback below covers even an
        # unimportable tune package (the solver must never require it).
        # Non-default integrators pin their autos directly instead (the
        # tuner's cached knobs describe the explicit program family) and
        # validate against the timeint builders' structural scope.
        if cfg.integrator != "explicit-euler":
            from heat3d_tpu import timeint

            cfg = timeint.pin_config(cfg)
            timeint.validate_config(cfg)
        else:
            try:
                from heat3d_tpu.tune.cache import resolve_config

                cfg = resolve_config(cfg)
            except Exception:  # noqa: BLE001 - resolution is optional
                if cfg.halo == "auto" or cfg.time_blocking == 0:
                    cfg = dataclasses.replace(
                        cfg,
                        halo="ppermute" if cfg.halo == "auto" else cfg.halo,
                        time_blocking=(
                            1 if cfg.time_blocking == 0 else cfg.time_blocking
                        ),
                    )
        if cfg.halo == "dma":
            platform = jax.devices()[0].platform
            # The fused DMA-overlap routes (overlap=True) have an off-TPU
            # emulation tier: HEAT3D_DIRECT_INTERPRET dispatches their
            # pure-XLA reference contracts (parallel/step._fused_dma_route
            # — interpret mode cannot discharge remote DMA on the 3-axis
            # mesh). The plain DMA exchange transport has no such tier.
            # The SHARED env gate decides (backend/padding rules included)
            # so this check cannot drift from the route dispatch.
            from heat3d_tpu.parallel.step import _kernel_env_gate

            gate_ok, gate_interpret = _kernel_env_gate(cfg)
            emulated = cfg.overlap and gate_ok and gate_interpret
            if platform != "tpu" and not emulated:
                raise ValueError(
                    f"halo='dma' needs TPU hardware (Mosaic remote-DMA "
                    f"kernels); platform is {platform!r} — use "
                    "halo='ppermute' (or set HEAT3D_DIRECT_INTERPRET=1 "
                    "with --overlap for the fused routes' XLA reference "
                    "emulation)"
                )
        self.cfg = cfg
        self.mesh = build_mesh(cfg.mesh, devices)
        self.sharding = field_sharding(self.mesh, cfg.mesh)
        # Built on first use: the fixed-step loop validates time_blocking
        # constraints (halo transport, local extents) that convergence-mode
        # runs never exercise.
        self._multistep_cache = None
        self._device_field_cache = {}
        if cfg.integrator != "explicit-euler":
            from heat3d_tpu import timeint

            self._compute = None
            self._step = jax.jit(
                timeint.make_step_fn(cfg, self.mesh), donate_argnums=0
            )
            self._step_res = jax.jit(
                timeint.make_step_fn(cfg, self.mesh, with_residual=True),
                donate_argnums=0,
            )
            # convergence mode is steady-state machinery: wave runs
            # oscillate forever and an implicit solve's change residual
            # measures dt, not proximity to steady state
            self._converge = None
            return
        compute = _select_backend(cfg)
        self._compute = compute
        # One executable per entrypoint; donation makes the time loop
        # double-buffer in place (SURVEY.md §1 L0 mapping).
        self._step = jax.jit(
            make_step_fn(cfg, self.mesh, compute), donate_argnums=0
        )
        self._step_res = jax.jit(
            make_step_fn(cfg, self.mesh, compute, with_residual=True),
            donate_argnums=0,
        )
        self._converge = jax.jit(
            make_converge_fn(cfg, self.mesh, compute), donate_argnums=0
        )

    @property
    def _multistep(self):
        if self._multistep_cache is None:
            if self.cfg.integrator != "explicit-euler":
                from heat3d_tpu import timeint

                self._multistep_cache = jax.jit(
                    timeint.make_multistep_fn(self.cfg, self.mesh),
                    donate_argnums=0,
                )
            else:
                self._multistep_cache = jax.jit(
                    make_multistep_fn(self.cfg, self.mesh, self._compute),
                    donate_argnums=0,
                )
        return self._multistep_cache

    # ---- state -----------------------------------------------------------

    @property
    def storage_dtype(self):
        return jnp.dtype(self.cfg.precision.storage)

    def init_state(self, init: Union[str, np.ndarray] = "hot-cube") -> jax.Array:
        """Build the sharded initial field. A string selects a named
        initializer (core.golden.INITIALIZERS); an array is used directly.

        Initializers whose values are exactly representable constants
        (``hot-cube``) are built ON DEVICE — a jitted elementwise iota
        program under ``out_shardings``, so no host buffer is materialized
        and no bulk host->device transfer happens (at 1024^3 the host path
        ships 4 GiB through the link before the first step can run; the
        device path ships nothing, and GSPMD partitions the iota masks with
        zero communication). The result is bitwise-identical to the host
        path; ``HEAT3D_DEVICE_INIT=0`` forces the host path for A/B.
        Everything else (value-generating initializers, explicit arrays)
        materializes per-shard via make_array_from_callback, so no process
        ever holds the full 4096^3 field either way (SURVEY.md §2 C8).

        Storage is ``cfg.padded_shape``; for uneven decompositions the
        region beyond ``cfg.grid.shape`` is pinned at bc_value (see
        parallel.step._pin_padding).

        Under ``integrator='leapfrog'`` the state is the TWO-LEVEL carry
        ``(u, u_prev)``: a single initializer yields a zero-velocity
        start (u_prev a copy of u — distinct buffers, so the donated
        step may alias either); a TUPLE of two initializers sets the
        levels independently (the MMS gates seed u(0) and u(-dt))."""
        if self.cfg.integrator == "leapfrog":
            if isinstance(init, tuple):
                if len(init) != 2:
                    raise ValueError(
                        f"leapfrog init tuple must have 2 levels (u, "
                        f"u_prev), got {len(init)}"
                    )
                return tuple(self._init_level(lv) for lv in init)
            u0 = self._init_level(init)
            return (u0, jnp.copy(u0))
        return self._init_level(init)

    def _init_level(self, init: Union[str, np.ndarray]) -> jax.Array:
        true_shape = self.cfg.grid.shape
        with obs.get().span(
            "init_state",
            init=init if isinstance(init, str) else "array",
            grid=list(true_shape),
        ):
            if isinstance(init, np.ndarray):
                if init.shape != true_shape:
                    raise ValueError(
                        f"init shape {init.shape} != grid {true_shape}"
                    )
                arr = init.astype(self.storage_dtype)
                return self._sharded_from_blocks(
                    lambda clipped: arr[clipped]
                )
            if init == "hot-cube" and _device_init_enabled():
                return self._device_field(hot_cube=True)
            name, seed = init, self.cfg.run.seed
            return self._sharded_from_blocks(
                lambda clipped: golden.make_init_block(
                    name, true_shape, clipped, seed=seed
                ).astype(self.storage_dtype)
            )

    def _device_field(self, hot_cube: bool) -> jax.Array:
        """All-zero (or hot-cube) TRUE grid in storage layout, built on
        device: elementwise over coordinate iotas, jitted with
        ``out_shardings``, bitwise-equal to the host block path (the only
        values are 0, 1, and bc_value — exactly representable in every
        storage dtype)."""
        jitted = self._device_field_cache.get(hot_cube)
        if jitted is not None:
            return jitted()
        storage = self.cfg.padded_shape
        true_shape = self.cfg.grid.shape
        bc_value = self.cfg.stencil.bc_value
        dtype = self.storage_dtype

        def build():
            in_true = None
            in_cube = None
            for ax, nt in enumerate(true_shape):
                io = jax.lax.broadcasted_iota(jnp.int32, storage, ax)
                t = io < nt
                in_true = t if in_true is None else in_true & t
                if hot_cube:
                    # same bounds arithmetic as golden.make_init_block
                    g0 = int(nt * (0.5 - 0.25 / 2))
                    g1 = max(int(nt * (0.5 + 0.25 / 2)), g0 + 1)
                    c = (io >= g0) & (io < g1)
                    in_cube = c if in_cube is None else in_cube & c
            val = jnp.zeros(storage, dtype)
            if hot_cube:
                val = jnp.where(in_cube, jnp.ones((), dtype), val)
            return jnp.where(in_true, val, jnp.full((), bc_value, dtype))

        jitted = jax.jit(build, out_shardings=self.sharding)
        self._device_field_cache[hot_cube] = jitted
        return jitted()

    def _sharded_from_blocks(self, true_block_fn) -> jax.Array:
        """Build a sharded storage-layout field from a function evaluating
        blocks of the TRUE grid. Regions beyond ``cfg.grid.shape`` (uneven-
        decomposition padding) are filled with bc_value; each shard callback
        clips its storage-index slices against the true extents."""
        true_shape = self.cfg.grid.shape
        storage_shape = self.cfg.padded_shape
        bc_value = self.cfg.stencil.bc_value

        def cb(idx):
            starts = [0 if s.start is None else s.start for s in idx]
            stops = [
                n if s.stop is None else s.stop
                for s, n in zip(idx, storage_shape)
            ]
            block = np.full(
                tuple(b - a for a, b in zip(starts, stops)),
                bc_value,
                self.storage_dtype,
            )
            clipped = tuple(
                slice(a, min(b, g))
                for a, b, g in zip(starts, stops, true_shape)
            )
            if all(c.stop > c.start for c in clipped):
                local = tuple(slice(0, c.stop - c.start) for c in clipped)
                block[local] = true_block_fn(clipped)
            return block

        return jax.make_array_from_callback(storage_shape, self.sharding, cb)

    def zeros_state(self) -> jax.Array:
        """An all-zero TRUE grid in storage layout (padding at bc_value) —
        cheap warmup input for the donated executables. Built on device
        (no host buffer, no transfer) unless HEAT3D_DEVICE_INIT=0. A
        two-level tuple under ``integrator='leapfrog'``, like
        :meth:`init_state`."""
        if _device_init_enabled():
            z = self._device_field(hot_cube=False)
        else:
            z = self._sharded_from_blocks(
                lambda clipped: np.zeros(
                    tuple(c.stop - c.start for c in clipped),
                    self.storage_dtype,
                )
            )
        if self.cfg.integrator == "leapfrog":
            return (z, jnp.copy(z))
        return z

    # ---- stepping --------------------------------------------------------

    def step(self, u: jax.Array) -> jax.Array:
        return self._step(u)

    def step_with_residual(self, u: jax.Array) -> Tuple[jax.Array, jax.Array]:
        return self._step_res(u)

    def run(self, u: jax.Array, num_steps: int) -> jax.Array:
        """num_steps updates as one device-side loop (benchmark mode: no
        mid-loop host syncs — SURVEY.md §3.3). Under
        ``integrator='implicit-cg'`` each update is a CG solve; the last
        solve's iteration count and relative residual come back with the
        field and land in the ledger as a ``cg_solve`` event (the one
        host sync happens after the loop, where the caller consumes the
        field anyway)."""
        if self.cfg.integrator == "implicit-cg":
            u, iters, relres = self._multistep(u, jnp.int32(num_steps))
            obs.get().event(
                "cg_solve",
                steps=int(num_steps),
                cg_iters=int(iters),
                cg_relres=float(relres),
            )
            return u
        return self._multistep(u, jnp.int32(num_steps))

    def run_to_convergence(
        self, u: jax.Array, tol: float, max_steps: int
    ) -> RunResult:
        if self._converge is None:
            raise ValueError(
                f"run_to_convergence needs integrator='explicit-euler' "
                f"(got {self.cfg.integrator!r}): wave runs oscillate "
                "instead of converging, and an implicit solve's change "
                "residual measures dt, not steady-state proximity — use "
                "fixed-step run() (docs/INTEGRATORS.md)"
            )
        u, steps, res = self._converge(u, jnp.int32(max_steps), jnp.float32(tol))
        return RunResult(u=u, steps=int(steps), residual=float(res))

    def run_supervised(
        self,
        total_steps: int,
        ckpt_root: str,
        checkpoint_every: int = 0,
        **kwargs,
    ):
        """Run to global step ``total_steps`` under the resilience
        supervisor: checkpoint generations every ``checkpoint_every``
        steps into ``ckpt_root``, auto-resume from the newest good
        generation (quarantining corrupt ones), survive backend
        loss/hang by waiting for heal and resuming. ``total_steps`` is
        the TARGET GLOBAL step — a resumed run finishes the original
        run, it does not append to it. See
        :func:`heat3d_tpu.resilience.supervisor.run_supervised` for the
        knobs; by default a recovery rebuilds a fresh solver for this
        config (re-resolving devices, so a TPU->CPU heal cross-mesh
        stitch-resumes through ``utils.checkpoint``'s block stitching).
        """
        from heat3d_tpu.resilience.supervisor import run_supervised

        kwargs.setdefault("make_solver", lambda: HeatSolver3D(self.cfg))
        # the elastic path (heal_mode='elastic'|'auto') needs a
        # config-parameterized factory: a survivor-mesh re-factorization
        # rebuilds the solver on the DEGRADED config, not this one
        # (resilience/elastic.py; docs/RESILIENCE.md)
        kwargs.setdefault("make_solver_for", lambda cfg: HeatSolver3D(cfg))
        kwargs.setdefault("base_cfg", self.cfg)
        return run_supervised(
            self, total_steps, ckpt_root, checkpoint_every, **kwargs
        )

    # ---- IO --------------------------------------------------------------

    def gather(self, u: jax.Array) -> np.ndarray:
        """Fetch the full field to host (small grids / tests only), with any
        uneven-decomposition storage padding stripped. Multi-host safe: when
        shards live on other processes this is a collective
        (process_allgather), so every process must call it. A multi-level
        carry gathers level 0 (the current field)."""
        if isinstance(u, tuple):
            u = u[0]
        if u.is_fully_addressable:
            full = np.asarray(jax.device_get(u))
        else:
            from jax.experimental import multihost_utils

            full = np.asarray(multihost_utils.process_allgather(u, tiled=True))
        if full.shape != self.cfg.grid.shape:
            full = full[tuple(slice(0, g) for g in self.cfg.grid.shape)]
        return full

    def gather_slice(self, u: jax.Array, axis: int, index: int) -> np.ndarray:
        """One global 2D plane of the field on the host — the reference
        class's visualization dump (SURVEY.md §4: correctness by "visual/
        numeric inspection of dumped slices") without materializing the
        full global array anywhere. ``index`` is a GLOBAL coordinate along
        ``axis``. Multi-host safe: the replicated out_sharding makes XLA
        gather just this plane to every process, so all processes must
        call it (like :meth:`gather`)."""
        if isinstance(u, tuple):
            u = u[0]
        g = self.cfg.grid.shape
        if not 0 <= axis <= 2:
            raise ValueError(f"slice axis must be 0..2, got {axis}")
        if not 0 <= index < g[axis]:
            raise ValueError(
                f"slice index {index} outside grid extent {g[axis]} on "
                f"axis {axis}"
            )
        from jax import lax
        from jax.sharding import NamedSharding, PartitionSpec

        # XLA's sharding propagation cannot slice a sharded dim to size 1
        # (ShardingTypeError), so extract per-shard: the one device row
        # holding the plane contributes it, everyone else zeros, and a psum
        # along the slice axis broadcasts it — traffic is one plane, never
        # the volume.
        names = self.cfg.mesh.axis_names
        axis_name = names[axis]

        def local_plane(x):
            i = lax.axis_index(axis_name)
            nloc = x.shape[axis]
            li = index - i * nloc  # storage coords == physical coords
            ok = jnp.logical_and(li >= 0, li < nloc)
            piece = lax.dynamic_index_in_dim(
                x, jnp.clip(li, 0, nloc - 1), axis, keepdims=False
            )
            piece = jnp.where(ok, piece, jnp.zeros_like(piece))
            return lax.psum(piece, axis_name)

        out_names = tuple(n for a, n in enumerate(names) if a != axis)
        plane = jax.jit(
            shard_map(
                local_plane,
                mesh=self.mesh,
                in_specs=PartitionSpec(*names),
                out_specs=PartitionSpec(*out_names),
                check_vma=False,
            ),
            out_shardings=NamedSharding(self.mesh, PartitionSpec()),
        )(u)
        keep = [s for a, s in enumerate(g) if a != axis]
        # strip any uneven-decomposition storage padding from the plane
        return np.asarray(plane)[: keep[0], : keep[1]]

    def save_checkpoint(self, path: str, u, step: int) -> None:
        """Checkpoint the state. A multi-level carry (leapfrog) writes
        level 0 at the generation top — manifest extra records
        ``levels``/``integrator`` — and each further level as a full
        per-shard checkpoint under ``<path>/level-<i>/``, so every level
        keeps the per-shard CRC sidecars and the cross-mesh re-stitch of
        ``utils.checkpoint`` unchanged."""
        import os

        from heat3d_tpu import timeint

        levels = timeint.carry_levels(self.cfg.integrator)
        if levels == 1:
            ckpt.save(path, u, step, extra={"config": repr(self.cfg)})
            return
        ckpt.save(
            path,
            u[0],
            step,
            extra={
                "config": repr(self.cfg),
                "levels": levels,
                "integrator": self.cfg.integrator,
            },
        )
        for lv in range(1, levels):
            ckpt.save(
                os.path.join(path, f"level-{lv}"),
                u[lv],
                step,
                extra={"level": lv, "integrator": self.cfg.integrator},
            )

    def load_checkpoint(self, path: str):
        """Load a checkpoint saved by :meth:`save_checkpoint`. The level
        structure is validated BEFORE any shard read: a manifest whose
        ``levels`` count disagrees with this integrator's carry, a
        missing level directory, a level step drift, or a per-level
        shard-shape mismatch raises
        :class:`heat3d_tpu.timeint.MultiLevelCheckpointError` — a
        ValueError, so the supervisor's resume scan skips the generation
        in place (the shards are not PROVEN corrupt; quarantine stays
        reserved for checksum/torn-manifest damage)."""
        import os

        from heat3d_tpu import timeint

        levels = timeint.carry_levels(self.cfg.integrator)
        man = ckpt.load_manifest(path)
        found = int((man.get("extra") or {}).get("levels", 1))
        if found != levels:
            raise timeint.MultiLevelCheckpointError(
                f"checkpoint {path} holds {found} field level(s) but "
                f"integrator {self.cfg.integrator!r} carries {levels} — "
                "wrong integrator for this checkpoint (docs/INTEGRATORS.md)"
            )
        u, step = self._load_level(path)
        if levels == 1:
            return u, step
        state = [u]
        for lv in range(1, levels):
            lp = os.path.join(path, f"level-{lv}")
            try:
                ulv, step_lv = self._load_level(
                    lp, error_cls=timeint.MultiLevelCheckpointError
                )
            except ckpt.ShardCorruptError:
                raise  # proven damage: let the supervisor quarantine
            except FileNotFoundError as e:
                raise timeint.MultiLevelCheckpointError(
                    f"checkpoint {path} is missing level {lv} "
                    f"({lp}): {e}"
                ) from e
            if step_lv != step:
                raise timeint.MultiLevelCheckpointError(
                    f"checkpoint {path} level {lv} is at step {step_lv} "
                    f"but level 0 is at step {step} — torn multi-level "
                    "save"
                )
            state.append(ulv)
        return tuple(state), step

    def _load_level(self, path: str, error_cls=ValueError):
        u, step, _ = ckpt.load(path, self.sharding)
        if tuple(u.shape) != self.cfg.padded_shape:
            # fail loudly: silently stepping a wrong-shape field would
            # finish "successfully" with metrics computed from the
            # CONFIGURED grid — an inflated/garbage summary with clean
            # provenance (and the supervised auto-resume path reaches
            # here without any --resume flag). Distinguish the two causes:
            # the same grid saved under a DIFFERENT mesh's bc-padding is a
            # known cross-mesh limitation, not a wrong checkpoint.
            # padding only ever rounds the grid UP, so saved >= grid on
            # every dim is consistent with "same grid, other mesh"; any
            # smaller dim proves a different grid outright
            same_grid_other_padding = all(
                s >= g for s, g in zip(u.shape, self.cfg.grid.shape)
            )
            hint = (
                "the checkpoint was padded for a different mesh "
                "(cross-mesh resume across bc-paddings is unsupported — "
                "use a grid divisible by both meshes, or consolidate and "
                "re-grid)"
                if same_grid_other_padding
                else "wrong checkpoint for this run"
            )
            raise error_cls(
                f"checkpoint {path} holds a {tuple(u.shape)} field but "
                f"this config's storage shape is {self.cfg.padded_shape} "
                f"(grid {self.cfg.grid.shape} on mesh {self.cfg.mesh.shape})"
                f" — {hint}"
            )
        if u.dtype != self.storage_dtype:
            u = u.astype(self.storage_dtype)
        return u, step
