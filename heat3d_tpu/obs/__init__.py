"""Unified telemetry: run ledger, metrics registry, named-span tracing —
plus the :mod:`~heat3d_tpu.obs.perf` layer that judges what they record
(profile capture, roofline attribution, the perf-regression gate,
multihost ledger merge; docs/OBSERVABILITY.md §5).

Three instruments, one package (see docs/OBSERVABILITY.md):

- :mod:`~heat3d_tpu.obs.ledger` — append-only JSONL event stream (spans +
  points, run-id/generation/process tagging) written by every entry point.
- :mod:`~heat3d_tpu.obs.metrics` — counters/gauges/histograms with a
  Prometheus-textfile/JSON exporter and a final per-run summary record.
- :mod:`~heat3d_tpu.obs.trace` — ``jax.named_scope`` / TraceAnnotation
  brackets so profiler traces attribute device time to *our* phases.

Library code uses the module-level conveniences and pays a no-op when
nothing is configured::

    from heat3d_tpu import obs

    obs.get().event("fault_injected", kind_="backend-loss", step=8)
    with obs.get().span("chunk", steps=4) as sp:
        ...
    obs.REGISTRY.counter("retries_total").inc()
    with obs.named_phase("halo_exchange"):
        ...  # traced code
"""

from heat3d_tpu.obs.ledger import (  # noqa: F401
    ENV_LEDGER,
    NULL,
    Ledger,
    NullLedger,
    activate,
    deactivate,
    get,
)
from heat3d_tpu.obs.metrics import (  # noqa: F401
    ENV_METRICS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    export_at_exit,
)
from heat3d_tpu.obs.trace import annotate, named_phase, scoped  # noqa: F401
