"""Streaming SLO evaluation: burn rate over sliding windows, live.

``obs slo`` is post-hoc — a breach at minute 7 of an hours-long soak is
only discovered after the session is spent. :class:`BurnEvaluator`
consumes ledger events incrementally (from
:class:`heat3d_tpu.obs.tailer.LedgerTailer`) and re-judges the SAME
objective spec continuously, as **burn rate over a fast/slow window
pair** (the SRE multi-window rule): an objective is *alerting* only when
BOTH windows burn at or above the threshold — the fast window for
responsiveness, the slow window so a single spike cannot page.

State is bounded: per-bucket latency samples live in ring-buffered
deques pruned past the slow window; nothing grows with run length except
the (tiny) step-time sample list, itself hard-capped.

The per-objective judgment is
:func:`heat3d_tpu.obs.perf.slo.evaluate_objective` — the one shared core
the post-hoc gate also uses — and :meth:`final_verdict` feeds the same
inputs post-hoc evaluation would read from the finished ledger (last
``serve_metrics_summary``, cumulative step samples) through
:func:`~heat3d_tpu.obs.perf.slo.evaluate`, so the live evaluator's final
state and a later ``heat3d obs slo`` on the same ledger agree by
construction (test-pinned in the soak battery).

Window semantics per objective kind:

- ``serve_latency`` — windowed per-bucket percentiles over the
  ``serve_result`` samples inside each window (worst bucket governs,
  same as post-hoc).
- ``step_time`` — windowed percentile over the step-span samples.
- ``serve_degraded`` — cumulative ``degraded_s`` from the latest
  ``serve_metrics_summary`` (a budget, not a rate: both windows see the
  same cumulative value).
- ``halo_share`` — needs a profile capture; always ``no_data`` live.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

ENV_BURN_FAST = "HEAT3D_BURN_FAST_S"
ENV_BURN_SLOW = "HEAT3D_BURN_SLOW_S"
ENV_BURN_THRESHOLD = "HEAT3D_BURN_THRESHOLD"

DEFAULT_FAST_S = 60.0
DEFAULT_SLOW_S = 300.0
DEFAULT_THRESHOLD = 1.0

# per-bucket ring size: at the soak's observed arrival rates this holds
# far more than a slow window's worth; the cap only guards pathology
WINDOW_SAMPLE_CAP = 4096
STEP_SAMPLE_CAP = 100_000

# windowed counts of these flag the watch view's anomaly line
ANOMALY_EVENTS = (
    "serve_requeue",
    "serve_shed",
    "fault_injected",
    "worker_scale",
)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        v = float(raw)
    except ValueError:
        return default
    return v if v > 0 else default


class BurnEvaluator:
    """Windowed incremental SLO evaluation over a live event stream."""

    def __init__(
        self,
        spec: Dict[str, Any],
        fast_s: Optional[float] = None,
        slow_s: Optional[float] = None,
        threshold: Optional[float] = None,
        warn_ratio: Optional[float] = None,
        min_samples: int = 1,
    ):
        from heat3d_tpu.obs.perf.slo import _warn_ratio

        self.spec = spec
        self.fast_s = fast_s or _env_float(ENV_BURN_FAST, DEFAULT_FAST_S)
        self.slow_s = slow_s or _env_float(ENV_BURN_SLOW, DEFAULT_SLOW_S)
        if self.slow_s < self.fast_s:
            self.slow_s = self.fast_s
        self.threshold = (
            threshold
            if threshold is not None
            else _env_float(ENV_BURN_THRESHOLD, DEFAULT_THRESHOLD)
        )
        self.min_samples = max(1, min_samples)
        self._warn = _warn_ratio(spec, warn_ratio)
        # (wall ts, latency_s) per bucket, pruned past the slow window
        self._lat: Dict[str, Deque[Tuple[float, float]]] = {}
        self._steps: Deque[Tuple[float, float]] = deque(
            maxlen=STEP_SAMPLE_CAP
        )
        self._arrivals: Deque[float] = deque(maxlen=WINDOW_SAMPLE_CAP)
        self._deliveries: Deque[float] = deque(maxlen=WINDOW_SAMPLE_CAP)
        self._anomalies: Dict[str, Deque[float]] = {
            name: deque(maxlen=WINDOW_SAMPLE_CAP) for name in ANOMALY_EVENTS
        }
        self._last_summary: Optional[Dict[str, Any]] = None
        self._last_depth: Optional[int] = None
        self._all_lat: List[float] = []  # pre-summary fallback only
        self._t_end: Optional[float] = None  # live edge = max ts seen
        self.events_seen = 0

    # ---- ingest ----------------------------------------------------------

    def consume(self, events: List[Dict[str, Any]]) -> None:
        from heat3d_tpu.obs.cli import STEP_SPANS

        for r in events:
            ts = r.get("ts")
            if not isinstance(ts, (int, float)):
                continue
            self.events_seen += 1
            if self._t_end is None or ts > self._t_end:
                self._t_end = float(ts)
            name = r.get("event")
            if name == "serve_result" and isinstance(
                r.get("queue_latency_s"), (int, float)
            ):
                bucket = str(r.get("bucket", "(all)"))
                dq = self._lat.get(bucket)
                if dq is None:
                    dq = self._lat[bucket] = deque(maxlen=WINDOW_SAMPLE_CAP)
                dq.append((float(ts), float(r["queue_latency_s"])))
                self._deliveries.append(float(ts))
                if self._last_summary is None:
                    self._all_lat.append(float(r["queue_latency_s"]))
            elif name == "serve_submit":
                self._arrivals.append(float(ts))
                if isinstance(r.get("queue_depth"), int):
                    self._last_depth = r["queue_depth"]
            elif name == "serve_metrics_summary" and isinstance(
                r.get("buckets"), dict
            ):
                self._last_summary = r
                self._all_lat = []  # superseded; drop the fallback state
            elif name in self._anomalies:
                self._anomalies[name].append(float(ts))
            elif (
                r.get("kind") == "span"
                and name in STEP_SPANS
                and r.get("status") == "ok"
                and isinstance(r.get("steps"), int)
                and r["steps"] > 0
                and isinstance(r.get("dur_s"), (int, float))
            ):
                self._steps.append(
                    (float(ts), float(r["dur_s"]) / r["steps"])
                )
        self._prune()

    def _prune(self) -> None:
        if self._t_end is None:
            return
        floor = self._t_end - self.slow_s
        for dq in self._lat.values():
            while dq and dq[0][0] < floor:
                dq.popleft()
        # step samples stay cumulative for final_verdict parity with the
        # post-hoc reconstruction; the deque maxlen bounds them

    # ---- windowed judgment ----------------------------------------------

    def _window_summary(self, window_s: float) -> Optional[Dict[str, Any]]:
        """A synthetic serve summary over the trailing ``window_s`` —
        the shape :func:`slo.evaluate_objective` reads, with percentiles
        computed from the windowed samples."""
        from heat3d_tpu.obs.metrics import percentile

        if self._t_end is None:
            return None
        floor = self._t_end - window_s
        buckets: Dict[str, Dict[str, Any]] = {}
        for bucket, dq in self._lat.items():
            vals = [v for t, v in dq if t >= floor]
            if len(vals) < self.min_samples:
                continue
            buckets[bucket] = {
                "count": len(vals),
                "p50_s": percentile(vals, 50),
                "p95_s": percentile(vals, 95),
                "p99_s": percentile(vals, 99),
                "max_s": max(vals),
            }
        summary: Dict[str, Any] = {
            "buckets": buckets,
            "source": f"burn window {window_s:g}s",
        }
        # degraded time is a cumulative budget, not a windowed rate:
        # carry the latest engine summary's counters into every window
        if self._last_summary is not None:
            summary["degraded"] = self._last_summary.get("degraded")
            summary["degraded_s"] = self._last_summary.get("degraded_s")
            summary["requeues"] = self._last_summary.get("requeues")
        return summary

    def _window_steps(self, window_s: float) -> List[float]:
        if self._t_end is None:
            return []
        floor = self._t_end - window_s
        return [v for t, v in self._steps if t >= floor]

    def evaluate(self) -> Dict[str, Any]:
        """Judge every objective over the fast and slow windows. An
        objective is ``alerting`` when BOTH windows burn >= threshold."""
        from heat3d_tpu.obs.perf.slo import evaluate_objective

        objectives = []
        for o in self.spec.get("objectives", []):
            windows = {}
            for label, win in (("fast", self.fast_s), ("slow", self.slow_s)):
                rec = evaluate_objective(
                    o,
                    self._window_summary(win),
                    self._window_steps(win),
                    None,
                    self._warn,
                )
                windows[label] = {
                    "window_s": win,
                    "burn": rec["burn_rate"],
                    "value": rec["value"],
                    "status": rec["status"],
                    "bucket": rec.get("bucket"),
                }
            alerting = all(
                w["burn"] is not None and w["burn"] >= self.threshold
                for w in windows.values()
            )
            objectives.append(
                {
                    "name": o.get("name", o["kind"]),
                    "kind": o["kind"],
                    "fast": windows["fast"],
                    "slow": windows["slow"],
                    "alerting": alerting,
                }
            )
        return {
            "objectives": objectives,
            "alerting": [x["name"] for x in objectives if x["alerting"]],
            "threshold": self.threshold,
            "fast_window_s": self.fast_s,
            "slow_window_s": self.slow_s,
        }

    # ---- watch view ------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The live terminal view's data: rates, depth, windowed bucket
        percentiles, degraded state, burn per objective, anomaly flags."""
        win = self.fast_s
        floor = (self._t_end or 0.0) - win
        arr = sum(1 for t in self._arrivals if t >= floor)
        dlv = sum(1 for t in self._deliveries if t >= floor)
        flags = {
            name: n
            for name, dq in self._anomalies.items()
            if (n := sum(1 for t in dq if t >= floor))
        }
        summary = self._window_summary(win) or {}
        return {
            "t_end": self._t_end,
            "events_seen": self.events_seen,
            "window_s": win,
            "arrival_hz": round(arr / win, 3),
            "delivery_hz": round(dlv / win, 3),
            "queue_depth": self._last_depth,
            "buckets": summary.get("buckets") or {},
            "degraded": (self._last_summary or {}).get("degraded"),
            "degraded_s": (self._last_summary or {}).get("degraded_s"),
            "flags": flags,
            "burn": self.evaluate(),
        }

    # ---- post-hoc parity -------------------------------------------------

    def _posthoc_summary(self) -> Optional[Dict[str, Any]]:
        """The serve summary post-hoc evaluation would derive from this
        ledger — mirror :func:`slo.serve_summary_from_events` exactly."""
        from heat3d_tpu.obs.metrics import percentile

        last = self._last_summary
        if last is not None:
            return {
                "buckets": last["buckets"],
                "depth_max": last.get("depth_max"),
                "degraded": last.get("degraded"),
                "degraded_s": last.get("degraded_s"),
                "requeues": last.get("requeues"),
                "source": "serve_metrics_summary",
            }
        if not self._all_lat:
            return None
        lat = self._all_lat
        return {
            "buckets": {
                "(all)": {
                    "count": len(lat),
                    "p50_s": percentile(lat, 50),
                    "p95_s": percentile(lat, 95),
                    "max_s": max(lat),
                }
            },
            "depth_max": None,
            "source": "serve_result reconstruction",
        }

    def final_verdict(self) -> Dict[str, Any]:
        """The report a post-hoc ``heat3d obs slo`` over the same ledger
        would produce: same inputs, same shared core — the live/post-hoc
        agreement the soak battery pins."""
        from heat3d_tpu.obs.perf.slo import evaluate

        return evaluate(
            [],
            self.spec,
            serve_summary=self._posthoc_summary(),
            warn_ratio=self._warn,
            step_samples=[v for _, v in self._steps],
        )
