"""``heat3d obs`` — turn a run ledger into human-readable timelines and
p50/p95 tables.

Subcommands::

    heat3d obs summary LEDGER [--run RUN_ID]   # per-run spans + timeline
    heat3d obs tail LEDGER [-n N]              # last N events, one per line
    heat3d obs check LEDGER [...]              # schema lint (scripts/check_ledger.py)
    heat3d obs roofline [...]                  # achieved-vs-peak (obs/perf/roofline)
    heat3d obs regress RESULTS [...]           # perf-regression gate (obs/perf/regress)
    heat3d obs merge LEDGERS... [...]          # multihost timeline join (obs/perf/merge)
    heat3d obs timeline LEDGERS... [...]       # Chrome-trace export + drift/stragglers (obs/perf/timeline)
    heat3d obs slo LEDGER [...]                # SLO burn-rate verdict (obs/perf/slo)
    heat3d obs adjudicate INPUTS... [...]      # POD_RUNBOOK A/B stage verdicts (obs/comm/adjudicate)

``summary`` is the operator's post-mortem view: for each run segment in
the ledger it prints the invocation, a span-duration table (count, total,
p50, p95 per event name), the derived **per-step latency** p50/p95
(reconstructed from ``steps``/``chunk`` spans carrying a ``steps`` field —
the number the bench harness computes independently at run time), and a
timeline of the notable events (faults, retries, heals, generation
transitions, checkpoint writes/quarantines) so an interrupted-and-resumed
session reads end to end.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from heat3d_tpu.obs.metrics import percentile

# events worth a timeline line (everything else shows in the span table)
NOTABLE = (
    "ledger_open",
    "run_start",
    "supervised_start",
    "fault_injected",
    "retry_outcome",
    "generation_save",
    "ckpt_corrupt",
    "ckpt_quarantine",
    "recovery",
    "elastic_refactor",
    "degraded_mode_enter",
    "degraded_mode_exit",
    "serve_requeue",
    "resume",
    "run_summary",
    "metrics_summary",
    "bench_row",
    "tune_search_start",
    "tune_trial",
    "tune_winner",
    "tune_budget_exhausted",
    "tune_cache_hit",
    "tune_cache_miss",
    "tune_cache_stale",
    "peak_calibrated",
    "serve_submit",
    "serve_batch_start",
    "serve_result",
    "serve_metrics_summary",
    "obs_anomaly",
    "slo_verdict",
    # live-monitor milestones (serve_span is deliberately absent: five
    # trace phases per delivered request would drown the timeline)
    "monitor_start",
    "slo_burn_alert",
    "monitor_summary",
    "timeline_export",
    # comm observatory (comm_probe is deliberately absent, like
    # serve_span: one row per link per probe pass would drown the
    # timeline — the per-link table renders them instead)
    "clock_align",
    "adjudicate_verdict",
    "run_end",
    "ledger_close",
)

# span names whose `steps` field makes them per-step latency samples
STEP_SPANS = ("steps", "chunk", "run_loop")


def read_ledger(path: str) -> List[Dict[str, Any]]:
    """All parseable events at ``path`` — including the rolled segments a
    HEAT3D_LEDGER_MAX_MB rotation left beside it (oldest first, so the
    concatenation is the writer's original append order). The base path
    must exist; a rolled sibling that races away mid-read is skipped."""
    from heat3d_tpu.obs.ledger import ledger_segments

    events = []
    for seg in ledger_segments(path):
        try:
            f = open(seg)
        except OSError:
            if seg == path:
                raise
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # the lint flags these; summary stays best-effort
                if isinstance(rec, dict):
                    events.append(rec)
    return events


def _fmt_ts(ts: Any) -> str:
    try:
        return time.strftime("%H:%M:%S", time.localtime(float(ts)))
    except (TypeError, ValueError):
        return "?"


def _fmt_s(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v >= 1.0:
        return f"{v:.3f}s"
    if v >= 1e-3:
        return f"{v * 1e3:.2f}ms"
    return f"{v * 1e6:.1f}us"


def step_latencies(events: List[Dict[str, Any]]) -> List[float]:
    """Per-step latency samples reconstructed from the step/chunk spans:
    one sample per span, dur_s / steps — the same rule the run-time
    metrics registry observes, so the two reconstructions are comparable."""
    out = []
    for r in events:
        if (
            r.get("kind") == "span"
            and r.get("event") in STEP_SPANS
            and r.get("status") == "ok"
            and isinstance(r.get("steps"), int)
            and r["steps"] > 0
            and isinstance(r.get("dur_s"), (int, float))
        ):
            out.append(float(r["dur_s"]) / r["steps"])
    return out


def _achieved_line(
    label: str,
    flops: Any,
    bytes_: Any,
    per_step_s: Any,
    platform: str,
) -> Optional[str]:
    """One roofline line: achieved GFLOP/s / GB/s for a per-step cost
    record against the platform's peak spec (obs/perf/roofline.py), or
    None when the record is incomplete."""
    if not (
        isinstance(per_step_s, (int, float))
        and per_step_s > 0
        and (isinstance(flops, (int, float)) or isinstance(bytes_, (int, float)))
    ):
        return None
    from heat3d_tpu.obs.perf.roofline import peak_spec

    spec = peak_spec(platform)
    parts = []
    if isinstance(flops, (int, float)):
        g = flops / per_step_s / 1e9
        peak = spec.get("vector_gflops")
        pct = f" ({g / peak:.1%} of peak)" if peak else ""
        parts.append(f"{g:.2f} GFLOP/s{pct}")
    if isinstance(bytes_, (int, float)):
        g = bytes_ / per_step_s / 1e9
        peak = spec.get("mem_gbps")
        pct = f" ({g / peak:.1%} of peak)" if peak else ""
        parts.append(f"{g:.2f} GB/s{pct}")
    return f"   roofline {label} [{platform}]: " + "  ".join(parts)


def roofline_lines(events: List[Dict[str, Any]]) -> List[str]:
    """The ``roofline`` section of a run summary: achieved-vs-peak lines
    joining (a) bench_row events that carry the cost-analysis fields with
    their own measured seconds, and (b) a ``step_cost`` event with the
    run_loop span's per-step latency. Empty when the run recorded no cost
    telemetry; never raises (telemetry display fails soft too)."""
    lines: List[str] = []
    try:
        for r in events:
            if r.get("event") != "bench_row":
                continue
            grid = "x".join(str(g) for g in (r.get("grid") or []))
            if r.get("bench") == "halo" and isinstance(
                r.get("cost_bytes_per_step"), (int, float)
            ):
                # halo rows carry their own exchange-program bytes
                # (ROADMAP "cost-analysis fields for halo rows"): the p50
                # divides them directly — no throughput-row join needed.
                # rtt_dominated rows are excluded, matching `obs regress`:
                # their p50 is mostly dispatch overhead, so bytes/p50
                # would claim an absurd fraction of peak
                p50 = r.get("p50_us")
                if (
                    isinstance(p50, (int, float))
                    and p50 > 0
                    and not r.get("rtt_dominated")
                ):
                    line = _achieved_line(
                        f"halo {grid} p50",
                        None,
                        r.get("cost_bytes_per_step"),
                        p50 * 1e-6,
                        str(r.get("platform", "?")),
                    )
                    if line:
                        lines.append(line)
                continue
            if isinstance(r.get("cost_flops_per_step"), (int, float)) or (
                isinstance(r.get("cost_bytes_per_step"), (int, float))
            ):
                steps = r.get("steps")
                sec = r.get("seconds_best")
                if isinstance(steps, int) and steps > 0 and isinstance(
                    sec, (int, float)
                ):
                    tb = r.get("time_blocking", 1)
                    label = f"bench {grid} tb={tb}"
                    # fused-route rows: say so in the label — the halo
                    # bytes ride inside the step kernel here, so these
                    # lines are not comparable to exchange-path rows of
                    # the same shape without the tag
                    if r.get("fused_rdma_path"):
                        label += " fused-rdma"
                    elif r.get("fused_dma_path"):
                        label += " fused-dma"
                    frac = r.get("cost_redundant_flops_frac")
                    if isinstance(frac, (int, float)) and frac > 0:
                        # deep-tb rows: flag how much of the raw rate is
                        # ghost-ring recompute, not simulated progress
                        label += f" ({frac:.0%} recompute)"
                    line = _achieved_line(
                        label,
                        r.get("cost_flops_per_step"),
                        r.get("cost_bytes_per_step"),
                        sec / steps,
                        str(r.get("platform", "?")),
                    )
                    if line:
                        lines.append(line)
        costs = [
            r
            for r in events
            if r.get("event") == "step_cost" and r.get("ok")
        ]
        loops = [
            r
            for r in events
            if r.get("kind") == "span"
            and r.get("event") == "run_loop"
            and isinstance(r.get("steps"), int)
            and r["steps"] > 0
            and isinstance(r.get("dur_s"), (int, float))
        ]
        if costs and loops:
            c, lp = costs[0], loops[0]
            line = _achieved_line(
                "run_loop",
                c.get("cost_flops_per_step"),
                c.get("cost_bytes_per_step"),
                lp["dur_s"] / lp["steps"],
                str(c.get("platform", "?")),
            )
            if line:
                lines.append(line)
    except Exception:  # noqa: BLE001 - a summary section must not kill summary
        return lines
    return lines


def ensemble_lines(events: List[Dict[str, Any]]) -> List[str]:
    """The ensemble section of a run summary: for every throughput
    bench_row aggregating more than one member per step (the batched
    scenario engine — docs/SERVING.md), print the total rate NEXT TO the
    per-member effective rate, so a packed batch's aggregate can never
    read as a single-run number. Empty for solo-only ledgers; never
    raises (summary sections fail soft)."""
    lines: List[str] = []
    try:
        for r in events:
            if r.get("event") != "bench_row" or r.get("bench") != "throughput":
                continue
            m = r.get("members_per_step")
            g = r.get("gcell_per_sec")
            if not (
                isinstance(m, int)
                and m > 1
                and isinstance(g, (int, float))
            ):
                continue
            grid = "x".join(str(x) for x in (r.get("grid") or []))
            bm = r.get("batch_mesh", 1)
            lines.append(
                f"   ensemble {grid} B={m} (batch_mesh={bm}): "
                f"{g:.4g} Gcell/s total -> {g / m:.4g} Gcell/s/member "
                f"effective"
            )
    except Exception:  # noqa: BLE001 - a summary section must not kill summary
        return lines
    return lines


def elastic_lines(events: List[Dict[str, Any]]) -> List[str]:
    """The elastic-degradation section of a run summary: each
    ``elastic_refactor`` (old mesh -> new mesh, re-stitch seconds) and
    the degraded windows (enter/exit pairs; an unclosed window is an
    honest ``still degraded``). Fails soft to [] like every summary
    section."""
    lines: List[str] = []
    try:
        for r in events:
            if r.get("event") == "elastic_refactor":
                lines.append(
                    f"   elastic {r.get('direction', 'degrade')}: "
                    f"mesh {r.get('old_mesh')} -> {r.get('new_mesh')} "
                    f"({r.get('survivors')} survivor(s), re-stitch "
                    f"{_fmt_s(r.get('restitch_s'))}) at step "
                    f"{r.get('step')}"
                )
            elif r.get("event") == "degraded_mode_enter":
                lines.append(
                    f"   degraded mode ENTER at step {r.get('step')} "
                    f"(mesh {r.get('mesh')})"
                )
            elif r.get("event") == "degraded_mode_exit":
                lines.append(
                    f"   degraded mode EXIT at step {r.get('step')} "
                    f"after {_fmt_s(r.get('degraded_s'))} "
                    f"(mesh {r.get('mesh')} restored)"
                )
        enters = sum(
            1 for r in events if r.get("event") == "degraded_mode_enter"
        )
        exits = sum(
            1 for r in events if r.get("event") == "degraded_mode_exit"
        )
        if enters > exits:
            lines.append("   degraded mode: still degraded at ledger end")
    except Exception:  # noqa: BLE001 - a summary section must not kill summary
        return []
    return lines


def summarize_run(run_id: str, events: List[Dict[str, Any]], out=None) -> None:
    out = out or sys.stdout
    head = events[0]
    procs = sorted({r.get("proc", 0) for r in events})
    print(f"\n== run {run_id} ({len(events)} events, procs {procs})", file=out)
    opens = [r for r in events if r.get("event") == "ledger_open"]
    if opens:
        argv = opens[0].get("argv")
        if argv:
            print(f"   argv: {' '.join(str(a) for a in argv)}", file=out)
    t_first, t_last = head.get("ts"), events[-1].get("ts")
    if isinstance(t_first, (int, float)) and isinstance(t_last, (int, float)):
        print(
            f"   wall: {_fmt_ts(t_first)} -> {_fmt_ts(t_last)} "
            f"({t_last - t_first:.3f}s)",
            file=out,
        )

    # span table
    by_name: Dict[str, List[float]] = defaultdict(list)
    errors: Dict[str, int] = defaultdict(int)
    for r in events:
        if r.get("kind") == "span" and isinstance(
            r.get("dur_s"), (int, float)
        ):
            by_name[r["event"]].append(float(r["dur_s"]))
            if r.get("status") == "error":
                errors[r["event"]] += 1
    if by_name:
        print(
            f"   {'span':<20} {'count':>6} {'total':>10} {'p50':>10} "
            f"{'p95':>10} {'err':>4}",
            file=out,
        )
        for name, durs in sorted(by_name.items(), key=lambda kv: -sum(kv[1])):
            print(
                f"   {name:<20} {len(durs):>6} {_fmt_s(sum(durs)):>10} "
                f"{_fmt_s(percentile(durs, 50)):>10} "
                f"{_fmt_s(percentile(durs, 95)):>10} "
                f"{errors.get(name, 0):>4}",
                file=out,
            )

    lat = step_latencies(events)
    if lat:
        print(
            f"   step latency ({len(lat)} chunks): "
            f"p50 {_fmt_s(percentile(lat, 50))}  "
            f"p95 {_fmt_s(percentile(lat, 95))}  "
            f"mean {_fmt_s(sum(lat) / len(lat))}",
            file=out,
        )

    # elastic-degradation section (docs/RESILIENCE.md): one line per
    # survivor-mesh re-factorization + the degraded windows, so an outage
    # that a run survived degraded is attributable at a glance
    for line in elastic_lines(events):
        print(line, file=out)

    # roofline section: cost-analysis telemetry joined with measured time
    for line in roofline_lines(events):
        print(line, file=out)

    # ensemble section: packed-batch rows split total vs per-member rate
    for line in ensemble_lines(events):
        print(line, file=out)

    # comm observatory: per-link probe table (docs/OBSERVABILITY.md §9)
    try:
        from heat3d_tpu.obs.comm.report import comm_lines

        for line in comm_lines(events):
            print(line, file=out)
    except Exception:  # noqa: BLE001 - a summary section must not kill summary
        pass

    # drift/straggler section: rolling-baseline step-time anomalies
    # (obs/perf/timeline.detect_anomalies — regress's tolerance bands);
    # fails soft like every other summary section
    try:
        from heat3d_tpu.obs.perf.timeline import (
            detect_anomalies,
            format_anomaly,
        )

        anomalies = detect_anomalies(events)
        for a in anomalies[:8]:
            print(f"   {format_anomaly(a)}", file=out)
        if len(anomalies) > 8:
            print(f"   ... ({len(anomalies) - 8} more anomalies)", file=out)
    except Exception:  # noqa: BLE001 - a summary section must not kill summary
        pass

    # timeline of notable events
    shown = 0
    for r in events:
        name = r.get("event")
        if name not in NOTABLE or name == "ledger_open":
            continue
        detail_keys = [
            k
            for k in (
                "kind_", "step", "steps", "steps_done", "generation",
                "resumed_from", "stop_reason", "attempts", "fault", "path",
                "reason", "status", "bench", "grid", "ok",
                "key", "knobs", "applied", "speedup_vs_default",
                "vector_gflops",
                "request_id", "members", "padded", "queue_depth",
                "batch_members", "queue_latency_s",
                "verdict", "depth_max", "delivered", "batches",
                "span", "delta_pct", "events", "streams",
                "direction", "old_mesh", "new_mesh", "survivors",
                "restitch_s", "mesh", "degraded_s", "bucket", "attempt",
                "backoff_s", "anchor_event", "ci_s", "stages",
            )
            if k in r
        ]
        detail = " ".join(f"{k}={r[k]}" for k in detail_keys)
        print(f"   {_fmt_ts(r.get('ts'))} {name:<18} {detail}", file=out)
        shown += 1
        if shown >= 60:
            print("   ... (timeline truncated)", file=out)
            break


def cmd_summary(args) -> int:
    events = read_ledger(args.ledger)
    if not events:
        print(f"no events in {args.ledger}", file=sys.stderr)
        return 1
    runs: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    order: List[str] = []
    for r in events:
        rid = str(r.get("run_id"))
        if rid not in runs:
            order.append(rid)
        runs[rid].append(r)
    if args.run:
        if args.run not in runs:
            print(f"run {args.run} not in {args.ledger}", file=sys.stderr)
            return 1
        order = [args.run]
    print(f"ledger: {args.ledger} ({len(events)} events, {len(runs)} run(s))")
    for rid in order:
        summarize_run(rid, runs[rid])
    return 0


def _tail_line(r: Dict[str, Any]) -> str:
    base = (
        f"{_fmt_ts(r.get('ts'))} [{str(r.get('run_id'))[:8]}/"
        f"{r.get('proc', '?')}] {r.get('event', '?')}"
    )
    rest = {
        k: v
        for k, v in r.items()
        if k
        not in ("ts", "run_id", "proc", "seq", "event", "kind", "t0", "t1")
    }
    if r.get("kind") == "span":
        base += f" [{_fmt_s(rest.pop('dur_s', None))}]"
        rest.pop("depth", None)
    return f"{base} {json.dumps(rest, default=repr)}"


def cmd_tail(args) -> int:
    if getattr(args, "follow", False):
        return _tail_follow(args)
    events = read_ledger(args.ledger)
    for r in events[-args.n:]:
        print(_tail_line(r))
    return 0


def _tail_follow(args) -> int:
    """``tail --follow``: print the last N events, then poll the growing
    ledger (rotation-aware via LedgerTailer) until --duration elapses
    (0 = until interrupted)."""
    from heat3d_tpu.obs.tailer import LedgerTailer

    tailer = LedgerTailer(args.ledger)
    deadline = (
        time.monotonic() + args.duration if args.duration > 0 else None
    )
    first = True
    try:
        while True:
            batch = tailer.poll()
            if first:
                batch = batch[-args.n:]
                first = False
            for r in batch:
                print(_tail_line(r))
            sys.stdout.flush()
            if deadline is not None and time.monotonic() >= deadline:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


def cmd_check(args) -> int:
    if getattr(args, "follow", False):
        return _check_follow(args)
    from heat3d_tpu.obs.check import main as check_main

    flags = []
    if args.taxonomy:
        flags.append("--taxonomy")
    if args.start_line != 1:
        flags.extend(["--start-line", str(args.start_line)])
    return check_main(flags + args.ledgers)


def _check_follow(args) -> int:
    """``check --follow``: incremental live lint — tail each growing
    ledger and feed new lines through the same rules as the post-hoc
    check, reporting each defect once as it appears. rc 1 if any defect
    surfaced by the time --duration elapses (0 = until interrupted)."""
    from heat3d_tpu.analysis.ledgerlint import StreamChecker
    from heat3d_tpu.obs.tailer import LedgerTailer

    pairs = [
        (path, LedgerTailer(path), StreamChecker(taxonomy=args.taxonomy))
        for path in args.ledgers
    ]
    deadline = (
        time.monotonic() + args.duration if args.duration > 0 else None
    )
    defects = 0
    try:
        while True:
            for path, tailer, checker in pairs:
                for raw in tailer.poll_lines():
                    for line_no, desc in checker.feed(raw):
                        defects += 1
                        print(f"{path}:{line_no}: {desc}")
            sys.stdout.flush()
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    total = sum(c.lines_seen for _, _, c in pairs)
    print(
        f"check --follow: {total} line(s) across {len(pairs)} ledger(s), "
        f"{defects} defect(s)"
    )
    return 1 if defects else 0


def cmd_trace(args) -> int:
    """``obs trace LEDGER REQUEST``: one request's end-to-end
    decomposition — queue / pack / compute / deliver shares plus requeue
    gaps — reconstructed from its ``serve_span`` events (rotation-aware).
    ``REQUEST`` is the integer request id or the 12-hex trace id. rc 1
    when the request has no trace in the ledger, rc 2 unreadable."""
    try:
        events = read_ledger(args.ledger)
    except OSError as e:
        print(f"trace: cannot read ledger: {e}", file=sys.stderr)
        return 2
    want_rid: Optional[int] = None
    try:
        want_rid = int(args.request)
    except ValueError:
        pass
    spans = [
        r
        for r in events
        if r.get("event") == "serve_span"
        and isinstance(r.get("t0_wall"), (int, float))
        and isinstance(r.get("t1_wall"), (int, float))
        and (
            r.get("request_id") == want_rid
            if want_rid is not None
            else r.get("trace_id") == args.request
        )
    ]
    if not spans:
        print(
            f"trace: no serve_span events for request {args.request!r} "
            f"in {args.ledger}",
            file=sys.stderr,
        )
        return 1
    root = next((r for r in spans if r.get("span") == "request"), spans[0])
    t0 = min(float(r["t0_wall"]) for r in spans)
    total = max(float(root["t1_wall"]) - float(root["t0_wall"]), 1e-12)
    rid = root.get("request_id")
    # shed events cannot carry a request id (a shed request never got
    # one); requeues do — annotate from the serve_requeue events too
    requeues = [
        r
        for r in events
        if r.get("event") == "serve_requeue"
        and isinstance(r.get("request_ids"), list)
        and rid in r["request_ids"]
    ]
    phases = []
    for r in spans:
        w0, w1 = float(r["t0_wall"]), float(r["t1_wall"])
        rec = {
            "span": r.get("span"),
            "start_s": round(w0 - t0, 6),
            "dur_s": round(w1 - w0, 6),
            "share": round((w1 - w0) / total, 4),
        }
        for k in ("attempt", "backoff_s"):
            if r.get(k) is not None:
                rec[k] = r[k]
        phases.append(rec)
    phases.sort(key=lambda p: (p["start_s"], -p["dur_s"]))
    out = {
        "request_id": rid,
        "trace_id": root.get("trace_id"),
        "bucket": root.get("bucket"),
        "stream": root.get("stream"),
        "attempts": root.get("attempts"),
        "total_s": round(total, 6),
        "phases": phases,
        "requeues": len(requeues),
    }
    if args.as_json:
        print(json.dumps(out))
        return 0
    head = f"request {rid} trace {out['trace_id']}"
    if out.get("bucket"):
        head += f" bucket {out['bucket']}"
    if out.get("stream"):
        head += f" stream {out['stream']}"
    print(f"{head}: total {_fmt_s(total)} ({out['attempts']} attempt(s))")
    for p in phases:
        extra = ""
        if p["span"] == "requeue_gap":
            extra = (
                f"  (attempt {p.get('attempt')}, "
                f"backoff {_fmt_s(p.get('backoff_s'))})"
            )
        print(
            f"  {p['span']:<12} +{p['start_s']:.3f}s  "
            f"{_fmt_s(p['dur_s']):>10}  {p['share']:>7.1%}{extra}"
        )
    if requeues:
        print(f"  ({len(requeues)} serve_requeue event(s) touched this request)")
    return 0


def _watch_block(status: Dict[str, Any]) -> List[str]:
    lines = [
        f"-- watch @ {_fmt_ts(status.get('t_end'))} "
        f"({status['events_seen']} events, window {status['window_s']:g}s)",
        f"   arrivals {status['arrival_hz']}/s  "
        f"deliveries {status['delivery_hz']}/s  "
        f"queue depth {status.get('queue_depth')}",
    ]
    for bucket, st in sorted((status.get("buckets") or {}).items()):
        lines.append(
            f"   {bucket}: n={st.get('count')} "
            f"p50 {_fmt_s(st.get('p50_s'))} p95 {_fmt_s(st.get('p95_s'))} "
            f"p99 {_fmt_s(st.get('p99_s'))}"
        )
    if status.get("degraded") or status.get("degraded_s"):
        lines.append(
            f"   degraded: {bool(status.get('degraded'))} "
            f"(cumulative {_fmt_s(status.get('degraded_s'))})"
        )
    burn = status.get("burn") or {}
    for o in burn.get("objectives", []):
        fast, slow = o["fast"], o["slow"]
        mark = " ALERT" if o.get("alerting") else ""
        f_burn = "-" if fast["burn"] is None else f"{fast['burn']:.2f}"
        s_burn = "-" if slow["burn"] is None else f"{slow['burn']:.2f}"
        lines.append(
            f"   burn {o['name']}: fast({fast['window_s']:g}s) {f_burn}  "
            f"slow({slow['window_s']:g}s) {s_burn}{mark}"
        )
    if status.get("flags"):
        lines.append(
            "   flags: "
            + "  ".join(f"{k}={v}" for k, v in sorted(status["flags"].items()))
        )
    return lines


def cmd_watch(args) -> int:
    """``obs watch LEDGER``: the live terminal view — tail the growing
    ledger through the streaming burn-rate evaluator and print a status
    block per tick. ``--once`` does a single pass (post-hoc replay of
    whatever the ledger holds now) and exits; rc is 1 when any objective
    is alerting at the end, 0 otherwise, 2 on an unreadable spec."""
    from heat3d_tpu.obs.burn import BurnEvaluator
    from heat3d_tpu.obs.perf.slo import load_spec
    from heat3d_tpu.obs.tailer import LedgerTailer

    try:
        spec = load_spec(args.spec)
    except (OSError, ValueError) as e:
        print(f"watch: {e}", file=sys.stderr)
        return 2
    be = BurnEvaluator(spec)
    tailer = LedgerTailer(args.ledger)
    deadline = (
        time.monotonic() + args.duration if args.duration > 0 else None
    )
    status: Dict[str, Any] = {}
    comm_events: List[Dict[str, Any]] = []
    try:
        while True:
            batch = tailer.poll()
            be.consume(batch)
            # comm observatory: accumulate the probe rows seen so far and
            # render the per-link table under the burn block (fail-soft,
            # like the summary section)
            comm_events.extend(
                r
                for r in batch
                if isinstance(r, dict) and r.get("event") == "comm_probe"
            )
            status = be.status()
            if args.as_json:
                if comm_events:
                    try:
                        from heat3d_tpu.obs.comm.report import comm_link_stats

                        status["comm"] = comm_link_stats(comm_events)
                    except Exception:  # noqa: BLE001 - fails soft
                        pass
                print(json.dumps(status))
            else:
                lines = _watch_block(status)
                if comm_events:
                    try:
                        from heat3d_tpu.obs.comm.report import comm_lines

                        lines += comm_lines(comm_events)
                    except Exception:  # noqa: BLE001 - fails soft
                        pass
                for line in lines:
                    print(line)
            sys.stdout.flush()
            if args.once or (
                deadline is not None and time.monotonic() >= deadline
            ):
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 1 if (status.get("burn") or {}).get("alerting") else 0


def main(argv: Optional[List[str]] = None) -> int:
    # these subcommands own their full argparse surfaces
    # (obs/perf/*.main, obs/comm/adjudicate.main); dispatch before the
    # ledger parser so their flags don't have to round-trip through it
    argv_l = list(sys.argv[1:] if argv is None else argv)
    owned = {
        "roofline": "heat3d_tpu.obs.perf.roofline",
        "regress": "heat3d_tpu.obs.perf.regress",
        "merge": "heat3d_tpu.obs.perf.merge",
        "timeline": "heat3d_tpu.obs.perf.timeline",
        "slo": "heat3d_tpu.obs.perf.slo",
        "adjudicate": "heat3d_tpu.obs.comm.adjudicate",
    }
    if argv_l and argv_l[0] in owned:
        import importlib

        mod = importlib.import_module(owned[argv_l[0]])
        return mod.main(argv_l[1:])

    p = argparse.ArgumentParser(
        prog="heat3d obs",
        description="inspect heat3d run ledgers (JSONL event streams) and "
        "judge performance (roofline / regress / merge — obs/perf)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("summary", help="per-run span tables and timeline")
    s.add_argument("ledger")
    s.add_argument("--run", default=None, help="only this run_id")
    s.set_defaults(fn=cmd_summary)

    t = sub.add_parser("tail", help="last N events, one per line")
    t.add_argument("ledger")
    t.add_argument("-n", type=int, default=20)
    t.add_argument(
        "--follow", action="store_true",
        help="keep polling the growing ledger (rotation-aware)",
    )
    t.add_argument("--interval", type=float, default=0.5)
    t.add_argument(
        "--duration", type=float, default=0.0,
        help="stop following after this many seconds (0 = until ^C)",
    )
    t.set_defaults(fn=cmd_tail)

    c = sub.add_parser("check", help="schema lint (same as scripts/check_ledger.py)")
    c.add_argument("ledgers", nargs="+")
    c.add_argument(
        "--taxonomy", action="store_true",
        help="also audit event names against the canonical registry "
        "(heat3d_tpu/analysis/registry.py)",
    )
    c.add_argument(
        "--start-line", type=int, default=1,
        help="report only defects at/after this line (append-mode "
        "session scoping)",
    )
    c.add_argument(
        "--follow", action="store_true",
        help="live lint: tail the growing ledger(s) and report each "
        "defect once as it appears",
    )
    c.add_argument("--interval", type=float, default=0.5)
    c.add_argument(
        "--duration", type=float, default=0.0,
        help="stop following after this many seconds (0 = until ^C)",
    )
    c.set_defaults(fn=cmd_check)

    tr = sub.add_parser(
        "trace",
        help="one request's queue/pack/compute/deliver decomposition "
        "from its serve_span events",
    )
    tr.add_argument("ledger")
    tr.add_argument(
        "request", help="request id (integer) or 12-hex trace id"
    )
    tr.add_argument("--json", action="store_true", dest="as_json")
    tr.set_defaults(fn=cmd_trace)

    w = sub.add_parser(
        "watch",
        help="live serve-tier view: rates, queue depth, windowed bucket "
        "percentiles, SLO burn rate per objective, anomaly flags",
    )
    w.add_argument("ledger")
    w.add_argument(
        "--spec", default=None,
        help="SLO spec JSON (default: $HEAT3D_SLO_SPEC or built-in)",
    )
    w.add_argument("--interval", type=float, default=2.0)
    w.add_argument(
        "--duration", type=float, default=0.0,
        help="stop watching after this many seconds (0 = until ^C)",
    )
    w.add_argument(
        "--once", action="store_true",
        help="one evaluation pass over the current ledger, then exit",
    )
    w.add_argument("--json", action="store_true", dest="as_json")
    w.set_defaults(fn=cmd_watch)

    # listed for --help discoverability; dispatched above before parsing
    sub.add_parser(
        "roofline", add_help=False,
        help="achieved-vs-peak: per-phase cost_analysis table (live) or "
        "the analytic row model over bench results",
    )
    sub.add_parser(
        "regress", add_help=False,
        help="perf-regression gate over bench history (pass/warn/fail)",
    )
    sub.add_parser(
        "merge", add_help=False,
        help="join per-process multihost ledgers with cross-host skew stats",
    )
    sub.add_parser(
        "timeline", add_help=False,
        help="unified performance timeline: Chrome-trace/Perfetto export "
        "+ step-time drift and host-straggler detection",
    )
    sub.add_parser(
        "slo", add_help=False,
        help="service-level objectives: burn-rate verdict over serve "
        "latency buckets, step-time and halo-share ceilings",
    )
    sub.add_parser(
        "adjudicate", add_help=False,
        help="POD_RUNBOOK A/B stage verdicts (halo_plan / halo_order / "
        "slab widths) from bench rows or merged ledgers",
    )

    args = p.parse_args(argv_l)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
