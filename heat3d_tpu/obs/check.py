"""Ledger schema lint — compatibility shim. The implementation was
promoted into :mod:`heat3d_tpu.analysis.ledgerlint` (the analysis
subsystem owns the data-lint cores and their shared finding format);
``scripts/check_ledger.py`` and ``heat3d obs check`` keep importing from
here, so the CI gate and the operator command still cannot drift apart.
"""

from __future__ import annotations

from heat3d_tpu.analysis.ledgerlint import (  # noqa: F401
    EPS,
    MAX_REPORT,
    Defect,
    _check_event,
    _check_nesting,
    check_file,
    check_file_findings,
    main,
)
