"""The run ledger: an append-only JSONL event stream for every entry point.

PR 1 made failures survivable; this makes them *explainable*. A supervised
run that heals and resumes, a bench sweep that journals half its rows and
is SIGTERM'd, a checkpoint generation that quarantines — each leaves a
machine-readable record of what happened, when, and at what cost, in one
place: the ledger file. ``scripts/check_ledger.py`` lints it,
``heat3d obs summary`` turns it into timelines and p50/p95 tables, and the
resilience tests assert observability of the failures they inject.

Event shape (one JSON object per line, append-only, flushed per event)::

    {"ts": <wall unix seconds at write>, "run_id": "...", "proc": 0,
     "seq": 7, "event": "<name>", "kind": "point" | "span", ...fields}

Span events additionally carry ``t0``/``t1`` (``time.monotonic`` bounds —
immune to wall-clock steps, comparable only within one process), ``dur_s``,
``depth`` (nesting level at open), and ``status`` (``ok`` | ``error``).
Spans are written AT CLOSE, so file order is end-time order and parent
spans appear after their children — the lint's nesting check and the
summary's timeline both rely on this.

Activation: entry points call :func:`activate` with their ``--ledger``
flag; library code calls :func:`get` unconditionally and writes through
whatever is active. With no flag, ``HEAT3D_LEDGER=<path>`` activates the
ledger from the environment (how ``run_bench_suite.sh`` threads one ledger
through every row's subprocess); with neither, :func:`get` returns the
:data:`NULL` ledger and every hook is a cheap no-op.

Context tagging: ``set_context(generation=8)`` merges fields into every
subsequent event (the supervisor tags its current generation this way), so
a heal/resume session is reconstructable from the ledger alone.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import uuid
from typing import Any, Dict, Optional

ENV_LEDGER = "HEAT3D_LEDGER"
ENV_LEDGER_MAX_MB = "HEAT3D_LEDGER_MAX_MB"
SCHEMA_VERSION = 1

# Fields every event must carry (the contract scripts/check_ledger.py
# enforces — change them together).
REQUIRED_FIELDS = ("ts", "run_id", "proc", "seq", "event", "kind")
SPAN_FIELDS = ("t0", "t1", "dur_s", "depth", "status")


def _process_index() -> int:
    """jax.process_index() without initializing the backend (the same lazy
    rule as utils.logging._Process0Filter: an early call would break a
    later jax.distributed.initialize)."""
    try:
        from jax._src import xla_bridge

        if not xla_bridge.backends_are_initialized():
            return 0
        import jax

        return int(jax.process_index())
    except (ImportError, AttributeError, RuntimeError):
        # jax private-API drift (module moved / function renamed) or
        # backend state not queryable: degrade to 0, never crash activate
        return 0


def _new_run_id() -> str:
    return uuid.uuid4().hex[:12]


def _segment_path(path: str, idx: int) -> str:
    """Rolled-segment naming: ``ledger.jsonl`` rolls to ``ledger.0.jsonl``,
    ``ledger.1.jsonl``, ... (oldest first); the base path is always the
    active file."""
    stem, ext = os.path.splitext(path)
    return f"{stem}.{idx}{ext}" if ext else f"{path}.{idx}"


def _env_max_bytes() -> int:
    """Rotation cap from ``HEAT3D_LEDGER_MAX_MB`` (float MB; unset,
    unparseable, or <= 0 disables rotation)."""
    raw = os.environ.get(ENV_LEDGER_MAX_MB, "")
    if not raw:
        return 0
    try:
        mb = float(raw)
    except ValueError:
        return 0
    return int(mb * 1e6) if mb > 0 else 0


def ledger_segments(path: str) -> "list[str]":
    """All on-disk segments of a (possibly rotated) ledger, oldest first,
    the active base path last. With no rolled siblings this is just
    ``[path]`` — readers can call it unconditionally."""
    out = []
    i = 0
    while True:
        seg = _segment_path(path, i)
        if not os.path.exists(seg):
            break
        out.append(seg)
        i += 1
    out.append(path)
    return out


class SpanHandle:
    """Mutable view of an in-flight span: ``add(**fields)`` attaches fields
    to the record written at close; ``dur_s`` is readable after the span
    exits (callers feed it to the metrics registry)."""

    def __init__(self) -> None:
        self.fields: Dict[str, Any] = {}
        self.dur_s: Optional[float] = None

    def add(self, **fields: Any) -> None:
        self.fields.update(fields)


class _SpanCtx:
    def __init__(self, ledger: "Ledger", name: str, fields: Dict[str, Any]):
        self._ledger = ledger
        self._name = name
        self._fields = fields
        self.handle = SpanHandle()

    def __enter__(self) -> SpanHandle:
        self._t0 = time.monotonic()
        self._depth = self._ledger._enter_span()
        return self.handle

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.monotonic()
        self.handle.dur_s = t1 - self._t0
        self._ledger._exit_span()
        status = "ok" if exc_type is None else "error"
        fields = dict(self._fields)
        fields.update(self.handle.fields)
        if exc_type is not None:
            fields.setdefault(
                "error", f"{exc_type.__name__}: {str(exc)[:200]}"
            )
        span_fields = {
            "t0": self._t0,
            "t1": t1,
            "dur_s": self.handle.dur_s,
            "depth": self._depth,
            "status": status,
        }
        span_fields.update(
            (k, v) for k, v in fields.items() if k not in span_fields
        )
        self._ledger._write(self._name, "span", span_fields)
        return False  # never swallow


class Ledger:
    """Append-only JSONL event stream for one process.

    Thread-safe for writes (one lock); span DEPTH is tracked per thread so
    a background thread's spans cannot corrupt the main thread's nesting.
    The file is opened in append mode and flushed per event — a crash
    (SIGKILL, backend wedge) loses at most the event being written, and a
    relaunched run appends a new ``run_id`` segment to the same file.
    """

    def __init__(
        self,
        path: str,
        run_id: Optional[str] = None,
        meta: Optional[Dict[str, Any]] = None,
    ):
        self.path = path
        self.run_id = run_id or _new_run_id()
        self._lock = threading.Lock()
        self._seq = 0
        self._ctx: Dict[str, Any] = {}
        self._depth = threading.local()
        # pinned ONCE at open: re-resolving per event would flip proc from
        # 0 (pre-backend-init) to the real index mid-stream, splitting one
        # stream into two (run_id, proc) lint keys. Entry points activate
        # after distributed.initialize, so the resolution here is final.
        self.proc = _process_index()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._f = open(path, "a")
        # size-capped rotation (HEAT3D_LEDGER_MAX_MB): one continuous
        # (run_id, proc, seq) stream spans the rolled segments, so the
        # lint's per-stream checks hold on the concatenation
        self._max_bytes = _env_max_bytes()
        self._rolled = 0
        while os.path.exists(_segment_path(path, self._rolled)):
            self._rolled += 1
        try:
            self._bytes = self._f.tell()
        except OSError:
            self._bytes = 0
        open_fields = {
            "schema": SCHEMA_VERSION,
            "pid": os.getpid(),
            "argv": list(sys.argv)[:12],
        }
        open_fields.update(meta or {})
        self._write("ledger_open", "point", open_fields)

    # ---- span-depth bookkeeping (per thread) -----------------------------

    def _enter_span(self) -> int:
        depth = getattr(self._depth, "v", 0)
        self._depth.v = depth + 1
        return depth

    def _exit_span(self) -> None:
        self._depth.v = max(getattr(self._depth, "v", 1) - 1, 0)

    # ---- the write path --------------------------------------------------

    def _write(self, name: str, kind: str, fields: Dict[str, Any]) -> None:
        with self._lock:
            if self._f.closed:  # post-close stragglers: drop, don't crash
                return
            record = {
                "ts": time.time(),
                "run_id": self.run_id,
                "proc": self.proc,
                "seq": self._seq,
                "event": name,
                "kind": kind,
            }
            # precedence: envelope > explicit event fields > ambient context
            for src in (fields, self._ctx):
                for k, v in src.items():
                    if k not in record:
                        record[k] = v
            self._seq += 1
            try:
                line = json.dumps(record, default=repr)
            except Exception:  # noqa: BLE001 - default=repr runs arbitrary
                # __repr__, so any exception class can surface here
                # a bad field must not kill the run being observed — AND
                # the salvage record must stay schema-valid (a span
                # stripped of its span fields would fail the project's own
                # lint and fail the bench suite): salvage per field,
                # dropping only the unserializable ones. The envelope and
                # span fields are self-constructed primitives and always
                # survive.
                salvaged = {}
                dropped = []
                for k, v in record.items():
                    try:
                        json.dumps(v, default=repr)
                        salvaged[k] = v
                    except Exception:  # noqa: BLE001 - same repr exposure
                        dropped.append(k)
                salvaged["malformed_fields"] = dropped
                try:
                    line = json.dumps(salvaged, default=repr)
                except Exception:  # noqa: BLE001
                    # a value whose repr itself raises: drop to the
                    # envelope alone, hand-formatted — the fields are
                    # self-constructed primitives, so this cannot raise
                    # and the record stays schema-valid
                    line = (
                        '{"ts": %r, "run_id": "%s", "proc": %d, "seq": %d,'
                        ' "event": "%s", "kind": "%s",'
                        ' "salvage_failed": true}'
                        % (
                            record["ts"], record["run_id"], record["proc"],
                            record["seq"], record["event"], record["kind"],
                        )
                    )
            try:
                self._f.write(line + "\n")
                self._f.flush()
                self._bytes += len(line) + 1
                if self._max_bytes and self._bytes >= self._max_bytes:
                    self._rotate_locked()
            except (OSError, ValueError) as e:
                # telemetry must never kill the run it observes: a failed
                # write (disk full, path gone read-only mid-run) disables
                # the ledger — one stderr note, every later event dropped
                try:
                    self._f.close()
                except OSError:
                    pass
                print(
                    f"heat3d: ledger {self.path} disabled "
                    f"({type(e).__name__}: {e}); further events dropped",
                    file=sys.stderr,
                )

    def _rotate_locked(self) -> None:
        """Roll the active file aside (``ledger.N.jsonl``, oldest ``.0``)
        and reopen the base path fresh; called under ``self._lock`` after a
        successful write. The rename preserves byte content, so a tailer's
        saved offset into the old base carries into the rolled segment.
        Fail-soft: any OSError disables rotation (one stderr note) and the
        ledger keeps appending to whatever file is open."""
        try:
            self._f.close()
            os.replace(self.path, _segment_path(self.path, self._rolled))
            self._rolled += 1
            self._f = open(self.path, "a")
            self._bytes = 0
        except OSError as e:
            self._max_bytes = 0
            print(
                f"heat3d: ledger rotation for {self.path} disabled "
                f"({type(e).__name__}: {e})",
                file=sys.stderr,
            )
            try:
                if self._f.closed:
                    self._f = open(self.path, "a")
            except OSError:
                pass  # next _write sees a closed file and drops, per fail-soft

    # ---- public API ------------------------------------------------------

    def set_context(self, **fields: Any) -> None:
        """Merge ``fields`` into every subsequent event (``None`` deletes
        a key) — run-scoped tags like the supervisor's current generation."""
        for k, v in fields.items():
            if v is None:
                self._ctx.pop(k, None)
            else:
                self._ctx[k] = v

    def event(self, name: str, **fields: Any) -> None:
        """Write one point event. Field names colliding with the envelope
        (ts/run_id/proc/seq/event/kind) are dropped by the envelope-first
        merge in ``_write`` — spell them differently (e.g. ``kind_``)."""
        self._write(name, "point", fields)

    def span(self, name: str, **fields: Any) -> _SpanCtx:
        """Context manager timing a region; writes one span event at exit
        (status ``error`` + the exception's repr if the body raised —
        re-raised, never swallowed). Yields a :class:`SpanHandle`."""
        return _SpanCtx(self, name, fields)

    def close(self, **fields: Any) -> None:
        self._write("ledger_close", "point", fields)
        with self._lock:
            try:
                self._f.close()
            except OSError as e:
                # close flushes; ENOSPC at the final flush must not turn
                # a completed run's exit path into a crash (the fail-soft
                # invariant heat3d lint enforces on this surface)
                print(
                    f"heat3d: ledger {self.path} close failed ({e})",
                    file=sys.stderr,
                )

    @property
    def active(self) -> bool:
        return True


class NullLedger:
    """The inactive ledger: same surface, no IO — library code calls
    ``obs.get().event(...)`` unconditionally and pays one attribute check
    when no ledger is configured."""

    path = None
    run_id = None
    active = False

    def set_context(self, **fields: Any) -> None:
        pass

    def event(self, name: str, **fields: Any) -> None:
        pass

    def span(self, name: str, **fields: Any) -> "_NullSpanCtx":
        return _NullSpanCtx()

    def close(self, **fields: Any) -> None:
        pass


class _NullSpanCtx:
    def __enter__(self) -> SpanHandle:
        self._t0 = time.monotonic()
        self.handle = SpanHandle()
        return self.handle

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.handle.dur_s = time.monotonic() - self._t0
        return False


NULL = NullLedger()
_active: Optional[Ledger] = None
_env_checked = False


def activate(
    path: Optional[str] = None,
    meta: Optional[Dict[str, Any]] = None,
) -> "Ledger | NullLedger":
    """Open the process ledger at ``path`` (or ``$HEAT3D_LEDGER`` when
    ``path`` is None) and make it the one :func:`get` returns. With
    neither configured, leaves the NULL ledger active. Idempotent per
    path: re-activating the already-active path is a no-op."""
    global _active, _env_checked
    _env_checked = True
    path = path or os.environ.get(ENV_LEDGER) or None
    if not path:
        return _active or NULL
    if _active is not None and _active.path == path:
        return _active
    if _active is not None:
        _active.close(reason="reactivated")
    try:
        _active = Ledger(path, meta=meta)
    except OSError as e:
        # an unwritable ledger path must fail soft at whatever call site
        # triggered activation (env-lazy get() can be deep inside library
        # code) — the run proceeds unledgered, loudly
        print(
            f"heat3d: cannot open ledger {path} ({e}); running without one",
            file=sys.stderr,
        )
        _active = None
        return NULL
    return _active


def get() -> "Ledger | NullLedger":
    """The active ledger (env-activated on first call when
    ``HEAT3D_LEDGER`` is set), or the no-op NULL ledger."""
    global _env_checked
    if _active is None and not _env_checked:
        _env_checked = True
        if os.environ.get(ENV_LEDGER):
            return activate()
    return _active or NULL


def deactivate(**fields: Any) -> None:
    """Close and detach the active ledger (entry points' exit path; also
    what tests use to isolate ledgers)."""
    global _active, _env_checked
    if _active is not None:
        _active.close(**fields)
    _active = None
    _env_checked = False
