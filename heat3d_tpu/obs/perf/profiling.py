"""Device-trace capture with ledger provenance.

``--profile DIR`` (solver CLI, bench CLI — ``--profile-dir`` stays as the
legacy spelling) brackets the timed region with ``jax.profiler`` trace
capture. Capture is not free: starting the profiler takes tens of ms,
stopping it serializes the trace to disk — both perturb the run being
measured. So the bracket records its own cost: one ``profile_capture``
ledger event at close carrying the artifact path (the newest
``*.xplane.pb`` under the directory), the start/stop overhead in seconds,
and whether capture actually engaged. A profiled bench row is then
tellable from an unprofiled one in the post-mortem, and the overhead is
auditable instead of silently folded into the measurement.

Failure posture: a profiler that cannot start (unwritable dir, platform
without profiler support, double-capture) must not kill the observed run —
the bracket degrades to a no-op and the ledger event says so
(``ok: false`` + the error). Exceptions from the BODY always propagate;
the trace is flushed (and recorded) either way, so a crashed run still
leaves its trace behind.
"""

from __future__ import annotations

import contextlib
import glob
import os
import time
from typing import Optional


def _newest_artifact(profile_dir: str) -> Optional[str]:
    """The newest .xplane.pb under ``profile_dir`` (the file
    scripts/summarize_trace.py reads), or None if capture left nothing."""
    try:
        files = glob.glob(
            os.path.join(profile_dir, "**", "*.xplane.pb"), recursive=True
        )
        return max(files, key=os.path.getmtime) if files else None
    except OSError:
        return None


def _force_reset_profiler_state() -> None:
    """Drop jax's module-level profiler session after a FAILED stop.

    ``jax.profiler.stop_trace`` clears its session only after a
    successful export; an export that raises (e.g. the target turned out
    not to be a directory) leaves the session set, and every LATER trace
    in the process then dies with "Only one profile may be run at a time"
    — one bad capture must not poison all subsequent ones. Private-API
    touch, fully guarded: on drift this degrades to the old behavior
    (later captures fail soft), never to a crash."""
    try:
        from jax._src import profiler as _profiler

        with _profiler._profile_state.lock:
            _profiler._profile_state.profile_session = None
    except Exception:  # noqa: BLE001 - best effort only
        pass


class _ProfileCapture:
    def __init__(self, profile_dir: str):
        self.profile_dir = profile_dir
        self._trace_cm = None
        self._enter_s: Optional[float] = None

    def __enter__(self) -> "_ProfileCapture":
        t0 = time.perf_counter()
        try:
            # pre-flight the target BEFORE starting the profiler: a bad
            # path (existing file, unwritable parent) otherwise surfaces
            # only at stop_trace's export, wedging the process-wide
            # profiler session (see _force_reset_profiler_state)
            os.makedirs(self.profile_dir, exist_ok=True)
            import jax

            cm = jax.profiler.trace(self.profile_dir)
            cm.__enter__()
            self._trace_cm = cm
        except Exception as e:  # noqa: BLE001 - capture must fail soft
            self._error = f"{type(e).__name__}: {str(e)[:200]}"
            self._trace_cm = None
        else:
            self._error = None
        self._enter_s = time.perf_counter() - t0
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t0 = time.perf_counter()
        if self._trace_cm is not None:
            try:
                # flush with clean (None) args even when the body raised:
                # the profiler context must not mask the body's exception,
                # and a failed run's trace is exactly the one worth keeping
                self._trace_cm.__exit__(None, None, None)
            except Exception as e:  # noqa: BLE001 - flush fails soft too
                self._error = f"{type(e).__name__}: {str(e)[:200]}"
                self._trace_cm = None
                _force_reset_profiler_state()
        exit_s = time.perf_counter() - t0
        from heat3d_tpu import obs

        fields = {
            "dir": self.profile_dir,
            "ok": self._trace_cm is not None,
            "enter_overhead_s": self._enter_s,
            "exit_overhead_s": exit_s,
        }
        artifact = _newest_artifact(self.profile_dir)
        if artifact is not None:
            fields["artifact"] = artifact
        if self._error is not None:
            fields["error"] = self._error
            import sys

            print(
                f"heat3d: profile capture to {self.profile_dir} degraded "
                f"({self._error}); run continues unprofiled",
                file=sys.stderr,
            )
        obs.get().event("profile_capture", **fields)
        obs.REGISTRY.gauge(
            "profile_capture_overhead_seconds",
            "profiler start+stop cost around the traced region",
        ).set(self._enter_s + exit_s, ok=str(fields["ok"]).lower())
        return False  # never swallow the body's exception


def profile_capture(profile_dir: Optional[str]):
    """The one profiler bracket every entry point wraps its timed region
    in (``utils.timing.maybe_profile`` delegates here): ``jax.profiler``
    trace capture into ``profile_dir`` + a ``profile_capture`` ledger
    event recording artifact path and capture overhead. A falsy dir is a
    plain no-op context."""
    if not profile_dir:
        return contextlib.nullcontext()
    return _ProfileCapture(profile_dir)
