"""Service-level objectives: ``heat3d obs slo`` — a declarative objective
spec evaluated from the run ledger (plus an optional profile capture)
into a burn-rate verdict.

PR 7's serve queue records latency histograms but had no *objectives*:
nothing said what latency is acceptable, so nothing could say whether a
drain was healthy. This module closes that loop the same way ``obs
regress`` closed the perf loop — a machine verdict with tolerance
structure and honest rc semantics (rc 1 ONLY on an objective breach;
warn and no-data exit 0, so a fresh deployment without traffic doesn't
redden CI).

**Objective spec** (JSON; ``--spec`` or ``HEAT3D_SLO_SPEC``)::

    {
      "warn_ratio": 0.9,
      "objectives": [
        {"name": "queue-p95", "kind": "serve_latency", "percentile": 95,
         "max_s": 0.5},
        {"name": "queue-p50-small", "kind": "serve_latency",
         "percentile": 50, "max_s": 0.1, "bucket": "(16, 16, 16)"},
        {"name": "step-p95", "kind": "step_time", "percentile": 95,
         "max_s": 0.05},
        {"name": "halo-share", "kind": "halo_share", "max_frac": 0.4}
      ]
    }

Three objective kinds, three sources:

- ``serve_latency`` — per-serve-bucket p50/p95 queue latency, from the
  ``serve_metrics_summary`` ledger event the queue emits at drain end
  (post-hoc evaluation never needs the live registry); ``bucket`` is a
  substring filter on the bucket key, absent = every bucket, and the
  WORST matching bucket governs. Falls back to reconstructing one
  ``(all)`` pseudo-bucket from ``serve_result`` events for pre-summary
  ledgers.
- ``step_time`` — per-run per-step latency ceiling, from the
  run_loop/chunk spans (the same reconstruction ``obs summary`` prints).
- ``halo_share`` — fraction of attributed device time spent in halo
  exchange, from a ``--profile`` capture's per-phase totals
  (``obs.perf.timeline``); without a capture the objective reports
  ``no_data`` rather than guessing from wall-clock.
- ``serve_degraded`` — cumulative seconds the serving tier spent in
  degraded mode (backend-loss requeues open the window, the next
  successful batch closes it; docs/SERVING.md "Degraded-mode
  serving"), judged against a ``max_s`` degraded-time budget. Source
  is the same ``serve_metrics_summary`` (``degraded_s`` field —
  always present on summaries new enough to carry the feature;
  pre-elastic ledgers report ``no_data``, never a vacuous pass).

**Burn rate** = measured / objective. ``breach`` above 1.0, ``warn`` at
or above ``warn_ratio`` (spec field; ``HEAT3D_SLO_WARN_RATIO``
overrides; default 0.9) — the early-warning margin before the ceiling,
mirroring regress's warn band. The verdict lands in the ledger as an
``slo_verdict`` event (fail-soft, like all telemetry) and in ``heat3d
serve --slo``'s drain report (docs/SERVING.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

ENV_SLO_SPEC = "HEAT3D_SLO_SPEC"
ENV_SLO_WARN_RATIO = "HEAT3D_SLO_WARN_RATIO"
DEFAULT_WARN_RATIO = 0.9

KINDS = ("serve_latency", "step_time", "halo_share", "serve_degraded")

# The spec used when none is configured: ceilings generous enough that
# only a genuinely wedged run breaches them — so the CI smoke exercises
# the whole evaluate path without inventing policy for the operator.
DEFAULT_SPEC: Dict[str, Any] = {
    "default_spec": True,
    "warn_ratio": DEFAULT_WARN_RATIO,
    "objectives": [
        {"name": "serve-queue-p95", "kind": "serve_latency",
         "percentile": 95, "max_s": 60.0},
        {"name": "step-p95", "kind": "step_time",
         "percentile": 95, "max_s": 60.0},
    ],
}


def validate_spec(spec: Any, origin: str = "spec") -> Dict[str, Any]:
    """Validate an in-memory objective spec (the loadgen soak passes its
    mix's inline ``slo`` block through here — same rules as a file spec,
    with ``origin`` naming the source in errors). Returns the spec with
    objective names defaulted; raises ValueError on malformation."""
    if not isinstance(spec, dict) or not isinstance(
        spec.get("objectives"), list
    ):
        raise ValueError(f"{origin}: SLO spec needs an 'objectives' list")
    for i, o in enumerate(spec["objectives"]):
        if not isinstance(o, dict):
            raise ValueError(f"{origin}: objective #{i} must be an object")
        kind = o.get("kind")
        if kind not in KINDS:
            raise ValueError(
                f"{origin}: objective #{i} kind must be one of {KINDS}, "
                f"got {kind!r}"
            )
        target_key = "max_frac" if kind == "halo_share" else "max_s"
        if not isinstance(o.get(target_key), (int, float)) or o[target_key] <= 0:
            raise ValueError(
                f"{origin}: objective #{i} ({o.get('name', kind)}) needs a "
                f"positive {target_key}"
            )
        # p99 is soak-only in practice: the drain reservoir records
        # p50/p95, and the load generator merges a full-sample p99 into
        # the summary it hands evaluate()
        if kind in ("serve_latency", "step_time") and o.get(
            "percentile"
        ) not in (50, 95, 99):
            raise ValueError(
                f"{origin}: objective #{i} percentile must be 50, 95 or "
                "99 (the percentiles the metrics/soak layers record)"
            )
        o.setdefault("name", f"{kind}-#{i}")
    return spec


def load_spec(path: Optional[str] = None) -> Dict[str, Any]:
    """The objective spec at ``path`` (or ``$HEAT3D_SLO_SPEC``), validated;
    :data:`DEFAULT_SPEC` when neither is configured. Raises ValueError on
    a malformed spec and OSError on an unreadable path — an SLO gate that
    cannot read its objectives must say so, not pass vacuously (the same
    posture as regress's unreadable-input rc 2)."""
    path = path or os.environ.get(ENV_SLO_SPEC) or None
    if not path:
        return dict(DEFAULT_SPEC)
    with open(path) as f:
        try:
            spec = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: unparseable SLO spec: {e}") from None
    spec = validate_spec(spec, origin=path)
    spec["path"] = path
    return spec


def _warn_ratio(spec: Dict[str, Any], override: Optional[float]) -> float:
    """Precedence: explicit argument > ``HEAT3D_SLO_WARN_RATIO`` > spec
    field > default — env beats the committed spec so an operator can
    tighten the early-warning margin for one session without editing
    policy files."""
    if override is not None:
        return override
    env = os.environ.get(ENV_SLO_WARN_RATIO)
    if env:
        try:
            return float(env)
        except ValueError:
            pass  # a bad override must not kill the gate
    wr = spec.get("warn_ratio")
    return float(wr) if isinstance(wr, (int, float)) else DEFAULT_WARN_RATIO


def serve_summary_from_events(
    events: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """The serve-side evaluation source: the LAST ``serve_metrics_summary``
    event (cumulative — later supersedes earlier), else a reconstruction
    from ``serve_result`` queue latencies as one ``(all)`` pseudo-bucket
    (pre-summary ledgers), else None."""
    from heat3d_tpu.obs.metrics import percentile

    last = None
    for r in events:
        if r.get("event") == "serve_metrics_summary" and isinstance(
            r.get("buckets"), dict
        ):
            last = r
    if last is not None:
        return {
            "buckets": last["buckets"],
            "depth_max": last.get("depth_max"),
            # degraded-mode provenance (absent on pre-elastic ledgers —
            # the serve_degraded objective then reads no_data)
            "degraded": last.get("degraded"),
            "degraded_s": last.get("degraded_s"),
            "requeues": last.get("requeues"),
            "source": "serve_metrics_summary",
        }
    lat = [
        float(r["queue_latency_s"])
        for r in events
        if r.get("event") == "serve_result"
        and isinstance(r.get("queue_latency_s"), (int, float))
    ]
    if not lat:
        return None
    return {
        "buckets": {
            "(all)": {
                "count": len(lat),
                "p50_s": percentile(lat, 50),
                "p95_s": percentile(lat, 95),
                "max_s": max(lat),
            }
        },
        "depth_max": None,
        "source": "serve_result reconstruction",
    }


def _status(burn: Optional[float], warn_ratio: float) -> str:
    if burn is None:
        return "no_data"
    if burn > 1.0:
        return "breach"
    if burn >= warn_ratio:
        return "warn"
    return "ok"


def evaluate_objective(
    o: Dict[str, Any],
    serve_summary: Optional[Dict[str, Any]],
    step_samples: List[float],
    phase_us: Optional[Dict[str, float]],
    warn_ratio: float,
) -> Dict[str, Any]:
    """ONE objective judged against prepared inputs — the shared core
    behind both the post-hoc gate (:func:`evaluate`) and the streaming
    burn-rate monitor (``obs.burn.BurnEvaluator``), so the two paths
    cannot drift: same worst-bucket rule, same rounding, same status
    bands. Returns the per-objective record (value / target / burn_rate /
    status)."""
    from heat3d_tpu.obs.metrics import percentile

    kind = o["kind"]
    rec: Dict[str, Any] = {
        "name": o.get("name", kind),
        "kind": kind,
    }
    value = None
    if kind == "serve_latency":
        rec["target_s"] = float(o["max_s"])
        field = f"p{o['percentile']}_s"
        want = o.get("bucket")
        per_bucket = {}
        for bucket, st in ((serve_summary or {}).get("buckets") or {}).items():
            if want and want not in str(bucket):
                continue
            v = st.get(field) if isinstance(st, dict) else None
            if isinstance(v, (int, float)):
                per_bucket[str(bucket)] = round(float(v), 6)
        if per_bucket:
            # the WORST matching bucket governs: an SLO met on average
            # but breached on one bucket is breached
            worst = max(per_bucket, key=per_bucket.get)
            value = per_bucket[worst]
            rec["bucket"] = worst
            rec["buckets"] = per_bucket
        burn = None if value is None else value / rec["target_s"]
    elif kind == "step_time":
        rec["target_s"] = float(o["max_s"])
        if step_samples:
            value = float(percentile(step_samples, o["percentile"]))
            rec["samples"] = len(step_samples)
        burn = None if value is None else value / rec["target_s"]
    elif kind == "serve_degraded":
        rec["target_s"] = float(o["max_s"])
        ds = (serve_summary or {}).get("degraded_s")
        if isinstance(ds, (int, float)):
            value = float(ds)
            if (serve_summary or {}).get("degraded"):
                rec["still_degraded"] = True
            rq = (serve_summary or {}).get("requeues")
            if isinstance(rq, int):
                rec["requeues"] = rq
        burn = None if value is None else value / rec["target_s"]
    else:  # halo_share
        rec["target_frac"] = float(o["max_frac"])
        if phase_us:
            known = {
                ph: us
                for ph, us in phase_us.items()
                if ph != "(unattributed)"
            }
            total = sum(known.values())
            if total > 0:
                value = known.get("halo_exchange", 0.0) / total
        burn = None if value is None else value / rec["target_frac"]
    rec["value"] = None if value is None else round(float(value), 6)
    rec["burn_rate"] = None if burn is None else round(burn, 4)
    rec["status"] = _status(burn, warn_ratio)
    return rec


def evaluate(
    events: List[Dict[str, Any]],
    spec: Dict[str, Any],
    serve_summary: Optional[Dict[str, Any]] = None,
    phase_us: Optional[Dict[str, float]] = None,
    warn_ratio: Optional[float] = None,
    step_samples: Optional[List[float]] = None,
) -> Dict[str, Any]:
    """Evaluate every objective in ``spec`` against the ledger ``events``
    (plus an optional live ``serve_summary`` — the serve CLI's drain
    wiring passes the queue's own summary so the verdict never waits on a
    ledger re-read — and a profile's ``phase_us`` for halo_share).
    ``step_samples`` overrides the ledger reconstruction (the streaming
    monitor passes its own accumulated samples). Returns the machine
    report: per-objective value/target/burn-rate/status and the overall
    verdict (``breach`` > ``warn`` > ``pass``)."""
    from heat3d_tpu.obs.cli import step_latencies

    wr = _warn_ratio(spec, warn_ratio)
    if serve_summary is None:
        serve_summary = serve_summary_from_events(events)
    if step_samples is None:
        step_samples = step_latencies(events)

    results = [
        evaluate_objective(o, serve_summary, step_samples, phase_us, wr)
        for o in spec.get("objectives", [])
    ]

    statuses = [r["status"] for r in results]
    verdict = (
        "breach"
        if "breach" in statuses
        else "warn"
        if "warn" in statuses
        else "pass"
    )
    report = {
        "verdict": verdict,
        "warn_ratio": wr,
        "objectives": results,
        "sources": {
            "serve": (serve_summary or {}).get("source"),
            "step_samples": len(step_samples),
            "profile_phases": sorted(phase_us) if phase_us else None,
        },
    }
    if spec.get("default_spec"):
        report["default_spec"] = True
    if spec.get("path"):
        report["spec"] = spec["path"]
    return report


def print_report(report: Dict[str, Any], out=None) -> None:
    out = out or sys.stdout
    tag = {"ok": "ok    ", "warn": "WARN  ", "breach": "BREACH",
           "no_data": "n/a   "}
    for r in report["objectives"]:
        target = r.get("target_s", r.get("target_frac"))
        burn = (
            f"burn {r['burn_rate']:.2f}"
            if r.get("burn_rate") is not None
            else "no data"
        )
        value = f"{r['value']}" if r.get("value") is not None else "-"
        bucket = f" [{r['bucket']}]" if r.get("bucket") else ""
        print(
            f"  {tag.get(r['status'], r['status'])} {r['name']}{bucket}: "
            f"{value} vs {target} ({burn})",
            file=out,
        )
    extra = " (built-in default spec)" if report.get("default_spec") else ""
    print(f"slo verdict: {report['verdict']}{extra}", file=out)


def record_verdict(report: Dict[str, Any]) -> None:
    """One ``slo_verdict`` ledger event (fail-soft; NULL ledger = no-op):
    the verdict, per-objective burn rates, and the spec provenance."""
    from heat3d_tpu import obs

    obs.get().event(
        "slo_verdict",
        verdict=report["verdict"],
        warn_ratio=report["warn_ratio"],
        objectives=[
            {
                "name": r["name"],
                "status": r["status"],
                "burn_rate": r.get("burn_rate"),
            }
            for r in report["objectives"]
        ],
        spec=report.get("spec"),
        default_spec=bool(report.get("default_spec")),
    )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="heat3d obs slo",
        description="evaluate declarative service-level objectives "
        "(per-bucket serve latency, step-time ceilings, halo share) "
        "against a run ledger; rc 1 ONLY on an objective breach "
        "(warn/no-data exit 0 — the obs regress rc convention)",
    )
    ap.add_argument("ledger", help="run ledger file (JSONL event stream)")
    ap.add_argument("--spec", default=None, metavar="FILE.json",
                    help="objective spec (default $HEAT3D_SLO_SPEC, else "
                    "a built-in generous default so the path stays "
                    "exercised)")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="profile capture for halo_share objectives "
                    "(per-phase device totals via obs timeline)")
    ap.add_argument("--warn-ratio", type=float, default=None,
                    help="warn at this fraction of a ceiling (default "
                    "$HEAT3D_SLO_WARN_RATIO, spec warn_ratio, or 0.9)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report")
    args = ap.parse_args(argv)

    try:
        spec = load_spec(args.spec)
    except (OSError, ValueError) as e:
        print(f"slo: {e}", file=sys.stderr)
        return 2
    try:
        from heat3d_tpu.obs.cli import read_ledger

        events = read_ledger(args.ledger)
    except OSError as e:
        print(f"slo: cannot read ledger: {e}", file=sys.stderr)
        return 2

    phase_us = None
    if args.profile:
        from heat3d_tpu.obs.perf.timeline import profile_phase_totals

        try:
            phase_us, _ = profile_phase_totals(args.profile)
        except (RuntimeError, OSError) as e:
            print(f"slo: profile ignored ({e})", file=sys.stderr)

    report = evaluate(
        events, spec, phase_us=phase_us, warn_ratio=args.warn_ratio
    )
    record_verdict(report)
    if args.json:
        print(json.dumps(report))
    else:
        print_report(report)
    return 1 if report["verdict"] == "breach" else 0


if __name__ == "__main__":
    sys.exit(main())
