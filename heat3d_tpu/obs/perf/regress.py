"""The automated perf-regression gate: ``heat3d obs regress``.

Compares a session's bench rows (the "current" file, optionally scoped
with ``--start-line`` to just the rows this session appended — the same
rule the provenance and ledger lints use) against the measured history:
other ``bench_results*.jsonl`` files, earlier rows of the current file,
and the committed driver artifacts (``BENCH_*.json``). Emits a
machine-readable pass/warn/fail verdict; ``run_bench_suite.sh`` runs it
next to the lints, so "did this PR regress the hot path" is a checked
fact, not a claim.

Baseline rules (each one exists because a naive diff lied once):

- **Platform-aware**: a row only ever compares against history measured
  on the same platform class. ``platform: cpu`` rows — including driver
  records flagged ``cpu_fallback`` — never compare against committed TPU
  records (rows predating the platform field default to ``tpu``: the
  committed record is on-chip by convention, bench.py applies the same
  default). A CPU smoke run therefore gets ``no_baseline``, not a
  100x "regression".
- **Config-keyed**: throughput rows match on (stencil, grid, mesh,
  dtype, compute_dtype, time_blocking, overlap, halo, backend); halo rows
  on (grid, mesh, dtype, halo); driver records on (metric, grid, dtype,
  time_blocking, backend).
- **Best-of-history**: the baseline is the best prior number (max
  throughput / min halo p50) — comparing against a one-off slow historic
  row would wave regressions through.
- **RTT-honest**: ``rtt_dominated`` rows (current or baseline) are
  excluded — their numbers are link artifacts, not measurements.
- **Age-windowed on request**: ``--window N`` limits the baseline pool to
  the last N measurement sessions (distinct UTC measurement dates from
  row ``ts`` provenance), so an ancient best row that stopped being
  reproducible can age out (ROADMAP "regression-gate history hygiene").
- **Tune-aware**: the report lists tuning-cache entries that flipped a
  default knob (``tuned_configs``), so a throughput shift coinciding
  with an autotuned route change is explainable from the verdict alone.

Tolerance bands are per-metric percentages: a drop worse than
``--fail-pct`` (default 15) fails, worse than ``--warn-pct`` (default 8)
warns, else passes. Halo latency regresses UPWARD; the directions are
encoded per metric, not per flag.

Exit code: 1 only on a ``fail`` verdict — ``warn`` and ``no_baseline``
exit 0, so fresh configs and noisy-but-tolerable sessions don't redden a
suite that just measured them.
"""

from __future__ import annotations

import argparse
import glob as _glob
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_WARN_PCT = 8.0
DEFAULT_FAIL_PCT = 15.0

# metric per bench kind: (field, direction) — +1 higher-is-better
METRICS: Dict[str, Tuple[str, int]] = {
    "throughput": ("gcell_per_sec_per_chip", +1),
    "halo": ("p50_us", -1),
    "driver": ("value", +1),
}


def band_status(
    delta_pct: float,
    warn_pct: float = DEFAULT_WARN_PCT,
    fail_pct: float = DEFAULT_FAIL_PCT,
) -> str:
    """pass/warn/fail for a signed regression percentage (positive =
    worse) — THE tolerance-band rule. :func:`compare` and the timeline
    drift detector (``obs.perf.timeline.detect_anomalies``) share it, so
    a step-time anomaly and a bench regression are judged by one band."""
    if delta_pct > fail_pct:
        return "fail"
    if delta_pct > warn_pct:
        return "warn"
    return "pass"


def _platform_class(row: Dict[str, Any]) -> str:
    """The comparability class: platform, with CPU-fallback driver records
    folded into 'cpu'. Rows predating the platform field are 'tpu' (the
    committed record is on-chip by convention — bench.py's rule)."""
    if row.get("cpu_fallback"):
        return "cpu"
    return str(row.get("platform") or "tpu")


def row_key(row: Dict[str, Any]) -> Optional[Tuple]:
    bench = row.get("bench")
    if bench == "throughput":
        return (
            "throughput",
            row.get("stencil", "7pt"),
            # equation-family leg: spec-built families (PR 11) never
            # cross-compare with heat — rows predating the field are
            # heat by construction (only heat existed)
            row.get("equation", "heat"),
            # time-integrator leg (PR 19): a CG solve or two-level
            # leapfrog step must never cross-compare with the explicit
            # sweep — rows predating the field are explicit-euler by
            # construction (only it existed)
            row.get("integrator", "explicit-euler"),
            tuple(row.get("grid") or ()),
            tuple(row.get("mesh") or ()),
            row.get("dtype"),
            row.get("compute_dtype", "float32"),
            row.get("time_blocking", 1),
            bool(row.get("overlap")),
            row.get("halo", "ppermute"),
            row.get("halo_order", "axis"),
            row.get("halo_plan", "monolithic"),
            # fused-RDMA route leg: a fused superstep's rate must never
            # baseline against the unfused exchange path of the same
            # shape — rows predating the knob are off by construction
            row.get("fused_rdma", "off"),
            row.get("backend", "auto"),
            # ensemble workload axis: a packed batch's aggregate rate must
            # only ever baseline against the same batch shape — without
            # this key leg an ensemble win would mask (or fake) a
            # single-run regression (rows predating the field are solo)
            tuple(row.get("batch_shape") or (1,)),
            _platform_class(row),
        )
    if bench == "halo":
        return (
            "halo",
            tuple(row.get("grid") or ()),
            tuple(row.get("mesh") or ()),
            row.get("dtype"),
            row.get("halo", "ppermute"),
            row.get("halo_order", "axis"),
            row.get("halo_plan", "monolithic"),
            _platform_class(row),
        )
    if bench == "driver":
        return (
            "driver",
            row.get("metric"),
            row.get("grid"),
            row.get("dtype"),
            row.get("time_blocking", 1),
            row.get("backend", "auto"),
            _platform_class(row),
        )
    return None


def _rows_from_jsonl(path: str, start_line: int = 1, stop_line=None):
    """Bench rows from a JSONL results file, 1-indexed [start_line,
    stop_line) — the scoping handles "this session's rows" vs "the same
    file's earlier rows are history". Parsing is the shared
    ``roofline.iter_result_rows`` (one brace-tolerant parser for the
    whole perf package)."""
    from heat3d_tpu.obs.perf.roofline import iter_result_rows

    rows = []
    try:
        row_iter = iter_result_rows(
            path,
            kinds=("throughput", "halo"),
            start_line=start_line,
            stop_line=stop_line,
        )
        for i, r in row_iter:
            r["_src"] = f"{path}:{i}"
            rows.append(r)
    except OSError:
        pass
    return rows


def _rows_from_driver_artifact(path: str) -> List[Dict[str, Any]]:
    """The committed BENCH_*.json driver artifacts: one record each
    (``parsed`` holds the JSON line bench.py printed). Converted to a
    pseudo-row keyed as bench='driver'."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return []
    rec = doc.get("parsed") if isinstance(doc, dict) else None
    if rec is None and isinstance(doc, dict) and "value" in doc:
        rec = doc
    if not (isinstance(rec, dict) and isinstance(rec.get("value"), (int, float))):
        return []
    detail = rec.get("detail") if isinstance(rec.get("detail"), dict) else {}
    return [
        {
            "bench": "driver",
            "metric": rec.get("metric"),
            "value": float(rec["value"]),
            "grid": detail.get("grid"),
            "dtype": detail.get("dtype"),
            "time_blocking": detail.get("time_blocking", 1),
            "backend": detail.get("backend", "auto"),
            "platform": detail.get("platform"),
            "cpu_fallback": bool(detail.get("cpu_fallback"))
            or bool(rec.get("error")),
            "_src": path,
        }
    ]


def load_history(paths: List[str]) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for p in paths:
        if p.endswith(".json"):
            rows.extend(_rows_from_driver_artifact(p))
        else:
            rows.extend(_rows_from_jsonl(p))
    return rows


def compare(
    current: List[Dict[str, Any]],
    history: List[Dict[str, Any]],
    warn_pct: float = DEFAULT_WARN_PCT,
    fail_pct: float = DEFAULT_FAIL_PCT,
) -> Dict[str, Any]:
    """The gate. Returns the machine-readable report:
    ``{"verdict": "pass"|"warn"|"fail", "comparisons": [...],
    "no_baseline": [...], "skipped": [...]}``."""
    by_key: Dict[Tuple, List[Dict[str, Any]]] = {}
    for r in history:
        k = row_key(r)
        if k is not None and not r.get("rtt_dominated"):
            by_key.setdefault(k, []).append(r)

    comparisons, no_baseline, skipped = [], [], []
    for r in current:
        k = row_key(r)
        if k is None:
            continue
        bench = k[0]
        field, direction = METRICS[bench]
        members = r.get("members_per_step", 1)
        label = {
            "throughput": lambda r=r: (
                f"throughput {r.get('stencil', '7pt')} "
                f"{'x'.join(map(str, r.get('grid') or []))} "
                f"{r.get('dtype')} tb={r.get('time_blocking', 1)}"
                + (
                    f" B={members}"
                    if isinstance(members, int) and members > 1
                    else ""
                )
            ),
            "halo": lambda r=r: (
                f"halo {'x'.join(map(str, r.get('grid') or []))} "
                f"{r.get('dtype')}"
            ),
            "driver": lambda r=r: f"driver {r.get('metric')}",
        }[bench]()
        cur_v = r.get(field)
        if not isinstance(cur_v, (int, float)):
            skipped.append({"row": label, "reason": f"no {field}"})
            continue
        if r.get("rtt_dominated"):
            skipped.append({"row": label, "reason": "rtt_dominated"})
            continue
        # self-comparison can't happen through the CLI (current/history
        # split by line range, the current file is dropped from history
        # paths) — the identity check guards direct compare() callers only
        cands = [
            h.get(field)
            for h in by_key.get(k, [])
            if isinstance(h.get(field), (int, float)) and h is not r
        ]
        if not cands:
            no_baseline.append(
                {"row": label, "platform": _platform_class(r)}
            )
            continue
        baseline = max(cands) if direction > 0 else min(cands)
        # signed regression percentage: positive = worse
        if baseline == 0:
            skipped.append({"row": label, "reason": "zero baseline"})
            continue
        delta = (baseline - cur_v) / abs(baseline) * 100.0 * direction
        status = band_status(delta, warn_pct, fail_pct)
        comp = {
            "row": label,
            "metric": field,
            "platform": _platform_class(r),
            "current": cur_v,
            "baseline": baseline,
            "regression_pct": round(delta, 2),
            "status": status,
        }
        if bench == "throughput" and isinstance(members, int) and members > 1:
            # per-member effective rate: the honest serving number — the
            # aggregate counts every member's updates, so packing B
            # members multiplies it even when each member got slower
            comp["members_per_step"] = members
            comp["current_per_member"] = cur_v / members
            comp["baseline_per_member"] = baseline / members
        comparisons.append(comp)

    statuses = [c["status"] for c in comparisons]
    verdict = (
        "fail"
        if "fail" in statuses
        else "warn"
        if "warn" in statuses
        else "pass"
    )
    return {
        "verdict": verdict,
        "warn_pct": warn_pct,
        "fail_pct": fail_pct,
        "comparisons": comparisons,
        "no_baseline": no_baseline,
        "skipped": skipped,
    }


def filter_window(
    rows: List[Dict[str, Any]], window: Optional[int]
) -> List[Dict[str, Any]]:
    """History limited to the last ``window`` measurement SESSIONS, where
    a session is a distinct UTC measurement date (the ``ts`` provenance
    field every post-PR-2 row carries). ``window`` None/0 keeps
    everything — the historical best-of-history behavior. Rows WITHOUT a
    parseable ``ts`` (pre-provenance rows, driver-artifact pseudo-rows)
    are excluded when a window is active: a baseline whose age cannot be
    established cannot be shown to be inside it — exactly the
    "ancient best row stops being reproducible" hygiene this knob exists
    for (ROADMAP: regression-gate history hygiene). Sessions are counted
    PER PLATFORM CLASS: two recent CPU debug sessions must not evict the
    TPU baseline pool (which the platform-aware keying exists to protect
    — windowing before it would disarm it). A negative window is a
    caller bug, not a slicing request — rejected."""
    if window is not None and window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if not window:
        return rows

    def _date(r: Dict[str, Any]) -> Optional[str]:
        ts = r.get("ts")
        if isinstance(ts, str) and len(ts) >= 10:
            d = ts[:10]
            if d[4:5] == "-" and d[7:8] == "-":
                return d
        return None

    dates_by_platform: Dict[str, set] = {}
    for r in rows:
        d = _date(r)
        if d:
            dates_by_platform.setdefault(_platform_class(r), set()).add(d)
    keep = {
        (plat, d)
        for plat, dates in dates_by_platform.items()
        for d in sorted(dates)[-window:]
    }
    return [
        r for r in rows if (_platform_class(r), _date(r)) in keep
    ]


def tune_notes() -> List[Dict[str, Any]]:
    """Tuning-cache entries whose winning config differs from the static
    defaults — the gate's awareness that an autotune CHANGED the baseline
    config: a throughput shift that coincides with a knob flip is a route
    change, not a silent regression, and these notes make that visible in
    the verdict (report field ``tuned_configs``; informational, never a
    comparison input). Fails soft to an empty list."""
    import dataclasses

    from heat3d_tpu.core.config import SolverConfig

    # the static defaults ARE SolverConfig's field defaults — derive them
    # so a future default flip cannot desynchronize this report
    static = {
        f.name: f.default
        for f in dataclasses.fields(SolverConfig)
        if f.name in ("halo", "overlap", "time_blocking", "halo_order")
    }
    notes: List[Dict[str, Any]] = []
    try:
        from heat3d_tpu.tune.cache import cache_path, load

        doc = load()
        for key, e in sorted((doc.get("entries") or {}).items()):
            if not isinstance(e, dict):
                continue
            cfgd = e.get("config") or {}
            flips = {
                k: cfgd.get(k)
                for k, dflt in static.items()
                if k in cfgd and cfgd.get(k) != dflt
            }
            if flips:
                notes.append(
                    {
                        "key": key,
                        "tuned": flips,
                        "config": cfgd,
                        "cache": cache_path(),
                    }
                )
    except Exception:  # noqa: BLE001 - awareness is informational
        return []
    return notes


def default_history_paths(current: Optional[str] = None) -> List[str]:
    """Default history: bench_results*.jsonl + BENCH_*.json next to the
    current results file AND in the working directory (a scratch-path
    session still sees the committed record; an out-of-repo invocation
    still finds the record next to its own file — without the anchor to
    ``current`` the gate passes vacuously from any other cwd)."""
    roots = [os.getcwd()]
    if current:
        d = os.path.dirname(os.path.abspath(current))
        if d not in roots:
            roots.append(d)
    out: List[str] = []
    seen = set()
    for root in roots:
        for pat in ("bench_results*.jsonl", "BENCH_*.json"):
            for p in sorted(_glob.glob(os.path.join(root, pat))):
                ap = os.path.abspath(p)
                if ap not in seen:
                    seen.add(ap)
                    out.append(p)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="heat3d obs regress",
        description="perf-regression gate: compare bench rows against "
        "measured history with per-metric tolerance bands and "
        "platform-aware baselines",
    )
    ap.add_argument("current", help="this session's results file (.jsonl)")
    ap.add_argument(
        "--start-line", type=int, default=1,
        help="first line of CURRENT that belongs to this session (earlier "
        "lines become history — same scoping as the provenance lint)",
    )
    ap.add_argument(
        "--history", nargs="*", default=None,
        help="history files (.jsonl rows and/or BENCH_*.json driver "
        "artifacts); default: bench_results*.jsonl + BENCH_*.json in the "
        "current directory",
    )
    ap.add_argument("--warn-pct", type=float, default=DEFAULT_WARN_PCT)
    ap.add_argument("--fail-pct", type=float, default=DEFAULT_FAIL_PCT)
    def _window(s: str) -> int:
        n = int(s)
        if n < 0:
            raise argparse.ArgumentTypeError("--window must be >= 0")
        return n

    ap.add_argument(
        "--window", type=_window, default=None, metavar="N",
        help="baseline against the last N measurement sessions only "
        "(sessions = distinct UTC measurement dates from row ts "
        "provenance; rows without ts are excluded when windowing; 0 = "
        "all). Default: all of history",
    )
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report (one JSON "
                    "object) instead of the table")
    args = ap.parse_args(argv)

    # a gate that can't read its input must say so, not pass vacuously
    # (a typo'd path would otherwise report "pass" forever)
    try:
        with open(args.current):
            pass
    except OSError as e:
        print(f"regress: cannot read current results: {e}", file=sys.stderr)
        return 2

    current = _rows_from_jsonl(args.current, start_line=args.start_line)
    history = _rows_from_jsonl(args.current, stop_line=args.start_line)
    hist_paths = (
        args.history
        if args.history is not None
        else default_history_paths(args.current)
    )
    cur_abs = os.path.abspath(args.current)
    history += load_history(
        [p for p in hist_paths if os.path.abspath(p) != cur_abs]
    )
    history = filter_window(history, args.window)
    report = compare(
        current, history, warn_pct=args.warn_pct, fail_pct=args.fail_pct
    )
    if args.window:
        report["window_sessions"] = args.window
    # autotune awareness: list cache entries that flipped a default knob,
    # so a route change reads as a route change, not a silent regression
    report["tuned_configs"] = tune_notes()

    if args.json:
        print(json.dumps(report))
    else:
        print(
            f"regress: {len(report['comparisons'])} compared, "
            f"{len(report['no_baseline'])} without baseline, "
            f"{len(report['skipped'])} skipped "
            f"(warn>{args.warn_pct}% fail>{args.fail_pct}%)"
        )
        for c in report["comparisons"]:
            arrow = {"pass": "ok  ", "warn": "WARN", "fail": "FAIL"}[
                c["status"]
            ]
            per_member = (
                f"  [{c['current_per_member']:.4g}/member]"
                if "current_per_member" in c
                else ""
            )
            print(
                f"  {arrow} {c['row']} [{c['platform']}]: "
                f"{c['current']:.4g} vs best {c['baseline']:.4g} "
                f"({c['regression_pct']:+.1f}% regression){per_member}"
            )
        for n in report["no_baseline"]:
            print(f"  new  {n['row']} [{n['platform']}]: no baseline")
        for s in report["skipped"]:
            print(f"  skip {s['row']}: {s['reason']}")
        for t in report["tuned_configs"]:
            flips = " ".join(f"{k}={v}" for k, v in t["tuned"].items())
            print(f"  note tune cache overrides defaults for {t['key']}: "
                  f"{flips}")
        print(f"verdict: {report['verdict']}")
    return 1 if report["verdict"] == "fail" else 0


if __name__ == "__main__":
    sys.exit(main())
