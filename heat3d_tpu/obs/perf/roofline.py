"""Roofline attribution: what fraction of the hardware's peak did each
phase achieve?

Two complementary models, one module:

**Row model** (promoted from ``scripts/roofline_check.py``, which is now a
thin wrapper): the analytic HBM traffic model + VPU op-cost model of the
tap chain, applied to measured ``bench_results.jsonl`` rows — exact for
the step paths this framework emits, but blind to anything XLA adds.

**Compiled model** (new): FLOPs/bytes straight from
``compiled.cost_analysis()`` — XLA's own cost accounting of the real
executable — per PHASE program (``parallel.step.phase_programs``: the
compile targets are keyed by the same ``heat3d.*`` names the named-scope
spans and the profiler trace tables use, so a cost record joins a
measured span on one key). Combined with per-backend peak specs
(:data:`PEAK_SPECS`) this turns a measured phase time into
achieved-vs-peak fractions: the ``heat3d obs roofline`` live table, the
``roofline`` section of ``obs summary``, and the ``cost_flops_per_step``
/ ``cost_bytes_per_step`` fields on every bench row.

Caveat the numbers honestly: XLA's cost model sees custom calls (the
Mosaic/Pallas kernels) as opaque — flops on those routes are
underestimates; the bytes side and the jnp/conv routes are solid. Peak
specs are deliberately conservative defaults (env-overridable:
``HEAT3D_PEAK_MEM_GBPS`` / ``HEAT3D_PEAK_GFLOPS``); a fraction over 100%
means the chip beats the spec table, not a measurement bug.

Failure posture: cost analysis is telemetry — every consumer treats a
raised :func:`step_cost_fields` as "fields unavailable", never as a run
failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

# ---- per-backend peak specs -------------------------------------------------

# Per-chip peaks the achieved fractions divide by. mem_gbps = HBM (TPU) /
# host DRAM (CPU) stream bandwidth; vector_gflops = practically
# sustainable VECTOR f32 rate (stencil tap chains ride the VPU, not the
# MXU — quoting MXU TFLOPs here would make every fraction meaningless).
# There is no trustworthy public per-chip VPU number (same posture as the
# row model's --vpu-gops: calibrate from a measured compute-bound row), so
# the TPU compute peak defaults to None and the table prints "-" for it.
# CPU defaults are order-of-magnitude nominals for a single host process.
PEAK_SPECS: Dict[str, Dict[str, Optional[float]]] = {
    "tpu": {"mem_gbps": 819.0, "vector_gflops": None},  # v5e HBM; v5p ~2765
    "cpu": {"mem_gbps": 20.0, "vector_gflops": 50.0},
}
_FALLBACK_SPEC: Dict[str, Optional[float]] = {
    "mem_gbps": None,
    "vector_gflops": None,
}


def peak_spec(platform: str) -> Dict[str, Optional[float]]:
    """Peak spec for ``platform``; precedence per field: env override
    (``HEAT3D_PEAK_MEM_GBPS`` / ``HEAT3D_PEAK_GFLOPS``) > CALIBRATED
    per-chip-generation value (``heat3d obs roofline --calibrate`` writes
    it into the shared tuning-cache store, vector peak only — measured on
    THIS chip beats any table) > the static conservative defaults."""
    spec = dict(PEAK_SPECS.get(platform, _FALLBACK_SPEC))
    env_overridden = set()
    for env, key in (
        ("HEAT3D_PEAK_MEM_GBPS", "mem_gbps"),
        ("HEAT3D_PEAK_GFLOPS", "vector_gflops"),
    ):
        v = os.environ.get(env)
        if v:
            try:
                spec[key] = float(v)
                env_overridden.add(key)
            except ValueError:
                pass  # a bad override must not kill a report
    if "vector_gflops" not in env_overridden:
        # calibrated lookup only when the CURRENT process runs the
        # platform being asked about — a CPU box summarizing TPU rows
        # must not apply its own calibrated CPU peak to them
        try:
            import jax

            if jax.default_backend() == platform:
                from heat3d_tpu.tune.cache import chip_generation, load_peak

                calibrated = load_peak(chip_generation())
                if calibrated:
                    spec["vector_gflops"] = calibrated
        except Exception:  # noqa: BLE001 - telemetry fails soft
            pass
    return spec


# ---- compiled-model cost extraction ----------------------------------------


def extract_cost(cost_analysis: Any) -> Tuple[Optional[float], Optional[float]]:
    """``(flops, bytes_accessed)`` from a ``compiled.cost_analysis()``
    result (a dict on current jax, a one-element list of dicts on 0.4.x);
    None for whatever the backend didn't report."""
    ca = cost_analysis
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None, None
    flops = ca.get("flops")
    bytes_ = ca.get("bytes accessed")
    return (
        float(flops) if isinstance(flops, (int, float)) else None,
        float(bytes_) if isinstance(bytes_, (int, float)) else None,
    )


def cost_analysis_enabled() -> bool:
    """``HEAT3D_COST_ANALYSIS=0`` disables the per-row/per-run compiled
    cost accounting (it costs one extra step-program compile)."""
    return os.environ.get("HEAT3D_COST_ANALYSIS", "1").lower() not in (
        "0",
        "false",
    )


def step_cost_fields(solver) -> Dict[str, Optional[float]]:
    """Cost-analysis fields for the program ``solver``'s hot loop actually
    runs — whole-program (all shards) numbers from XLA's compiled cost
    model, normalized PER UPDATE. For ``time_blocking == 1`` that is the
    single-step executable; for ``time_blocking > 1`` it is the k-update
    superstep (``make_superstep_fn`` — one exchange amortized over k
    updates, ghost-ring recompute included) divided by k: costing the
    single step there would describe a program the bench never ran.
    Raises on any failure; callers treat that as "fields unavailable"
    (telemetry fails soft), never as a run failure."""
    import jax

    cfg = solver.cfg
    aval = jax.ShapeDtypeStruct(
        cfg.padded_shape, solver.storage_dtype, sharding=solver.sharding
    )
    if cfg.time_blocking > 1:
        from heat3d_tpu.parallel.step import make_superstep_fn

        program = jax.jit(
            make_superstep_fn(cfg, solver.mesh, solver._compute)
        )
        updates = cfg.time_blocking
    else:
        program, updates = solver._step, 1
    compiled = program.lower(aval).compile()
    flops, bytes_ = extract_cost(compiled.cost_analysis())
    # Raw-vs-effective honesty for temporally-blocked supersteps: the
    # XLA-counted flops are RAW (the chip executes the shrinking-ring
    # recompute trapezoid); the effective fraction discounts them to the
    # k useful sweeps (parallel.step.redundant_flops_frac) so a deep-tb
    # "win" that is mostly recompute is visible from the fields alone.
    from heat3d_tpu.parallel.step import redundant_flops_frac

    frac = redundant_flops_frac(cfg)
    raw_per_step = None if flops is None else flops / updates
    return {
        "cost_flops_per_step": raw_per_step,
        "cost_bytes_per_step": None if bytes_ is None else bytes_ / updates,
        "cost_redundant_flops_frac": frac,
        "cost_effective_flops_per_step": (
            None if raw_per_step is None else raw_per_step * (1.0 - frac)
        ),
    }


def halo_cost_fields(cfg) -> Dict[str, Optional[float]]:
    """Cost-analysis bytes for ONE ghost exchange of ``cfg`` — the
    ``halo_exchange`` phase program (``parallel.step.phase_programs``)
    compiled and read through XLA's cost model, so bench halo rows carry
    their own achieved-vs-peak denominator (ROADMAP "cost-analysis
    fields for halo rows"). The program includes the face-sized
    keep-alive writes that make every transport data-live (a small,
    honest overcount documented there). Raises on failure; callers treat
    that as "fields unavailable" (telemetry fails soft)."""
    import jax
    import jax.numpy as jnp

    from heat3d_tpu.models.heat3d import _select_backend
    from heat3d_tpu.parallel.step import PHASE_HALO, phase_programs
    from heat3d_tpu.parallel.topology import build_mesh, field_sharding

    mesh = build_mesh(cfg.mesh)
    sharding = field_sharding(mesh, cfg.mesh)
    program = phase_programs(cfg, mesh, _select_backend(cfg))[PHASE_HALO]
    aval = jax.ShapeDtypeStruct(
        cfg.padded_shape, jnp.dtype(cfg.precision.storage), sharding=sharding
    )
    compiled = jax.jit(program).lower(aval).compile()
    _, bytes_ = extract_cost(compiled.cost_analysis())
    return {"cost_bytes_per_step": bytes_}


def record_step_cost(solver, **extra: Any) -> Optional[Dict[str, Any]]:
    """Compute :func:`step_cost_fields` for ``solver`` and append one
    ``step_cost`` ledger event (plus the platform, so ``obs summary`` can
    pick the right peak spec). Fails soft: any error becomes an
    ``ok: false`` event and a None return."""
    from heat3d_tpu import obs

    if not cost_analysis_enabled():
        return None
    if not obs.get().active:
        # the ledger event is this function's ONLY output: without an
        # active ledger the extra lower+compile of the step program (tens
        # of seconds at pod-scale grids) would buy a discarded event
        return None
    try:
        import jax

        fields = step_cost_fields(solver)
        fields["platform"] = jax.default_backend()
    except Exception as e:  # noqa: BLE001 - telemetry fails soft
        obs.get().event(
            "step_cost", ok=False,
            error=f"{type(e).__name__}: {str(e)[:200]}",
        )
        return None
    obs.get().event("step_cost", ok=True, **fields, **extra)
    return fields


# ---- the live per-phase table ----------------------------------------------


def phase_costs_and_times(
    cfg, iters: int = 3, warmup: int = 1
) -> List[Dict[str, Any]]:
    """Compile each phase program of ``cfg``
    (:func:`heat3d_tpu.parallel.step.phase_programs`), read its
    cost_analysis, and time it: one record per phase with ``flops``,
    ``bytes``, ``seconds`` (best of ``iters``, RTT-subtracted), and the
    achieved rates. Runs on any platform — on CPU the numbers are XLA's
    CPU cost model over the same programs."""
    import jax
    import jax.numpy as jnp

    from heat3d_tpu.models.heat3d import _select_backend
    from heat3d_tpu.parallel.step import phase_programs
    from heat3d_tpu.parallel.topology import build_mesh, field_sharding
    from heat3d_tpu.utils.timing import force_sync, honest_time, sync_overhead

    mesh = build_mesh(cfg.mesh)
    sharding = field_sharding(mesh, cfg.mesh)
    compute = _select_backend(cfg)
    programs = phase_programs(cfg, mesh, compute)
    u = jax.device_put(
        jnp.zeros(cfg.padded_shape, jnp.dtype(cfg.precision.storage)),
        sharding,
    )
    rtt = sync_overhead()
    import time as _time

    out = []
    seen = {}
    for phase, fn in programs.items():
        if id(fn) in seen:  # fused_dma aliases the step program
            rec = dict(seen[id(fn)])
            rec["phase"] = phase
            rec["alias_of"] = seen[id(fn)]["phase"]
            out.append(rec)
            continue
        jitted = jax.jit(fn)
        try:
            compiled = jitted.lower(u).compile()
            flops, bytes_ = extract_cost(compiled.cost_analysis())
        except Exception as e:  # noqa: BLE001 - keep the table best-effort
            out.append(
                {
                    "phase": phase,
                    "error": f"{type(e).__name__}: {str(e)[:200]}",
                }
            )
            continue
        for _ in range(warmup):
            force_sync(jitted(u))
        times = []
        for _ in range(iters):
            t0 = _time.perf_counter()
            force_sync(jitted(u))
            times.append(honest_time(_time.perf_counter() - t0, rtt))
        sec = min(times)
        rec = {
            "phase": phase,
            "flops": flops,
            "bytes": bytes_,
            "seconds": sec,
            "gflops": (flops / sec / 1e9) if flops else None,
            "gbps": (bytes_ / sec / 1e9) if bytes_ else None,
        }
        if phase == "step" and cfg.time_blocking > 1:
            # the step program is the k-update SUPERSTEP: its flops/gflops
            # are RAW (recompute trapezoid included). Attach the effective
            # side — useful updates per second and the recompute discount
            # — so the table can show both without re-deriving.
            from heat3d_tpu.parallel.step import redundant_flops_frac

            rec["updates_per_call"] = cfg.time_blocking
            rec["redundant_flops_frac"] = redundant_flops_frac(cfg)
            rec["eff_gcell_per_s"] = (
                cfg.grid.num_cells * cfg.time_blocking / sec / 1e9
            )
        seen[id(fn)] = rec
        out.append(rec)
    return out


def _pct(v: Optional[float], peak: Optional[float]) -> str:
    if v is None or not peak:
        return "-"
    return f"{v / peak:7.1%}"


def print_live_table(
    cfg, records: List[Dict[str, Any]], platform: str, out=None
) -> None:
    """The per-phase achieved-vs-peak table ``heat3d obs roofline``
    prints: phase, XLA-counted flops/bytes, measured time, achieved
    GFLOP/s and GB/s, and the fraction of each peak — plus which ceiling
    binds."""
    out = out or sys.stdout
    spec = peak_spec(platform)
    mem, vec = spec.get("mem_gbps"), spec.get("vector_gflops")
    grid = "x".join(str(g) for g in cfg.grid.shape)
    print(
        f"roofline [{platform}] grid={grid} stencil={cfg.stencil.kind} "
        f"dtype={cfg.precision.storage} tb={cfg.time_blocking} "
        f"backend={cfg.backend} "
        f"(peaks: mem {mem or '?'} GB/s, vector {vec or '?'} GFLOP/s)",
        file=out,
    )
    print(
        f"{'phase':<16} {'flops':>12} {'bytes':>12} {'time':>10} "
        f"{'GFLOP/s':>9} {'GB/s':>8} {'%flops':>8} {'%mem':>8} {'bound':>6}",
        file=out,
    )
    for r in records:
        if "error" in r:
            print(f"{r['phase']:<16} error: {r['error']}", file=out)
            continue
        alias = f" (= {r['alias_of']})" if r.get("alias_of") else ""
        fm = _pct(r.get("gflops"), vec)
        bm = _pct(r.get("gbps"), mem)
        bound = "?"
        if r.get("gbps") is not None and mem:
            bound = "mem"
            if (
                r.get("gflops") is not None
                and vec
                and r["gflops"] / vec > r["gbps"] / mem
            ):
                bound = "flops"
        # Deep-tb honesty: the superstep's %flops is achieved-vs-peak on
        # RAW flops (what the chip executes); print the EFFECTIVE rate
        # (useful updates only) and the recompute discount next to it so
        # a tb=k row whose raw rate rides on ghost-ring recompute cannot
        # read as a clean win.
        eff = ""
        if r.get("eff_gcell_per_s") is not None:
            eff = (
                f"  eff {r['eff_gcell_per_s']:.3f} Gcell/s "
                f"({r.get('redundant_flops_frac', 0.0):.0%} recompute, "
                f"{r.get('updates_per_call')} upd/call)"
            )
        print(
            f"{r['phase']:<16} "
            f"{r['flops'] if r['flops'] is not None else '-':>12} "
            f"{r['bytes'] if r['bytes'] is not None else '-':>12} "
            f"{r['seconds'] * 1e3:>8.2f}ms "
            f"{r['gflops'] if r['gflops'] is not None else 0:>9.2f} "
            f"{r['gbps'] if r['gbps'] is not None else 0:>8.2f} "
            f"{fm:>8} {bm:>8} {bound:>6}{alias}{eff}",
            file=out,
        )


# ---- profile -> roofline join ----------------------------------------------


def phase_cost_records(cfg) -> Dict[str, Dict[str, Any]]:
    """Compile-only cost records per phase program — the COST side of the
    profile→roofline join (:func:`profile_join_records`): each phase of
    ``parallel.step.phase_programs`` lowered over an abstract sharded
    field and read through ``compiled.cost_analysis()``. No timing, no
    device_put — the measured side comes from the profile capture."""
    import jax
    import jax.numpy as jnp

    from heat3d_tpu.models.heat3d import _select_backend
    from heat3d_tpu.parallel.step import phase_programs
    from heat3d_tpu.parallel.topology import build_mesh, field_sharding

    mesh = build_mesh(cfg.mesh)
    sharding = field_sharding(mesh, cfg.mesh)
    programs = phase_programs(cfg, mesh, _select_backend(cfg))
    aval = jax.ShapeDtypeStruct(
        cfg.padded_shape, jnp.dtype(cfg.precision.storage), sharding=sharding
    )
    out: Dict[str, Dict[str, Any]] = {}
    seen: Dict[int, str] = {}
    for phase, fn in programs.items():
        if id(fn) in seen:  # fused_dma aliases the step program
            rec = dict(out[seen[id(fn)]])
            rec["alias_of"] = seen[id(fn)]
            out[phase] = rec
            continue
        try:
            compiled = jax.jit(fn).lower(aval).compile()
            flops, bytes_ = extract_cost(compiled.cost_analysis())
            out[phase] = {"flops": flops, "bytes": bytes_}
        except Exception as e:  # noqa: BLE001 - keep the join best-effort
            out[phase] = {"error": f"{type(e).__name__}: {str(e)[:200]}"}
            continue
        seen[id(fn)] = phase
    return out


def _phase_calls(phase: str, steps: int, tb: int) -> Optional[int]:
    """How many times the phase program ran across ``steps`` updates:
    one stencil sweep per update; one exchange (one fused kernel) per tb
    updates. None means "total time only, no achieved rate": residual
    cadence is run-configured and not recoverable from the capture, and
    the ``step`` scope's device time is EXCLUSIVE (ops inside the inner
    stencil/halo scopes attribute there, leaving only dispatch glue) —
    dividing the FULL step program's cost by glue-only time would claim
    absurd fractions of peak."""
    from heat3d_tpu.parallel.step import (
        PHASE_FUSED,
        PHASE_HALO,
        PHASE_STENCIL,
    )

    if phase == PHASE_STENCIL:
        return max(1, steps)
    if phase in (PHASE_HALO, PHASE_FUSED):
        return max(1, steps // max(1, tb))
    return None


def profile_join_records(
    cfg, phase_us: Dict[str, float], steps: int
) -> List[Dict[str, Any]]:
    """THE join (ROADMAP carry-over from PR 3): measured per-phase DEVICE
    time from a ``--profile`` capture (``obs.perf.timeline
    .profile_phase_totals`` — keyed by the ``heat3d.*`` named scopes)
    against the cost-analysis FLOPs/bytes of the same-named phase
    programs. One record per phase: total device time, its share of
    attributed device time, per-call device seconds (``steps`` and
    ``cfg.time_blocking`` set the call counts), and the achieved
    GFLOP/s / GB/s those imply — "stencil at X% of HBM peak, halo at Y%"
    from measured times, not span wall-clock."""
    from heat3d_tpu.parallel.step import (
        PHASE_FUSED,
        PHASE_HALO,
        PHASE_STEP,
        PHASES,
    )

    costs = phase_cost_records(cfg)
    tb = cfg.time_blocking
    # Fused-route captures (DMA-overlap or in-kernel RDMA) run NO
    # standalone exchange — the halo bytes move inside the step-scope
    # kernel. Joining the compiled halo_exchange program's bytes against
    # the capture's (absent) halo span would print the phase as missing
    # when it honestly VANISHED into the fused kernel, so drop it from
    # the join; its traffic is already attributed to the fused span.
    fused_active = costs.get(PHASE_FUSED, {}).get("alias_of") == PHASE_STEP
    if fused_active and phase_us.get(PHASE_HALO) is None:
        costs.pop(PHASE_HALO, None)
    attributed = sum(
        us for ph, us in phase_us.items() if ph != "(unattributed)"
    )
    records: List[Dict[str, Any]] = []
    order = (
        [ph for ph in PHASES if ph in costs]
        + [ph for ph in costs if ph not in PHASES]
        + [ph for ph in sorted(phase_us) if ph not in costs]
    )
    for phase in order:
        cost = costs.get(phase, {})
        us = phase_us.get(phase)
        rec: Dict[str, Any] = {
            "phase": phase,
            "device_us": None if us is None else round(us, 3),
            "share": (
                round(us / attributed, 4)
                if us is not None and attributed > 0
                and phase != "(unattributed)"
                else None
            ),
            "flops": cost.get("flops"),
            "bytes": cost.get("bytes"),
        }
        if cost.get("error"):
            rec["error"] = cost["error"]
        if cost.get("alias_of"):
            rec["alias_of"] = cost["alias_of"]
        calls = _phase_calls(phase, steps, tb)
        if us is not None and calls:
            sec = us * 1e-6 / calls
            rec["calls"] = calls
            rec["seconds"] = sec
            flops, bytes_ = cost.get("flops"), cost.get("bytes")
            rec["gflops"] = (flops / sec / 1e9) if flops and sec > 0 else None
            rec["gbps"] = (bytes_ / sec / 1e9) if bytes_ and sec > 0 else None
        records.append(rec)
    return records


def print_profile_table(
    cfg, records: List[Dict[str, Any]], platform: str, steps: int,
    artifact: str, out=None,
) -> None:
    """The measured-device-time achieved-vs-peak table ``heat3d obs
    roofline --from-profile`` prints. Same peak specs and %-of-peak
    semantics as the live table; the time column is DEVICE time from the
    capture, divided over the calls the run made."""
    out = out or sys.stdout
    spec = peak_spec(platform)
    mem, vec = spec.get("mem_gbps"), spec.get("vector_gflops")
    grid = "x".join(str(g) for g in cfg.grid.shape)
    print(
        f"roofline from profile [{platform}] grid={grid} "
        f"stencil={cfg.stencil.kind} tb={cfg.time_blocking} steps={steps} "
        f"(peaks: mem {mem or '?'} GB/s, vector {vec or '?'} GFLOP/s)\n"
        f"  measured device time: {artifact}",
        file=out,
    )
    print(
        f"{'phase':<16} {'dev total':>10} {'share':>6} {'per-call':>10} "
        f"{'GFLOP/s':>9} {'GB/s':>8} {'%flops':>8} {'%mem':>8}",
        file=out,
    )
    for r in records:
        if r.get("error"):
            print(f"{r['phase']:<16} cost error: {r['error']}", file=out)
            continue
        dev = (
            f"{r['device_us'] / 1e3:.3f}ms"
            if r.get("device_us") is not None
            else "-"
        )
        share = f"{r['share']:.1%}" if r.get("share") is not None else "-"
        per_call = (
            f"{r['seconds'] * 1e6:.1f}us" if r.get("seconds") else "-"
        )
        gf = f"{r['gflops']:.2f}" if r.get("gflops") is not None else "-"
        gb = f"{r['gbps']:.2f}" if r.get("gbps") is not None else "-"
        alias = f" (= {r['alias_of']})" if r.get("alias_of") else ""
        print(
            f"{r['phase']:<16} {dev:>10} {share:>6} {per_call:>10} "
            f"{gf:>9} {gb:>8} {_pct(r.get('gflops'), vec):>8} "
            f"{_pct(r.get('gbps'), mem):>8}{alias}",
            file=out,
        )


def _steps_from_ledger(path: str, run_id: Optional[str] = None) -> Optional[int]:
    """Stepped updates of ONE run segment — the ``steps`` fields of its
    ok run_loop/chunk spans. The profile bracket covers exactly one
    run's timed loop (``--profile``'s documented scope), but ledgers
    hold many segments (APPEND bench sessions thread one
    ``$HEAT3D_LEDGER`` through every row) — summing across them would
    inflate the step count and corrupt every per-call rate. Default:
    the LAST segment with step spans (the run that just wrote the
    capture); ``run_id`` selects another explicitly."""
    from heat3d_tpu.obs.cli import STEP_SPANS, read_ledger

    per_run: Dict[str, int] = {}
    order: List[str] = []
    for r in read_ledger(path):
        if (
            r.get("kind") == "span"
            and r.get("event") in STEP_SPANS
            and r.get("status") == "ok"
            and isinstance(r.get("steps"), int)
        ):
            rid = str(r.get("run_id"))
            if rid not in per_run:
                order.append(rid)
            per_run[rid] = per_run.get(rid, 0) + r["steps"]
    if run_id is not None:
        return per_run.get(str(run_id)) or None
    return (per_run[order[-1]] if order else 0) or None


# ---- peak calibration -------------------------------------------------------


def calibrate_vpu_peak(
    grid: int = 48,
    iters: int = 3,
    backend: str = "auto",
    cache_path: Optional[str] = None,
) -> Dict[str, Any]:
    """Derive a calibrated VPU vector peak from a measured COMPUTE-BOUND
    phase — the 27pt tb=1 stencil program (the densest tap chain; at
    these arithmetic intensities its achieved GFLOP/s is a floor on the
    sustainable vector rate, which is exactly what the roofline's
    "fraction of peak" should divide by; see --vpu-gops's no-default
    posture) — and store it per chip generation in the shared tuning
    cache (``tune.cache.store_peak``). Returns the record; raises when
    the stencil phase produced no usable flops/seconds (callers print
    the error; calibration is an explicit operator action, not
    fail-soft telemetry)."""
    from heat3d_tpu.core.config import (
        GridConfig,
        MeshConfig,
        Precision,
        RunConfig,
        SolverConfig,
        StencilConfig,
    )
    from heat3d_tpu.tune.cache import chip_generation, store_peak

    cfg = SolverConfig(
        grid=GridConfig.cube(grid),
        stencil=StencilConfig(kind="27pt"),
        mesh=MeshConfig(shape=(1, 1, 1)),
        precision=Precision.fp32(),
        run=RunConfig(num_steps=1),
        backend=backend,
        time_blocking=1,
    )
    records = phase_costs_and_times(cfg, iters=iters)
    stencil = next(
        (r for r in records if r.get("phase") == "stencil"), None
    )
    if not stencil or stencil.get("error"):
        raise RuntimeError(
            "calibration needs the stencil phase program: "
            f"{(stencil or {}).get('error', 'phase missing')}"
        )
    gflops = stencil.get("gflops")
    if not isinstance(gflops, (int, float)) or gflops <= 0:
        raise RuntimeError(
            "stencil phase reported no flops (XLA treats custom calls as "
            "opaque — calibrate with --backend jnp on that platform)"
        )
    chip = chip_generation()
    path = store_peak(
        chip,
        float(gflops),
        path=cache_path,
        source=f"27pt tb=1 {grid}^3 stencil phase, backend={backend}",
    )
    from heat3d_tpu import obs

    obs.get().event(
        "peak_calibrated", chip=chip, vector_gflops=float(gflops),
        grid=grid, backend=backend, path=path,
    )
    return {
        "chip": chip,
        "vector_gflops": float(gflops),
        "seconds": stencil.get("seconds"),
        "path": path,
    }


# ---- row model (promoted from scripts/roofline_check.py) -------------------


def bytes_per_cell_update(row) -> tuple:
    """Traffic model per path (BASELINE.md 'HBM traffic model')."""
    item = 2 if row["dtype"] == "bfloat16" else 4
    tb = row.get("time_blocking", 1)
    mesh = row.get("mesh", [1, 1, 1])
    single = all(m == 1 for m in mesh)
    halo = row.get("halo", "ppermute")
    overlap = row.get("overlap", False)
    # the direct kernels apply on unpadded shards for ppermute transport;
    # DMA transport and tb>2 keep the padded exchange (one extra volume
    # read+write per exchange). Prefer the RESOLVED selection the harness
    # recorded (exact even for HEAT3D_NO_DIRECT A/B rows); derive for
    # legacy rows.
    if row.get("fused_rdma_path"):
        # fused in-kernel RDMA superstep: the halo bytes ride remote
        # copies INSIDE the sweep kernel (no standalone exchange phase),
        # so HBM traffic is one unpadded read+write per sweep of tb
        # updates — counting an exchange copy here would double-count
        # bytes the kernel never materializes
        per_update = 2 * item / tb
        path = f"fused-rdma{'' if tb == 1 else '2'}"
        if row.get("halo_plan") == "partitioned":
            path += "+planned-partitioned"
        return per_update, path
    if row.get("fused_dma_path"):
        # fused DMA-overlap kernels: unpadded streaming sweep, one
        # read+write per sweep of tb updates — same traffic shape as the
        # direct kernels
        return 2 * item / tb, f"fused-dma{'' if tb == 1 else '2'}"
    if row.get("streamk_path"):
        # fused k-sweep streaming kernel (deep tb): the width-k exchange
        # still materializes the padded copy (one r+w per superstep), but
        # the k updates then share ONE sweep of it — vs k sweeps on the
        # plain exchange path
        return 4 * item / tb, f"streamk(tb={tb})"
    direct = row.get("direct_path")
    if direct is None:
        direct = halo == "ppermute" and tb in (1, 2)
    if direct and not (overlap and tb == 2):
        per_update = 2 * item / tb  # one read + one write per sweep of tb
        path = f"direct{'' if tb == 1 else '2'}{'' if single else '+faces'}"
    else:
        # exchange path: padded copy (r+w) once per exchange + sweep per
        # update (tb updates share one exchange)
        per_update = 2 * item + 2 * item / tb
        path = f"exchange(tb={tb})"
    # planned-exchange arm: a partitioned plan ships the SAME boundary
    # bytes as monolithic in sub-block messages (the p50 A/B measures
    # schedule, not traffic) — label the path so partitioned rows are
    # attributable without changing the byte model
    if row.get("halo_plan") == "partitioned":
        path += "+planned-partitioned"
    return per_update, path


def vpu_ops_per_cell_update(row):
    """Vector ops/cell/update of the row's tap chain. Prefers the
    ``chain_ops`` the harness recorded at measurement time (exact even for
    factoring-knob A/B rows); falls back to re-deriving under the CURRENT
    factoring env for rows predating that field. Tap VALUES don't matter
    for the count, only which offsets are nonzero, so nominal
    alpha/dt/spacing are fine for the fallback."""
    if "chain_ops" in row:
        return row["chain_ops"]  # may be None: conv rows run no tap chain
    if row.get("backend") == "conv":
        return None
    from heat3d_tpu.core.stencils import chain_ops_for

    return chain_ops_for(row.get("stencil", "7pt"))


def iter_result_rows(path, kinds=None, start_line=1, stop_line=None):
    """Yield ``(lineno, row)`` bench rows from a results file, tolerating
    log-style line prefixes ("factor_y=0 tb=1: {...}" — the factoring A/B
    stages log their rows rather than appending them to the suite
    record). ``kinds`` filters on the ``bench`` field; the 1-indexed
    ``[start_line, stop_line)`` window is how the regression gate scopes
    "this session's rows" (the ONE parser both this module and
    obs/perf/regress.py read rows through)."""
    with open(path) as f:
        for i, line in enumerate(f, start=1):
            if i < start_line or (stop_line is not None and i >= stop_line):
                continue
            line = line.strip()
            brace = line.find("{")
            if brace < 0:
                continue
            try:
                r = json.loads(line[brace:])
            except json.JSONDecodeError:
                continue
            if isinstance(r, dict) and (
                kinds is None or r.get("bench") in kinds
            ):
                yield i, r


def load_rows(paths: List[str]) -> List[Dict[str, Any]]:
    """Throughput rows from row files (see :func:`iter_result_rows`)."""
    return [
        r
        for results in paths
        for _, r in iter_result_rows(results, kinds=("throughput",))
    ]


def report_rows(rows, hbm_gbps: float, vpu_gops, out=None) -> None:
    out = out or sys.stdout
    print(
        f"{'grid':>6} {'dtype':>8} {'st':>4} {'tb':>2} {'path':>16} "
        f"{'B/cell/upd':>10} {'ops':>4} {'ceiling':>9} {'bind':>4} "
        f"{'measured':>9} {'achieved':>8}",
        file=out,
    )
    for r in rows:
        per_update, path = bytes_per_cell_update(r)
        bw_ceiling = hbm_gbps / per_update  # Gcell/s/chip
        ops = vpu_ops_per_cell_update(r)
        ceiling, bind = bw_ceiling, "hbm"
        # ops is None for conv rows (one XLA conv op, no tap chain): the
        # VPU model doesn't apply — report against the HBM ceiling only
        if vpu_gops is not None and ops is not None:
            vpu_ceiling = vpu_gops / ops
            if vpu_ceiling < bw_ceiling:
                ceiling, bind = vpu_ceiling, "vpu"
        meas = r["gcell_per_sec_per_chip"]
        grid = (
            r["grid"][0]
            if len(set(r["grid"])) == 1
            else "x".join(map(str, r["grid"]))
        )
        flag = " (RTT!)" if r.get("rtt_dominated") else ""
        # compute dtype doesn't change HBM traffic (storage dtype does),
        # but label it so bf16-compute A/B rows are tellable apart
        if r.get("compute_dtype", "float32") != "float32":
            flag = " (c=bf16)" + flag
        print(
            f"{grid:>6} {r['dtype']:>8} {r.get('stencil', '7pt'):>4} "
            f"{r.get('time_blocking', 1):>2} {path:>16} "
            f"{per_update:>10.1f} {'n/a' if ops is None else ops:>4} "
            f"{ceiling:>9.1f} {bind:>4} "
            f"{meas:>9.2f} {meas / ceiling:>7.1%}{flag}",
            file=out,
        )


def fit_op_cost(rows, out=None) -> None:
    """Least-squares time/cell/update = a + b*ops over rows that differ
    ONLY in their emitted chain (same grid/dtype/tb/path). A good linear
    fit is direct evidence the kernels are compute-bound in chain ops;
    a >> b would instead indict fixed per-cell cost (assembly/shifts)."""
    from collections import defaultdict

    out = out or sys.stdout
    groups = defaultdict(list)
    for r in rows:
        if r.get("rtt_dominated"):
            continue
        _, path = bytes_per_cell_update(r)
        # compute_dtype/backend in the key: a bf16-compute A/B row has the
        # same chain_ops as its fp32-compute twin but different per-op
        # cost — pooling them would corrupt the fit silently
        key = (
            tuple(r["grid"]), r["dtype"],
            r.get("compute_dtype", "float32"), r.get("backend", "auto"),
            r.get("time_blocking", 1), path,
        )
        ops = vpu_ops_per_cell_update(r)
        if ops is None:
            continue  # conv rows: no tap chain, nothing to fit against
        ns_per_cell = 1.0 / r["gcell_per_sec_per_chip"]  # ns/cell/update
        groups[key].append((ops, ns_per_cell))
    printed = False
    for key, pts in sorted(groups.items()):
        by_ops = {}
        for ops, t in pts:
            by_ops.setdefault(ops, []).append(t)
        if len(by_ops) < 2:
            continue
        xs, ys = zip(*((o, min(ts)) for o, ts in sorted(by_ops.items())))
        n = len(xs)
        mx, my = sum(xs) / n, sum(ys) / n
        sxx = sum((x - mx) ** 2 for x in xs)
        b = sum((x - mx) * (y - my) for x, y in zip(xs, ys)) / sxx
        a = my - b * mx
        if n >= 3:
            ss_res = sum((y - (a + b * x)) ** 2 for x, y in zip(xs, ys))
            ss_tot = sum((y - my) ** 2 for y in ys) or 1e-30
            fit_q = f"R^2={1 - ss_res / ss_tot:.3f}"
        else:
            # a line through 2 points always "fits"; don't dress that up
            fit_q = "2-point (no linearity evidence)"
        grid, dtype, cdtype, backend, tb, path = key
        cflag = "" if cdtype == "float32" else f" c={cdtype}"
        glabel = (
            f"{grid[0]}^3"
            if len(set(grid)) == 1
            else "x".join(map(str, grid))
        )
        if b <= 0:
            # higher-ops rows timed FASTER: noise or a confound — that's
            # anti-evidence of compute-boundedness, not an infinite rate
            verdict = "non-positive slope — unfittable/not compute-bound"
        else:
            verdict = (
                f"marginal {1.0 / b:.0f} Gop/s, "
                f"fixed {a / (a + b * xs[0]):.0%} of the {xs[0]}-op chain"
            )
        print(
            f"\nfit {glabel} {dtype}{cflag} tb={tb} {path}: "
            f"t/cell = {a:.3f} + {b:.4f}*ops ns "
            f"({verdict}, {fit_q}, points={list(by_ops)})",
            file=out,
        )
        printed = True
    if not printed:
        print(
            "\nfit: no group has >=2 distinct chain_ops values "
            "(need factoring A/B rows, e.g. HEAT3D_FACTOR_Y=0)",
            file=sys.stderr,
        )


# ---- CLI -------------------------------------------------------------------


def _cfg_from_args(args):
    from heat3d_tpu.core.config import (
        GridConfig,
        MeshConfig,
        Precision,
        RunConfig,
        SolverConfig,
        StencilConfig,
    )

    return SolverConfig(
        grid=GridConfig.cube(args.grid),
        stencil=StencilConfig(kind=args.stencil),
        mesh=MeshConfig(shape=(1, 1, 1)),
        precision=Precision.bf16() if args.dtype == "bf16" else Precision.fp32(),
        run=RunConfig(num_steps=1),
        backend=args.backend,
        time_blocking=args.time_blocking,
    )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="heat3d obs roofline",
        description="Achieved-vs-peak attribution. With row files: the "
        "analytic traffic/op-cost model over measured bench rows "
        "(scripts/roofline_check.py compatible). Without: compile this "
        "config's phase programs, read XLA's cost_analysis, time them, "
        "and print the per-phase achieved-vs-peak table (works on CPU).",
    )
    ap.add_argument(
        "results", nargs="*",
        help="row files (bench_results.jsonl / extracted A/B rows); "
        "empty selects the live per-phase mode",
    )
    ap.add_argument("--hbm-gbps", type=float, default=819.0,
                    help="chip HBM bandwidth (GB/s); v5e ~819, v5p ~2765")
    ap.add_argument("--vpu-gops", type=float, default=None,
                    help="VPU vector throughput (Gop/s, one op = one "
                    "full-width FMA or add); calibrate from a measured "
                    "compute-bound row — no default on purpose")
    ap.add_argument("--fit", action="store_true",
                    help="(row mode) fit time/cell/update = a + b*ops per "
                    "config group — linearity in ops IS the compute-bound "
                    "evidence")
    ap.add_argument("--grid", type=int, default=32,
                    help="(live mode) cube edge")
    ap.add_argument("--stencil", choices=["7pt", "27pt"], default="7pt")
    ap.add_argument("--dtype", choices=["fp32", "bf16"], default="fp32")
    ap.add_argument("--backend",
                    choices=["auto", "jnp", "pallas", "conv"], default="auto")
    ap.add_argument("--time-blocking", type=int, default=1)
    ap.add_argument("--iters", type=int, default=3,
                    help="(live mode) timing iterations per phase")
    ap.add_argument("--from-profile", default=None, metavar="DIR",
                    help="join MEASURED per-phase device times from a "
                    "--profile capture (dir or .xplane.pb) onto this "
                    "config's cost_analysis model — achieved-vs-peak from "
                    "device time, not span wall-clock (needs the config "
                    "flags to match the profiled run)")
    ap.add_argument("--steps", type=int, default=None,
                    help="(with --from-profile) updates the capture "
                    "covers; default: reconstructed from --ledger's "
                    "run_loop/chunk spans, else 1 (per-call rates then "
                    "read as per-capture)")
    ap.add_argument("--ledger", default=None,
                    help="(with --from-profile) run ledger of the "
                    "profiled run — supplies the step count (the LAST "
                    "run segment with step spans; --run selects another)")
    ap.add_argument("--run", default=None, metavar="RUN_ID",
                    help="(with --from-profile --ledger) the ledger run "
                    "segment the capture belongs to")
    ap.add_argument("--calibrate", action="store_true",
                    help="measure a compute-bound 27pt tb=1 stencil phase "
                    "and cache its achieved GFLOP/s as this chip "
                    "generation's VPU peak (shared tuning-cache store; "
                    "later reports divide by it — ROADMAP 'calibrated "
                    "peak specs')")
    ap.add_argument("--cache", default=None,
                    help="(with --calibrate) tuning-cache store path "
                    "(default $HEAT3D_TUNE_CACHE)")
    ap.add_argument("--json", action="store_true",
                    help="(live mode) machine-readable records instead of "
                    "the table")
    args = ap.parse_args(argv)

    if args.calibrate:
        try:
            rec = calibrate_vpu_peak(
                grid=args.grid,
                iters=args.iters,
                backend=args.backend,
                cache_path=args.cache,
            )
        except Exception as e:  # noqa: BLE001 - report, don't traceback
            print(f"roofline --calibrate: {e}", file=sys.stderr)
            return 1
        if args.json:
            print(json.dumps(rec))
        else:
            print(
                f"calibrated {rec['chip']}: vector peak "
                f"{rec['vector_gflops']:.2f} GFLOP/s "
                f"(stored in {rec['path']})"
            )
        return 0

    if args.from_profile:
        import jax

        from heat3d_tpu.obs.perf.timeline import profile_phase_totals

        try:
            phase_us, artifact = profile_phase_totals(args.from_profile)
        except (RuntimeError, OSError) as e:
            print(f"roofline --from-profile: {e}", file=sys.stderr)
            return 1
        steps = args.steps
        if steps is None and args.ledger:
            try:
                steps = _steps_from_ledger(args.ledger, run_id=args.run)
            except OSError as e:
                print(
                    f"roofline --from-profile: cannot read ledger: {e}",
                    file=sys.stderr,
                )
                return 2
            if steps is None:
                which = (
                    f"run {args.run}" if args.run else "any run segment"
                )
                print(
                    f"roofline --from-profile: no ok step spans for "
                    f"{which} in {args.ledger} — treating the capture as "
                    "ONE update (rates read as per-capture)",
                    file=sys.stderr,
                )
        elif steps is None:
            print(
                "roofline --from-profile: no --steps/--ledger — treating "
                "the capture as ONE update (rates read as per-capture)",
                file=sys.stderr,
            )
        steps = steps or 1
        cfg = _cfg_from_args(args)
        records = profile_join_records(cfg, phase_us, steps)
        platform = jax.default_backend()
        if args.json:
            print(
                json.dumps(
                    {
                        "platform": platform,
                        "artifact": artifact,
                        "steps": steps,
                        "phases": records,
                    }
                )
            )
        else:
            print_profile_table(cfg, records, platform, steps, artifact)
        return 0

    if args.results:
        rows = load_rows(args.results)
        if not rows:
            print("no throughput rows found", file=sys.stderr)
            return 1
        report_rows(rows, args.hbm_gbps, args.vpu_gops)
        if args.fit:
            fit_op_cost(rows)
        return 0

    import jax

    cfg = _cfg_from_args(args)
    records = phase_costs_and_times(cfg, iters=args.iters)
    platform = jax.default_backend()
    if args.json:
        print(json.dumps({"platform": platform, "phases": records}))
    else:
        print_live_table(cfg, records, platform)
    return 0


if __name__ == "__main__":
    sys.exit(main())
