"""``heat3d obs merge`` — join per-process ledgers into one timeline.

A multihost run writes one ledger per process (each entry point activates
its own ``--ledger`` path; run ids are per-process). Post-mortem questions
— "did proc 3 start its chunk late", "which host stalled the collective"
— need the per-process streams on ONE timeline, plus an estimate of how
far the hosts' wall clocks disagree (events are stamped with each host's
own ``time.time()``; ``t0``/``t1`` are per-process monotonic and never
comparable across hosts).

The merge tags every event with its source file (``src``), stable-sorts
by wall ``ts`` (ties keep per-stream order, so each stream's ``seq``
stays monotone and the merged file still passes ``heat3d obs check`` /
``obs summary`` groups it per run segment), and computes **cross-host
skew stats** from anchor events: for every event name that appears
exactly once per source (``run_start``, ``ledger_open``,
``supervised_start``, ...), the spread of its ``ts`` across sources
bounds the skew-plus-real-stagger for that phase boundary; the reported
``skew_s`` per source is its offset from the earliest anchor. True clock
skew and genuine start stagger are indistinguishable from ledgers alone —
the stats say so rather than pretending otherwise.

``--align`` (the comm-observatory leg, docs/OBSERVABILITY.md §9) goes
one step further: it subtracts each source's anchor offset from every
wall ``ts`` in that source's stream (the original stamp is preserved as
``ts_raw``), so downstream consumers — ``obs timeline --align``, the
cross-host straggler detectors — compare hosts on one estimated clock
and a skewed host clock can no longer masquerade as a straggler. The
correction is exactly as good as the anchor barrier: the recorded
``clock_align`` stats carry a confidence interval (``ci_s``) bounding
the alignment error by the worst residual once-per-source spread after
alignment plus the worst host's measured sync RTT, and real start
stagger widens it honestly. Monotonic ``t0``/``t1``/``dur_s`` are
per-process and are never rewritten.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional

# preferred anchor events, most-synchronized-first: run_start is written
# right after distributed.initialize (a real barrier on multihost), so its
# spread is closest to pure clock skew
ANCHOR_PREFERENCE = ("run_start", "supervised_start", "ledger_open")


def read_events(path: str) -> List[Dict[str, Any]]:
    """``obs.cli.read_ledger`` (the ONE tolerant ledger parser — merge
    and summary/check must agree on which events they see) plus an
    unreadable-path warning instead of a raise."""
    from heat3d_tpu.obs.cli import read_ledger

    try:
        return read_ledger(path)
    except OSError as e:
        print(f"merge: cannot open {path}: {e}", file=sys.stderr)
        return []


def merge_ledgers(
    paths: List[str], anchor: Optional[str] = None, align: bool = False
) -> Dict[str, Any]:
    """Merge the ledgers at ``paths``. Returns ``{"events": [...],
    "stats": {...}}`` — events tagged with ``src`` and sorted by ``ts``
    (stable: per-stream order preserved), stats as described in the
    module docstring. ``align=True`` additionally rewrites each
    source's wall timestamps onto the anchor-aligned clock (originals
    kept as ``ts_raw``; ``stats["clock_align"]`` records the offsets
    and the confidence interval)."""
    per_src: Dict[str, List[Dict[str, Any]]] = {}
    for p in paths:
        evs = read_events(p)
        src = os.path.basename(p)
        if src in per_src:  # two paths with one basename: disambiguate
            src = p
        for e in evs:
            e.setdefault("src", src)
        per_src[src] = evs

    merged: List[Dict[str, Any]] = []
    for evs in per_src.values():
        merged.extend(evs)
    merged.sort(
        key=lambda e: e["ts"] if isinstance(e.get("ts"), (int, float)) else 0.0
    )

    # pick the anchor: requested, else the first preference present in
    # EVERY source (a skew stat from an event only some hosts wrote would
    # compare different phase boundaries)
    def anchor_ts(evs: List[Dict[str, Any]], name: str) -> Optional[float]:
        for e in evs:
            if e.get("event") == name and isinstance(
                e.get("ts"), (int, float)
            ):
                return float(e["ts"])
        return None

    chosen = anchor
    if chosen is None:
        for cand in ANCHOR_PREFERENCE:
            if all(anchor_ts(evs, cand) is not None for evs in per_src.values()):
                chosen = cand
                break

    anchors = {
        src: anchor_ts(evs, chosen) if chosen else None
        for src, evs in per_src.items()
    }
    known = [v for v in anchors.values() if v is not None]
    base = min(known) if known else None

    sources = {}
    for src, evs in per_src.items():
        tss = [
            float(e["ts"])
            for e in evs
            if isinstance(e.get("ts"), (int, float))
        ]
        sources[src] = {
            "events": len(evs),
            "procs": sorted({e.get("proc") for e in evs if "proc" in e}),
            "run_ids": sorted(
                {str(e.get("run_id")) for e in evs if "run_id" in e}
            ),
            "t_first": min(tss) if tss else None,
            "t_last": max(tss) if tss else None,
            "anchor_ts": anchors[src],
            "skew_s": (
                round(anchors[src] - base, 6)
                if anchors[src] is not None and base is not None
                else None
            ),
        }

    # ``--align``: subtract each source's anchor offset from its wall
    # stamps, preserving the original as ``ts_raw``. Requires a common
    # anchor across >= 2 sources — with nothing to align against, the
    # merge stays raw and says so instead of silently rewriting time.
    aligned = False
    if align and chosen is not None and base is not None and len(known) > 1:
        for src, evs in per_src.items():
            off = sources[src]["skew_s"]
            sources[src]["align_offset_s"] = off
            if off:
                for e in evs:
                    if isinstance(e.get("ts"), (int, float)):
                        e["ts_raw"] = e["ts"]
                        e["ts"] = float(e["ts"]) - off
        merged.sort(
            key=lambda e: (
                e["ts"] if isinstance(e.get("ts"), (int, float)) else 0.0
            )
        )
        aligned = True

    stats = {
        "sources": sources,
        "anchor_event": chosen,
        "max_skew_s": (
            round(max(known) - min(known), 6) if len(known) > 1 else 0.0
        ),
        "note": (
            "skew_s mixes wall-clock skew with real start stagger; "
            "monotonic t0/t1 are per-process and never comparable "
            "across hosts"
        ),
        "total_events": len(merged),
    }

    # per-anchor-candidate spread table: every event name written exactly
    # once per source gives an independent skew sample along the run
    spreads = {}
    counts: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
    first_ts: Dict[str, Dict[str, float]] = defaultdict(dict)
    for src, evs in per_src.items():
        for e in evs:
            name = e.get("event")
            if isinstance(name, str) and isinstance(
                e.get("ts"), (int, float)
            ):
                counts[name][src] += 1
                first_ts[name].setdefault(src, float(e["ts"]))
    for name, per in counts.items():
        if len(per) == len(per_src) > 1 and all(
            c == 1 for c in per.values()
        ):
            tss = list(first_ts[name].values())
            spreads[name] = round(max(tss) - min(tss), 6)
    stats["anchor_spreads_s"] = dict(
        sorted(spreads.items(), key=lambda kv: kv[1])
    )

    if aligned:
        # the chosen anchor's aligned spread is 0 by construction; the
        # worst REMAINING once-per-source spread bounds how well one
        # offset per host explained the rest of the run (residual skew
        # drift + real stagger), and each host's own timestamp jitter is
        # bounded by its measured sync RTT where one was recorded
        residual = max(
            (v for k, v in spreads.items() if k != chosen), default=0.0
        )
        rtts: Dict[str, Optional[float]] = {}
        for src, evs in per_src.items():
            vals = [
                float(e["sync_rtt_s"])
                for e in evs
                if isinstance(e.get("sync_rtt_s"), (int, float))
            ]
            rtts[src] = round(max(vals), 6) if vals else None
        ci = round(
            residual
            + max((v for v in rtts.values() if v is not None), default=0.0),
            6,
        )
        stats["clock_align"] = {
            "applied": True,
            "anchor_event": chosen,
            "offsets_s": {s: sources[s]["skew_s"] for s in per_src},
            "sync_rtt_s": rtts,
            "residual_spread_s": residual,
            "ci_s": ci,
            "note": (
                "offsets are each source's anchor skew; ci_s bounds the "
                "alignment error by the worst residual once-per-source "
                "spread plus the worst measured sync RTT — real stagger "
                "widens it honestly"
            ),
        }
        from heat3d_tpu import obs

        obs.get().event(
            "clock_align",
            anchor_event=chosen,
            sources=len(per_src),
            max_offset_s=round(max(known) - min(known), 6),
            ci_s=ci,
        )
    elif align:
        stats["clock_align"] = {
            "applied": False,
            "anchor_event": chosen,
            "note": (
                "alignment needs an anchor event present in every "
                "source and >= 2 sources; merge left on raw clocks"
            ),
        }
    return {"events": merged, "stats": stats}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="heat3d obs merge",
        description="join per-process run ledgers into one timeline with "
        "cross-host skew stats",
    )
    ap.add_argument("ledgers", nargs="+", help="per-process ledger files")
    ap.add_argument(
        "-o", "--out", default=None, metavar="PATH",
        help="write the merged timeline here (JSONL, src-tagged, "
        "ts-sorted); stats print to stdout either way",
    )
    ap.add_argument(
        "--anchor", default=None,
        help="event name to anchor skew on (default: first of "
        f"{'/'.join(ANCHOR_PREFERENCE)} present in every ledger)",
    )
    ap.add_argument(
        "--align", action="store_true",
        help="rewrite each source's wall timestamps onto the "
        "anchor-aligned clock (originals kept as ts_raw; the recorded "
        "clock_align stats carry the confidence interval)",
    )
    ap.add_argument("--json", action="store_true",
                    help="print stats as one JSON object")
    args = ap.parse_args(argv)

    result = merge_ledgers(args.ledgers, anchor=args.anchor, align=args.align)
    if args.out:
        with open(args.out, "w") as f:
            for e in result["events"]:
                f.write(json.dumps(e, default=repr) + "\n")
    stats = result["stats"]
    if args.json:
        print(json.dumps(stats))
        return 0
    print(
        f"merged {len(args.ledgers)} ledger(s), "
        f"{stats['total_events']} events"
        + (f" -> {args.out}" if args.out else "")
    )
    print(
        f"anchor: {stats['anchor_event'] or '(none common)'}  "
        f"max skew {stats['max_skew_s']}s"
    )
    for src, s in stats["sources"].items():
        skew = f"{s['skew_s']:+.3f}s" if s["skew_s"] is not None else "?"
        print(
            f"  {src}: procs {s['procs']} {s['events']} events "
            f"skew {skew} wall "
            f"[{s['t_first']}, {s['t_last']}]"
        )
    if stats["anchor_spreads_s"]:
        worst = sorted(
            stats["anchor_spreads_s"].items(), key=lambda kv: -kv[1]
        )[:5]
        print(
            "  spread per once-per-source event (skew + stagger): "
            + ", ".join(f"{n}={v}s" for n, v in worst)
        )
    ca = stats.get("clock_align")
    if ca is not None:
        if ca["applied"]:
            print(
                f"  aligned on {ca['anchor_event']}: "
                f"ci ±{ca['ci_s']}s (residual {ca['residual_spread_s']}s); "
                "originals kept as ts_raw"
            )
        else:
            print(f"  NOT aligned: {ca['note']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
