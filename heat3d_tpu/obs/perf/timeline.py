"""Unified performance timeline: one normalized event model over the run
ledger, ``obs merge``'d multihost streams, and ``--profile`` captures.

The paper's whole premise is overlap — compute hiding communication — yet
until this module no single command could answer "what fraction of a real
step was stencil vs halo vs stall". Three consumers share the model:

- **Chrome-trace export** (``heat3d obs timeline LEDGER -o trace.json``):
  ledger spans become nested ``X`` slices per process stream (multihost
  ledgers keep their ``src`` tags as separate process tracks), point
  events become instants, and a profile capture's per-phase device totals
  ride along as an aggregate track — one file, openable in Perfetto
  (ui.perfetto.dev) or ``chrome://tracing``.
- **Profile→roofline join**: :func:`profile_phase_totals` turns a
  ``--profile`` capture into measured device microseconds per ``heat3d.*``
  phase (the named-scope names ``parallel.step.PHASES`` pins), which
  ``obs roofline --from-profile`` divides cost-analysis FLOPs/bytes by —
  achieved-vs-peak from *measured device time*, not span wall-clock.
- **Drift/straggler detection** (:func:`detect_anomalies`): rolling
  per-span baselines over the per-step latency samples, classified with
  the same tolerance bands as ``obs regress`` (``band_status``), plus a
  cross-stream straggler check on merged multihost ledgers. Findings
  surface in ``obs summary``, in ``obs timeline --anomalies``, and as
  ``obs_anomaly`` ledger events.

The xplane-parsing core here is promoted from
``scripts/summarize_trace.py`` (now a thin same-flags wrapper), matching
the roofline/ab_decide promotion pattern: the aggregation stays pure and
duck-typed (``pick_line`` / ``aggregate_line`` / ``phase_totals``) so
tests drive it with synthetic plane objects when the ``xplane_pb2`` proto
module is absent.

Wall-time normalization: ledger spans carry ``t0``/``t1`` (per-process
monotonic) and ``ts`` (wall clock at write — spans are written AT CLOSE),
so a span's wall start is ``ts - dur_s`` without any cross-stream clock
fitting; cross-host placement inherits whatever wall-clock skew ``obs
merge`` already quantifies rather than pretending to correct it.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

# ---- xplane parsing (promoted from scripts/summarize_trace.py) -------------

# innermost heat3d phase token in an op/metadata name: named_scope nests
# (heat3d.stencil/heat3d.halo_exchange/...), and the INNERMOST scope is
# the phase that op belongs to — findall + [-1] picks it. The (?!py\b)
# lookahead keeps host-plane PYTHON FRAMES ("$heat3d.py:301 run") from
# masquerading as a phase named "heat3d.py". Dotted sub-phases
# ("heat3d.halo.x") are one token: the continuation admits further
# components unless they open with a digit (XLA's ".N" op suffixes, as in
# "fusion.2", are not phase path components).
PHASE_RE = re.compile(
    r"heat3d\.(?!py\b)[A-Za-z_][A-Za-z0-9_]*"
    r"(?:\.(?!py\b)[A-Za-z_][A-Za-z0-9_]*)*"
)


def find_xplane(logdir: str):
    pats = os.path.join(logdir, "**", "*.xplane.pb")
    files = sorted(glob.glob(pats, recursive=True))
    return files[-1] if files else None


def pick_line(lines):
    """The ONE line to aggregate per plane. A device plane carries several
    lines covering the SAME wall time (XLA Modules / XLA Ops / Steps);
    summing across them would double-count. Pick the op-level line if
    present, else the busiest line. ``lines`` must be pre-filtered to
    non-empty (``ln.events``)."""

    def line_us(line):
        return sum(ev.duration_ps for ev in line.events) / 1e6

    ops = [ln for ln in lines if "op" in ln.name.lower()]
    return ops[0] if ops else max(lines, key=line_us)


def aggregate_line(line, event_metadata):
    """(totals_us, counts) per metadata name for one line's events.
    ``event_metadata`` is the plane's metadata_id -> metadata mapping
    (proto map or plain dict of objects with ``.name``)."""
    totals = defaultdict(float)
    counts = defaultdict(int)
    for ev in line.events:
        meta = event_metadata[ev.metadata_id]
        totals[meta.name] += ev.duration_ps / 1e6
        counts[meta.name] += 1
    return totals, counts


def phase_name(op_name: str):
    """The heat3d phase an op belongs to (its innermost ``heat3d.*`` scope
    token), or None for ops outside any named phase."""
    hits = PHASE_RE.findall(op_name)
    return hits[-1] if hits else None


def phase_totals(totals):
    """Group per-op totals by heat3d phase; unscoped time lands in
    ``(unattributed)``."""
    phases = defaultdict(float)
    for name, us in totals.items():
        phases[phase_name(name) or "(unattributed)"] += us
    return dict(phases)


def summarize_plane(plane, top: int = 25, out=None) -> None:
    out = out or sys.stdout
    lines = [ln for ln in plane.lines if ln.events]
    if not lines:
        return
    line = pick_line(lines)
    totals, counts = aggregate_line(line, plane.event_metadata)
    print(
        f"\n== {plane.name} [line: {line.name or '?'}] "
        f"(total {sum(totals.values())/1e3:.2f} ms)",
        file=out,
    )
    for name, us in sorted(totals.items(), key=lambda kv: -kv[1])[:top]:
        print(f"  {us/1e3:9.3f} ms  x{counts[name]:<6} {name[:90]}", file=out)
    phases = phase_totals(totals)
    # a phase table with ONLY unattributed time is noise (a trace captured
    # without the named scopes); print it when any phase resolved
    if set(phases) - {"(unattributed)"}:
        total_us = sum(phases.values()) or 1.0
        print("  -- by heat3d phase --", file=out)
        for name, us in sorted(phases.items(), key=lambda kv: -kv[1]):
            print(
                f"  {us/1e3:9.3f} ms  {100.0 * us / total_us:5.1f}%  {name}",
                file=out,
            )


def _load_xspace(path: str):
    """Parse one ``.xplane.pb`` file; raises RuntimeError when the proto
    module is unavailable (callers decide whether that is fatal — the
    summarize CLI degrades to a TensorBoard pointer, the roofline join
    cannot run without it)."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2  # type: ignore
    except ImportError as e:
        raise RuntimeError(
            "xplane_pb2 unavailable — cannot parse the profile capture "
            f"({e}); open the trace in TensorBoard instead "
            f"(tensorboard --logdir {os.path.dirname(path)})"
        ) from None
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())
    return xs


def _device_planes(xs):
    """``(planes, host_fallback)``: the planes whose time is DEVICE time,
    or — when the capture has none (CPU-only runs) — every plane with
    lines, flagged as a host fallback so callers can treat its lines
    more skeptically (ONE selection rule for the summarize display and
    the roofline join)."""
    planes = [
        p
        for p in xs.planes
        if "TPU" in p.name or "/device" in p.name.lower()
    ]
    if planes:
        return planes, False
    return [p for p in xs.planes if p.lines], True


def summarize(path: str) -> int:
    try:
        xs = _load_xspace(path)
    except RuntimeError as e:
        # soft fallback: the capture itself succeeded, so don't fail the
        # calling script — just point at the trace
        print(e)
        return 0
    planes, _ = _device_planes(xs)
    for plane in planes:
        summarize_plane(plane)
    return 0


def summarize_trace_main(argv: Optional[List[str]] = None) -> int:
    """The historical ``scripts/summarize_trace.py`` surface, unchanged:
    one positional trace path (file or capture dir)."""
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(
            "usage: summarize_trace.py TRACE_DIR_OR_XPLANE_PB — per-op and "
            "per-phase device time of a jax.profiler capture "
            "(heat3d_tpu/obs/perf/timeline.py)",
            file=sys.stderr,
        )
        return 2
    path = argv[0]
    if os.path.isdir(path):
        xp = find_xplane(path)
        if xp is None:
            print(f"no .xplane.pb under {path}")
            return 1
        path = xp
    print(f"trace: {path}")
    return summarize(path)


# ---- per-phase device totals (the roofline join's measured side) -----------


def normalize_phase(token: str) -> str:
    """Canonical phase key for a ``heat3d.*`` scope token: the prefix is
    stripped and the halo sub-scopes — per-axis (``halo.x``), the comm
    observatory's per-direction (``halo.x.lo``), per-sub-block
    (``halo.x.lo.p0``) and per-axis DMA (``halo.x.dma``) scopes — all
    fold into ``halo_exchange``, so the roofline/timeline joins keep
    attributing the finer-grained exchange scopes to the one exchange
    phase instead of ``(unattributed)``. The names then join
    ``parallel.step.phase_programs`` / the ledger spans on one key."""
    if token.startswith("heat3d."):
        token = token[len("heat3d."):]
    if token == "halo" or token.startswith("halo."):
        return "halo_exchange"
    return token


def device_phase_totals(xs) -> Dict[str, float]:
    """Measured device microseconds per normalized phase, summed over the
    device planes of an XSpace-like object (duck-typed: tests drive it
    with synthetic planes). Unscoped device time lands in
    ``(unattributed)`` — the honest bucket for dispatch gaps and ops the
    named scopes don't cover.

    Host-plane-only captures (real CPU runs) contribute ONLY their
    op-level lines: the ``python`` frames line sums wall time across
    every host thread, which would fabricate "device" totals several
    times the run's wall clock — better an honest "no device events"
    than a confident wrong table."""
    planes, host_fallback = _device_planes(xs)
    out: Dict[str, float] = defaultdict(float)
    for plane in planes:
        lines = [ln for ln in plane.lines if ln.events]
        if host_fallback:
            lines = [ln for ln in lines if "op" in ln.name.lower()]
        if not lines:
            continue
        totals, _ = aggregate_line(pick_line(lines), plane.event_metadata)
        for phase, us in phase_totals(totals).items():
            key = (
                "(unattributed)"
                if phase == "(unattributed)"
                else normalize_phase(phase)
            )
            out[key] += us
    return dict(out)


def profile_phase_totals(path: str) -> Tuple[Dict[str, float], str]:
    """``(phase -> device us, artifact path)`` for a profile capture
    (``--profile DIR`` output, or one ``.xplane.pb`` directly). Raises
    RuntimeError when there is no artifact or no proto parser — the join
    consumers report that instead of printing an empty table."""
    artifact = path
    if os.path.isdir(path):
        artifact = find_xplane(path)
        if artifact is None:
            raise RuntimeError(f"no .xplane.pb under {path}")
    totals = device_phase_totals(_load_xspace(artifact))
    if not totals:
        raise RuntimeError(f"no device events in {artifact}")
    return totals, artifact


# ---- ledger -> normalized timeline ----------------------------------------


def timeline_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The normalized event model: one record per ledger event with a
    wall-clock placement. Spans are written at close, so wall start is
    ``ts - dur_s``; points sit at ``ts``. Events without a numeric ``ts``
    are dropped (the ledger lint flags them; the timeline stays
    best-effort). ``src`` survives from ``obs merge``'d streams."""
    out: List[Dict[str, Any]] = []
    for r in events:
        ts = r.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        rec = {
            "name": str(r.get("event", "?")),
            "kind": "span" if r.get("kind") == "span" else "point",
            "src": str(r.get("src", "")),
            "proc": r.get("proc", 0),
            "run_id": str(r.get("run_id", "")),
            "depth": r.get("depth", 0),
        }
        if rec["kind"] == "span" and isinstance(
            r.get("dur_s"), (int, float)
        ):
            rec["t_wall"] = float(ts) - float(r["dur_s"])
            rec["dur_s"] = float(r["dur_s"])
        else:
            # spans missing dur_s degrade to instants — best-effort, like
            # every other ledger reader
            rec["t_wall"] = float(ts)
            rec["dur_s"] = None
        rec["args"] = {
            k: v
            for k, v in r.items()
            if k
            not in (
                "ts", "run_id", "proc", "seq", "event", "kind",
                "t0", "t1", "dur_s", "depth", "src",
            )
        }
        out.append(rec)
    out.sort(key=lambda e: e["t_wall"])
    return out


def to_chrome_trace(
    tl_events: List[Dict[str, Any]],
    profile_totals: Optional[Dict[str, float]] = None,
) -> Dict[str, Any]:
    """Chrome-trace/Perfetto JSON (the legacy ``traceEvents`` format) from
    normalized timeline events. One integer pid per (src, proc) stream
    (named via ``M`` metadata events), spans as ``X`` complete events
    (nesting renders from time containment — the ledger guarantees proper
    per-thread nesting), points as ``i`` instants. A profile capture's
    per-phase totals export as ONE aggregate track: each phase is a slice
    whose duration is its total device time — honest about being an
    aggregate, not a placement (per-op placement lives in the xplane
    itself, which Perfetto opens natively). ``serve_span`` request-trace
    events (points carrying wall-clock ``t0_wall``/``t1_wall`` — see
    serve/queue.new_trace) additionally render as a per-request
    waterfall: one dedicated pid, one tid per request, the queue / pack /
    compute / deliver phases (and requeue gaps) as slices under the
    request's root span."""
    trace: List[Dict[str, Any]] = []
    req_spans = [
        e
        for e in tl_events
        if e["name"] == "serve_span"
        and isinstance(e["args"].get("t0_wall"), (int, float))
        and isinstance(e["args"].get("t1_wall"), (int, float))
    ]
    if tl_events:
        base = min(e["t_wall"] for e in tl_events)
        if req_spans:
            # a request's queue phase starts at submit — earlier than any
            # serve_span EMISSION ts; the origin must cover it
            base = min(
                base, min(e["args"]["t0_wall"] for e in req_spans)
            )
    else:
        base = 0.0
    pids: Dict[Tuple[str, Any], int] = {}
    req_ids = {id(e) for e in req_spans}
    for e in tl_events:
        if id(e) in req_ids:
            continue  # rendered on the waterfall track below, not as instants
        stream = (e["src"], e["proc"])
        if stream not in pids:
            pid = len(pids) + 1
            pids[stream] = pid
            label = e["src"] or "ledger"
            trace.append(
                {
                    "name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": f"{label}/proc{e['proc']}"},
                }
            )
        pid = pids[stream]
        ts_us = round((e["t_wall"] - base) * 1e6, 3)
        if e["dur_s"] is not None:
            trace.append(
                {
                    "name": e["name"], "ph": "X", "pid": pid, "tid": 0,
                    "ts": ts_us, "dur": round(e["dur_s"] * 1e6, 3),
                    "args": e["args"],
                }
            )
        else:
            trace.append(
                {
                    "name": e["name"], "ph": "i", "s": "p", "pid": pid,
                    "tid": 0, "ts": ts_us, "args": e["args"],
                }
            )
    if req_spans:
        # the per-request waterfall: one tid per request, phases as X
        # slices at their wall-clock bounds (the root "request" span
        # contains its phases by time, so Perfetto nests them)
        pid = len(pids) + 1
        trace.append(
            {
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": "requests (serve traces)"},
            }
        )
        tids: Dict[Any, int] = {}
        for e in req_spans:
            a = e["args"]
            rid = a.get("request_id")
            if rid not in tids:
                tids[rid] = len(tids)
                trace.append(
                    {
                        "name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tids[rid],
                        "args": {
                            "name": f"req {rid} [{a.get('trace_id')}]"
                        },
                    }
                )
            t0w, t1w = float(a["t0_wall"]), float(a["t1_wall"])
            trace.append(
                {
                    "name": str(a.get("span", "?")), "ph": "X",
                    "pid": pid, "tid": tids[rid],
                    "ts": round((t0w - base) * 1e6, 3),
                    "dur": round(max(t1w - t0w, 0.0) * 1e6, 3),
                    "args": {
                        k: v
                        for k, v in a.items()
                        if k not in ("t0_wall", "t1_wall") and v is not None
                    },
                }
            )
    if profile_totals:
        pid = len(pids) + 2 if req_spans else len(pids) + 1
        trace.append(
            {
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": "device profile (per-phase aggregate)"},
            }
        )
        for tid, (phase, us) in enumerate(
            sorted(profile_totals.items(), key=lambda kv: -kv[1])
        ):
            trace.append(
                {
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": phase},
                }
            )
            trace.append(
                {
                    "name": phase, "ph": "X", "pid": pid, "tid": tid,
                    "ts": 0.0, "dur": round(us, 3),
                    "args": {"aggregate_device_us": round(us, 3)},
                }
            )
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


# ---- drift / straggler detection ------------------------------------------

# how many leading samples seed a rolling baseline before judging starts
BASELINE_SAMPLES = 4


def _span_samples(
    events: List[Dict[str, Any]],
) -> Dict[Tuple[str, Any, str, str], List[float]]:
    """Ordered latency samples per (src, proc, run_id, span name): for
    the step spans (``obs.cli.STEP_SPANS``) the sample is per-step
    latency (dur_s / steps — the same rule ``obs summary`` reconstructs
    with); for every other ok span it is the raw duration. run_id is in
    the key because one ledger file holds MANY run segments (APPEND
    bench sessions, the suite ledger) with legitimately different step
    times — a baseline must never cross a run boundary, or every
    config change reads as drift."""
    from heat3d_tpu.obs.cli import STEP_SPANS

    out: Dict[Tuple[str, Any, str, str], List[float]] = defaultdict(list)
    for r in events:
        if r.get("kind") != "span" or r.get("status") != "ok":
            continue
        dur = r.get("dur_s")
        if not isinstance(dur, (int, float)):
            continue
        name = str(r.get("event"))
        key = (
            str(r.get("src", "")), r.get("proc", 0),
            str(r.get("run_id", "")), name,
        )
        if name in STEP_SPANS:
            steps = r.get("steps")
            if isinstance(steps, int) and steps > 0:
                out[key].append(float(dur) / steps)
        else:
            out[key].append(float(dur))
    return out


def detect_anomalies(
    events: List[Dict[str, Any]],
    warn_pct: Optional[float] = None,
    fail_pct: Optional[float] = None,
    baseline: int = BASELINE_SAMPLES,
) -> List[Dict[str, Any]]:
    """Step-time drift and host stragglers from a (possibly merged)
    ledger, classified with the SAME tolerance bands as ``obs regress``
    (latency regresses upward; default warn >8% / fail >15%).

    - **Drift** (``kind_: span_drift``): per (src, proc, run, span-name)
      stream — the run_id in the key keeps a baseline from crossing run
      boundaries, so an APPEND-session ledger of differently-configured
      runs doesn't read as drift — a rolling baseline (the p50 of the
      last ``baseline`` ACCEPTED samples; flagged samples don't poison
      it, so a sustained slowdown keeps firing instead of absorbing into
      the baseline) judges every sample after the seed window.
    - **Straggler** (``kind_: host_straggler``): with two or more
      distinct (src, proc) HOST identities carrying step samples (an
      ``obs merge``'d pod ledger, or multi-proc), each host's per-step
      p50 is judged against the fleet p50. Sequential runs in a
      single-host ledger are ONE identity — never compared against each
      other. DURATION-based, so it is immune to wall-clock skew.
    - **Late starter** (``kind_: start_straggler``): cross-host
      comparison of step-span WALL STARTS (``ts - dur_s``), matched by
      per-host sample index and judged as a fraction of the fleet's
      step-span p50. This one READS WALL CLOCKS, so it is exactly as
      trustworthy as the clocks are aligned: on raw merged ledgers a
      skewed host clock masquerades as a late starter, and ``obs merge
      --align`` / ``obs timeline --align`` is the documented cure (the
      tests pin both directions). Only LATE hosts flag — a fast clock
      reads as early, which is not a straggler.
    - **Slow link** (``kind_: link_straggler``): per-(axis, direction)
      ``comm_probe`` samples (the ``HEAT3D_COMM_PROBE`` probe — sub-block
      rows fold into their parent link) compared across hosts: each
      host's per-link p50 is judged against the fleet p50 for the SAME
      link, naming the slow link rather than just the slow host.
      DURATION-based like host_straggler, so skew-immune.

    All percentiles use ``obs.metrics.percentile`` (nearest-rank) — the
    one rule every obs reconstruction shares. Returns records ready to
    print (``format_anomaly``) or to emit as ``obs_anomaly`` ledger
    events (``emit_anomalies``); ``fail`` records sort first."""
    from heat3d_tpu.obs.cli import STEP_SPANS
    from heat3d_tpu.obs.metrics import percentile
    from heat3d_tpu.obs.perf.regress import (
        DEFAULT_FAIL_PCT,
        DEFAULT_WARN_PCT,
        band_status,
    )

    warn_pct = DEFAULT_WARN_PCT if warn_pct is None else warn_pct
    fail_pct = DEFAULT_FAIL_PCT if fail_pct is None else fail_pct
    anomalies: List[Dict[str, Any]] = []
    samples = _span_samples(events)

    for (src, proc, run_id, name), vals in sorted(samples.items()):
        if len(vals) <= baseline:
            continue
        accepted = list(vals[:baseline])
        for i, v in enumerate(vals[baseline:], start=baseline):
            base = percentile(accepted[-baseline:], 50)
            if base <= 0:
                accepted.append(v)
                continue
            delta = (v - base) / base * 100.0
            status = band_status(delta, warn_pct, fail_pct)
            if status == "pass":
                accepted.append(v)
                continue
            anomalies.append(
                {
                    "kind_": "span_drift",
                    "span": name,
                    "src": src,
                    "proc": proc,
                    "run_id_": run_id,
                    "sample": i,
                    "value_s": round(v, 9),
                    "baseline_s": round(base, 9),
                    "delta_pct": round(delta, 2),
                    "status": status,
                    "per_step": name in STEP_SPANS,
                }
            )

    # straggler: cross-HOST comparison of per-step p50s (runs merged per
    # host — every host mixes the same session's runs, so the comparison
    # stays apples-to-apples)
    step_streams: Dict[Tuple[str, Any], List[float]] = defaultdict(list)
    for (src, proc, run_id, name), vals in samples.items():
        if name in STEP_SPANS and vals:
            step_streams[(src, proc)].extend(vals)
    if len(step_streams) > 1:
        p50s = {
            k: percentile(v, 50) for k, v in sorted(step_streams.items())
        }
        fleet = percentile(list(p50s.values()), 50)
        if fleet > 0:
            for (src, proc), p50 in p50s.items():
                delta = (p50 - fleet) / fleet * 100.0
                status = band_status(delta, warn_pct, fail_pct)
                if status != "pass":
                    anomalies.append(
                        {
                            "kind_": "host_straggler",
                            "src": src,
                            "proc": proc,
                            "p50_s": round(p50, 9),
                            "fleet_p50_s": round(fleet, 9),
                            "delta_pct": round(delta, 2),
                            "status": status,
                        }
                    )
    # late starter: cross-host comparison of step-span WALL STARTS
    # (ts - dur_s), index-matched so step i is compared against the
    # fleet's step i. Judged as a fraction of the fleet step-span p50;
    # wall-clock-based by construction (see docstring) — feed it aligned
    # time (obs merge --align) on multihost ledgers.
    start_streams: Dict[Tuple[str, Any], List[float]] = defaultdict(list)
    span_durs: List[float] = []
    for r in events:
        if r.get("kind") != "span" or r.get("status") != "ok":
            continue
        if str(r.get("event")) not in STEP_SPANS:
            continue
        ts, dur = r.get("ts"), r.get("dur_s")
        if isinstance(ts, (int, float)) and isinstance(dur, (int, float)):
            start_streams[(str(r.get("src", "")), r.get("proc", 0))].append(
                float(ts) - float(dur)
            )
            span_durs.append(float(dur))
    if len(start_streams) > 1 and span_durs:
        fleet_dur = percentile(span_durs, 50)
        n = min(len(v) for v in start_streams.values())
        if fleet_dur > 0 and n > 0:
            hosts = sorted(start_streams)
            med = [
                percentile([start_streams[h][i] for h in hosts], 50)
                for i in range(n)
            ]
            for h in hosts:
                offs = [start_streams[h][i] - med[i] for i in range(n)]
                off = percentile(offs, 50)
                delta = off / fleet_dur * 100.0
                status = band_status(delta, warn_pct, fail_pct)
                if status != "pass":
                    anomalies.append(
                        {
                            "kind_": "start_straggler",
                            "src": h[0],
                            "proc": h[1],
                            "offset_s": round(off, 9),
                            "fleet_span_p50_s": round(fleet_dur, 9),
                            "delta_pct": round(delta, 2),
                            "status": status,
                        }
                    )

    # slow link: per-(axis, direction) comm_probe samples compared
    # across hosts — the link, not just the host, gets named. Sub-block
    # rows fold into their parent link (one attribution unit).
    by_link: Dict[
        Tuple[str, str], Dict[Tuple[str, Any], List[float]]
    ] = defaultdict(lambda: defaultdict(list))
    for r in events:
        if r.get("event") != "comm_probe":
            continue
        t, ax, dr = r.get("t_s"), r.get("axis_name"), r.get("direction")
        if (
            isinstance(t, (int, float))
            and t > 0
            and isinstance(ax, str)
            and dr in ("lo", "hi")
        ):
            by_link[(ax, str(dr))][
                (str(r.get("src", "")), r.get("proc", 0))
            ].append(float(t))
    for (ax, dr), hosts_d in sorted(by_link.items()):
        if len(hosts_d) < 2:
            continue  # a link seen by one host has no fleet to lag
        p50s = {h: percentile(v, 50) for h, v in sorted(hosts_d.items())}
        fleet = percentile(list(p50s.values()), 50)
        if fleet <= 0:
            continue
        for (src, proc), p50 in p50s.items():
            delta = (p50 - fleet) / fleet * 100.0
            status = band_status(delta, warn_pct, fail_pct)
            if status != "pass":
                anomalies.append(
                    {
                        "kind_": "link_straggler",
                        "src": src,
                        "proc": proc,
                        "axis": ax,
                        "direction": dr,
                        "p50_s": round(p50, 9),
                        "fleet_p50_s": round(fleet, 9),
                        "delta_pct": round(delta, 2),
                        "status": status,
                    }
                )

    anomalies.sort(key=lambda a: (a["status"] != "fail", -a["delta_pct"]))
    return anomalies


def format_anomaly(a: Dict[str, Any]) -> str:
    tag = {"fail": "ANOMALY", "warn": "drift?"}.get(a["status"], a["status"])
    who = f"{a['src'] + '/' if a.get('src') else ''}proc{a.get('proc', 0)}"
    if a.get("kind_") == "host_straggler":
        return (
            f"{tag} straggler {who}: step p50 {a['p50_s'] * 1e3:.3f}ms vs "
            f"fleet {a['fleet_p50_s'] * 1e3:.3f}ms ({a['delta_pct']:+.1f}%)"
        )
    if a.get("kind_") == "start_straggler":
        return (
            f"{tag} late starter {who}: steps begin "
            f"{a['offset_s'] * 1e3:+.3f}ms vs fleet "
            f"({a['delta_pct']:+.1f}% of a step span; wall-clock-based — "
            "align merged ledgers first)"
        )
    if a.get("kind_") == "link_straggler":
        return (
            f"{tag} slow link {a.get('axis')}.{a.get('direction')} {who}: "
            f"p50 {a['p50_s'] * 1e6:.1f}us vs fleet "
            f"{a['fleet_p50_s'] * 1e6:.1f}us ({a['delta_pct']:+.1f}%)"
        )
    unit = "/step" if a.get("per_step") else ""
    return (
        f"{tag} {a.get('span')} {who} sample {a.get('sample')}: "
        f"{a['value_s'] * 1e3:.3f}ms{unit} vs baseline "
        f"{a['baseline_s'] * 1e3:.3f}ms ({a['delta_pct']:+.1f}%)"
    )


def emit_anomalies(anomalies: List[Dict[str, Any]]) -> None:
    """Append each anomaly as an ``obs_anomaly`` ledger event (a no-op
    without an active ledger — detection is read-side, the events are for
    pipelines that run the detector right after the run they observed)."""
    from heat3d_tpu import obs

    for a in anomalies:
        obs.get().event("obs_anomaly", **a)


# ---- CLI -------------------------------------------------------------------


def _read_streams(
    paths: List[str], align: bool = False
) -> List[Dict[str, Any]]:
    """One ledger reads directly; several merge through
    ``obs.perf.merge.merge_ledgers`` so each keeps its ``src`` tag (the
    straggler detector and the per-stream tracks key on it).
    ``align=True`` merges onto the anchor-aligned clock (obs merge
    --align) so the wall-clock-based detectors judge estimated true
    time; it is meaningless (and ignored) for a single ledger."""
    if len(paths) == 1:
        from heat3d_tpu.obs.cli import read_ledger

        return read_ledger(paths[0])
    from heat3d_tpu.obs.perf.merge import merge_ledgers

    return merge_ledgers(paths, align=align)["events"]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="heat3d obs timeline",
        description="unified performance timeline: normalize a run "
        "ledger (or several multihost ledgers) plus an optional "
        "--profile capture into one event model; export Chrome-trace/"
        "Perfetto JSON and detect step-time drift / host stragglers",
    )
    ap.add_argument("ledgers", nargs="+", help="run ledger file(s); "
                    "several are src-tagged and merged (obs merge)")
    ap.add_argument("-o", "--out", default=None, metavar="TRACE.json",
                    help="write the Chrome-trace JSON here (open in "
                    "Perfetto: ui.perfetto.dev)")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="profile capture dir (or .xplane.pb): adds the "
                    "per-phase device-time aggregate track and the phase "
                    "table")
    ap.add_argument("--align", action="store_true",
                    help="merge multiple ledgers onto the anchor-aligned "
                    "clock (obs merge --align) before detection, so a "
                    "skewed host clock cannot masquerade as a late "
                    "starter")
    ap.add_argument("--anomalies", action="store_true",
                    help="also emit obs_anomaly ledger events for every "
                    "detected drift/straggler (detection itself always "
                    "runs)")
    ap.add_argument("--warn-pct", type=float, default=None,
                    help="drift warn band (default: obs regress's 8)")
    ap.add_argument("--fail-pct", type=float, default=None,
                    help="drift fail band (default: obs regress's 15)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report instead of the text "
                    "summary")
    args = ap.parse_args(argv)

    try:
        events = _read_streams(args.ledgers, align=args.align)
    except OSError as e:
        print(f"timeline: cannot read ledger: {e}", file=sys.stderr)
        return 2
    if not events:
        print(f"timeline: no events in {' '.join(args.ledgers)}",
              file=sys.stderr)
        return 1

    tl = timeline_events(events)
    profile_totals: Optional[Dict[str, float]] = None
    profile_note = None
    if args.profile:
        try:
            profile_totals, artifact = profile_phase_totals(args.profile)
            profile_note = artifact
        except (RuntimeError, OSError) as e:
            # the ledger timeline is still worth exporting without the
            # device track — degrade with a note, like every obs reader
            print(f"timeline: profile ignored ({e})", file=sys.stderr)

    anomalies = detect_anomalies(
        events, warn_pct=args.warn_pct, fail_pct=args.fail_pct
    )
    if args.anomalies and anomalies:
        emit_anomalies(anomalies)

    out_path = None
    if args.out:
        doc = to_chrome_trace(tl, profile_totals)
        with open(args.out, "w") as f:
            json.dump(doc, f)
        out_path = args.out
        from heat3d_tpu import obs

        obs.get().event(
            "timeline_export",
            path=os.path.abspath(args.out),
            events=len(doc["traceEvents"]),
            streams=len({(e["src"], e["proc"]) for e in tl}),
            anomalies=len(anomalies),
        )

    spans = sum(1 for e in tl if e["dur_s"] is not None)
    if args.json:
        print(
            json.dumps(
                {
                    "events": len(tl),
                    "spans": spans,
                    "streams": len({(e["src"], e["proc"]) for e in tl}),
                    "out": out_path,
                    "profile": profile_note,
                    "phase_device_us": profile_totals,
                    "anomalies": anomalies,
                }
            )
        )
        return 0
    streams = len({(e["src"], e["proc"]) for e in tl})
    wall = tl[-1]["t_wall"] - tl[0]["t_wall"] if len(tl) > 1 else 0.0
    print(
        f"timeline: {len(tl)} events ({spans} spans) across {streams} "
        f"stream(s), {wall:.3f}s wall"
    )
    if profile_totals:
        total = sum(profile_totals.values()) or 1.0
        print(f"device time by phase ({profile_note}):")
        for phase, us in sorted(
            profile_totals.items(), key=lambda kv: -kv[1]
        ):
            print(f"  {us / 1e3:9.3f} ms  {100.0 * us / total:5.1f}%  {phase}")
    for a in anomalies[:10]:
        print("  " + format_anomaly(a))
    if len(anomalies) > 10:
        print(f"  ... ({len(anomalies) - 10} more anomalies)")
    if not anomalies:
        print("no drift/straggler anomalies detected")
    if out_path:
        print(f"wrote {out_path} (open in Perfetto: ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
