"""Performance observability: is a run FAST for its hardware, and did it
regress?

The base obs package (ledger / metrics / trace) makes runs *explainable*;
this layer makes them *judged* (docs/OBSERVABILITY.md §"Performance
observability"). Six instruments:

- :mod:`~heat3d_tpu.obs.perf.profiling` — ``--profile DIR`` device-trace
  capture on every entry point, with the artifact path and the capture
  overhead recorded into the run ledger (a profiled run must say it was
  profiled — capture cost is measurement perturbation).
- :mod:`~heat3d_tpu.obs.perf.roofline` — per-config FLOPs/bytes from
  ``compiled.cost_analysis()`` joined with per-backend peak specs:
  ``heat3d obs roofline`` prints a per-phase achieved-vs-peak table (the
  phases are the same ``heat3d.*`` names the named-scope spans use —
  ``parallel.step.phase_programs`` is the keying), and the promoted
  ``scripts/roofline_check.py`` row model lives here too.
- :mod:`~heat3d_tpu.obs.perf.regress` — ``heat3d obs regress``: the
  automated perf-regression gate comparing a session's bench rows against
  committed history with per-metric tolerance bands and
  platform/cpu_fallback-aware baselines (a CPU run never fails against a
  TPU record).
- :mod:`~heat3d_tpu.obs.perf.merge` — ``heat3d obs merge``: join the
  per-process ledgers of a multihost run into one timeline with
  cross-host skew stats.
- :mod:`~heat3d_tpu.obs.perf.timeline` — ``heat3d obs timeline``: one
  normalized event model over ledger + merged streams + profile
  captures; Chrome-trace/Perfetto export, per-phase device totals (the
  measured side of ``roofline --from-profile``), and step-time
  drift / host-straggler detection (``obs_anomaly`` events).
- :mod:`~heat3d_tpu.obs.perf.slo` — ``heat3d obs slo``: declarative
  service-level objectives (per-bucket serve latency, step-time and
  halo-share ceilings) evaluated into a burn-rate verdict; rc 1 only on
  breach.

Failure posture (inherited from obs): perf telemetry never kills the run
it observes — profiling and cost-analysis errors degrade to a ledger note.
"""

from heat3d_tpu.obs.perf.profiling import profile_capture  # noqa: F401
