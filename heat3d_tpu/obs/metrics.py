"""The metrics registry: counters, gauges, histograms, one process-wide
instance, exportable as a Prometheus textfile or a JSON snapshot.

The subsystems register their own instruments (step latency, halo-exchange
latency, retry attempts/outcomes, checkpoint write/verify/quarantine
counts, supervisor generation transitions, sync-overhead RTT) and the
entry points export: every run writes a final ``metrics_summary`` event
into the run ledger, and ``HEAT3D_METRICS=<path>`` additionally writes a
snapshot file at exit — ``.prom`` suffix selects the Prometheus textfile
exposition format (node_exporter textfile-collector compatible), anything
else JSON.

Design: stdlib-only, lock-per-registry, label sets as sorted tuples.
Histograms keep exact samples up to a cap (8192) plus running
count/sum/min/max, enough for the p50/p95 the judged metrics need without
pre-committing to bucket boundaries; past the cap new samples still update
the running aggregates but are not stored (``clipped`` marks the snapshot
so a percentile over a clipped reservoir is never mistaken for exact).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

METRIC_PREFIX = "heat3d_"
ENV_METRICS = "HEAT3D_METRICS"
HISTOGRAM_SAMPLE_CAP = 8192

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_str(key: LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (the same rule as utils.timing.percentile,
    duplicated here so obs never imports jax-importing modules)."""
    if not values:
        raise ValueError("no values")
    s = sorted(values)
    k = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[k]


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, n: float = 1.0, **labels: Any) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def value(self, **labels: Any) -> float:
        return self._values.get(_label_key(labels), 0.0)

    def snapshot(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "values": {_label_str(k) or "": v for k, v in self._values.items()},
        }

    def prom_lines(self) -> List[str]:
        return [
            f"{self.name}{_label_str(k)} {v}"
            for k, v in sorted(self._values.items())
        ]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._values: Dict[LabelKey, float] = {}

    def set(self, v: float, **labels: Any) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(v)

    def value(self, **labels: Any) -> Optional[float]:
        return self._values.get(_label_key(labels))

    def snapshot(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "values": {_label_str(k) or "": v for k, v in self._values.items()},
        }

    def prom_lines(self) -> List[str]:
        return [
            f"{self.name}{_label_str(k)} {v}"
            for k, v in sorted(self._values.items())
        ]


class _HistState:
    __slots__ = ("count", "sum", "min", "max", "samples", "clipped")

    def __init__(self) -> None:
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.samples: List[float] = []
        self.clipped = False


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._states: Dict[LabelKey, _HistState] = {}

    def observe(self, v: float, **labels: Any) -> None:
        v = float(v)
        key = _label_key(labels)
        with self._lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _HistState()
            st.count += 1
            st.sum += v
            st.min = v if st.min is None else min(st.min, v)
            st.max = v if st.max is None else max(st.max, v)
            if len(st.samples) < HISTOGRAM_SAMPLE_CAP:
                st.samples.append(v)
            else:
                st.clipped = True

    def stats(self, **labels: Any) -> Optional[Dict[str, Any]]:
        st = self._states.get(_label_key(labels))
        return None if st is None else self._stat_dict(st)

    @staticmethod
    def _stat_dict(st: _HistState) -> Dict[str, Any]:
        out = {
            "count": st.count,
            "sum": st.sum,
            "min": st.min,
            "max": st.max,
            "mean": (st.sum / st.count) if st.count else None,
        }
        if st.samples:
            out["p50"] = percentile(st.samples, 50)
            out["p95"] = percentile(st.samples, 95)
        if st.clipped:
            out["clipped"] = True
        return out

    def snapshot(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "values": {
                _label_str(k) or "": self._stat_dict(st)
                for k, st in self._states.items()
            },
        }

    def prom_lines(self) -> List[str]:
        # summary-style exposition: _count/_sum plus p50/p95 as quantile
        # labels — exact percentiles over the stored reservoir, not
        # pre-bucketed (the judged metrics are p50/p95, so the export
        # carries precisely those)
        lines = []
        for key, st in sorted(self._states.items()):
            base = dict(key)
            lines.append(f"{self.name}_count{_label_str(key)} {st.count}")
            lines.append(f"{self.name}_sum{_label_str(key)} {st.sum}")
            if st.samples:
                for q, qs in ((50, "0.5"), (95, "0.95")):
                    qkey = _label_key({**base, "quantile": qs})
                    lines.append(
                        f"{self.name}{_label_str(qkey)} "
                        f"{percentile(st.samples, q)}"
                    )
        return lines


class MetricsRegistry:
    """Get-or-create registry of named instruments; one per process
    (:data:`REGISTRY`), fresh instances for tests."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str) -> _Metric:
        if not name.startswith(METRIC_PREFIX):
            name = METRIC_PREFIX + name
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "") -> Histogram:
        return self._get_or_create(Histogram, name, help)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able state of every instrument — the final per-run summary
        record the entry points append to the ledger."""
        return {name: m.snapshot() for name, m in sorted(self._metrics.items())}

    def to_prometheus_text(self) -> str:
        lines: List[str] = []
        for name, m in sorted(self._metrics.items()):
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            # histograms export as Prometheus 'summary' (quantile labels)
            ptype = "summary" if m.kind == "histogram" else m.kind
            lines.append(f"# TYPE {name} {ptype}")
            lines.extend(m.prom_lines())
        return "\n".join(lines) + ("\n" if lines else "")

    def write_snapshot(self, path: str) -> None:
        """Atomic snapshot file: ``.prom`` suffix selects the Prometheus
        textfile format (a half-written textfile would be scraped as
        corrupt, hence tmp+replace), anything else JSON."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        if path.endswith(".prom"):
            payload = self.to_prometheus_text()
        else:
            payload = json.dumps(self.snapshot(), indent=2, default=repr)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, path)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


REGISTRY = MetricsRegistry()


def export_at_exit(registry: Optional[MetricsRegistry] = None) -> Optional[str]:
    """Write the ``HEAT3D_METRICS`` snapshot file if the env asks for one
    (entry points call this on their way out). Returns the path written,
    or None — including on an unwritable path: telemetry export must not
    turn a COMPLETED run into a nonzero exit (the run's results already
    printed; the ledger carries the metrics_summary either way)."""
    path = os.environ.get(ENV_METRICS)
    if not path:
        return None
    try:
        (registry or REGISTRY).write_snapshot(path)
    except (OSError, TypeError, ValueError) as e:
        # OSError: unwritable path; TypeError/ValueError: a snapshot
        # value json.dumps rejects — either way the run's results already
        # printed, so export degrades to a stderr note (fail-soft)
        import sys

        print(f"heat3d: metrics export to {path} failed: {e}", file=sys.stderr)
        return None
    return path
