"""``python -m heat3d_tpu.obs ...`` — the obs CLI (same surface as
``heat3d obs ...``)."""

import sys

from heat3d_tpu.obs.cli import main

sys.exit(main())
