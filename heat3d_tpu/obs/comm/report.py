"""Per-link aggregation of ``comm_probe`` events (jax-free).

Shared by ``obs summary``, ``obs watch`` and the tests: groups the
``comm_probe`` rows the probe (:mod:`heat3d_tpu.obs.comm.probe`) emitted
by (axis, direction), reduces them to p50 latency and
predicted-vs-achieved bytes, and renders the small table both CLI
surfaces show. Sub-blocks of a partitioned exchange fold into their
parent (axis, direction) link — the *link* is the unit of attribution,
matching the ``link_straggler`` detector in
:mod:`heat3d_tpu.obs.perf.timeline`.

Everything here fails soft and imports nothing heavier than stdlib —
``obs summary`` must keep working on a laptop with no accelerator stack.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Any, Dict, Iterable, List, Optional, Tuple

from heat3d_tpu.obs.metrics import percentile

__all__ = ["comm_link_stats", "comm_lines"]


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def comm_link_stats(events: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Reduce ``comm_probe`` events to one record per (axis, direction).

    Returns a list of JSON-safe dicts sorted by (axis_name, direction),
    each with ``axis``, ``direction``, ``n`` (sample count, sub-blocks
    included), ``p50_us`` (p50 of per-sample link time), ``bytes``
    (plan-predicted bytes for the link, summed over its sub-blocks),
    ``gbps`` (predicted bytes over measured p50 time) and ``worst``
    (True on the slowest link). Empty list when no usable samples.
    """
    per_link: Dict[Tuple[str, str], List[Dict[str, Any]]] = defaultdict(list)
    for e in events:
        if e.get("event") != "comm_probe":
            continue
        ax, dr, t = e.get("axis_name"), e.get("direction"), e.get("t_s")
        if isinstance(ax, str) and dr in ("lo", "hi") and _is_num(t) and t > 0:
            per_link[(ax, str(dr))].append(e)
    out: List[Dict[str, Any]] = []
    for (ax, dr), rows in sorted(per_link.items()):
        # A link's predicted bytes is the sum over its distinct
        # sub-blocks (each sub-block row repeats across probe passes —
        # count each once); its time is the p50 over per-sub-block
        # samples summed per pass would over-model pipelining, so we
        # stay honest and report the p50 of the per-row samples next to
        # the per-row predicted bytes ratio.
        t_p50 = percentile([float(r["t_s"]) for r in rows], 50)
        by_block: Dict[Any, float] = {}
        for r in rows:
            b = r.get("bytes_predicted")
            if _is_num(b) and b > 0:
                by_block[r.get("sub_block")] = float(b)
        bytes_pred = sum(by_block.values())
        gbps = [
            float(r["bytes_predicted"]) / float(r["t_s"]) / 1e9
            for r in rows
            if _is_num(r.get("bytes_predicted")) and r["bytes_predicted"] > 0
        ]
        out.append(
            {
                "axis": ax,
                "direction": dr,
                "n": len(rows),
                "p50_us": round(t_p50 * 1e6, 3),
                "bytes": int(bytes_pred),
                "gbps": round(percentile(gbps, 50), 3) if gbps else None,
                "worst": False,
            }
        )
    if out:
        worst = max(out, key=lambda r: r["p50_us"])
        worst["worst"] = True
    return out


def _fmt_bytes(n: int) -> str:
    v = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if v < 1024.0 or unit == "GiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024.0
    return f"{n}B"


def comm_lines(events: Iterable[Dict[str, Any]], indent: str = "   ") -> List[str]:
    """Render the per-axis comm table for ``obs summary`` / ``obs watch``.

    Empty list when there are no ``comm_probe`` samples (the section
    simply does not appear). Never raises.
    """
    try:
        stats = comm_link_stats(events)
        if not stats:
            return []
        lines = ["", " comm links (probe):"]
        lines.append(
            f"{indent}{'link':<10} {'n':>4} {'p50':>12} {'pred bytes':>12} {'GB/s':>8}"
        )
        for s in stats:
            gbps = f"{s['gbps']:.3f}" if s["gbps"] is not None else "-"
            flag = "  <- worst" if s["worst"] and len(stats) > 1 else ""
            lines.append(
                f"{indent}{s['axis'] + '.' + s['direction']:<10} {s['n']:>4} "
                f"{s['p50_us']:>10.1f}us {_fmt_bytes(s['bytes']):>12} {gbps:>8}{flag}"
            )
        return lines
    except Exception:  # pragma: no cover - observability fails soft
        return []
