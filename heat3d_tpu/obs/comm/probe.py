"""Opt-in per-link halo probe (``HEAT3D_COMM_PROBE``).

The step programs attribute exchange time to per-(axis, direction,
sub-block) named scopes (parallel/halo.py, parallel/plan.py), but scope
attribution needs a profiler capture — and a fused program cannot tell
you which *link* is slow from wall clocks alone. This probe answers that
with direct measurement: for every link the :class:`ExchangePlan`'s
schedule would exercise — one (axis, direction) pair per mesh axis in
monolithic mode, one per sub-block in partitioned mode — it compiles a
separate micro-program (a device-side ``fori_loop`` of back-to-back
``ppermute`` of exactly that link's face sub-block), times it with the
honest blocking semantics the benches use (``force_sync`` readback, RTT
subtraction, trip-count calibration so device time swamps the host round
trip), and emits one ``comm_probe`` ledger event per link carrying the
plan's OWN predicted bytes for that message — so every link reports
predicted-vs-achieved GB/s and the merged-ledger straggler detector
(``link_straggler`` in obs/perf/timeline.py) can name the slow link
across hosts.

Honesty caveats, recorded on every row: per-link micro-programs time
each collective in ISOLATION — the production exchange pipelines links
(partitioned early-bird sends overlap sub-blocks), so the sum of probed
link times is an upper bound on exchange latency, not a reconstruction
of it. Rows where the host round trip dominates carry
``rtt_dominated: true`` just like bench rows.

Activation: ``HEAT3D_COMM_PROBE=1`` makes ``bench_halo`` run the probe
after its row (fail-soft — a probe failure never kills a bench);
``python -m heat3d_tpu.obs.comm.probe`` runs it standalone (the CLI is
its own opt-in). ``HEAT3D_COMM_PROBE_ITERS`` overrides the timed-sample
count (default ``5``).

This module imports jax at module level — consumers that must stay
jax-free (obs/cli.py) import it lazily.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from heat3d_tpu import obs
from heat3d_tpu.core.config import GridConfig, MeshConfig, SolverConfig
from heat3d_tpu.obs.trace import named_phase
from heat3d_tpu.parallel.plan import (
    ExchangePlan,
    effective_halo_plan,
    partition_bounds,
    plan_for,
)
from heat3d_tpu.parallel.topology import build_mesh
from heat3d_tpu.utils.compat import shard_map
from heat3d_tpu.utils.timing import (
    calibrate_trip_count,
    force_sync,
    honest_time,
    percentile,
    sync_overhead,
)

ENV_COMM_PROBE = "HEAT3D_COMM_PROBE"
ENV_COMM_PROBE_ITERS = "HEAT3D_COMM_PROBE_ITERS"
DEFAULT_ITERS = 5


def comm_probe_enabled() -> bool:
    """True when ``HEAT3D_COMM_PROBE`` opts the process into the per-link
    probe (``0``/empty/unset stay off — the probe adds per-link compiles
    and timed loops, never free)."""
    return os.environ.get(ENV_COMM_PROBE, "") not in ("", "0")


def probe_iters(default: int = DEFAULT_ITERS) -> int:
    """Timed samples per link (``HEAT3D_COMM_PROBE_ITERS`` override;
    malformed values fall back — observability never raises over an env
    typo)."""
    raw = os.environ.get(ENV_COMM_PROBE_ITERS)
    if raw is None or raw == "":
        return default
    try:
        return max(1, int(raw))
    except ValueError:
        return default


def probe_links(
    plan: ExchangePlan, local_shape, itemsize: int
) -> List[Dict[str, Any]]:
    """Enumerate the links ``plan`` would exercise on a block of
    ``local_shape``, with the plan's own predicted bytes per message.

    Mirrors :meth:`ExchangePlan.traffic` exactly — progressive face
    extension under axis ordering, the partition granularity floor — so
    the per-link ``bytes_predicted`` sum to the ``plan_bytes_per_device``
    the bench rows record (the tests pin that identity). Size-1 axes
    have no remote party and yield no links. Pure Python — no jax.
    """
    ext = list(local_shape)
    w = plan.width
    links: List[Dict[str, Any]] = []
    for spec in plan.axis_specs:
        if spec.size > 1:
            face_shape = [w if d == spec.axis else ext[d] for d in range(3)]
            if plan.mode == "partitioned":
                nparts = plan._face_partitions(face_shape, itemsize)
            else:
                nparts = 1
            bounds = partition_bounds(face_shape[spec.part_dim], nparts)
            for direction, perm in (
                # "lo" = the transfer that fills my LOW ghost (the low
                # neighbor's high face, shifted up) — same orientation as
                # the halo.<axis>.lo scope in parallel/halo.py
                ("lo", spec.perm_up),
                ("hi", spec.perm_down),
            ):
                for i, (a, b) in enumerate(bounds):
                    sub = list(face_shape)
                    sub[spec.part_dim] = b - a
                    elems = sub[0] * sub[1] * sub[2]
                    sub_block = i if len(bounds) > 1 else None
                    scope = f"halo.{spec.name}.{direction}" + (
                        f".p{i}" if sub_block is not None else ""
                    )
                    links.append(
                        {
                            "axis": spec.axis,
                            "axis_name": spec.name,
                            "direction": direction,
                            "sub_block": sub_block,
                            "sub_shape": tuple(sub),
                            "bytes_predicted": elems * itemsize,
                            "scope": scope,
                            "perm": perm,
                        }
                    )
        if plan.halo_order == "axis":
            ext[spec.axis] += 2 * w
    return links


def _link_program(mesh, axis_names, axis_name: str, perm, scope: str):
    """One link's micro-program: jitted shard_map'd fori_loop of ``n``
    back-to-back ppermutes of the link's face sub-block (carry = the
    block, so no transfer can be DCE'd), under the link's named scope."""
    spec_p = P(*axis_names)

    def _loop(f, n):
        def body(_, x):
            with named_phase(scope):
                return jax.lax.ppermute(x, axis_name, perm)

        return jax.lax.fori_loop(0, n, body, f)

    return jax.jit(
        shard_map(
            _loop,
            mesh=mesh,
            in_specs=(spec_p, P()),
            out_specs=spec_p,
            check_vma=False,
        )
    )


def probe_plan(
    cfg: SolverConfig,
    width: int = 1,
    iters: Optional[int] = None,
    warmup: int = 1,
    emit: bool = True,
) -> List[Dict[str, Any]]:
    """Time every link of ``cfg``'s effective exchange plan; return (and
    by default ledger-emit) one ``comm_probe`` row per link.

    Row fields: link identity (``axis``/``axis_name``/``direction``/
    ``sub_block``/``scope``), plan provenance (``plan_key``,
    ``plan_mode``, ``width``, ``mesh``), the plan-predicted message bytes
    (``bytes_predicted``), the measured per-collective p50 (``t_s``) and
    the ratio (``gbps``), plus the bench-grade timing provenance
    (``iters``, ``trips``, ``sync_rtt_s``, ``rtt_dominated``,
    ``platform``). Empty list on a (1,1,1) mesh — no link exists.
    """
    it = probe_iters() if iters is None else max(1, int(iters))
    eff = effective_halo_plan(cfg)
    plan = plan_for(dataclasses.replace(cfg, halo_plan=eff), width)
    itemsize = jnp.dtype(cfg.precision.storage).itemsize
    links = probe_links(plan, cfg.local_shape, itemsize)
    if not links:
        return []
    mesh = build_mesh(cfg.mesh)
    sharding = NamedSharding(mesh, P(*cfg.mesh.axis_names))
    rtt = sync_overhead(probe=jnp.zeros((8, 128)))
    ledger = obs.get()
    rows: List[Dict[str, Any]] = []
    for link in links:
        run_n = _link_program(
            mesh, cfg.mesh.axis_names, link["axis_name"], link["perm"],
            link["scope"],
        )
        gshape = tuple(
            link["sub_shape"][d] * cfg.mesh.shape[d] for d in range(3)
        )
        f = jax.device_put(
            jnp.zeros(gshape, jnp.dtype(cfg.precision.storage)), sharding
        )
        for _ in range(warmup):
            force_sync(run_n(f, jnp.int32(1)))

        def _timed(n, _run=run_n, _f=f):
            t0 = time.perf_counter()
            force_sync(_run(_f, jnp.int32(n)))
            return time.perf_counter() - t0

        trips, _ = calibrate_trip_count(_timed, rtt, start=25)
        raws = [_timed(trips) for _ in range(it)]
        times = [honest_time(t, rtt) / trips for t in raws]
        t50 = percentile(times, 50)
        row = {
            "axis": link["axis"],
            "axis_name": link["axis_name"],
            "direction": link["direction"],
            "sub_block": link["sub_block"],
            "scope": link["scope"],
            "width": plan.width,
            "mesh": list(cfg.mesh.shape),
            "plan_key": plan.key,
            "plan_mode": plan.mode,
            "bytes_predicted": link["bytes_predicted"],
            "t_s": t50,
            "gbps": link["bytes_predicted"] / t50 / 1e9 if t50 > 0 else None,
            "iters": it,
            "trips": trips,
            "sync_rtt_s": rtt,
            "rtt_dominated": min(raws) < 2 * rtt,
            "platform": jax.default_backend(),
        }
        rows.append(row)
        if emit:
            ledger.event("comm_probe", **row)
    return rows


def maybe_probe(cfg: SolverConfig, width: int = 1) -> List[Dict[str, Any]]:
    """The env-gated hook ``bench_halo`` calls after its row: runs the
    probe iff ``HEAT3D_COMM_PROBE`` opts in, and fails SOFT — probe
    telemetry must never kill the bench that hosts it."""
    if not comm_probe_enabled():
        return []
    try:
        return probe_plan(cfg, width=width)
    except Exception as e:  # noqa: BLE001 - telemetry fails soft
        print(
            f"heat3d: comm probe failed ({type(e).__name__}: "
            f"{str(e)[:120]}); run continues unprobed",
            file=sys.stderr,
        )
        return []


def main(argv: Optional[List[str]] = None) -> int:
    """Standalone probe CLI (``python -m heat3d_tpu.obs.comm.probe``) —
    invoking it IS the opt-in, no env needed. Prints one JSON row per
    link (``--json``) or a readable table; ledger events go to
    ``--ledger`` / ``HEAT3D_LEDGER`` when configured."""
    ap = argparse.ArgumentParser(
        prog="heat3d-comm-probe",
        description="time every (axis, direction, sub-block) halo link "
        "of an exchange plan as its own micro-program",
    )
    ap.add_argument("--grid", type=int, nargs="+", default=[16],
                    help="global grid (1 value = cube, or 3)")
    ap.add_argument("--mesh", type=int, nargs="+", required=True,
                    help="device mesh extents (1 value = slab, or 3)")
    ap.add_argument("--width", type=int, default=1, help="ghost width")
    ap.add_argument("--halo-plan", default="monolithic",
                    choices=("monolithic", "partitioned", "auto"))
    ap.add_argument("--halo-order", default="axis",
                    choices=("axis", "pairwise"))
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--iters", type=int, default=None,
                    help=f"timed samples per link (default {DEFAULT_ITERS})")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="one JSON row per link on stdout")
    ap.add_argument("--ledger", default=None,
                    help="ledger path (HEAT3D_LEDGER fallback)")
    args = ap.parse_args(argv)

    grid = args.grid if len(args.grid) == 3 else [args.grid[0]] * 3
    mesh = list(args.mesh) + [1] * (3 - len(args.mesh))
    cfg = SolverConfig(
        grid=GridConfig(shape=tuple(grid)),
        mesh=MeshConfig(shape=tuple(mesh[:3])),
        halo_plan=args.halo_plan,
        halo_order=args.halo_order,
    )
    cfg = dataclasses.replace(
        cfg, precision=dataclasses.replace(cfg.precision, storage=args.dtype)
    )
    obs.activate(args.ledger, meta={"entry": "comm_probe"})
    try:
        rows = probe_plan(cfg, width=args.width, iters=args.iters)
        if args.as_json:
            for row in rows:
                print(json.dumps(row))
        elif not rows:
            print("comm probe: no links (single-device mesh)")
        else:
            for row in rows:
                blk = (
                    f".p{row['sub_block']}"
                    if row["sub_block"] is not None
                    else ""
                )
                flag = " (rtt-dominated)" if row["rtt_dominated"] else ""
                print(
                    f"{row['axis_name']}.{row['direction']}{blk}: "
                    f"{row['t_s'] * 1e6:.1f}us for "
                    f"{row['bytes_predicted']}B predicted -> "
                    f"{row['gbps']:.3f} GB/s{flag}"
                )
        return 0
    finally:
        obs.deactivate(rc=0)


if __name__ == "__main__":
    sys.exit(main())
