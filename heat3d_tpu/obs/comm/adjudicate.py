"""``heat3d obs adjudicate`` — one command from captured rows to the
POD_RUNBOOK stage verdicts (jax-free).

The pod campaign's A/B stages (halo_order, monolithic-vs-partitioned
exchange plans, DMA slab widths / temporal-blocking depth) used to be
hand-assembled: scrape bench rows out of logs, eyeball pairs, write the
verdict into BASELINE.md. This module consumes the SAME captures the
campaign already produces — bench ``*.jsonl`` row files, run ledgers,
``obs merge`` outputs (``bench_row`` events are unwrapped; plain rows
pass through) — and emits every stage's verdict through the existing
:mod:`heat3d_tpu.tune.decide` pairing logic: rows pair only when every
context field (grid, mesh, dtype, platform, granularity-floor note, the
OTHER stage knobs) matches and exactly ONE stage knob differs, so rows
from different shapes or floors can never adjudicate each other.

Stage verdicts:

- ``pass`` — at least one single-knob pair, and no contradiction: the
  per-(context, value-pair) decisions never name two different decisive
  winners for the SAME comparison (duplicate measurements of one A/B
  disagreeing decisively is a measurement problem the campaign must
  resolve, not average away). Per-context winners are reported; a
  winner flipping ACROSS contexts (partitioned wins above the
  granularity floor, monolithic below it) is the expected physics, not
  a conflict.
- ``no-data`` — no rows carry the stage's knob, or rows exist but no
  pair differs in exactly that knob.
- ``fail`` — a same-context, same-value-pair decisive contradiction.

Exit code matches ``obs regress``: 1 only on a ``fail`` verdict —
``no-data`` and ``pass`` exit 0 (a stage you didn't run yet must not
break the campaign pipeline); 2 when an input is unreadable. The
verdict is also emitted as an ``adjudicate_verdict`` ledger event when
a ledger is active (docs/OBSERVABILITY.md §6).
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from heat3d_tpu.obs.metrics import percentile
from heat3d_tpu.tune.decide import DEFAULT_MIN_WIN_PCT, decide, format_decision

# envelope fields Ledger._write owns; stripped when unwrapping bench_row
# events back into bench rows (plus merge's src tag)
_ENVELOPE = ("ts", "run_id", "proc", "seq", "event", "kind", "src")

# every knob any stage adjudicates — each stage's context includes the
# OTHER stages' knobs, so a halo_plan pair can never straddle two
# halo_orders
_STAGE_KNOBS = ("halo_plan", "halo_order", "time_blocking", "fused_rdma")

# context fields that must match for two rows to be comparable (the
# union present in the eligible rows is used — files predating a field
# still pair among themselves)
_CONTEXT_KEYS = (
    "bench", "grid", "mesh", "dtype", "platform", "note", "backend",
    "halo", "overlap", "stencil", "width",
) + _STAGE_KNOBS


def _p50_us(row: Dict[str, Any]) -> Optional[float]:
    v = row.get("p50_us")
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        return float(v)
    return None


# POD_RUNBOOK stages (docs/POD_RUNBOOK.md §3). Halo stages judge the
# bench_halo p50 latency (lower wins); the slab-width stage judges the
# throughput rows' per-chip rate through decide()'s own METRIC_KEYS
# lookup (higher wins).
STAGES: Tuple[Dict[str, Any], ...] = (
    {
        "stage": "halo_plan",
        "knob": "halo_plan",
        "bench": "halo",
        "metric": _p50_us,
        "prefer": "lower",
        "title": "monolithic-vs-partitioned exchange plan (p50_us)",
    },
    {
        "stage": "halo_order",
        "knob": "halo_order",
        "bench": "halo",
        "metric": _p50_us,
        "prefer": "lower",
        "title": "axis-vs-pairwise halo ordering (p50_us)",
    },
    {
        "stage": "slab_width",
        "knob": "time_blocking",
        "bench": "throughput",
        "metric": None,  # decide()'s throughput METRIC_KEYS
        "prefer": "higher",
        "title": "slab width / temporal-blocking depth (Gcell/s/chip)",
    },
    {
        # stage 3-fused: the fused in-kernel RDMA superstep vs the
        # unfused exchange route — rows stamp the EFFECTIVE knob
        # (bench/harness), so an env-forced arm pairs correctly
        "stage": "fused_rdma",
        "knob": "fused_rdma",
        "bench": "throughput",
        "metric": None,  # decide()'s throughput METRIC_KEYS
        "prefer": "higher",
        "title": "fused in-kernel RDMA superstep vs unfused (Gcell/s/chip)",
    },
)


def load_rows(path: str) -> List[Dict[str, Any]]:
    """Bench rows from ``path`` — a plain ``*.jsonl`` row file, a run
    ledger, or an ``obs merge`` output. Ledger ``bench_row`` events are
    unwrapped (envelope stripped, the respelled ``ts_`` measurement
    timestamp restored to ``ts``); non-row lines are skipped, unreadable
    files raise ``OSError`` (rc 2 at the CLI)."""
    from heat3d_tpu.obs.cli import read_ledger

    rows: List[Dict[str, Any]] = []
    for e in read_ledger(path):
        if not isinstance(e, dict):
            continue
        if e.get("event") == "bench_row":
            row = {k: v for k, v in e.items() if k not in _ENVELOPE}
            if "ts_" in row:
                row["ts"] = row.pop("ts_")
            rows.append(row)
        elif "bench" in e and "event" not in e:
            rows.append(e)
    return rows


def _ctx_str(v: Any) -> str:
    if isinstance(v, (list, tuple)):
        return "x".join(str(x) for x in v)
    return "-" if v is None else str(v)


def _stage_verdict(
    st: Dict[str, Any],
    rows: List[Dict[str, Any]],
    min_win_pct: float,
) -> Dict[str, Any]:
    knob = st["knob"]
    metric = st["metric"]

    def _m(row):
        if metric is not None:
            return metric(row)
        from heat3d_tpu.tune.decide import _metric

        return _metric(row)

    eligible = [
        r
        for r in rows
        if r.get("bench") == st["bench"] and knob in r and _m(r) is not None
    ]
    out: Dict[str, Any] = {
        "stage": st["stage"],
        "title": st["title"],
        "rows": len(eligible),
        "pairs": 0,
        "decisions": [],
        "winners": [],
        "conflicts": [],
    }
    if not eligible:
        out["verdict"] = "no-data"
        out["reason"] = f"no {st['bench']} rows carrying {knob}"
        return out
    ctx_keys = sorted(
        {k for r in eligible for k in _CONTEXT_KEYS if k in r} - {knob}
    )
    entries = [
        (
            {knob: _ctx_str(r[knob]),
             **{k: _ctx_str(r.get(k)) for k in ctx_keys}},
            r,
        )
        for r in eligible
    ]
    decisions = [
        d
        for d in decide(
            entries, min_win_pct, metric=_m, prefer=st["prefer"]
        )
        if d["knob"] == knob
    ]
    out["pairs"] = len(decisions)
    out["decisions"] = decisions
    if not decisions:
        out["verdict"] = "no-data"
        out["reason"] = (
            f"{len(eligible)} row(s) but no pair differs in {knob} alone"
        )
        return out
    # contradiction check: the SAME comparison (same context, same two
    # knob values) naming two different decisive winners. Distinct
    # winners across different value-pairs (tb=2 beats tb=1, tb=3 beats
    # tb=4) or across different contexts are legitimate outcomes.
    by_cmp: Dict[Tuple, set] = defaultdict(set)
    for d in decisions:
        if not d["decisive"]:
            continue
        cmp_key = (
            tuple(sorted(d["context"].items())),
            frozenset(d["values"]),
        )
        by_cmp[cmp_key].add(d["winner"])
    conflicts = [
        {
            "context": dict(ctx),
            "values": sorted(vals),
            "winners": sorted(winners),
        }
        for (ctx, vals), winners in sorted(by_cmp.items())
        if len(winners) > 1
    ]
    out["conflicts"] = conflicts
    # per-context champion: best representative (p50 across duplicates)
    # value in that context — the row the runbook's flip/keep call reads
    per_ctx: Dict[Tuple, Dict[str, List[float]]] = defaultdict(
        lambda: defaultdict(list)
    )
    for knobs, r in entries:
        ctx = tuple(
            sorted((k, v) for k, v in knobs.items() if k != knob)
        )
        per_ctx[ctx][knobs[knob]].append(float(_m(r)))
    lower = st["prefer"] == "lower"
    for ctx, vals in sorted(per_ctx.items()):
        if len(vals) < 2:
            continue
        reps = {v: percentile(ms, 50) for v, ms in vals.items()}
        ranked = sorted(reps.items(), key=lambda kv: kv[1], reverse=not lower)
        (win_v, win_m), (run_v, run_m) = ranked[0], ranked[1]
        margin = (
            (run_m / win_m - 1.0) if lower else (win_m / run_m - 1.0)
        ) * 100.0
        out["winners"].append(
            {
                "context": dict(ctx),
                "winner": win_v,
                "speedup_pct": round(margin, 1),
                "decisive": margin >= min_win_pct,
                "values": {v: round(m, 2) for v, m in reps.items()},
            }
        )
    if conflicts:
        out["verdict"] = "fail"
        out["reason"] = (
            f"{len(conflicts)} same-context comparison(s) with "
            "contradictory decisive winners"
        )
    else:
        out["verdict"] = "pass"
        out["reason"] = (
            f"{len(decisions)} pair(s), "
            f"{sum(1 for d in decisions if d['decisive'])} decisive"
        )
    return out


def adjudicate_rows(
    rows: List[Dict[str, Any]],
    min_win_pct: float = DEFAULT_MIN_WIN_PCT,
) -> Dict[str, Any]:
    """Every stage's verdict over ``rows`` plus the overall verdict and
    the ``obs regress``-compatible exit code (1 only on ``fail``)."""
    stages = [_stage_verdict(st, rows, min_win_pct) for st in STAGES]
    if any(s["verdict"] == "fail" for s in stages):
        overall = "fail"
    elif any(s["verdict"] == "pass" for s in stages):
        overall = "pass"
    else:
        overall = "no-data"
    return {
        "verdict": overall,
        "rc": 1 if overall == "fail" else 0,
        "rows": len(rows),
        "min_win_pct": min_win_pct,
        "stages": stages,
    }


def _emit_verdict(report: Dict[str, Any], inputs: List[str]) -> None:
    from heat3d_tpu import obs

    obs.get().event(
        "adjudicate_verdict",
        verdict=report["verdict"],
        rc=report["rc"],
        rows=report["rows"],
        stages={s["stage"]: s["verdict"] for s in report["stages"]},
        inputs=[str(p) for p in inputs],
    )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="heat3d obs adjudicate",
        description="emit the POD_RUNBOOK A/B stage verdicts from bench "
        "row files / run ledgers / merged ledgers",
    )
    ap.add_argument("inputs", nargs="+",
                    help="bench *.jsonl row files, ledgers, or merges")
    ap.add_argument("--min-win", type=float, default=DEFAULT_MIN_WIN_PCT,
                    help="speedup %% below which a win is not decisive")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="machine verdict (one JSON object) on stdout")
    args = ap.parse_args(argv)

    rows: List[Dict[str, Any]] = []
    for path in args.inputs:
        try:
            rows.extend(load_rows(path))
        except OSError as e:
            print(f"adjudicate: cannot read {path}: {e}", file=sys.stderr)
            return 2
    report = adjudicate_rows(rows, args.min_win)
    _emit_verdict(report, args.inputs)
    if args.as_json:
        print(json.dumps(report))
        return report["rc"]
    print(
        f"adjudicate: {report['rows']} row(s) from "
        f"{len(args.inputs)} input(s)"
    )
    for s in report["stages"]:
        print(f"stage {s['stage']} ({s['title']}): "
              f"{s['verdict']} — {s['reason']}")
        for d in s["decisions"]:
            print(f"  {format_decision(d)}")
        for w in s["winners"]:
            ctx = " ".join(
                f"{k}={v}" for k, v in sorted(w["context"].items())
                if k not in ("bench",)
            )
            call = "decisive" if w["decisive"] else "below threshold"
            print(
                f"  winner[{ctx or 'no context'}]: "
                f"{s['stage']}={w['winner']} by {w['speedup_pct']}% "
                f"({call})"
            )
        for c in s["conflicts"]:
            print(
                f"  CONFLICT: values {c['values']} -> winners "
                f"{c['winners']} in {c['context']}"
            )
    print(f"verdict: {report['verdict']} (rc {report['rc']})")
    return report["rc"]


if __name__ == "__main__":
    sys.exit(main())
