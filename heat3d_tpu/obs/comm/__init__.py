"""The communication observatory (docs/OBSERVABILITY.md §9).

Makes every halo transfer individually attributable and turns merged pod
ledgers into machine-readable campaign verdicts:

- :mod:`~heat3d_tpu.obs.comm.probe` — the opt-in ``HEAT3D_COMM_PROBE``
  per-link probe: one micro-program per (axis, direction, sub-block)
  collective, timed with honest blocking semantics (force_sync + RTT
  subtraction), emitted as ``comm_probe`` ledger events carrying the
  ExchangePlan's own predicted bytes so every link reports
  predicted-vs-achieved GB/s. Imports jax — keep it out of this
  package's import path.
- :mod:`~heat3d_tpu.obs.comm.report` — pure (jax-free) aggregation of
  ``comm_probe`` events into the per-link table ``obs summary`` and
  ``obs watch`` render.
- :mod:`~heat3d_tpu.obs.comm.adjudicate` — ``heat3d obs adjudicate``:
  one command from merged ledgers / bench rows to the POD_RUNBOOK stage
  verdicts (halo_plan, halo_order, slab widths) through the
  ``tune/decide.py`` pairing logic; rc semantics match ``obs regress``
  (1 only on a ``fail`` verdict).

Like :mod:`heat3d_tpu.obs` itself, importing this package must stay
cheap and jax-free (the obs CLI dispatches through it on machines with
no accelerator stack warm) — submodules that need jax import it at
their own module level and are imported lazily by their consumers.
"""
