"""Named-scope tracing: attribute device time to *our* phases.

A jax.profiler trace of the solver shows raw XLA op names (fusion.123,
dynamic-update-slice.7) — useless for answering "how much of the step is
halo exchange vs stencil compute vs fused-DMA wait". These helpers bracket
the phase boundaries the roofline analysis cares about:

- :func:`named_phase` — ``jax.named_scope`` under the ``heat3d.`` prefix,
  used INSIDE traced code (parallel/step.py, parallel/halo.py): the scope
  name lands in every emitted op's metadata, so profiler tools (and
  ``scripts/summarize_trace.py``'s phase table) can group device time by
  phase instead of by op. Zero runtime cost — it only renames ops at trace
  time.
- :func:`annotate` — ``jax.profiler.TraceAnnotation`` for HOST-side
  runtime regions (warmup, checkpoint IO, heal waits): shows up on the
  host timeline of a captured trace.

Phase names used by the step builders (the contract the
``obs/perf/timeline.py`` phase tables and the profile→roofline join
group by — canonical list: ``parallel.step.PHASES``; keep in sync with
docs/OBSERVABILITY.md):

- ``heat3d.step`` (the whole step/superstep program — dispatch glue
  attributes here instead of ``(unattributed)``)
- ``heat3d.halo_exchange`` (and ``heat3d.halo.<axis>`` per axis)
- ``heat3d.stencil``
- ``heat3d.fused_dma``
- ``heat3d.residual``

Everything imports lazily and degrades to a no-op context when jax is
absent or too old, so the obs package stays importable anywhere.
"""

from __future__ import annotations

import contextlib

PHASE_PREFIX = "heat3d."


def named_phase(name: str):
    """``jax.named_scope('heat3d.<name>')`` — wrap traced (inside-jit)
    code so emitted ops carry the phase in their metadata."""
    if not name.startswith(PHASE_PREFIX):
        name = PHASE_PREFIX + name
    try:
        import jax

        return jax.named_scope(name)
    except (ImportError, AttributeError):
        return contextlib.nullcontext()


def annotate(name: str, **kwargs):
    """``jax.profiler.TraceAnnotation`` for host-side runtime regions —
    visible on the host timeline when a profiler trace is being captured;
    a cheap context either way."""
    if not name.startswith(PHASE_PREFIX):
        name = PHASE_PREFIX + name
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(name, **kwargs)
    except (ImportError, AttributeError):
        return contextlib.nullcontext()


def scoped(name: str, fn):
    """``fn`` wrapped in :func:`named_phase` — for decorating a built step
    callable without restructuring it."""

    def wrapper(*args, **kwargs):
        with named_phase(name):
            return fn(*args, **kwargs)

    return wrapper
