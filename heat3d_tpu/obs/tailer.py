"""Incremental ledger tailing: the input layer for live observability.

``obs tail --follow``, ``obs check --follow``, ``obs watch``, and the soak
monitor all need to read a ledger *while it is being written* without
re-reading the whole file per tick. :class:`LedgerTailer` keeps a byte
offset into the active file plus a count of fully-consumed rolled segments
(``HEAT3D_LEDGER_MAX_MB`` rotation renames the base aside, preserving byte
offsets), so each :meth:`poll` returns exactly the lines appended since the
last one — across rotations, with no duplicates and no loss.

Partial lines (a poll racing the writer mid-line) are buffered and
completed on the next poll. All IO errors fail soft: a poll that cannot
read returns what it has and tries again next tick — a live viewer must
never crash the run it watches (nor itself) over a transient read.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

from heat3d_tpu.obs.ledger import ledger_segments


class LedgerTailer:
    """Stateful incremental reader over one (possibly rotating) ledger."""

    def __init__(self, path: str):
        self.path = path
        self._consumed_rolled = 0  # rolled segments fully consumed
        self._offset = 0  # byte offset into the current file
        self._buf = ""  # partial trailing line awaiting its remainder

    # ---- raw line layer --------------------------------------------------

    def _read_from(self, path: str, offset: int) -> Tuple[Optional[str], int]:
        try:
            with open(path) as f:
                f.seek(offset)
                data = f.read()
                return data, f.tell()
        except OSError:
            return None, offset

    def _split(self, data: str) -> List[str]:
        data = self._buf + data
        lines = data.split("\n")
        self._buf = lines.pop()  # "" when data ended on a newline
        return [ln for ln in (s.strip() for s in lines) if ln]

    def poll_lines(self) -> List[str]:
        """Complete raw lines appended since the last poll (oldest first)."""
        out: List[str] = []
        rolled = ledger_segments(self.path)[:-1]
        # drain segments that rolled since the last poll: the rename kept
        # their bytes, so the saved base offset points into the first one
        while self._consumed_rolled < len(rolled):
            data, _ = self._read_from(
                rolled[self._consumed_rolled], self._offset
            )
            if data is None:
                return out  # transient; retry next poll
            out.extend(self._split(data))
            self._consumed_rolled += 1
            self._offset = 0
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return out
        if size < self._offset:  # base replaced/truncated out-of-band
            self._offset = 0
            self._buf = ""
        if size > self._offset:
            data, end = self._read_from(self.path, self._offset)
            if data is None:
                return out
            # a rotation racing this read means `data` may belong to either
            # the old or the new base: discard it (the bytes survive in the
            # rolled segment, which the next poll drains from our offset)
            if len(ledger_segments(self.path)) - 1 != self._consumed_rolled:
                return out
            out.extend(self._split(data))
            self._offset = end
        return out

    # ---- parsed layer ----------------------------------------------------

    def poll(self) -> List[Dict[str, Any]]:
        """Parsed events appended since the last poll; unparseable lines
        are skipped (use :meth:`poll_lines` to see them)."""
        out: List[Dict[str, Any]] = []
        for line in self.poll_lines():
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out
