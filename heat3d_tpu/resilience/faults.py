"""Deterministic fault injection — make every failure path run on CPU.

The resilience machinery (supervised runs, checkpoint quarantine, sweep
resume) exists because of backend outages that cannot be reproduced on
demand. This module makes the failure paths *testable*: a fault plan,
declared in the ``HEAT3D_FAULTS`` env var (or built directly in tests),
fires precisely-placed faults at the supervisor/sweep instrumentation
points so pytest can drive loss/hang/kill/corruption scenarios on CPU.

Spec grammar (comma-separated faults; colon-separated ``key=value`` params)::

    HEAT3D_FAULTS="backend-loss:step=8:down=2,sigterm:row=3"

Fault kinds:

- ``backend-loss:step=N[:down=K]`` — the first time the supervised run
  reaches global step >= N, raise :class:`InjectedBackendLoss`; the next
  K heal-probes (default 1) report the backend down, then healthy.
- ``partial-device-loss:step=N:keep=K`` (or ``batch=N`` / ``after=S``
  for the serving tier) — raise :class:`InjectedBackendLoss` at global
  step >= N (or before the Nth packed serve batch, 0-based, or before
  the first serve batch starting >= S seconds after the plan was built
  — the soak's mid-run chaos trigger), and make every
  device-count probe afterwards report only K surviving devices
  (:meth:`FaultPlan.device_override`) — the elastic-degradation
  injection primitive (docs/RESILIENCE.md "Elastic degradation").
  ``down=D`` makes the first D heal-probes report fully down first
  (default 0: the survivors answer immediately — a partial loss is not
  an outage); ``restore=R`` restores full capacity after R shrunken
  device probes (default 0 = the loss persists), the re-expand tests'
  knob.
- ``hang:step=N`` — at global step >= N, sleep just past the supervisor's
  watchdog budget, then raise :class:`InjectedHang` — the
  hang-until-deadline scenario (a wedged tunnel that never errors).
- ``sigterm:step=N`` / ``sigterm:row=K`` — send SIGTERM to this process
  when the supervised run reaches step N / before sweep row K is
  measured. With the entry points' SIGTERM->SystemExit conversion this
  reproduces a measurement script's ``timeout`` killing a run mid-flight.
- ``corrupt-shard:save=N`` — after the Nth checkpoint-generation save
  (1-based), flip bytes in one shard file of that generation (leaving its
  checksum sidecar stale) — the corrupted-checkpoint scenario.

One-shot semantics survive process death: when ``HEAT3D_FAULT_STATE``
names a directory, a fired fault leaves a marker file there and never
fires again — so a SIGTERM'd run, restarted with the same env, resumes
instead of being killed at the same row forever. Without the state dir,
fired-ness is tracked in-process only.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

ENV_SPEC = "HEAT3D_FAULTS"
ENV_STATE = "HEAT3D_FAULT_STATE"


class InjectedFault(Exception):
    """Base for injected faults (never raised by real failures)."""


class InjectedBackendLoss(InjectedFault):
    """Simulated backend death (the mid-run tunnel loss)."""


class InjectedHang(InjectedFault):
    """Simulated hang: raised only after sleeping past the watchdog."""


class _Fault:
    def __init__(self, kind: str, params: Dict[str, int], key: str):
        self.kind = kind
        self.params = params
        self.key = key  # stable id for the fired-marker file


def _parse_spec(spec: str) -> List[_Fault]:
    faults = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        pieces = part.split(":")
        kind, params = pieces[0], {}
        for kv in pieces[1:]:
            k, _, v = kv.partition("=")
            try:
                params[k] = int(v)
            except ValueError:
                raise ValueError(
                    f"{ENV_SPEC}: bad param {kv!r} in fault {part!r} "
                    "(values must be ints)"
                ) from None
        known = {
            "backend-loss": {"step", "down"},
            "partial-device-loss": {"step", "batch", "after", "keep",
                                    "down", "restore"},
            "hang": {"step"},
            "sigterm": {"step", "row"},
            "corrupt-shard": {"save"},
        }
        if kind not in known:
            raise ValueError(
                f"{ENV_SPEC}: unknown fault kind {kind!r} "
                f"(want one of {sorted(known)})"
            )
        bad = set(params) - known[kind]
        if bad:
            raise ValueError(
                f"{ENV_SPEC}: fault {kind!r} got unknown params {sorted(bad)}"
            )
        if kind == "partial-device-loss":
            # explicit validation at PARSE time: a partial loss without a
            # survivor count (or with both/neither trigger points) would
            # only fail deep inside a recovery, where the diagnosis is
            # worst
            if params.get("keep", 0) < 1:
                raise ValueError(
                    f"{ENV_SPEC}: partial-device-loss needs keep=K >= 1 "
                    "(the surviving device count)"
                )
            triggers = sum(k in params for k in ("step", "batch", "after"))
            if triggers != 1:
                raise ValueError(
                    f"{ENV_SPEC}: partial-device-loss needs exactly one "
                    "of step=N (supervised runs), batch=N, or "
                    "after=SECONDS (serve tier)"
                )
        faults.append(_Fault(kind, params, key=part.replace(":", "_")))
    return faults


class FaultPlan:
    """A parsed fault plan plus its firing state.

    All hooks are no-ops on an empty plan, so production paths pay one
    attribute check when no faults are declared.
    """

    def __init__(self, faults: Optional[List[_Fault]] = None,
                 state_dir: Optional[str] = None):
        self.faults = faults or []
        self.state_dir = state_dir
        # plan birth time: the after=SECONDS serve trigger's clock (the
        # engine builds its plan at construction, so "after" means
        # seconds into the serving session)
        self._t0 = time.monotonic()
        self._fired: set = set()
        self._down_probes_left = 0
        self._saves_seen = 0
        # partial-device-loss state: the survivor count device probes
        # report while the loss persists, and how many shrunken probes
        # remain before full capacity "returns" (0 = persists forever)
        self._device_keep: Optional[int] = None
        self._device_restore = 0

    @classmethod
    def from_env(cls, environ=None) -> "FaultPlan":
        env = os.environ if environ is None else environ
        spec = env.get(ENV_SPEC, "")
        state = env.get(ENV_STATE) or None
        if state:
            os.makedirs(state, exist_ok=True)
        return cls(_parse_spec(spec) if spec else [], state_dir=state)

    def __bool__(self) -> bool:
        return bool(self.faults)

    # ---- fired-marker bookkeeping ---------------------------------------

    def _has_fired(self, fault: _Fault) -> bool:
        if fault.key in self._fired:
            return True
        if self.state_dir:
            return os.path.exists(
                os.path.join(self.state_dir, fault.key + ".fired")
            )
        return False

    def _mark_fired(self, fault: _Fault, **context) -> None:
        self._fired.add(fault.key)
        if self.state_dir:
            marker = os.path.join(self.state_dir, fault.key + ".fired")
            with open(marker, "w") as f:
                f.write(str(time.time()))
        # The injection itself must be observable: fault-injection tests
        # assert the ledger records every fired fault (and the sigterm
        # fault kills the process right after — the flush-per-event ledger
        # still lands this line first). `kind_`, not `kind`: the envelope
        # owns the `kind` key (point|span).
        from heat3d_tpu import obs

        obs.get().event(
            "fault_injected",
            kind_=fault.kind,
            key=fault.key,
            params=fault.params,
            **context,
        )
        obs.REGISTRY.counter(
            "faults_injected_total", "injected faults fired"
        ).inc(kind=fault.kind)

    # ---- instrumentation points -----------------------------------------

    def on_step(self, global_step: int, watchdog_s: Optional[float] = None):
        """Called by the supervised loop before launching each chunk."""
        for f in self.faults:
            if self._has_fired(f):
                continue
            if f.kind == "backend-loss" and global_step >= f.params["step"]:
                self._mark_fired(f, step=global_step)
                self._down_probes_left = f.params.get("down", 1)
                raise InjectedBackendLoss(
                    f"injected backend loss at step {global_step}"
                )
            if (
                f.kind == "partial-device-loss"
                and "step" in f.params
                and global_step >= f.params["step"]
            ):
                self._mark_fired(f, step=global_step)
                self._arm_partial(f)
                raise InjectedBackendLoss(
                    f"injected partial device loss at step {global_step} "
                    f"({f.params['keep']} device(s) survive)"
                )
            if f.kind == "hang" and global_step >= f.params["step"]:
                self._mark_fired(f, step=global_step)
                # sleep PAST the watchdog budget: the supervisor must
                # classify the overrun itself, like a real wedged chunk
                time.sleep((watchdog_s or 0.0) + 0.05)
                self._down_probes_left = 1
                raise InjectedHang(
                    f"injected hang at step {global_step} "
                    f"(watchdog {watchdog_s}s exceeded)"
                )
            if (
                f.kind == "sigterm"
                and "step" in f.params
                and global_step >= f.params["step"]
            ):
                self._mark_fired(f, step=global_step)
                self._sigterm_self()

    def on_sweep_row(self, row_index: int):
        """Called by sweep runners before measuring row ``row_index``."""
        for f in self.faults:
            if (
                f.kind == "sigterm"
                and "row" in f.params
                and row_index >= f.params["row"]
                and not self._has_fired(f)
            ):
                self._mark_fired(f, row=row_index)
                self._sigterm_self()

    def _arm_partial(self, f: _Fault) -> None:
        # down=0 by default: a PARTIAL loss is not an outage — the
        # surviving devices answer the very first heal probe, and only
        # the device-count probe reports the shrunken set
        self._down_probes_left = f.params.get("down", 0)
        self._device_keep = f.params["keep"]
        self._device_restore = f.params.get("restore", 0)

    def on_serve_batch(self, batch_index: int):
        """Called by the async serve engine before executing packed batch
        ``batch_index`` (0-based count of batches started) — the serving
        tier's partial-device-loss instrumentation point. Fires on the
        batch-count trigger (``batch=N``) or the elapsed-time trigger
        (``after=S`` seconds since the plan was built — the soak's
        mid-run chaos injection)."""
        for f in self.faults:
            if f.kind != "partial-device-loss" or self._has_fired(f):
                continue
            hit_batch = (
                "batch" in f.params and batch_index >= f.params["batch"]
            )
            elapsed = time.monotonic() - self._t0
            hit_after = (
                "after" in f.params and elapsed >= f.params["after"]
            )
            if hit_batch or hit_after:
                self._mark_fired(
                    f, batch=batch_index, elapsed_s=round(elapsed, 3)
                )
                self._arm_partial(f)
                raise InjectedBackendLoss(
                    f"injected partial device loss at serve batch "
                    f"{batch_index} ({f.params['keep']} device(s) survive)"
                )

    def on_checkpoint_saved(self, gen_dir: str):
        """Called after each checkpoint generation lands on disk."""
        self._saves_seen += 1
        for f in self.faults:
            if (
                f.kind == "corrupt-shard"
                and self._saves_seen >= f.params.get("save", 1)
                and not self._has_fired(f)
            ):
                self._mark_fired(f, save=self._saves_seen, gen=gen_dir)
                corrupt_one_shard(gen_dir)

    def probe_override(self) -> Optional[str]:
        """Heal-probe hook: ``"down"`` while an injected outage persists
        (each call consumes one down-probe), None = no override (use the
        real probe)."""
        if self._down_probes_left > 0:
            self._down_probes_left -= 1
            return "down"
        return None

    def device_override(self) -> Optional[int]:
        """Survivor-count probe hook: the shrunken device count while an
        injected partial loss persists, None = no override (use the real
        ``backendprobe.probe_device_count``). With ``restore=R`` the
        override decays after R probes — full capacity "returns", the
        re-expand path's trigger."""
        if self._device_keep is None:
            return None
        keep = self._device_keep
        if self._device_restore > 0:
            self._device_restore -= 1
            if self._device_restore == 0:
                self._device_keep = None
        return keep

    @staticmethod
    def _sigterm_self():
        import signal

        os.kill(os.getpid(), signal.SIGTERM)
        # the handler fires between bytecodes; make sure it gets one
        time.sleep(5)
        raise RuntimeError("injected SIGTERM did not terminate the process")


def corrupt_one_shard(ckpt_dir: str) -> str:
    """Flip bytes in the middle of the first shard file of ``ckpt_dir``
    WITHOUT touching its checksum sidecar — the on-disk bit-rot the
    checksum verification exists to catch. Returns the corrupted path."""
    shards = sorted(
        f for f in os.listdir(ckpt_dir)
        if f.startswith("shard_") and f.endswith(".npy")
    )
    if not shards:
        raise FileNotFoundError(f"no shard files to corrupt in {ckpt_dir}")
    target = os.path.join(ckpt_dir, shards[0])
    size = os.path.getsize(target)
    # flip data bytes past the ~128-byte .npy header so np.load still
    # parses the file and only the checksum can catch the damage; the
    # clamp must stay INSIDE the file — writing at/past EOF would append
    # bytes np.load never reads and leave the fault invisible (a
    # vacuously passing corruption test)
    offset = max(min(max(size // 2, 128), size - 8), 0)
    with open(target, "r+b") as f:
        f.seek(offset)
        chunk = f.read(8)
        if not chunk:
            raise ValueError(f"shard {target} too small to corrupt ({size}B)")
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in chunk))
    return target
