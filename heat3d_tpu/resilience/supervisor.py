"""The supervised run loop: checkpoint-gated, watchdogged, self-resuming.

``utils/checkpoint.py`` has long supported sharded save/restore and
cross-mesh stitch-resume — but nothing *drove* it automatically: a run
killed at step N was a dead run, and a backend outage mid-run lost
everything since step 0. This module is the driver:

- **Checkpoint every K steps** into *generations* —
  ``<root>/gen-<step>/`` directories, each a complete checksummed
  checkpoint. The newest ``keep_generations`` are retained; the rest
  pruned after each successful save.
- **Detect backend death** (exceptions out of the compiled step, injected
  faults) and **suspect hangs** (a chunk overrunning the watchdog budget)
  — then confirm with the bounded out-of-process probes
  (``utils/backendprobe``; a killable child, never an in-process
  ``jax.devices()`` that can wedge forever).
- **Wait for the backend to heal** through the one
  :class:`~heat3d_tpu.resilience.retry.RetryPolicy` implementation, then
  **rebuild the solver and resume from the last good generation**. A
  corrupt generation (checksum mismatch, torn manifest) is quarantined
  and the previous generation is loaded instead. Because
  ``checkpoint.load`` stitches across meshes, the rebuilt solver may
  legitimately land on different hardware (TPU -> CPU cross-mesh
  stitch-resume) — the resume path is the same either way.

Hang honesty: an in-process supervisor can only *classify* a chunk that
eventually returns (or a fault that raises). A chunk truly stuck inside a
non-returning C call never comes back to Python — that tier of protection
stays with the process-level guards (coreutils ``timeout`` + the SIGTERM
-> SystemExit claim-release installed by every entry point), and a
SIGTERM'd supervised run resumes from its last generation on relaunch.

Scope: single-controller supervision. On multi-host launches the
quarantine rename and generation prune would race across processes, and
a process that merely cannot SEE its peers' shards must not condemn a
generation — coordinate supervision from the launcher (one supervisor,
per-host workers) before lifting this.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, List, Optional, Tuple

from heat3d_tpu import obs
from heat3d_tpu.resilience.faults import (
    FaultPlan,
    InjectedBackendLoss,
    InjectedFault,
    InjectedHang,
)
from heat3d_tpu.resilience.retry import RetryPolicy
from heat3d_tpu.utils import checkpoint as ckpt
from heat3d_tpu.utils.logging import get_logger

log = get_logger("heat3d.supervisor")

GEN_PREFIX = "gen-"

# Heal-wait default: the supervisor resolves its policy through
# elastic.default_heal_policy() — probe every 60 s, 1.5x backoff capped at
# 5 min (every probe is a claim attempt, see backendprobe), with the total
# deadline owned by the HEAT3D_HEAL_DEADLINE_S knob (default 30 min, like
# TPU_WAIT; in `auto` heal mode its expiry is what triggers the elastic
# fallback — docs/RESILIENCE.md "Elastic degradation"). The one schedule
# definition lives in resilience/elastic.py.


class BackendSuspect(RuntimeError):
    """A chunk overran the watchdog and the follow-up probe found the
    backend unreachable."""


@dataclasses.dataclass
class Recovery:
    """One survived failure, as a structured record for the run summary."""

    step: int
    kind: str  # 'backend-loss' | 'hang' | 'error'
    error: str
    heal_wait_s: float
    heal_attempts: int
    resumed_from: Optional[int]
    quarantined: List[str] = dataclasses.field(default_factory=list)
    # elastic recoveries re-factorized the mesh over survivors instead of
    # waiting the backend whole again (resilience/elastic.py); the mesh
    # the run continued on is part of the record so degraded progress can
    # never masquerade as full-capacity progress downstream
    elastic: bool = False
    mesh_shape: Optional[List[int]] = None
    restitch_s: Optional[float] = None

    def to_record(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SupervisedResult:
    u: object
    steps_done: int
    start_step: int
    resumed_from: Optional[int]
    residual: Optional[float]
    checkpoints_written: int
    recoveries: List[Recovery]
    # the solver that produced u — NOT necessarily the one passed in: a
    # recovery rebuilds it (possibly on different hardware/mesh), and any
    # post-run operation on u (gather, slice dump, golden check) must use
    # this one, not the caller's stale instance
    solver: object = None
    # elastic-degradation provenance (resilience/elastic.py): whether the
    # run FINISHED degraded, the mesh it finished on, and how many
    # re-factorizations (degrade + expand) happened — run summaries carry
    # these so degraded throughput is labeled at the source
    degraded: bool = False
    mesh_shape: Optional[tuple] = None
    refactors: int = 0

    def to_record(self) -> dict:
        return {
            "steps_done": self.steps_done,
            "start_step": self.start_step,
            "resumed_from": self.resumed_from,
            "checkpoints_written": self.checkpoints_written,
            "recoveries": [r.to_record() for r in self.recoveries],
            "degraded": self.degraded,
            "mesh_shape": (
                None if self.mesh_shape is None else list(self.mesh_shape)
            ),
            "refactors": self.refactors,
        }


# ---- generation bookkeeping ---------------------------------------------


def generation_dirs(root: str) -> List[Tuple[int, str]]:
    """(step, path) for every generation under ``root``, oldest first.
    Quarantined directories are invisible by construction (their names no
    longer parse)."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        if not name.startswith(GEN_PREFIX):
            continue
        try:
            step = int(name[len(GEN_PREFIX):])
        except ValueError:
            continue
        out.append((step, os.path.join(root, name)))
    out.sort()
    return out


def save_generation(solver, u, step: int, root: str, keep: int = 2) -> str:
    """Write ``<root>/gen-<step>`` and prune to the newest ``keep``.

    The prune happens only AFTER the new generation's manifest landed, so
    a crash mid-save can orphan at most one partial directory — which the
    load path then quarantines (no manifest) and skips."""
    gen = os.path.join(root, f"{GEN_PREFIX}{step:08d}")
    solver.save_checkpoint(gen, u, step)
    # the generation TRANSITION is the supervisor-level fact (the save
    # itself is the nested ckpt_save span): tag every later event with the
    # new generation so a heal/resume session reads end to end
    obs.get().event("generation_save", step=step, path=gen)
    obs.get().set_context(generation=step)
    obs.REGISTRY.counter(
        "generation_transitions_total", "supervisor generation saves"
    ).inc()
    gens = generation_dirs(root)
    for _, old in gens[:-keep] if keep > 0 else []:
        if os.path.realpath(old) == os.path.realpath(gen):
            continue
        import shutil

        shutil.rmtree(old, ignore_errors=True)
    return gen


def load_latest_generation(solver, root: str):
    """Restore from the newest loadable generation.

    Walks generations newest-first; a generation that fails to load —
    checksum mismatch (:class:`~heat3d_tpu.utils.checkpoint.ShardCorruptError`),
    torn manifest, missing shards — is QUARANTINED (renamed out of the
    scan) and the previous one is tried. Returns
    ``((u, step) | None, quarantined_paths)`` — the quarantine list is
    returned even when NOTHING loads, so an every-generation-corrupt
    recovery still gets a truthful post-mortem record.
    """
    quarantined: List[str] = []
    for step, gen in reversed(generation_dirs(root)):
        # Quarantine only on PROVEN damage: a checksum mismatch, or a
        # missing/torn manifest (a save that died mid-write). Any other
        # load failure — shard files not visible from this process, a
        # stale different-grid file, a config mismatch — may be the
        # ENVIRONMENT's or the CONFIG's fault, and renaming the
        # generation would destroy a resume some other context could
        # still perform; those are skipped in place.
        try:
            ckpt.load_manifest(gen)
        except FileNotFoundError as e:  # save died before its manifest
            log.warning("generation %s has no manifest (%s); quarantining",
                        gen, e)
            quarantined.append(ckpt.quarantine(gen, reason=str(e)))
            continue
        except ValueError as e:  # torn/truncated JSON: proven damage
            log.warning("generation %s manifest is torn (%s); quarantining",
                        gen, e)
            quarantined.append(ckpt.quarantine(gen, reason=str(e)))
            continue
        except OSError as e:
            # EIO/ESTALE/EACCES on a flaky FS is the ENVIRONMENT's fault,
            # not proven damage — skip in place, never rename away a
            # generation that may read fine next attempt
            log.warning(
                "generation %s manifest unreadable here (%s); skipping "
                "WITHOUT quarantine", gen, e,
            )
            continue
        try:
            u, got_step = solver.load_checkpoint(gen)
            return (u, got_step), quarantined
        except ckpt.ShardCorruptError as e:
            log.warning("generation %s corrupt (%s); quarantining", gen, e)
            quarantined.append(ckpt.quarantine(gen, reason=str(e)))
        except (OSError, ValueError, KeyError) as e:
            log.warning(
                "generation %s unloadable here (%s: %s); skipping WITHOUT "
                "quarantine — not proven corrupt (check shard visibility "
                "and that --grid/--mesh match the checkpoint)",
                gen, type(e).__name__, e,
            )
    return None, quarantined


# ---- the supervised loop -------------------------------------------------


def _default_probe(want: Optional[str]) -> Optional[str]:
    from heat3d_tpu.utils.backendprobe import probe_platform

    p = probe_platform()
    if p is None or (want is not None and p != want):
        return None
    return p


def _wait_for_heal(
    policy: RetryPolicy,
    plan: FaultPlan,
    want: Optional[str],
    probe: Optional[Callable[[], Optional[str]]],
):
    """Probe (fault-overridable) under the retry policy until the backend
    answers. Returns the RetryOutcome; ``outcome.ok`` False = never healed."""

    def attempt():
        override = plan.probe_override()
        if override == "down":
            return None
        if probe is not None:
            return probe()
        return _default_probe(want)

    return policy.run(attempt)


def run_supervised(
    solver,
    total_steps: int,
    ckpt_root: str,
    checkpoint_every: int = 0,
    *,
    make_solver: Optional[Callable[[], object]] = None,
    heal_policy: Optional[RetryPolicy] = None,
    watchdog_s: Optional[float] = None,
    max_recoveries: int = 3,
    keep_generations: int = 2,
    want_platform: Optional[str] = None,
    probe: Optional[Callable[[], Optional[str]]] = None,
    faults: Optional[FaultPlan] = None,
    init: str = "hot-cube",
    finish_with_residual: bool = True,
    heal_mode: Optional[str] = None,
    make_solver_for: Optional[Callable[[object], object]] = None,
    base_cfg=None,
    device_probe: Optional[Callable[[], Optional[int]]] = None,
    reexpand: bool = False,
) -> SupervisedResult:
    """Run ``solver`` to global step ``total_steps`` under supervision.

    ``total_steps`` is the TARGET GLOBAL STEP, not a relative count: a
    fresh run advances 0 -> total, a resumed run advances from its newest
    generation's step — so re-launching the same command after a kill
    finishes the run instead of running past it (the property the
    interrupted-equals-uninterrupted tests assert, bit-for-bit on the
    same mesh).

    ``make_solver`` rebuilds the solver after a backend loss (default:
    reuse ``solver`` — correct when the process and its backend survived,
    as with injected faults; a real cross-backend recovery passes a
    factory that re-resolves devices). ``probe`` overrides the heal probe
    (tests); ``faults`` overrides the env-parsed
    :class:`~heat3d_tpu.resilience.faults.FaultPlan`.

    **Elastic degradation** (``heal_mode='elastic'|'auto'``;
    resilience/elastic.py, docs/RESILIENCE.md): on a confirmed loss the
    supervisor re-probes the device set (``device_probe`` override >
    fault-plan override > bounded out-of-process probe) and, when
    devices are missing, re-factorizes the mesh over the survivors —
    ``make_solver_for(new_cfg)`` rebuilds the solver for the certified
    degraded config derived from ``base_cfg`` (default: ``solver.cfg``),
    the ``gen-<step>`` shards re-stitch onto the new mesh through the
    existing cross-mesh path, and the run continues degraded
    (``elastic_refactor`` + ``degraded_mode_enter`` ledger events). In
    ``auto`` mode the heal DEADLINE is the trigger: wait first, degrade
    only when the deadline expires or the healed backend comes back
    smaller. ``reexpand=True`` opts into re-factorizing back to the
    original mesh when a later probe reports full capacity
    (``degraded_mode_exit``).
    """
    from heat3d_tpu.resilience import elastic

    from heat3d_tpu.utils.timing import force_sync

    plan = faults if faults is not None else FaultPlan.from_env()
    policy = heal_policy or elastic.default_heal_policy()
    mode = elastic.resolve_heal_mode(heal_mode)
    if base_cfg is None:
        base_cfg = getattr(solver, "cfg", None)
    if make_solver_for is None and mode != "wait":
        # elastic needs a config-parameterized factory; without one the
        # mode silently behaving like `wait` would be a lie — refuse
        raise ValueError(
            f"heal_mode={mode!r} needs make_solver_for (a cfg -> solver "
            "factory; HeatSolver3D.run_supervised provides one)"
        )
    if base_cfg is None and mode != "wait":
        raise ValueError(
            f"heal_mode={mode!r} needs base_cfg (or a solver with a .cfg)"
        )
    cur_cfg = base_cfg
    degraded = False
    degraded_t0 = 0.0
    refactors = 0
    recoveries: List[Recovery] = []
    checkpoints = 0
    resumed_from = None
    ledger = obs.get()
    step_hist = obs.REGISTRY.histogram(
        "step_latency_seconds", "per-step wall latency (chunk dur / steps)"
    )

    os.makedirs(ckpt_root, exist_ok=True)
    loaded, quarantined = load_latest_generation(solver, ckpt_root)
    if quarantined:
        log.warning(
            "resume quarantined %d generation(s): %s",
            len(quarantined), quarantined,
        )
    if loaded is not None:
        u, done = loaded
        resumed_from = done
        log.info("supervised run resuming at step %d from %s", done, ckpt_root)
    else:
        if generation_dirs(ckpt_root):
            # generations survive on disk but none loaded HERE (skipped
            # without quarantine: FS blip, config mismatch): restarting
            # at step 0 would silently orphan real progress — refuse, the
            # same rule the CLI applies to flat checkpoints
            raise ValueError(
                f"{ckpt_root} holds generations but none is loadable from "
                "this process/config (see warnings above) — fix the "
                "mismatch or point the run at a fresh directory; refusing "
                "to restart at step 0 over existing progress"
            )
        u, done = solver.init_state(init), 0
    start_step = done
    if done > total_steps:
        raise ValueError(
            f"checkpoint at step {done} is past the target {total_steps} — "
            "refusing to run backwards (raise --steps or point --checkpoint "
            "at a fresh directory)"
        )
    ledger.set_context(generation=resumed_from)
    ledger.event(
        "supervised_start",
        total_steps=total_steps,
        start_step=start_step,
        resumed_from=resumed_from,
        checkpoint_every=checkpoint_every,
        ckpt_root=ckpt_root,
        quarantined=quarantined,
    )

    residual = None
    while done < total_steps:
        # next boundary: a checkpoint point or the end
        if checkpoint_every > 0:
            nxt = min(
                (done // checkpoint_every + 1) * checkpoint_every, total_steps
            )
        else:
            nxt = total_steps
        n = nxt - done
        try:
            # the chunk span covers fault hooks + the compiled steps + the
            # sync, so an injected loss lands INSIDE it (status=error) and
            # a healed session's timeline shows exactly which step window
            # died; per-step latency (dur/n) feeds the same histogram the
            # obs CLI reconstructs post-hoc from these spans
            with ledger.span(
                "chunk", step_start=done, step_end=nxt, steps=n
            ) as chunk_span:
                plan.on_step(done, watchdog_s=watchdog_s)
                t0 = time.monotonic()
                if nxt == total_steps and finish_with_residual:
                    if n > 1:
                        u = solver.run(u, n - 1)
                    u, r2 = solver.step_with_residual(u)
                    import numpy as np

                    residual = float(np.sqrt(np.float64(r2)))
                else:
                    u = solver.run(u, n)
                force_sync(u)
                chunk_s = time.monotonic() - t0
                chunk_span.add(steps_s=chunk_s)
            step_hist.observe(chunk_s / n)
            if watchdog_s is not None and chunk_s > watchdog_s:
                # the chunk RETURNED but blew its budget: a wedging tunnel
                # slow-walks before it stops answering. Probe before
                # trusting the result.
                log.warning(
                    "chunk %d->%d took %.1fs (watchdog %.1fs); probing",
                    done, nxt, chunk_s, watchdog_s,
                )
                if (probe() if probe is not None
                        else _default_probe(want_platform)) is None:
                    raise BackendSuspect(
                        f"chunk overran watchdog ({chunk_s:.1f}s > "
                        f"{watchdog_s:.1f}s) and the backend probe failed"
                    )
            # the save sits INSIDE the recovery envelope: checkpoint.save
            # reads shard data off the device, and a backend dying exactly
            # at a chunk boundary (a developing outage's likeliest moment)
            # must trigger heal-and-resume like any other loss, not escape
            # the supervisor uncaught
            gen = save_generation(
                solver, u, nxt, ckpt_root, keep=keep_generations
            )
            checkpoints += 1
            plan.on_checkpoint_saved(gen)
        except (InjectedBackendLoss, InjectedHang, BackendSuspect,
                RuntimeError) as e:
            if isinstance(e, InjectedFault):
                kind = "hang" if isinstance(e, InjectedHang) else "backend-loss"
            elif isinstance(e, BackendSuspect):
                kind = "hang"
            else:
                # a real RuntimeError: only treat as an outage if the
                # bounded probe agrees the backend is gone — a genuine
                # bug must not be silently retried into oblivion
                kind = "error"
                if (probe() if probe is not None
                        else _default_probe(want_platform)) is not None:
                    raise
            if len(recoveries) >= max_recoveries:
                log.error(
                    "supervised run: %d recoveries exhausted; re-raising",
                    max_recoveries,
                )
                raise
            failed_step = done  # before the reload rewinds it
            log.warning(
                "supervised run lost the backend at step %d (%s: %s); "
                "waiting for heal", failed_step, kind, e,
            )
            # Elastic triage (resilience/elastic.py) — the three modes
            # genuinely differ here:
            #   wait    — wait for the ORIGINAL platform to heal; the
            #             deadline re-raises (PR 1 behavior).
            #   elastic — a loss is a RE-PLAN event: the wait's success
            #             criterion is ANY SURVIVORS ANSWERING (the
            #             device-set probe), so the run re-plans the
            #             moment the surviving chips respond instead of
            #             waiting out the platform-heal deadline.
            #   auto    — wait-first: the full platform heal wait runs,
            #             and the DEADLINE (or a backend that healed
            #             smaller) is what triggers the elastic fallback.
            with ledger.span(
                "heal_wait", step=failed_step, failure=kind, mode=mode
            ) as heal_span:
                if mode == "elastic":
                    outcome = policy.run(
                        lambda: elastic.probe_survivors(plan, device_probe)
                    )
                else:
                    outcome = _wait_for_heal(
                        policy, plan, want_platform, probe
                    )
                heal_span.add(
                    ok=outcome.ok,
                    attempts=len(outcome.attempts),
                    stop_reason=outcome.stop_reason,
                )
            survivors = None
            if mode == "elastic":
                if not outcome.ok:
                    log.error(
                        "no survivors answered (%s after %.1fs); "
                        "re-raising", outcome.stop_reason,
                        outcome.elapsed_s,
                    )
                    raise
                survivors = outcome.value
            elif not outcome.ok:
                if mode == "auto":
                    survivors = elastic.probe_survivors(plan, device_probe)
                if not survivors:
                    log.error(
                        "backend never healed (%s after %.1fs); re-raising",
                        outcome.stop_reason, outcome.elapsed_s,
                    )
                    raise
            elif mode == "auto":
                survivors = elastic.probe_survivors(plan, device_probe)

            new_cfg = None
            if (
                survivors is not None
                and survivors < cur_cfg.mesh.num_devices
            ):
                new_cfg = elastic.survivor_config(base_cfg, survivors)
                if new_cfg is None:
                    if not outcome.ok:
                        log.error(
                            "no certified survivor mesh for %d device(s) "
                            "and the heal deadline expired; re-raising",
                            survivors,
                        )
                        raise
                    # the backend healed but no degraded config
                    # certifies (e.g. the padded shape cannot survive
                    # the re-stitch contract): resume on the current
                    # mesh — honest fallback, loudly logged
                    log.warning(
                        "no certified survivor mesh for %d device(s); "
                        "resuming on the current mesh", survivors,
                    )
            restitch_s = None
            if new_cfg is not None:
                solver, loaded, quarantined, restitch_s = (
                    elastic.refactor_and_restitch(
                        new_cfg, make_solver_for, ckpt_root,
                        old_mesh=cur_cfg.mesh.shape, step=failed_step,
                        survivors=survivors,
                    )
                )
                cur_cfg = new_cfg
                refactors += 1
                if (
                    not degraded
                    and new_cfg.mesh.num_devices
                    < base_cfg.mesh.num_devices
                ):
                    degraded = True
                    degraded_t0 = time.monotonic()
                    ledger.event(
                        "degraded_mode_enter",
                        step=failed_step,
                        mesh=list(new_cfg.mesh.shape),
                        survivors=int(survivors),
                    )
            else:
                if (
                    make_solver_for is not None
                    and cur_cfg is not None
                    and cur_cfg is not base_cfg
                ):
                    # already degraded: a rebuild must land on the mesh
                    # the run is CURRENTLY on, not the original one
                    solver = make_solver_for(cur_cfg)
                elif make_solver is not None:
                    solver = make_solver()
                loaded, quarantined = load_latest_generation(
                    solver, ckpt_root
                )
            if loaded is not None:
                u, done = loaded
            elif generation_dirs(ckpt_root):
                # generations remain but none loads here (skipped, not
                # quarantined): restarting at 0 would orphan them —
                # surface the original failure instead
                log.error(
                    "recovery found unloadable (but intact) generations "
                    "in %s; re-raising rather than restarting at step 0",
                    ckpt_root,
                )
                raise
            else:
                # every generation was quarantined (proven corrupt):
                # restarting from scratch is the only honest option, and
                # the Recovery record says so (resumed_from=None)
                u, done = solver.init_state(init), 0
            recoveries.append(
                Recovery(
                    step=failed_step,  # where the failure hit, not the rewind
                    kind=kind,
                    error=f"{type(e).__name__}: {str(e)[:200]}",
                    heal_wait_s=round(outcome.elapsed_s, 3),
                    heal_attempts=len(outcome.attempts),
                    resumed_from=done if loaded is not None else None,
                    quarantined=quarantined,
                    elastic=new_cfg is not None,
                    mesh_shape=(
                        list(cur_cfg.mesh.shape)
                        if cur_cfg is not None
                        else None
                    ),
                    restitch_s=(
                        None if restitch_s is None else round(restitch_s, 3)
                    ),
                )
            )
            ledger.set_context(
                generation=done if loaded is not None else None
            )
            rec_record = recoveries[-1].to_record()
            rec_record["kind_"] = rec_record.pop("kind")  # envelope owns kind
            ledger.event("recovery", **rec_record)
            if make_solver is not None or new_cfg is not None:
                # the rebuilt solver may have landed on different hardware
                # or a different mesh (cross-mesh stitch-resume), where its
                # compiled step program — and therefore its cost model —
                # differs from the one recorded at run start. Re-emit
                # step_cost so post-heal throughput is judged against the
                # program that is NOW running (ROADMAP "supervised-path
                # step_cost"). Fails soft and no-ops without a ledger; the
                # extra step-program compile is paid once per recovery.
                try:
                    from heat3d_tpu.obs.perf.roofline import record_step_cost

                    record_step_cost(solver, post_heal=True, step=done)
                except Exception as rexc:  # noqa: BLE001 - telemetry only
                    log.warning(
                        "post-heal step_cost re-record unavailable: %s", rexc
                    )
            obs.REGISTRY.counter(
                "recoveries_total", "survived supervised failures"
            ).inc(kind=kind)
            log.info(
                "backend healed (%s); resumed at step %d",
                outcome.value, done,
            )
            continue
        done = nxt

        # Opt-in re-expand (the elastic loop's other half): while
        # degraded, after each generation lands, ask whether capacity
        # returned — and if the FULL original device count answers,
        # re-factorize back onto the original mesh, re-stitching from
        # the generation just saved. Probing only at checkpoint
        # boundaries bounds the probe cost; skipping the final boundary
        # avoids a pointless rebuild the run would never step on.
        if degraded and reexpand and done < total_steps:
            survivors = elastic.probe_survivors(plan, device_probe)
            if (
                survivors is not None
                and survivors >= base_cfg.mesh.num_devices
            ):
                try:
                    # commit NOTHING until the re-stitch proves loadable:
                    # rebinding `solver` before the load check would leave
                    # a full-mesh solver driving the degraded-mesh `u` on
                    # the next chunk — exactly the crash this except
                    # exists to prevent
                    exp_solver, loaded, quarantined, _rs = (
                        elastic.refactor_and_restitch(
                            base_cfg, make_solver_for, ckpt_root,
                            old_mesh=cur_cfg.mesh.shape, step=done,
                            survivors=survivors, direction="expand",
                        )
                    )
                    if loaded is None:
                        raise RuntimeError(
                            "no loadable generation for re-expand"
                        )
                    solver = exp_solver
                    u, done = loaded
                    cur_cfg = base_cfg
                    refactors += 1
                    degraded = False
                    ledger.event(
                        "degraded_mode_exit",
                        step=done,
                        mesh=list(base_cfg.mesh.shape),
                        degraded_s=round(
                            time.monotonic() - degraded_t0, 3
                        ),
                    )
                except Exception as rexc:  # noqa: BLE001 - stay degraded
                    # a failed expand must not kill a run that is
                    # healthily serving degraded — log and keep going;
                    # the next boundary retries
                    log.warning(
                        "re-expand to %s failed (%s); staying degraded",
                        base_cfg.mesh.shape, rexc,
                    )

    ledger.event(
        "supervised_end",
        steps_done=done,
        start_step=start_step,
        resumed_from=resumed_from,
        checkpoints_written=checkpoints,
        recoveries=len(recoveries),
        degraded=degraded,
        refactors=refactors,
        mesh=(
            None if cur_cfg is None else list(cur_cfg.mesh.shape)
        ),
    )
    ledger.set_context(generation=None)
    return SupervisedResult(
        u=u,
        steps_done=done,
        start_step=start_step,
        resumed_from=resumed_from,
        residual=residual,
        checkpoints_written=checkpoints,
        recoveries=recoveries,
        solver=solver,
        degraded=degraded,
        mesh_shape=(None if cur_cfg is None else cur_cfg.mesh.shape),
        refactors=refactors,
    )
