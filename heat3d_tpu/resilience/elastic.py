"""Elastic degradation: survivor-mesh re-factorization for supervised runs.

PR 1's supervisor treats every backend loss as a *wait state*: probe
until the original backend returns, then resume on the original mesh.
That is the right posture for a transient tunnel outage — and the wrong
one for real hardware churn, where a chip or host is gone for hours and
the production answer is to keep serving on the survivors (the
Exascale-framework / GPU-aware-async-tasks papers' recovery-without-
restart thesis; ROADMAP "elastic weak-scaling to pod scale"). This
module makes loss a *re-plan event*:

- **heal_mode** (``HEAT3D_HEAL_MODE`` / ``--heal-mode``):
  ``wait`` (the PR 1 behavior, default), ``elastic`` (on a confirmed
  loss, re-probe the device set and re-factorize over survivors), or
  ``auto`` (heal-wait first; the heal DEADLINE — not an operator —
  triggers the elastic fallback).
- **Survivor meshes are certified, not improvised**:
  :func:`survivor_config` reuses the tuner's mesh factorization
  candidates (:func:`heat3d_tpu.tune.space.mesh_candidates`) and the
  production validation (``SolverConfig.__post_init__`` +
  ``prune_reason`` building the real solver), plus the re-stitch
  contract — the degraded config must keep the checkpoint's storage
  shape (``padded_shape``) so the ``gen-<step>`` shards stitch onto the
  new mesh through the existing cross-mesh path in
  ``utils/checkpoint.py``.
- **The re-stitch is the existing path**: :func:`refactor_and_restitch`
  rebuilds the solver for the survivor config, loads the newest good
  generation (block-stitching shards saved on the dead mesh), drops the
  dead mesh's cached :class:`~heat3d_tpu.parallel.plan.ExchangePlan`\\ s
  and pre-builds the survivor mesh's, and emits one ``elastic_refactor``
  ledger event (old/new mesh, survivor count, re-stitch seconds) so
  ``heat3d obs timeline`` can attribute the outage end to end.
- **Deadline knob**: ``HEAT3D_HEAL_DEADLINE_S`` caps the heal wait
  (:func:`default_heal_policy`); in ``auto`` mode its expiry is what
  flips the run from waiting to degrading.

The supervisor (``resilience/supervisor.py``) owns the loop state —
``degraded_mode_enter`` / ``degraded_mode_exit`` events, the opt-in
re-expand when capacity returns — and the serving tier's analogue
(requeue-with-backoff + the ``degraded`` ServeStats flag) lives in
``serve/engine/core.py``. docs/RESILIENCE.md "Elastic degradation" is
the operator contract.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional

from heat3d_tpu import obs
from heat3d_tpu.resilience.retry import RetryPolicy
from heat3d_tpu.utils.logging import get_logger

log = get_logger("heat3d.elastic")

ENV_HEAL_MODE = "HEAT3D_HEAL_MODE"
ENV_HEAL_DEADLINE = "HEAT3D_HEAL_DEADLINE_S"
HEAL_MODES = ("wait", "elastic", "auto")

# the PR 1 heal-wait deadline, now the overridable default
DEFAULT_HEAL_DEADLINE_S = 1800.0


def resolve_heal_mode(mode: Optional[str] = None) -> str:
    """The concrete heal mode: explicit argument > ``HEAT3D_HEAL_MODE``
    env > ``wait`` (the PR 1 behavior). Raises on unknown values — a
    typo'd mode silently heal-waiting forever is the exact failure this
    knob exists to end."""
    mode = mode or os.environ.get(ENV_HEAL_MODE) or "wait"
    if mode not in HEAL_MODES:
        raise ValueError(
            f"unknown heal_mode {mode!r} (want one of {HEAL_MODES}; "
            f"{ENV_HEAL_MODE} is the env default)"
        )
    return mode


def heal_deadline_s(default: float = DEFAULT_HEAL_DEADLINE_S) -> float:
    """The heal-wait total deadline: ``HEAT3D_HEAL_DEADLINE_S`` override,
    else ``default``. A non-numeric override falls back (the knob must
    never kill the recovery it bounds)."""
    raw = os.environ.get(ENV_HEAL_DEADLINE)
    if raw:
        try:
            v = float(raw)
            if v > 0:
                return v
            log.warning(
                "%s=%r is not positive; using %.0fs",
                ENV_HEAL_DEADLINE, raw, default,
            )
        except ValueError:
            log.warning(
                "%s=%r is not a number; using %.0fs",
                ENV_HEAL_DEADLINE, raw, default,
            )
    return default


def default_heal_policy() -> RetryPolicy:
    """The supervisor's heal-wait policy, deadline-capped by
    ``HEAT3D_HEAL_DEADLINE_S``: same shape as the measurement scripts'
    gate (probe every 60 s, 1.5x backoff capped at 5 min, jittered —
    every probe is a claim attempt). In ``wait`` mode the deadline is
    where an unhealable backend finally re-raises instead of waiting
    forever; in ``auto`` mode it is what triggers the elastic
    fallback."""
    return RetryPolicy(
        base_delay_s=60.0,
        multiplier=1.5,
        max_delay_s=300.0,
        jitter_frac=0.1,
        deadline_s=heal_deadline_s(),
    )


def probe_survivors(
    plan=None,
    device_probe: Optional[Callable[[], Optional[int]]] = None,
) -> Optional[int]:
    """How many devices survive, or None when nothing answers.

    The injected :class:`~heat3d_tpu.resilience.faults.FaultPlan`
    override is consulted first (the deterministic CPU tier), then the
    caller's ``device_probe`` (tests), then the bounded out-of-process
    ``backendprobe.probe_device_count`` — never an in-process
    ``jax.devices()`` that can wedge forever."""
    if plan is not None:
        override = plan.device_override()
        if override is not None:
            return override
    if device_probe is not None:
        return device_probe()
    from heat3d_tpu.utils.backendprobe import probe_device_count

    return probe_device_count()


def survivor_config(base_cfg, num_devices: int):
    """The certified degraded config for ``num_devices`` survivors, or
    None when no candidate passes.

    Candidates come from the tuner's mesh factorizations
    (``tune.space.survivor_candidates``): slab-first, each validated by
    the PRODUCTION rules — ``SolverConfig.__post_init__`` plus a real
    solver build (``prune_reason``) — and by the re-stitch contract
    (``padded_shape`` preserved, so the checkpoint saved on the dead
    mesh stitches onto the new one). The first certified candidate
    wins; None means the caller must fall back to heal-wait semantics.
    """
    if num_devices < 1:
        return None
    from heat3d_tpu.tune.space import survivor_candidates

    cands = survivor_candidates(base_cfg, num_devices)
    return cands[0] if cands else None


def refactor_and_restitch(
    new_cfg,
    make_solver_for: Callable[[object], object],
    ckpt_root: str,
    *,
    old_mesh,
    step: int,
    survivors: int,
    direction: str = "degrade",
):
    """Rebuild the solver on ``new_cfg``'s mesh and re-stitch the newest
    good generation onto it. Returns ``(solver, loaded, quarantined,
    restitch_s)`` with the supervisor's ``load_latest_generation``
    semantics (``loaded`` None = nothing loadable; the caller applies
    the same refuse-to-restart rules as a normal resume).

    Side effects: the dead mesh's cached exchange plans are dropped and
    the survivor mesh's plan pre-built (``exchange_plan_built`` audits
    the rebuild during the recovery, not the first post-resume step),
    and ONE ``elastic_refactor`` ledger event records old/new mesh,
    survivor count and re-stitch seconds — the outage-attribution row
    ``heat3d obs timeline`` reads."""
    from heat3d_tpu.resilience.supervisor import load_latest_generation

    t0 = time.monotonic()
    solver = make_solver_for(new_cfg)
    loaded, quarantined = load_latest_generation(solver, ckpt_root)
    restitch_s = time.monotonic() - t0

    # plan hygiene: the dead mesh's precomputed permutations can never be
    # exchanged again this process — drop them, and pre-build the
    # survivor mesh's plan so the audit event lands inside the recovery
    # window (both fail soft: plans rebuild on demand at the first step
    # either way)
    try:
        from heat3d_tpu.parallel import plan as planmod

        planmod.drop_plans_for_mesh(tuple(old_mesh))
        planmod.plan_for(new_cfg, width=max(1, new_cfg.time_blocking))
    except Exception as e:  # noqa: BLE001 - plan warm-up is best-effort
        log.warning("exchange-plan rebuild deferred to first step: %s", e)

    obs.get().event(
        "elastic_refactor",
        direction=direction,
        old_mesh=list(old_mesh),
        new_mesh=list(new_cfg.mesh.shape),
        old_devices=int(
            old_mesh[0] * old_mesh[1] * old_mesh[2]
        ),
        survivors=int(survivors),
        lost_devices=int(
            old_mesh[0] * old_mesh[1] * old_mesh[2]
            - new_cfg.mesh.num_devices
        ),
        restitch_s=round(restitch_s, 6),
        step=int(step),
        resumed_from=None if loaded is None else int(loaded[1]),
        quarantined=quarantined,
    )
    obs.REGISTRY.counter(
        "elastic_refactors_total", "survivor-mesh re-factorizations"
    ).inc(direction=direction)
    log.warning(
        "elastic refactor (%s): mesh %s -> %s (%d survivor(s)), "
        "re-stitch %.3fs",
        direction, tuple(old_mesh), new_cfg.mesh.shape, survivors,
        restitch_s,
    )
    return solver, loaded, quarantined, restitch_s


