"""Resilience subsystem: survive the environment, test the failure paths.

Five rounds of history say the dominant failure mode is not the solver but
the backend: tunnel outages killed measurement stages and put CPU fallbacks
into graded artifacts. The defenses used to live scattered across
``utils/backendprobe.py``, ``bench.py``, and shell sleep loops — divergent,
duplicated, and untestable without a real outage. This package unifies them:

- ``retry``      — the ONE retry/backoff implementation (jittered
                   exponential backoff, deadline budgets, structured
                   outcome records). ``backendprobe.wait_for_backend``,
                   ``bench.py``'s probe loop, and the measurement scripts'
                   pacing all route through it.
- ``faults``     — deterministic fault injection (backend-loss-at-step-N,
                   hang-until-deadline, SIGTERM-mid-sweep, corrupted
                   checkpoint shard) so every retry/resume path runs under
                   pytest on CPU.
- ``supervisor`` — the supervised run loop: checkpoint every K steps into
                   checksummed generations, watchdog the backend, quarantine
                   corrupt generations, resume from the last good one when
                   the backend heals (including cross-mesh stitch-resume).
- ``sweepstate`` — per-row sweep state so an interrupted A/B measurement
                   session resumes at the first missing row.

See docs/RESILIENCE.md for the operator-facing protocol.
"""

from heat3d_tpu.resilience.retry import RetryOutcome, RetryPolicy
from heat3d_tpu.resilience.faults import (
    FaultPlan,
    InjectedBackendLoss,
    InjectedFault,
)
from heat3d_tpu.resilience.sweepstate import SweepState
from heat3d_tpu.resilience.supervisor import SupervisedResult, run_supervised

__all__ = [
    "FaultPlan",
    "InjectedBackendLoss",
    "InjectedFault",
    "RetryOutcome",
    "RetryPolicy",
    "SupervisedResult",
    "SweepState",
    "run_supervised",
]
