"""Resumable sweep state: an interrupted A/B session resumes at the first
missing row.

A measurement session is a sequence of rows (suite configs, A/B arms,
profile stages). When the tunnel dies mid-session, the rows already landed
must never be re-measured — a 30-minute healthy window should spend itself
on the MISSING rows (round 5 lost stages 3b–3g exactly this way: the
headline re-ran, the counterfactual arms never got their turn).

``SweepState`` is an append-only JSONL journal of completed row keys.
Appends are O(one line) and crash-safe in the only way that matters: a
torn final line (power loss mid-append) is ignored on reload, so the worst
case is re-measuring the one row whose record tore. The shell drivers use
the CLI form::

    python -m heat3d_tpu.resilience.sweepstate done  STATE KEY   # rc 0 if done
    python -m heat3d_tpu.resilience.sweepstate mark  STATE KEY [JSON]
    python -m heat3d_tpu.resilience.sweepstate list  STATE
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterable, List, Optional


class SweepState:
    """Per-row completion journal backed by one JSONL file.

    Keys are caller-chosen strings; make them a stable function of the
    row's full configuration (the bench harness uses
    :func:`row_key`), never of its position in the sweep — reordering
    the sweep must not orphan completed work.
    """

    def __init__(self, path: str):
        self.path = path
        self._done: Dict[str, dict] = {}
        self._load()

    def _load(self) -> None:
        try:
            f = open(self.path)
        except OSError:
            return
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line from a killed append
                if isinstance(rec, dict) and "key" in rec:
                    self._done[rec["key"]] = rec

    def is_done(self, key: str) -> bool:
        return key in self._done

    def record(self, key: str) -> Optional[dict]:
        return self._done.get(key)

    def mark_done(self, key: str, record: Optional[dict] = None) -> None:
        rec = {"key": key, "ts": time.time()}
        if record is not None:
            rec["record"] = record
        self._done[key] = rec
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def pending(self, keys: Iterable[str]) -> List[str]:
        return [k for k in keys if not self.is_done(k)]

    def keys(self) -> List[str]:
        return list(self._done)


# Env knobs that select which kernel route a throughput row measures
# (the A/B counterfactual arms in tpu_measure_all.sh flip exactly these).
# They are row IDENTITY: two arms differing only in one of them must
# never share a journal entry, or a resume re-emits arm 0's record as
# arm 1's measurement.
ROUTE_ENV_KNOBS = (
    "HEAT3D_MEHRSTELLEN",
    "HEAT3D_FACTOR_Y",
    "HEAT3D_FACTOR_7PT",
    "HEAT3D_NO_DIRECT",
    "HEAT3D_DIRECT_INTERPRET",
    "HEAT3D_DIRECT_FORCE",
    # bypasses the exchange-plan layer (partitioned degrades to the
    # ad-hoc monolithic path — a different measured schedule)
    "HEAT3D_NO_PLAN",
    # forces/stands down the fused in-kernel RDMA superstep route — a
    # fused arm and an unfused arm must never share a journal entry
    "HEAT3D_FUSED_RDMA",
)


def row_key(cfg, bench: str = "throughput") -> str:
    """Stable row key for a bench config: every knob that changes what the
    row measures — config fields AND the route env knobs — none that
    doesn't (steps/repeats tune precision, not identity). Halo rows key
    on the EXCHANGE SHAPE only (grid, mesh, storage dtype, transport —
    run_suite's own dedup rule; route knobs don't touch the exchange):
    the same physical halo measurement must hit the same journal entry no
    matter which config in the sweep happened to land it first."""
    g = "x".join(str(v) for v in cfg.grid.shape)
    m = "x".join(str(v) for v in cfg.mesh.shape)
    # the halo-ordering knob changes what a row measures, so it is part
    # of the identity — suffixed ONLY when non-default, so every journal
    # written before the knob existed keeps resuming cleanly
    ho = "" if cfg.halo_order == "axis" else f":ho{cfg.halo_order}"
    # the exchange-plan mode changes the message schedule a row measures
    # — suffixed ONLY when non-default, same legacy-journal rule as ho.
    # The EFFECTIVE mode keys the journal (HEAT3D_NO_PLAN degrades
    # partitioned to the ad-hoc monolithic schedule; the key must match
    # what the row measured — one rule, parallel.plan).
    from heat3d_tpu.parallel.plan import effective_halo_plan

    hp_mode = effective_halo_plan(cfg)
    hp = "" if hp_mode == "monolithic" else f":hp{hp_mode}"
    if bench == "halo":
        return (
            f"halo:g{g}:m{m}:{cfg.precision.storage}:h{cfg.halo}{ho}{hp}"
        )
    env_bits = ",".join(
        f"{k}={os.environ[k]}" for k in ROUTE_ENV_KNOBS if k in os.environ
    )
    # equation leg only when non-heat (same legacy-journal-compatible
    # suffix rule as :hp / halo_order): every pre-eqn journal key stays
    # byte-identical, and a spec-built family's stage can never collide
    # with the heat stage of the same shape
    eq = "" if cfg.equation == "heat" else f":eq{cfg.equation}"
    # time-integrator leg (same non-default suffix rule): a leapfrog or
    # CG stage of the same shape must not resume an explicit-euler row,
    # while every pre-timeint journal key stays byte-identical
    ti = (
        ""
        if cfg.integrator == "explicit-euler"
        else f":ti{cfg.integrator}"
    )
    # fused-RDMA leg (same non-default suffix rule): the EFFECTIVE knob
    # value (env override / auto fallback resolved — one rule,
    # parallel.step.resolve_fused_rdma), so a fused arm never resumes an
    # unfused row while every pre-fused journal key stays byte-identical
    from heat3d_tpu.parallel.step import resolve_fused_rdma

    fr_mode = resolve_fused_rdma(cfg)
    fr = "" if fr_mode == "off" else f":fr{fr_mode}"
    return (
        f"{bench}:g{g}:m{m}:{cfg.stencil.kind}:{cfg.precision.storage}"
        f":c{cfg.precision.compute}:b{cfg.backend}:tb{cfg.time_blocking}"
        f":ov{int(cfg.overlap)}:h{cfg.halo}{ho}{hp}{eq}{ti}{fr}"
        + (f":env[{env_bits}]" if env_bits else "")
    )


def _main(argv=None) -> int:
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    usage = "usage: sweepstate {done|mark|list} STATE_FILE [KEY] [RECORD_JSON]"
    if len(argv) < 2:
        print(usage, file=sys.stderr)
        return 2
    cmd, path = argv[0], argv[1]
    state = SweepState(path)
    if cmd == "list":
        for k in state.keys():
            print(k)
        return 0
    if len(argv) < 3:
        print(usage, file=sys.stderr)
        return 2
    key = argv[2]
    if cmd == "done":
        return 0 if state.is_done(key) else 1
    if cmd == "mark":
        record = json.loads(argv[3]) if len(argv) > 3 else None
        state.mark_done(key, record)
        return 0
    print(usage, file=sys.stderr)
    return 2


if __name__ == "__main__":
    import sys

    sys.exit(_main())
