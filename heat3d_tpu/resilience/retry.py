"""The one retry/backoff implementation.

Before this module, the framework had three divergent retry loops — the
probe wait in ``backendprobe.wait_for_backend`` (1.5x backoff, 300 s cap,
deadline-clamped sleeps), ``bench.py``'s probe loop (fixed backoff, a
shared deadline with a CPU-fallback reserve), and the shell scripts' bare
``sleep`` pacing — each re-deriving the same claim-expiry arithmetic and
none testable without a live outage. :class:`RetryPolicy` is the single
implementation they all route through.

Design rules, learned the hard way (SURVEY.md §7.0, bench.py docstring):

- **The first attempt always runs.** A zero/expired deadline still gets
  one try — ``wait_for_backend(0)`` has always meant "probe once".
- **Sleeps are clamped to the remaining deadline**, so the last attempt
  fires right at the deadline edge instead of oversleeping past it.
- **Jitter is bounded and injectable.** Every probe against the axon pool
  is a claim attempt; jitter de-synchronizes fleets of waiting clients.
  Tests inject a seeded ``random.Random`` for determinism.
- **Outcomes are structured records**, not log lines: every attempt's
  duration, error, and sleep is kept so a post-mortem can reconstruct
  what the retry loop actually did inside an outage window.

Clock and sleep are injectable throughout: the entire policy is testable
in milliseconds on CPU, which is the point of this subsystem.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Iterator, List, Optional


@dataclasses.dataclass
class Attempt:
    """One attempt's structured record (offsets are from the run start)."""

    index: int
    started_s: float
    duration_s: float
    ok: bool
    error: Optional[str] = None
    slept_s: float = 0.0


@dataclasses.dataclass
class RetryOutcome:
    """What a :meth:`RetryPolicy.run` actually did.

    ``stop_reason`` is one of ``success`` | ``deadline`` | ``attempts`` |
    ``gave_up`` (the caller's ``proceed`` hook said stop).
    """

    ok: bool
    value: Any
    stop_reason: str
    elapsed_s: float
    attempts: List[Attempt] = dataclasses.field(default_factory=list)

    def to_record(self) -> dict:
        """JSON-able summary for logs/bench rows."""
        return {
            "ok": self.ok,
            "stop_reason": self.stop_reason,
            "elapsed_s": round(self.elapsed_s, 3),
            "attempts": len(self.attempts),
            "errors": [a.error for a in self.attempts if a.error],
        }


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Jittered exponential backoff under an optional deadline budget.

    ``max_attempts=None`` means attempt-unbounded (the deadline is then the
    only stop). ``deadline_s`` measures from the start of the first
    attempt; callers with a DYNAMIC budget (bench.py reserving wall clock
    for its CPU fallback) express it through the ``proceed`` hook and a
    per-attempt timeout instead. ``jitter_frac`` spreads each sleep uniformly
    over ``[delay*(1-j), delay*(1+j)]`` (clamped to the cap and deadline).
    """

    max_attempts: Optional[int] = None
    base_delay_s: float = 60.0
    multiplier: float = 1.5
    max_delay_s: float = 300.0
    jitter_frac: float = 0.0
    deadline_s: Optional[float] = None

    def __post_init__(self):
        if self.max_attempts is not None and self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1 (or None)")
        if self.base_delay_s < 0 or self.max_delay_s < 0:
            raise ValueError("delays must be >= 0")
        if self.multiplier < 1.0:
            raise ValueError("multiplier must be >= 1.0 (backoff never shrinks)")
        if not 0.0 <= self.jitter_frac <= 1.0:
            raise ValueError("jitter_frac must be in [0, 1]")
        if self.max_attempts is None and self.deadline_s is None:
            raise ValueError(
                "unbounded policy: set max_attempts and/or deadline_s"
            )

    def delays(self) -> Iterator[float]:
        """The un-jittered backoff schedule: base, base*m, ... capped
        (a view over :meth:`delay_for`, which owns the arithmetic)."""
        i = 1
        while True:
            yield self.delay_for(i)
            i += 1

    def delay_for(self, attempt: int, rng=None) -> float:
        """The (jittered) sleep after the ``attempt``-th failure (1-based).

        The ONE place the backoff+jitter arithmetic lives — ``run()`` and
        the shell-pacing CLI both call it, so in-process and script
        pacing cannot drift apart. ``rng`` needs ``.uniform``; None (or
        ``jitter_frac`` 0) means the bare schedule value."""
        if attempt < 1:
            return 0.0
        delay = min(
            self.base_delay_s * self.multiplier ** (attempt - 1),
            self.max_delay_s,
        )
        if self.jitter_frac and rng is not None:
            lo = delay * (1.0 - self.jitter_frac)
            hi = min(delay * (1.0 + self.jitter_frac), self.max_delay_s)
            delay = rng.uniform(lo, hi)
        return delay

    def run(
        self,
        fn: Callable[..., Any],
        *,
        success: Callable[[Any], bool] = lambda v: v is not None,
        proceed: Optional[Callable[[], bool]] = None,
        on_attempt: Optional[Callable[[Attempt], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng=None,
    ) -> RetryOutcome:
        """Run ``fn`` until ``success(value)``, the deadline, the attempt
        cap, or ``proceed()`` returning False (checked before every attempt
        AFTER the first — the first attempt always runs).

        ``fn`` is called with no arguments; wrap context in a closure. An
        exception from ``fn`` counts as a failed attempt (recorded, then
        retried) — raise through ``proceed`` if an error must abort.
        """
        import random as _random

        from heat3d_tpu import obs

        jrng = rng if rng is not None else _random
        start = clock()
        attempts: List[Attempt] = []
        ledger = obs.get()

        def record(rec: Attempt) -> None:
            # one observation pipeline for every exit path: the caller's
            # on_attempt hook, the ledger's per-attempt event, the counter
            if on_attempt is not None:
                on_attempt(rec)
            ledger.event(
                "retry_attempt",
                index=rec.index,
                ok=rec.ok,
                error=rec.error,
                duration_s=round(rec.duration_s, 6),
                slept_s=round(rec.slept_s, 6),
            )
            obs.REGISTRY.counter(
                "retry_attempts_total", "RetryPolicy attempts"
            ).inc(ok=str(rec.ok).lower())

        def outcome(ok, value, reason):
            out = RetryOutcome(
                ok=ok,
                value=value,
                stop_reason=reason,
                elapsed_s=clock() - start,
                attempts=attempts,
            )
            ledger.event("retry_outcome", **out.to_record())
            obs.REGISTRY.counter(
                "retry_outcomes_total", "RetryPolicy.run results"
            ).inc(reason=reason)
            return out

        i = 0
        while True:
            if i > 0 and proceed is not None and not proceed():
                return outcome(False, None, "gave_up")
            t0 = clock()
            err = None
            try:
                value = fn()
                ok = bool(success(value))
            except Exception as e:  # noqa: BLE001 - a failed attempt, not a crash
                value, ok = None, False
                err = f"{type(e).__name__}: {str(e)[:200]}"
            rec = Attempt(
                index=i,
                started_s=t0 - start,
                duration_s=clock() - t0,
                ok=ok,
                error=err,
            )
            attempts.append(rec)
            if ok:
                record(rec)
                return outcome(True, value, "success")
            i += 1
            if self.max_attempts is not None and i >= self.max_attempts:
                record(rec)
                return outcome(False, None, "attempts")
            delay = self.delay_for(i, jrng)
            if self.deadline_s is not None:
                remaining = self.deadline_s - (clock() - start)
                if remaining <= 0:
                    record(rec)
                    return outcome(False, None, "deadline")
                # clamp so the next (= last) attempt fires at the edge
                delay = min(delay, remaining)
            # recorded unconditionally: the outcome's post-mortem value is
            # reconstructing the sleep schedule that actually ran
            rec.slept_s = delay
            record(rec)
            if delay > 0:
                sleep(delay)


def _main(argv=None) -> int:
    """``python -m heat3d_tpu.resilience.retry --attempt N [...]``

    Prints the policy's backoff delay for attempt N (1-based: the sleep
    AFTER the Nth failure) and, with ``--sleep``, sleeps it. This is how
    shell drivers (measure_until_complete.sh) pace their retry loops
    through the one policy implementation instead of a bare ``sleep 60``.
    Jitter is seeded by the attempt index, so a restarted driver sleeps
    the same schedule (deterministic, still fleet-desynchronized via
    --seed-extra, e.g. a hostname hash).
    """
    import argparse
    import random
    import sys

    ap = argparse.ArgumentParser(description=_main.__doc__)
    ap.add_argument("--attempt", type=int, required=True)
    ap.add_argument("--base", type=float, default=60.0)
    ap.add_argument("--multiplier", type=float, default=1.5)
    ap.add_argument("--cap", type=float, default=300.0)
    ap.add_argument("--jitter", type=float, default=0.1)
    ap.add_argument("--seed-extra", default="")
    ap.add_argument("--sleep", action="store_true")
    args = ap.parse_args(argv)
    if args.attempt < 1:
        print("0.0")
        return 0
    policy = RetryPolicy(
        max_attempts=args.attempt + 1,
        base_delay_s=args.base,
        multiplier=args.multiplier,
        max_delay_s=args.cap,
        jitter_frac=args.jitter,
    )
    delay = policy.delay_for(
        args.attempt, random.Random(f"{args.seed_extra}:{args.attempt}")
    )
    print(f"{delay:.1f}")
    if args.sleep:
        time.sleep(delay)
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
