"""heat3d_tpu — a TPU-native 3D heat-equation framework.

A ground-up re-design of the capability set of the reference repo
``fredrickhang/Cuda-aware-MPI-on-3D-heate-quation`` (CUDA kernels +
CUDA-aware MPI halo exchange + MPI_Cart_create 3D decomposition) as an
idiomatic JAX/XLA/Pallas program:

- the CUDA 7-point Jacobi stencil kernel        -> Pallas TPU kernels: BC-fused direct
  streaming kernels reading the unpadded field (``ops.stencil_pallas_direct``,
  single- and fused two-update forms) plus exchange-padded kernels (``ops.stencil_pallas``)
- CUDA-aware MPI_Isend/Irecv ghost-cell exchange -> ``shard_map`` + ``lax.ppermute``
  over ICI (``parallel.halo``), with a Pallas ``make_async_remote_copy`` tier
- MPI_Cart_create 3D Cartesian decomposition     -> ``jax.sharding.Mesh`` mapped onto
  the TPU torus (``parallel.topology``)
- the mpirun driver + time-stepping loop         -> ``jax.distributed`` entrypoint and a
  jit-compiled ``lax.fori_loop`` time loop (``models.heat3d``, ``cli``); the
  pointer swap is a ping-pong pair carry that XLA compiles to copy-free
  buffer alternation (``parallel.step._pingpong_loop``)

The reference mount is empty in this environment (see SURVEY.md §0); the
capability spec is BASELINE.json's north star and config matrix, and
reference-parity notes in docstrings cite SURVEY.md sections instead of
file:line.
"""

from heat3d_tpu.core.config import (
    BoundaryCondition,
    GridConfig,
    MeshConfig,
    Precision,
    RunConfig,
    SolverConfig,
    StencilConfig,
)
from heat3d_tpu.core.stencils import STENCILS, Stencil, stencil_taps
from heat3d_tpu.models.heat3d import HeatSolver3D

__version__ = "0.4.0"

__all__ = [
    "BoundaryCondition",
    "GridConfig",
    "MeshConfig",
    "Precision",
    "RunConfig",
    "SolverConfig",
    "StencilConfig",
    "STENCILS",
    "Stencil",
    "stencil_taps",
    "HeatSolver3D",
]
