"""Persistent halo-exchange plans — setup amortized across the run,
partitioned early-bird sends as a tuned knob.

"Persistent and Partitioned MPI for Stencil Communication" (PAPERS.md)
shows two wins for repeated ghost exchanges: amortize the exchange
*setup* across steps (persistent channels: the neighbor graph, buffer
slices and message schedule are built once, not per iteration) and ship
each face as *partitions* that leave as soon as their tile of data is
ready (early-bird sends) instead of when the whole face is assembled.
Our pre-plan equivalent of the setup cost was re-deriving the six
``shift_perm`` permutations, face slices and corner-propagation order
inside every step trace; this module hoists all of it into an
:class:`ExchangePlan` built once per (mesh, boundary condition, width-k,
halo ordering, transport, plan mode) and reused by every step,
superstep, phase, bench and ensemble program in the process
(``plan_for`` caches; the ``exchange_plan_built`` / ``plan_cache_hit``
ledger events audit reuse — one build per plan key per run is the
contract the tests pin).

Plan modes (the ``halo_plan`` config knob, ``auto`` resolved through
the tuning cache like every other knob — docs/TUNING.md):

- ``monolithic`` — one collective per face, exactly the pre-plan
  exchange structure; plan-built programs are BITWISE-identical to the
  ad-hoc path (the permutations and slices are precomputed, the traced
  ops are the same).
- ``partitioned`` — each face at or above the granularity floor
  (:data:`DEFAULT_PART_MIN_BYTES`, ``HEAT3D_PLAN_PART_MIN_BYTES``) is
  split into :data:`DEFAULT_PARTITIONS`
  sub-blocks and every sub-block ships as its OWN ppermute, issued from
  its own strip of the boundary (the early-bird ordering: no sub-block's
  transfer waits for the whole face, the first consumer of each landed
  sub-block is the ghost concatenate, and the interior sweep carries no
  dependence on any of them — XLA's async collective-permutes overlap
  the transport with the remaining compute; compose with ``overlap=True``
  for the interior/boundary-tiled sweep). The assembled ghost faces are
  bitwise-identical to the monolithic exchange (ppermute is pure data
  movement), so partitioned A/Bs are value-safe on every stencil,
  ordering and decomposition — the tuner decides where the message-size
  trade wins. ``partitioned`` pins the exchange path (the kernel
  families synthesize ghosts in-kernel — ``parallel.step``'s shared
  kernel gate stands them down) and requires the ppermute transport
  (the DMA slab kernels are monolithic by construction; config-rejected).

``HEAT3D_NO_PLAN=1`` bypasses the plan layer entirely (the legacy
ad-hoc dispatch — the reference arm of the plan-vs-ad-hoc parity tests
and a production escape hatch; ``halo_plan='partitioned'`` then degrades
to the monolithic ad-hoc path).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional, Tuple

from heat3d_tpu.core.config import BoundaryCondition, MeshConfig, SolverConfig
from heat3d_tpu.obs.trace import named_phase
from heat3d_tpu.parallel.halo import (
    axis_ghosts,
    exchange_halo,
    exchange_halo_pairwise,
    shift_perm,
    substitute_domain_bc,
)

HALO_PLANS = ("monolithic", "partitioned", "auto")

# sub-blocks per face in partitioned mode: 2 halves the message size
# (first half lands while the second is in flight) without fragmenting
# faces below useful DMA granularity on the judged shard shapes
DEFAULT_PARTITIONS = 2

# granularity floor: a face below this many bytes ships whole even under
# halo_plan='partitioned' — sub-messages smaller than this cannot
# pipeline usefully (per-collective setup dominates transport; the
# partitioned-MPI literature sizes partitions to network granularity
# for the same reason, and the CPU A/B at smoke sizes measures exactly
# that overhead regime). 1 MiB keeps every pod-scale judged face
# partitioned (a 1024^2 fp32 slab face is 4 MiB) while small-face
# exchanges keep the monolithic schedule. HEAT3D_PLAN_PART_MIN_BYTES
# overrides (0 forces genuine sub-blocks everywhere — the IR matrix and
# the identity tests use it so partitioned programs are certified with
# real sub-block permutes, not the degenerate schedule).
DEFAULT_PART_MIN_BYTES = 1 << 20

ENV_NO_PLAN = "HEAT3D_NO_PLAN"
ENV_PART_MIN_BYTES = "HEAT3D_PLAN_PART_MIN_BYTES"


def part_min_bytes() -> int:
    """The effective partition granularity floor (env override or the
    default). Never raises — a malformed override falls back."""
    raw = os.environ.get(ENV_PART_MIN_BYTES)
    if raw is None or raw == "":
        return DEFAULT_PART_MIN_BYTES
    try:
        return max(0, int(raw))
    except ValueError:
        return DEFAULT_PART_MIN_BYTES


def partition_bounds(extent: int, parts: int) -> Tuple[Tuple[int, int], ...]:
    """Split ``[0, extent)`` into up to ``parts`` contiguous sub-ranges,
    as even as possible, never empty (an extent smaller than ``parts``
    yields ``extent`` unit ranges — the degenerate plan is still valid)."""
    p = max(1, min(int(parts), int(extent)))
    base, rem = divmod(int(extent), p)
    bounds = []
    start = 0
    for i in range(p):
        step = base + (1 if i < rem else 0)
        bounds.append((start, start + step))
        start += step
    return tuple(bounds)


@dataclasses.dataclass(frozen=True)
class AxisExchangeSpec:
    """Everything one axis's exchange needs, precomputed: the mesh axis,
    its precomputed ±1 ring/line permutations (``None`` on size-1 axes —
    no remote party), and the in-plane dim partitioned sub-blocks split
    along (the first non-exchange dim; irrelevant in monolithic mode)."""

    axis: int
    name: str
    size: int
    perm_up: Optional[Tuple[Tuple[int, int], ...]]  # shift_perm(size, +1)
    perm_down: Optional[Tuple[Tuple[int, int], ...]]  # shift_perm(size, -1)
    part_dim: int


@dataclasses.dataclass(frozen=True)
class ExchangePlan:
    """One persistent exchange schedule. ``apply`` must run inside
    shard_map over the plan's mesh; ``bc_value`` stays an apply-time
    argument (it may be a TRACED scalar — the ensemble's per-member
    boundary value), so one plan serves every tenant of a mesh shape."""

    mesh: MeshConfig
    bc: BoundaryCondition
    width: int
    halo_order: str  # 'axis' | 'pairwise'
    transport: str  # 'ppermute' | 'dma'
    mode: str  # 'monolithic' | 'partitioned'
    partitions: int
    min_part_bytes: int  # faces below this ship whole (granularity floor)
    axis_specs: Tuple[AxisExchangeSpec, ...]

    @property
    def periodic(self) -> bool:
        return self.bc is BoundaryCondition.PERIODIC

    @property
    def key(self) -> str:
        """Stable human-readable plan identity (ledger event key)."""
        m = "x".join(str(p) for p in self.mesh.shape)
        return (
            f"m{m}|{self.bc.value}|w{self.width}|{self.halo_order}"
            f"|{self.transport}|{self.mode}"
            + (
                # the granularity floor changes the executed schedule, so
                # two plans differing only in it must not alias to one
                # audit-event key (the reuse contract counts per key)
                f"|p{self.partitions}|f{self.min_part_bytes}"
                if self.mode == "partitioned"
                else ""
            )
        )

    # ---- execution --------------------------------------------------------

    def apply(self, u, bc_value: Any = 0.0):
        """Ghost-grow ``u`` by ``width`` on every axis through this plan's
        schedule: (nx,ny,nz) -> (nx+2w,ny+2w,nz+2w). Must run inside
        shard_map over the mesh the plan was built for."""
        if self.transport == "dma":
            from heat3d_tpu.ops.halo_pallas import exchange_halo_dma_planned

            return exchange_halo_dma_planned(u, self, bc_value)
        ghosts_fn = (
            self._partitioned_ghosts
            if self.mode == "partitioned"
            else self._monolithic_ghosts
        )
        if self.halo_order == "pairwise":
            return exchange_halo_pairwise(
                u, self.mesh, self.bc, bc_value, self.width,
                ghosts_fn=ghosts_fn,
            )
        return exchange_halo(
            u, self.mesh, self.bc, bc_value, self.width, ghosts_fn=ghosts_fn
        )

    def _spec(self, axis: int) -> AxisExchangeSpec:
        return self.axis_specs[axis]

    def _monolithic_ghosts(
        self, lo_face, hi_face, axis, axis_name, axis_size, periodic, bc_value
    ):
        """One collective per face, permutation precomputed — the ad-hoc
        exchange structure with the per-trace setup hoisted into the
        plan (bitwise-identical traced ops)."""
        spec = self._spec(axis)
        return axis_ghosts(
            lo_face, hi_face, axis_name, axis_size, periodic, bc_value,
            perms=(spec.perm_up, spec.perm_down),
        )

    def _partitioned_ghosts(
        self, lo_face, hi_face, axis, axis_name, axis_size, periodic, bc_value
    ):
        """Early-bird partitioned sends: each face sub-block is its own
        ppermute pair, issued from its own boundary strip. The assembled
        ghost faces equal the monolithic exchange bitwise (ppermute moves
        values unchanged; the domain-edge BC substitution is the SHARED
        ``substitute_domain_bc`` tail ``axis_ghosts`` applies to the
        whole face)."""
        from jax import lax

        if axis_size == 1:
            # degenerate ring: nothing to partition, same special cases
            return axis_ghosts(
                lo_face, hi_face, axis_name, axis_size, periodic, bc_value
            )
        spec = self._spec(axis)
        pd = spec.part_dim
        bounds = partition_bounds(
            lo_face.shape[pd],
            self._face_partitions(lo_face.shape, lo_face.dtype.itemsize),
        )
        glo_parts, ghi_parts = [], []
        for i, (a, b) in enumerate(bounds):
            lo_p = lax.slice_in_dim(lo_face, a, b, axis=pd)
            hi_p = lax.slice_in_dim(hi_face, a, b, axis=pd)
            # per-sub-block scopes (halo.<axis>.<dir>.p<i>, degenerate
            # single-block schedules keep the plain per-direction name):
            # each early-bird send's device time attributes to ITS
            # sub-block — the granularity the partitioned-MPI trade
            # actually lives at (normalize_phase folds all of them back
            # into halo_exchange for the coarse joins)
            blk = f".p{i}" if len(bounds) > 1 else ""
            with named_phase(f"halo.{axis_name}.lo{blk}"):
                # my low ghost = low neighbor's high face (shift up)
                glo_parts.append(
                    lax.ppermute(hi_p, axis_name, spec.perm_up)
                )
            with named_phase(f"halo.{axis_name}.hi{blk}"):
                ghi_parts.append(
                    lax.ppermute(lo_p, axis_name, spec.perm_down)
                )
        if len(bounds) == 1:
            ghost_lo, ghost_hi = glo_parts[0], ghi_parts[0]
        else:
            ghost_lo = lax.concatenate(glo_parts, dimension=pd)
            ghost_hi = lax.concatenate(ghi_parts, dimension=pd)
        return substitute_domain_bc(
            ghost_lo, ghost_hi, axis_name, axis_size, periodic, bc_value
        )

    def _face_partitions(self, face_shape, itemsize: int) -> int:
        """Sub-blocks for a face of this shape: the requested partition
        count, gated by the granularity floor (a face too small to
        pipeline ships whole — the monolithic schedule, same values)."""
        elems = 1
        for s in face_shape:
            elems *= int(s)
        if elems * itemsize < self.min_part_bytes:
            return 1
        return self.partitions

    def face_partition_bounds(
        self, axis: int, local_shape, itemsize: int
    ) -> Tuple[Tuple[int, int], ...]:
        """The sub-block decomposition this plan ships ``axis``'s faces
        as: contiguous ``(start, end)`` ranges along the face's partition
        dim (``axis_specs[axis].part_dim``). Monolithic mode — or a face
        under the granularity floor — is the degenerate single
        whole-face range, so callers can drive one loop for both modes.
        The fused in-kernel RDMA route (ops/stencil_fused_rdma) derives
        its per-sub-block remote-copy descriptors from THIS schedule, so
        the kernel's sends ride the same audited decomposition the
        partitioned ppermute exchange uses."""
        spec = self._spec(axis)
        pd = spec.part_dim
        extent = int(local_shape[pd])
        if self.mode != "partitioned":
            return ((0, extent),)
        face_shape = tuple(
            self.width if d == axis else int(local_shape[d])
            for d in range(3)
        )
        return partition_bounds(
            extent, self._face_partitions(face_shape, itemsize)
        )

    # ---- cost/footprint metadata -----------------------------------------

    def messages_per_exchange(self) -> int:
        """Collectives (or DMA pairs) one full exchange issues per device
        at the SCHEDULE ceiling (the granularity floor may ship small
        faces whole — :meth:`traffic` prices the shape-aware count)."""
        n = 0
        for spec in self.axis_specs:
            if spec.size <= 1:
                continue
            if self.mode == "partitioned":
                n += 2 * self.partitions
            else:
                n += 2
        return n

    def traffic(self, local_shape, itemsize: int) -> Dict[str, int]:
        """Per-device transport model of ONE exchange: messages issued and
        boundary bytes sent, accounting the progressive face extension
        axis ordering implies (later faces carry earlier ghosts) and the
        partition granularity floor. The roofline's planned-exchange arm
        and the halo bench rows record this beside XLA's cost-analysis
        bytes."""
        ext = list(local_shape)
        w = self.width
        messages = 0
        bytes_sent = 0
        for spec in self.axis_specs:
            if spec.size > 1:
                face_shape = [
                    w if d == spec.axis else ext[d] for d in range(3)
                ]
                face = face_shape[0] * face_shape[1] * face_shape[2]
                if self.mode == "partitioned":
                    nparts = len(partition_bounds(
                        ext[spec.part_dim],
                        self._face_partitions(face_shape, itemsize),
                    ))
                else:
                    nparts = 1
                messages += 2 * nparts
                bytes_sent += 2 * face * itemsize
            if self.halo_order == "axis":
                ext[spec.axis] += 2 * w
        return {"messages": messages, "bytes_per_device": bytes_sent}

    def describe(self) -> Dict[str, Any]:
        """The built-plan record (the ``exchange_plan_built`` payload)."""
        return {
            "mesh": list(self.mesh.shape),
            "bc": self.bc.value,
            "width": self.width,
            "halo_order": self.halo_order,
            "transport": self.transport,
            "mode": self.mode,
            "partitions": (
                self.partitions if self.mode == "partitioned" else 1
            ),
            "min_part_bytes": self.min_part_bytes,
            "messages_per_exchange": self.messages_per_exchange(),
        }


def build_plan(
    mesh_cfg: MeshConfig,
    bc: BoundaryCondition,
    width: int = 1,
    halo_order: str = "axis",
    transport: str = "ppermute",
    mode: str = "monolithic",
    partitions: int = DEFAULT_PARTITIONS,
    min_part_bytes: Optional[int] = None,
) -> ExchangePlan:
    """Uncached plan constructor: precompute every permutation, the axis
    schedule and the partition dims for this exchange shape."""
    if mode not in ("monolithic", "partitioned"):
        raise ValueError(
            f"plan mode must be monolithic|partitioned, got {mode!r} "
            "(resolve 'auto' through the tuning cache before building)"
        )
    if mode == "partitioned" and transport != "ppermute":
        raise ValueError(
            "halo_plan='partitioned' applies to the ppermute transport; "
            "the DMA slab kernels ship whole faces by construction"
        )
    periodic = bc is BoundaryCondition.PERIODIC
    specs = []
    for axis, (name, size) in enumerate(
        zip(mesh_cfg.axis_names, mesh_cfg.shape)
    ):
        if size > 1:
            up = tuple(shift_perm(size, +1, periodic))
            down = tuple(shift_perm(size, -1, periodic))
        else:
            up = down = None
        # partition along the first in-plane dim (x faces split along y,
        # y/z faces along x): a fixed rule the IR partition checker can
        # re-derive from the sub-block shapes alone
        part_dim = min(d for d in range(3) if d != axis)
        specs.append(
            AxisExchangeSpec(
                axis=axis, name=name, size=size,
                perm_up=up, perm_down=down, part_dim=part_dim,
            )
        )
    return ExchangePlan(
        mesh=mesh_cfg,
        bc=bc,
        width=int(width),
        halo_order=halo_order,
        transport=transport,
        mode=mode,
        partitions=int(partitions),
        min_part_bytes=(
            part_min_bytes() if min_part_bytes is None else int(min_part_bytes)
        ),
        axis_specs=tuple(specs),
    )


# ---- the process plan cache -------------------------------------------------

_PLAN_CACHE: Dict[Tuple, ExchangePlan] = {}

# per-run dedup of the audit events: exchange() runs several times per
# trace (ping-pong loop bodies, residual programs, phase programs), and
# the reuse contract is "one exchange_plan_built per plan key per run"
_EVENT_ONCE: set = set()


def _event_once(name: str, key: str, **fields: Any) -> None:
    from heat3d_tpu import obs

    ledger = obs.get()
    tag = (ledger.run_id, name, key)
    if tag in _EVENT_ONCE:
        return
    _EVENT_ONCE.add(tag)
    ledger.event(name, key=key, **fields)


def clear_plan_cache() -> None:
    """Drop every cached plan (tests; plans are content-addressed, so
    production never needs this)."""
    _PLAN_CACHE.clear()


def drop_plans_for_mesh(mesh_shape) -> int:
    """Forget every cached plan keyed to ``mesh_shape`` — the elastic
    re-factorization hook (resilience/elastic.py): after a survivor-mesh
    re-plan the dead mesh's precomputed permutations can never be
    exchanged again in this process, and the survivor mesh builds fresh
    plans (audited by their own ``exchange_plan_built`` events). Returns
    how many plans were dropped."""
    shape = tuple(mesh_shape)
    gone = [k for k in _PLAN_CACHE if k[0] == shape]
    for k in gone:
        del _PLAN_CACHE[k]
    return len(gone)


def resolve_halo_plan(cfg: SolverConfig) -> str:
    """The concrete plan mode for ``cfg``: the tuning cache resolves
    ``'auto'`` at the entry points (tune.cache.resolve_config); any
    ``'auto'`` still standing here takes the static fallback
    (monolithic) — same belt-and-braces posture as the other knobs."""
    mode = getattr(cfg, "halo_plan", "monolithic")
    return "monolithic" if mode == "auto" else mode


def effective_halo_plan(cfg: SolverConfig) -> str:
    """The plan mode that actually EXECUTES for ``cfg`` in the current
    env: ``'auto'`` takes the static fallback, and ``HEAT3D_NO_PLAN``
    degrades partitioned to the ad-hoc monolithic schedule. Bench rows
    and sweep journals record THIS value — provenance must say which
    schedule ran, not which was requested (a requested-partitioned row
    measured on the ad-hoc path masquerading as partitioned would
    corrupt the very A/B the knob exists for)."""
    if os.environ.get(ENV_NO_PLAN):
        return "monolithic"
    return resolve_halo_plan(cfg)


def plan_for(cfg: SolverConfig, width: int = 1) -> ExchangePlan:
    """The cached plan for ``cfg``'s exchange at ``width`` ghost layers.

    Cache key = everything that shapes the exchange (mesh, BC, width,
    ordering, transport, plan mode) and nothing that doesn't (bc_value,
    dtype, grid size — the plan is shape-agnostic until ``apply``).
    Emits ``exchange_plan_built`` on a genuine build and
    ``plan_cache_hit`` on reuse, each once per (run, plan key)."""
    mode = resolve_halo_plan(cfg)
    transport = "dma" if cfg.halo == "dma" else "ppermute"
    key = (
        cfg.mesh.shape,
        cfg.mesh.axis_names,
        cfg.stencil.bc,
        int(width),
        cfg.halo_order,
        transport,
        mode,
        DEFAULT_PARTITIONS,
        part_min_bytes(),  # env-overridable floor keys its own plans
    )
    plan = _PLAN_CACHE.get(key)
    if plan is not None:
        _event_once("plan_cache_hit", plan.key)
        return plan
    plan = build_plan(
        cfg.mesh,
        cfg.stencil.bc,
        width=width,
        halo_order=cfg.halo_order,
        transport=transport,
        mode=mode,
    )
    _PLAN_CACHE[key] = plan
    _event_once("exchange_plan_built", plan.key, **plan.describe())
    return plan


def adhoc_exchange(u, cfg: SolverConfig, width: int = 1, bc_value: Any = None):
    """The pre-plan dispatch, kept verbatim as the ``HEAT3D_NO_PLAN``
    escape hatch and the reference arm of the plan-vs-ad-hoc parity
    tests (``halo_plan='partitioned'`` degrades to the monolithic ad-hoc
    structure here — the legacy path has no partitioned form)."""
    bcv = cfg.stencil.bc_value if bc_value is None else bc_value
    if cfg.halo == "dma":
        from heat3d_tpu.ops.halo_pallas import exchange_halo_dma

        return exchange_halo_dma(
            u, cfg.mesh, cfg.stencil.bc, bcv, width=width
        )
    if cfg.halo_order == "pairwise":
        return exchange_halo_pairwise(
            u, cfg.mesh, cfg.stencil.bc, bcv, width
        )
    return exchange_halo(u, cfg.mesh, cfg.stencil.bc, bcv, width)


def exchange_with_plan(
    u, cfg: SolverConfig, width: int = 1, bc_value: Any = None
):
    """Plan-routed ghost exchange: the ONE entry every step, superstep,
    phase, bench and ensemble program goes through. Must run inside
    shard_map over ``cfg.mesh``."""
    if os.environ.get(ENV_NO_PLAN):
        return adhoc_exchange(u, cfg, width, bc_value)
    bcv = cfg.stencil.bc_value if bc_value is None else bc_value
    return plan_for(cfg, width).apply(u, bcv)
