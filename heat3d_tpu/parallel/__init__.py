"""Distributed layer: the TPU-native communication backend.

Replaces the reference's CUDA-aware MPI stack wholesale (SURVEY.md §2
C2/C3/C6, §5 "Distributed communication backend"):

- ``topology``    — jax.sharding.Mesh Cartesian topology (MPI_Cart_create)
- ``halo``        — axis-ordered ppermute ghost-cell exchange
  (MPI_Isend/Irecv/Waitall + pack/unpack kernels)
- ``step``        — shard_map-ped distributed stencil step + psum residual
  (MPI_Allreduce)
- ``distributed`` — multi-host bootstrap (mpirun -> jax.distributed)
- ``halo_pallas`` — hand-rolled ICI DMA halo tier
  (pltpu.make_async_remote_copy — the GPUDirect RDMA analogue)
"""

from heat3d_tpu.parallel.topology import abstract_mesh, build_mesh, partition_spec
from heat3d_tpu.parallel.halo import exchange_halo
from heat3d_tpu.parallel.step import (
    exchange,
    make_converge_fn,
    make_multistep_fn,
    make_step_fn,
    make_superstep_fn,
)
