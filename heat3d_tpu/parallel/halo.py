"""Ghost-cell halo exchange over ICI — the CUDA-aware MPI_Isend/Irecv analogue.

Reference parity (SURVEY.md §2 C2, §3.2): per face the reference packs the
boundary layer into a contiguous device buffer, posts CUDA-aware
Isend/Irecv with device pointers, Waitalls, and unpacks into the ghost
layer. The TPU-native formulation is one ``lax.ppermute`` per (axis,
direction) inside ``shard_map``: XLA compiles each permute into an ICI DMA
between neighbor chips — pack/unpack, transport, and sync all collapse
into the collective.

Key structural property: exchanges are **axis-ordered** (x, then y, then
z), each operating on the array *already padded by previous axes*. The face
slabs therefore carry prior ghosts with them, which propagates edge- and
corner-ghost data in 3 exchanges instead of 26 — required by the 27-point
stencil (SURVEY.md §7.3 item 1) and exactly equivalent to a global
pad-then-shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from heat3d_tpu.core.config import BoundaryCondition, MeshConfig
from heat3d_tpu.obs.trace import named_phase


def shift_perm(n: int, direction: int, periodic: bool):
    """Permutation (source, dest) pairs shifting data one step along a ring
    of size n. ``direction=+1``: device i's slab goes to device i+1 (so the
    receiver sees its *low*-side neighbor's face). Non-periodic drops the
    wrap pair; undelivered ppermute outputs are zero-filled, which is the
    Dirichlet-0 ghost for free (nonzero BC values are patched by the
    caller).

    Public because it IS the mesh neighbor graph: the IR collective-
    topology checker (``heat3d lint --ir``, analysis/ir/collectives.py)
    proves every traced ppermute permutation equals one of these shifts
    — verifying the compiled exchange against the same source of truth
    the exchange is built from."""
    if periodic:
        return [(i, (i + direction) % n) for i in range(n)]
    if direction > 0:
        return [(i, i + 1) for i in range(n - 1)]
    return [(i, i - 1) for i in range(1, n)]


def exchange_axis(
    u: jax.Array,
    axis: int,
    axis_name: str,
    axis_size: int,
    periodic: bool,
    bc_value: float = 0.0,
    width: int = 1,
    ghosts_fn=None,
) -> jax.Array:
    """Pad local block ``u`` with ``width`` ghost layers along ``axis``,
    filled from the neighbors along mesh axis ``axis_name`` (or the BC at
    the domain boundary). Must run inside shard_map. Returns u grown by
    2*width on ``axis``. width > 1 serves temporal blocking (several stencil
    applications per exchange — fewer, larger messages).

    ``ghosts_fn`` overrides the communication core (an
    :class:`~heat3d_tpu.parallel.plan.ExchangePlan` supplies its
    precomputed-permutation or partitioned-sub-block form); signature
    ``(lo_face, hi_face, axis, axis_name, axis_size, periodic, bc_value)``,
    default :func:`axis_ghosts` (which ignores ``axis``)."""
    n = u.shape[axis]
    if n < width:
        raise ValueError(
            f"halo width {width} exceeds local extent {n} on axis {axis}"
        )
    # per-axis scope nested under heat3d.halo_exchange: trace tools can
    # attribute ICI time to the axis whose permutes carry it
    with named_phase(f"halo.{axis_name}"):
        lo_face = lax.slice_in_dim(u, 0, width, axis=axis)
        hi_face = lax.slice_in_dim(u, n - width, n, axis=axis)
        if ghosts_fn is None:
            ghost_lo, ghost_hi = axis_ghosts(
                lo_face, hi_face, axis_name, axis_size, periodic, bc_value
            )
        else:
            ghost_lo, ghost_hi = ghosts_fn(
                lo_face, hi_face, axis, axis_name, axis_size, periodic,
                bc_value,
            )
        return lax.concatenate([ghost_lo, u, ghost_hi], dimension=axis)


def axis_ghosts(
    lo_face: jax.Array,
    hi_face: jax.Array,
    axis_name: str,
    axis_size: int,
    periodic: bool,
    bc_value: float = 0.0,
    perms=None,
):
    """The communication core of one axis exchange: given my two boundary
    faces, return my two ghost faces (neighbor data, wrap, or the BC).
    Must run inside shard_map. ``perms`` takes precomputed
    ``(shift_perm(+1), shift_perm(-1))`` pairs (an
    :class:`~heat3d_tpu.parallel.plan.ExchangePlan` builds them once per
    run instead of once per trace); ``None`` derives them in place —
    identical values either way."""
    if axis_size == 1 and periodic:
        # self-wrap: my own faces are my ghosts
        return hi_face, lo_face
    if axis_size == 1:
        return (
            jnp.full_like(lo_face, bc_value),
            jnp.full_like(hi_face, bc_value),
        )
    if perms is None:
        perm_up = shift_perm(axis_size, +1, periodic)
        perm_down = shift_perm(axis_size, -1, periodic)
    else:
        perm_up, perm_down = perms
    # per-direction scopes nested under halo.<axis>: each ppermute's
    # device time attributes to the LINK that carried it ("lo" = the
    # transfer filling my low ghost — the same link key the comm-probe
    # rows and the link_straggler detector use; normalize_phase folds
    # halo.* back into halo_exchange for the coarse joins)
    with named_phase(f"halo.{axis_name}.lo"):
        # my low ghost = low neighbor's high face: shift high faces "up" (+1)
        ghost_lo = lax.ppermute(hi_face, axis_name, perm_up)
    with named_phase(f"halo.{axis_name}.hi"):
        # my high ghost = high neighbor's low face: shift low faces "down" (-1)
        ghost_hi = lax.ppermute(lo_face, axis_name, perm_down)
    return substitute_domain_bc(
        ghost_lo, ghost_hi, axis_name, axis_size, periodic, bc_value
    )


def substitute_domain_bc(
    ghost_lo: jax.Array,
    ghost_hi: jax.Array,
    axis_name: str,
    axis_size: int,
    periodic: bool,
    bc_value=0.0,
):
    """Domain-edge BC substitution over freshly exchanged ghost faces —
    the ONE tail every ppermute-built ghost pair (monolithic or
    partitioned sub-block assembly, parallel/plan.py) runs, so the edge
    semantics cannot diverge between plan modes. bc_value may be a
    TRACED scalar (the batched ensemble path threads a per-member
    boundary value through vmap — serve/ensemble.py); the 0.0 fast path
    then cannot be decided at trace time, and substituting
    unconditionally is value-identical (undelivered ppermute outputs are
    zero-filled, so where(edge, 0.0, ghost) == ghost)."""
    if not periodic and (isinstance(bc_value, jax.Array) or bc_value != 0.0):
        idx = lax.axis_index(axis_name)
        ghost_lo = jnp.where(idx == 0, jnp.full_like(ghost_lo, bc_value), ghost_lo)
        ghost_hi = jnp.where(
            idx == axis_size - 1, jnp.full_like(ghost_hi, bc_value), ghost_hi
        )
    return ghost_lo, ghost_hi


def exchange_halo(
    u: jax.Array,
    mesh_cfg: MeshConfig,
    bc: BoundaryCondition,
    bc_value: float = 0.0,
    width: int = 1,
    ghosts_fn=None,
) -> jax.Array:
    """Full 3D ghost exchange: local (nx,ny,nz) -> (nx+2w,ny+2w,nz+2w),
    ghosts filled from mesh neighbors / the boundary condition. Axis-ordered
    so the result equals a global pad-then-shard (corner ghosts included).
    Must run inside shard_map over the mesh in ``mesh_cfg``. ``ghosts_fn``
    swaps the per-axis communication core (see :func:`exchange_axis`)."""
    periodic = bc is BoundaryCondition.PERIODIC
    for axis, (axis_name, axis_size) in enumerate(
        zip(mesh_cfg.axis_names, mesh_cfg.shape)
    ):
        u = exchange_axis(
            u, axis, axis_name, axis_size, periodic, bc_value, width,
            ghosts_fn=ghosts_fn,
        )
    return u


def exchange_halo_pairwise(
    u: jax.Array,
    mesh_cfg: MeshConfig,
    bc: BoundaryCondition,
    bc_value: float = 0.0,
    width: int = 1,
    ghosts_fn=None,
) -> jax.Array:
    """Neighbor-pairwise ghost exchange: all six face ppermutes issued
    concurrently from the RAW boundary faces, with no cross-axis data
    dependence — the stagger-tolerant ordering (a host arriving one
    exchange latency late delays only its own pairs, not a 3-deep axis
    chain; ROADMAP "skew-aware halo tuning"). The price: corner and edge
    ghost regions carry ``bc_value`` instead of diagonal-neighbor data,
    so this ordering is only valid for face-only stencils (7pt) at
    ``time_blocking <= 1`` — ``SolverConfig.__post_init__`` enforces it.
    For those configs the padded result is value-identical to
    :func:`exchange_halo` on every cell the stencil reads (a step's
    output may still differ in final-ulp rounding: the differently
    shaped pad/concat graph can change XLA's fusion/FMA contraction).
    Must run inside shard_map over the mesh in ``mesh_cfg``."""
    periodic = bc is BoundaryCondition.PERIODIC
    with named_phase("halo_exchange"):
        ghosts = []
        for axis, (name, size) in enumerate(
            zip(mesh_cfg.axis_names, mesh_cfg.shape)
        ):
            n = u.shape[axis]
            if n < width:
                raise ValueError(
                    f"halo width {width} exceeds local extent {n} on "
                    f"axis {axis}"
                )
            lo = lax.slice_in_dim(u, 0, width, axis=axis)
            hi = lax.slice_in_dim(u, n - width, n, axis=axis)
            # every axis_ghosts call reads only the RAW faces of u: the
            # six permutes have no data dependence on each other, so
            # XLA is free to run them all concurrently
            if ghosts_fn is None:
                ghosts.append(
                    axis_ghosts(lo, hi, name, size, periodic, bc_value)
                )
            else:
                ghosts.append(
                    ghosts_fn(lo, hi, axis, name, size, periodic, bc_value)
                )
        out = u
        for axis, (glo, ghi) in enumerate(ghosts):
            # earlier axes already grew `out` by 2*width; the raw-face
            # ghosts are padded with bc_value over those extents (the
            # corner/edge zones a face-only stencil never reads)
            pads = [
                (width, width) if prev < axis else (0, 0)
                for prev in range(3)
            ]
            if any(p != (0, 0) for p in pads):
                glo = jnp.pad(glo, pads, constant_values=bc_value)
                ghi = jnp.pad(ghi, pads, constant_values=bc_value)
            out = lax.concatenate([glo, out, ghi], dimension=axis)
        return out


def exchange_halo_faces(
    u: jax.Array,
    mesh_cfg: MeshConfig,
    bc: BoundaryCondition,
    bc_value: float = 0.0,
    width: int = 1,
    x_ghosts=None,
):
    """Faces-only ghost exchange: the six width-``w`` ghost faces of the
    axis-ordered exchange WITHOUT materializing the padded volume (whose
    concatenate is a full read+write of the field — the dominant HBM cost
    of the exchange path; see ops/stencil_pallas_direct.py).

    Returns ``(xlo, xhi, ylo, yhi, zlo, zhi)`` with the progressive
    extension the axis ordering implies: x faces are raw (w, ny, nz), y
    faces x-extended (nx+2w, w, nz), z faces x+y-extended
    (nx+2w, ny+2w, w) — exactly the slices the width-w padded array would
    have, corners included (the later-axis send faces are built by
    concatenating the earlier ghosts onto the boundary slab, which is how
    corner data propagates here). Must run inside shard_map over the mesh
    in ``mesh_cfg``.

    ``x_ghosts`` = (xlo, xhi), each (w, ny, nz): x ghost faces already
    landed by another transport (the fused DMA-overlap kernel's in-sweep
    RDMA — parallel/step._local_step_fused_dma_3d), domain-BC values
    already substituted at x-edge devices. The x ppermutes are skipped and
    the y/z propagation proceeds from the supplied faces, so corner data
    still flows x -> y -> z exactly as in the pure-ppermute form."""
    periodic = bc is BoundaryCondition.PERIODIC
    names, sizes = mesh_cfg.axis_names, mesh_cfg.shape
    w = width
    if min(u.shape) < w:
        raise ValueError(
            f"halo width {w} exceeds a local extent of {u.shape}"
        )
    with named_phase("halo_exchange"):
        return _exchange_halo_faces(
            u, names, sizes, periodic, bc_value, w, x_ghosts
        )


def _exchange_halo_faces(u, names, sizes, periodic, bc_value, w, x_ghosts):
    if x_ghosts is not None:
        xlo, xhi = x_ghosts
    else:
        xlo, xhi = axis_ghosts(
            u[:w], u[-w:], names[0], sizes[0], periodic, bc_value
        )
    # y send faces carry the x ghosts (corner propagation)
    y_lo_send = lax.concatenate([xlo[:, :w], u[:, :w], xhi[:, :w]], 0)
    y_hi_send = lax.concatenate([xlo[:, -w:], u[:, -w:], xhi[:, -w:]], 0)
    ylo, yhi = axis_ghosts(
        y_lo_send, y_hi_send, names[1], sizes[1], periodic, bc_value
    )
    # z send faces carry the x AND y ghosts
    mid_lo = lax.concatenate([xlo[:, :, :w], u[:, :, :w], xhi[:, :, :w]], 0)
    mid_hi = lax.concatenate([xlo[:, :, -w:], u[:, :, -w:], xhi[:, :, -w:]], 0)
    z_lo_send = lax.concatenate([ylo[:, :, :w], mid_lo, yhi[:, :, :w]], 1)
    z_hi_send = lax.concatenate([ylo[:, :, -w:], mid_hi, yhi[:, :, -w:]], 1)
    zlo, zhi = axis_ghosts(
        z_lo_send, z_hi_send, names[2], sizes[2], periodic, bc_value
    )
    return xlo, xhi, ylo, yhi, zlo, zhi
