"""Multi-host bootstrap — the mpirun replacement.

Reference parity (SURVEY.md §1 L5, §3.1): the reference is launched as
``mpirun -np P ./heat3d ...`` and calls MPI_Init to join the world. The
TPU-native equivalent is one Python process per host running the same
module, rendezvousing through ``jax.distributed.initialize`` (BASELINE.json
north star: "the existing mpirun driver is replaced by a jax.distributed
entrypoint"). On a single host this is a no-op; on a pod slice the TPU
runtime supplies coordinates, and on plain multi-host the standard
environment variables do (set by scripts/run_multihost.sh).
"""

from __future__ import annotations

import os
from typing import Optional

import jax

_INITIALIZED = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the distributed world if one is configured; otherwise no-op.

    Resolution order: explicit args > HEAT3D_* env vars > JAX's own
    autodetection (TPU pod runtime). Safe to call more than once.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get("HEAT3D_COORDINATOR")
    if num_processes is None and "HEAT3D_NUM_PROCESSES" in os.environ:
        num_processes = int(os.environ["HEAT3D_NUM_PROCESSES"])
    if process_id is None and "HEAT3D_PROCESS_ID" in os.environ:
        process_id = int(os.environ["HEAT3D_PROCESS_ID"])

    if coordinator_address is None and num_processes is None:
        # Single-process (or TPU-pod auto-config when env provides it).
        if os.environ.get("HEAT3D_AUTO_DISTRIBUTED"):
            jax.distributed.initialize()
            _INITIALIZED = True
        return

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _INITIALIZED = True


def process_index() -> int:
    return jax.process_index()


def is_coordinator() -> bool:
    """True on the rank-0 analogue — gate logging/IO on this
    (SURVEY.md §5 'Metrics / logging': rank-0 printf)."""
    return jax.process_index() == 0
