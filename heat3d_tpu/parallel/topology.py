"""Cartesian device topology — the MPI_Cart_create / MPI_Cart_shift analogue.

Reference parity (SURVEY.md §2 C3): the reference builds a 3D Cartesian
communicator and derives 6 neighbor ranks per rank. Here the topology is a
``jax.sharding.Mesh`` with axes ('x','y','z'); neighbor relationships are
implicit in the ppermute permutations built by ``parallel.halo``, and XLA
maps the logical mesh onto the physical TPU torus (the "maps directly onto
the v5p 3D torus mesh" part of BASELINE.json's north star).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import AbstractMesh, Mesh, NamedSharding, PartitionSpec

from heat3d_tpu.core.config import MeshConfig
from heat3d_tpu.utils.compat import make_abstract_mesh


def build_mesh(cfg: MeshConfig, devices: Optional[Sequence] = None) -> Mesh:
    """Build the device mesh for (Px, Py, Pz).

    With no explicit device list and a mesh spanning every visible device,
    defer to ``jax.make_mesh`` (which picks an ICI-friendly physical
    ordering on TPU). Otherwise take the first Px*Py*Pz devices in default
    order — the moral equivalent of MPI_Cart_create(reorder=0).
    """
    n = cfg.num_devices
    if devices is None:
        avail = jax.devices()
        if len(avail) == n:
            return jax.make_mesh(cfg.shape, cfg.axis_names)
        if len(avail) < n:
            raise ValueError(
                f"mesh {cfg.shape} needs {n} devices, only {len(avail)} visible"
            )
        devices = avail[:n]
    dev = np.asarray(devices).reshape(cfg.shape)
    return Mesh(dev, cfg.axis_names)


def abstract_mesh(cfg: MeshConfig) -> AbstractMesh:
    """Device-free mesh for compile-only lowering of multi-chip programs —
    how multi-chip paths are validated on a single-chip dev box
    (SURVEY.md §4 'Distributed-without-cluster', §7.0)."""
    return make_abstract_mesh(cfg.shape, cfg.axis_names)


def lower_for_mesh(fn, cfg: MeshConfig, *avals, platform: str = "tpu"):
    """Lower ``fn`` (built over ``abstract_mesh(cfg)``) for an N-device mesh
    with zero devices present, returning the Lowered object. The text of the
    lowering is what tests assert collectives/shardings on — the
    single-chip-dev-box substitute for running on a pod (SURVEY.md §4).
    Each aval is a (shape, dtype, PartitionSpec) triple or ShapeDtypeStruct.
    """
    am = abstract_mesh(cfg)
    args = []
    for a in avals:
        if isinstance(a, jax.ShapeDtypeStruct):
            args.append(a)
        else:
            shape, dtype, spec = a
            args.append(
                jax.ShapeDtypeStruct(shape, dtype, sharding=NamedSharding(am, spec))
            )
    return jax.jit(fn).trace(*args).lower(lowering_platforms=(platform,))


def partition_spec(cfg: MeshConfig) -> PartitionSpec:
    """The field's sharding: block-decompose all three spatial dims over the
    mesh axes — the direct image of the reference's 3D block decomposition."""
    return PartitionSpec(*cfg.axis_names)


def field_sharding(mesh: Mesh, cfg: MeshConfig) -> NamedSharding:
    return NamedSharding(mesh, partition_spec(cfg))
