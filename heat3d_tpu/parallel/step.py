"""The distributed stencil step: shard_map(halo exchange -> stencil -> psum).

Reference parity (SURVEY.md §3.2): one reference iteration is
``exchange_halos(u); jacobi_step<<<...>>>(u_new, u); swap; [residual +
MPI_Allreduce]``. Here the whole iteration is one SPMD program: ghost
exchange (ppermute), tap application (jnp slices or the Pallas kernel),
and the fp32 residual psum, all inside ``jax.shard_map`` over the
(x, y, z) mesh. The time loop wraps it in ``lax.fori_loop`` under jit, so
Python launches the entire run once (SURVEY.md §1 L4 mapping).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from heat3d_tpu.core.config import (
    BoundaryCondition,
    MeshConfig,
    Precision,
    SolverConfig,
)
from heat3d_tpu.core.stencils import STENCILS, effective_num_taps
from heat3d_tpu.obs.trace import named_phase, scoped
from heat3d_tpu.ops.stencil_jnp import apply_taps_padded, residual_sumsq
from heat3d_tpu.utils.compat import shard_map

# Local compute on a ghost-padded block: (up, taps, compute_dtype, out_dtype) -> interior
LocalCompute = Callable[..., jax.Array]

_logged_paths: set = set()


def _log_step_path_once(msg: str) -> None:
    """INFO-log a step-path selection once per process (make_step_fn is
    built several times per solver — step / residual / converge)."""
    if msg not in _logged_paths:
        _logged_paths.add(msg)
        from heat3d_tpu.utils.logging import get_logger

        get_logger(__name__).info("%s", msg)


def _solver_taps(cfg: SolverConfig) -> np.ndarray:
    """The config's update taps, via the declarative equation frontend
    (heat3d_tpu.eqn): the spec compiler lowers ``cfg.equation`` onto the
    stencil footprint — bit-identical to the old inline ``stencil_taps``
    call for the heat family (docs/EQUATIONS.md; ``HEAT3D_EQN_LEGACY=1``
    inside eqn keeps the verbatim legacy derivation as the parity arm)."""
    from heat3d_tpu import eqn

    return eqn.solver_taps(cfg)


def _pin_padding(
    u_new: jax.Array, cfg: SolverConfig, bc_value=None
) -> jax.Array:
    """For uneven decompositions, re-pin storage-padding cells (global index
    >= grid extent) to bc_value after each update. Real cells adjacent to
    the true boundary then read bc_value from their padded neighbors —
    exactly the Dirichlet ghost — and padded cells contribute zero to the
    residual (old == new == bc_value). Must run inside shard_map.
    ``bc_value`` overrides the config's (may be a TRACED scalar — the
    batched ensemble path's per-member boundary value, serve/ensemble)."""
    if not cfg.is_padded:
        return u_new
    if bc_value is None:
        bc_value = cfg.stencil.bc_value
    mask = None
    for axis, (name, g, n) in enumerate(
        zip(cfg.mesh.axis_names, cfg.grid.shape, cfg.local_shape)
    ):
        if g == cfg.padded_shape[axis]:
            continue
        global_idx = lax.axis_index(name) * n + jnp.arange(n)
        shape = [1, 1, 1]
        shape[axis] = n
        m = (global_idx < g).reshape(shape)
        mask = m if mask is None else jnp.logical_and(mask, m)
    return jnp.where(mask, u_new, jnp.asarray(bc_value, u_new.dtype))


def exchange(
    u_local: jax.Array, cfg: SolverConfig, width: int = 1
) -> jax.Array:
    """Ghost exchange via this config's persistent :class:`ExchangePlan`
    (heat3d_tpu.parallel.plan): transport (cfg.halo), ordering
    (cfg.halo_order) and plan mode (cfg.halo_plan — monolithic face
    collectives or partitioned early-bird sub-block sends) are all
    resolved ONCE per (mesh, bc, width, knobs) and reused by every step,
    superstep, phase and bench program in the process. ``HEAT3D_NO_PLAN``
    falls back to the legacy ad-hoc dispatch (bitwise-identical on the
    monolithic path — the parity tests' reference arm). The
    ``heat3d.halo_exchange`` named scope brackets every transport so a
    profiler trace attributes the permutes/DMAs to OUR phase, not to raw
    XLA op names (scripts/summarize_trace.py groups on it)."""
    from heat3d_tpu.parallel.plan import exchange_with_plan

    with named_phase("halo_exchange"):
        return exchange_with_plan(u_local, cfg, width)


def _pin_outside_domain(
    arr: jax.Array, cfg: SolverConfig, local_indices, bc_value=None
) -> jax.Array:
    """Pin cells of ``arr`` whose GLOBAL index lies outside the domain to
    bc_value (Dirichlet; periodic has no out-of-domain cells — wrap ghosts
    are genuine). ``local_indices[a]`` gives each dim's local indices
    (local i maps to global device_start + i). Must run inside shard_map.
    ``bc_value`` overrides the config's (may be a TRACED scalar — the
    batched ensemble path's per-member boundary value, serve/ensemble)."""
    if cfg.stencil.bc is BoundaryCondition.PERIODIC:
        return arr
    if bc_value is None:
        bc_value = cfg.stencil.bc_value
    mask = None
    for axis, (name, g, n) in enumerate(
        zip(cfg.mesh.axis_names, cfg.grid.shape, cfg.local_shape)
    ):
        global_idx = lax.axis_index(name) * n + local_indices[axis]
        m = jnp.logical_and(global_idx >= 0, global_idx < g)
        shape = [1, 1, 1]
        shape[axis] = arr.shape[axis]
        m = m.reshape(shape)
        mask = m if mask is None else jnp.logical_and(mask, m)
    return jnp.where(mask, arr, jnp.asarray(bc_value, arr.dtype))


def _fill_mid_ghosts(
    mid: jax.Array, cfg: SolverConfig, rings: int = 1, bc_value=None
) -> jax.Array:
    """Between the applications of a temporally-blocked superstep, pin the
    cells of the ring-carrying intermediate that are NOT true interior
    cells — global domain ghosts (Dirichlet rings) and uneven-decomposition
    padding — back to bc_value, exactly as the unfused sequence sees them.
    ``mid`` carries ``rings`` ghost rings: local index i maps to global
    index device_start + i - rings."""
    return _pin_outside_domain(
        mid,
        cfg,
        [jnp.arange(-rings, n + rings) for n in cfg.local_shape],
        bc_value=bc_value,
    )


def _local_stepk(
    u_local: jax.Array,
    taps: np.ndarray,
    cfg: SolverConfig,
    compute_padded: LocalCompute,
) -> jax.Array:
    """One temporally-blocked superstep: ``k = cfg.time_blocking`` stencil
    updates per ghost exchange — the overlapping-halo trick (exchange
    width-k ghosts, apply the stencil k times; application j consumes the
    ring application j-1 produced). Cuts ICI messages per update k-fold at
    the cost of recomputing shrinking ghost rings."""
    k = cfg.time_blocking
    compute_dtype = jnp.dtype(cfg.precision.compute)
    out_dtype = jnp.dtype(cfg.precision.storage)
    cur = exchange(u_local, cfg, width=k)
    with named_phase("stencil"):
        for j in range(k):
            cur = compute_padded(
                cur, taps, compute_dtype=compute_dtype, out_dtype=out_dtype
            )
            rings = k - 1 - j  # ghost rings still carried by cur
            if rings > 0:
                cur = _fill_mid_ghosts(cur, cfg, rings)
        return _pin_padding(cur, cfg)


def _local_step(
    u_local: jax.Array,
    taps: np.ndarray,
    cfg: SolverConfig,
    compute_padded: LocalCompute,
) -> jax.Array:
    up = exchange(u_local, cfg)
    with named_phase("stencil"):
        u_new = compute_padded(
            up,
            taps,
            compute_dtype=jnp.dtype(cfg.precision.compute),
            out_dtype=jnp.dtype(cfg.precision.storage),
        )
        return _pin_padding(u_new, cfg)


def _kernel_env_gate(cfg: SolverConfig, allow_partitioned_plan: bool = False):
    """Shared dispatch gate for the Mosaic kernel routes: returns
    ``(ok, interpret)`` — ok=False when the config/env rules out any real
    kernel (backend, padding, platform), interpret=True when
    HEAT3D_DIRECT_INTERPRET routes the kernel through the Pallas
    interpreter off-TPU (tests). ``allow_partitioned_plan`` is the fused
    RDMA route's carve-out: that kernel CONSUMES the partitioned plan
    (its sends ride the sub-block schedule), so the knob selects rather
    than vetoes it."""
    import os

    if cfg.backend not in ("pallas", "auto"):
        return False, False
    if cfg.is_padded:
        return False, False
    if cfg.halo_order != "axis":
        # the direct/fused kernel families synthesize or patch ghosts
        # assuming axis-ordered corner propagation; the pairwise ordering
        # A/B is an EXCHANGE-path knob, so it pins the exchange path
        return False, False
    if cfg.halo_plan == "partitioned" and not allow_partitioned_plan:
        # partitioned early-bird sends are likewise an exchange-path
        # structure (the kernels never issue per-face collectives to
        # partition) — the A/B must measure the exchange path, not
        # silently run a kernel that ignores the knob. The fused RDMA
        # kernel is the one route that implements the knob in-kernel
        # (per-sub-block remote-copy descriptors), so it passes
        # allow_partitioned_plan=True.
        return False, False
    interpret = bool(os.environ.get("HEAT3D_DIRECT_INTERPRET"))
    forced = bool(os.environ.get("HEAT3D_DIRECT_FORCE"))
    if not interpret and not forced and jax.devices()[0].platform != "tpu":
        return False, False
    return True, interpret


def _direct_kernel_fn(cfg: SolverConfig, halo: int, multichip: bool = False):
    """Return the BC-fused direct Pallas kernel for this config, or None.

    On a (1, 1, 1) mesh every shard boundary is a domain boundary, so the
    kernel can synthesize the ghosts in-register and skip the ghost-padded
    copy that ``exchange`` materializes (its concatenates are full-volume
    HBM writes) — halving (tb=1) or quartering (tb=2) traffic on the
    bandwidth-bound roofline. ``halo`` = updates fused per HBM sweep (1|2).

    With ``multichip=True`` (the faces+shells steps — _local_step_direct_faces
    for halo=1, _local_superstep_direct_faces for halo=2) any mesh
    qualifies: the kernel computes the bulk and the exchanged faces patch
    the shard-boundary shells.
    """
    import os

    if os.environ.get("HEAT3D_NO_DIRECT"):
        return None
    if not multichip and cfg.mesh.shape != (1, 1, 1):
        return None
    if cfg.halo != "ppermute":
        return None
    # overlap=True is satisfied BY the faces-direct step (the kernel has no
    # data dependence on the face ppermutes, so XLA runs them concurrently);
    # only the tb=2 superstep keeps its overlap mutual exclusion
    if cfg.overlap and halo != 1:
        return None
    # HEAT3D_DIRECT_INTERPRET exercises this dispatch path off-TPU (tests);
    # HEAT3D_DIRECT_FORCE selects the real (Mosaic) kernels off-TPU for
    # compile-only cross-lowering tests
    ok, interpret = _kernel_env_gate(cfg)
    if not ok:
        return None
    try:
        from heat3d_tpu.ops.stencil_pallas_direct import (
            apply_taps_direct,
            apply_taps_direct2,
            direct_supported,
        )
    except ImportError:
        return None
    itemsize = jnp.dtype(cfg.precision.storage).itemsize
    n_taps = effective_num_taps(STENCILS[cfg.stencil.kind].weights)
    c_item = jnp.dtype(cfg.precision.compute).itemsize
    # taps: weights suffice for the gate's mehrstellen predicate — the
    # update taps T = I + c*W decompose iff W does (affine in the center)
    if not direct_supported(
        cfg.local_shape, halo, itemsize, itemsize, n_taps, c_item,
        taps=STENCILS[cfg.stencil.kind].weights,
    ):
        return None
    import functools

    kernel = apply_taps_direct if halo == 1 else apply_taps_direct2
    return functools.partial(kernel, interpret=True) if interpret else kernel


def _padded_slab(
    u: jax.Array, faces, axis: int, start: int, w: int = 1,
    thickness: int = None,
) -> jax.Array:
    """``thickness``-thick slice [start, start+thickness) along ``axis`` of
    the VIRTUAL width-``w`` ghost-padded array (in padded coordinates),
    fully w-padded in the other two axes — reassembled from the local block
    and the six ``exchange_halo_faces(width=w)`` faces, without the padded
    volume ever existing. Default thickness 2w+1 (one output plane's
    dependence)."""
    thickness = thickness if thickness is not None else 2 * w + 1
    xlo, xhi, ylo, yhi, zlo, zhi = faces
    nx, ny, nz = u.shape
    s = slice(start, start + thickness)
    rng = range(start, start + thickness)
    if axis == 0:
        parts = []
        for p in rng:
            if p < w:
                parts.append(xlo[p : p + 1])
            elif p >= nx + w:
                parts.append(xhi[p - nx - w : p - nx - w + 1])
            else:
                parts.append(u[p - w : p - w + 1])
        core = lax.concatenate(parts, 0)  # (thickness, ny, nz)
        core = lax.concatenate([ylo[s], core, yhi[s]], 1)
        return lax.concatenate([zlo[s], core, zhi[s]], 2)
    if axis == 1:

        def xrow(p):  # x-extended row at padded y coord p: (nx+2w, 1, nz)
            if p < w:
                return ylo[:, p : p + 1]
            if p >= ny + w:
                return yhi[:, p - ny - w : p - ny - w + 1]
            q = p - w
            return lax.concatenate(
                [xlo[:, q : q + 1], u[:, q : q + 1], xhi[:, q : q + 1]], 0
            )

        core = lax.concatenate([xrow(p) for p in rng], 1)
        return lax.concatenate([zlo[:, s], core, zhi[:, s]], 2)

    def xycol(p):  # x+y-extended column at padded z coord p: (nx+2w, ny+2w, 1)
        if p < w:
            return zlo[:, :, p : p + 1]
        if p >= nz + w:
            return zhi[:, :, p - nz - w : p - nz - w + 1]
        q = p - w
        mid = lax.concatenate(
            [xlo[:, :, q : q + 1], u[:, :, q : q + 1], xhi[:, :, q : q + 1]], 0
        )
        return lax.concatenate(
            [ylo[:, :, q : q + 1], mid, yhi[:, :, q : q + 1]], 1
        )

    return lax.concatenate([xycol(p) for p in rng], 2)


def _local_step_direct_faces(
    u_local: jax.Array,
    taps: np.ndarray,
    cfg: SolverConfig,
    direct,
) -> jax.Array:
    """Multi-chip direct step: faces-only exchange + BC-fused bulk kernel +
    shard-boundary shell patches.

    The direct kernel sweeps the UNPADDED local block (its in-register
    domain-BC ghosts are exact on axes of mesh size 1, wrong only in the
    outermost shell of sharded axes), while the six ghost faces travel over
    ICI with no data dependence between them — XLA runs the collectives
    under the kernel. The thin shells of sharded axes are then recomputed
    from virtual padded slabs and patched in. Vs the exchange path this
    removes the full-volume padded concatenate (≈half the HBM traffic of a
    step) and overlaps comm with compute; vs the overlap split it needs no
    zero-init/interior DUS of the full volume. Arithmetic matches the
    unsplit step (same taps, same op order per cell) to FMA rounding.
    """
    from heat3d_tpu.parallel.halo import exchange_halo_faces

    periodic = cfg.stencil.bc is BoundaryCondition.PERIODIC
    compute_dtype = jnp.dtype(cfg.precision.compute)
    out_dtype = jnp.dtype(cfg.precision.storage)
    faces = exchange_halo_faces(
        u_local, cfg.mesh, cfg.stencil.bc, cfg.stencil.bc_value
    )
    out = direct(
        u_local,
        taps,
        periodic=periodic,
        bc_value=cfg.stencil.bc_value,
        compute_dtype=compute_dtype,
        out_dtype=out_dtype,
    )
    return _patch_boundary_shells(
        out, u_local, faces, taps, cfg, (0, 1, 2), compute_dtype, out_dtype
    )


def _patch_boundary_shells(
    out, u_local, faces, taps, cfg, axes, compute_dtype, out_dtype
):
    """Recompute the 1-deep shard-boundary shells of ``axes`` (where a
    bulk kernel's in-register ghost synthesis was wrong) from virtual
    padded slabs over the exchanged ``faces``, and patch them into
    ``out``. Axes of mesh size 1 are skipped — local BC/wrap synthesis is
    already exact there."""
    for axis in axes:
        if cfg.mesh.shape[axis] == 1:
            continue
        n = u_local.shape[axis]
        for start, pos in ((0, 0), (n - 1, n - 1)):
            slab = _padded_slab(u_local, faces, axis, start)
            shell = apply_taps_padded(
                slab, taps, compute_dtype=compute_dtype, out_dtype=out_dtype
            )
            idx = [0, 0, 0]
            idx[axis] = pos
            out = lax.dynamic_update_slice(out, shell, tuple(idx))
    return out


def _pin_slab_mid(
    mid: jax.Array, cfg: SolverConfig, axis: int, start: int
) -> jax.Array:
    """Dirichlet ghost pinning for a slab-shaped superstep intermediate:
    the slab analogue of _fill_mid_ghosts. ``mid`` carries one ghost ring;
    along ``axis`` its plane q maps to local index start + q - 1 (``start``
    in width-2 padded coordinates), on the other axes index r maps to local
    r - 1."""
    return _pin_outside_domain(
        mid,
        cfg,
        [
            start - 1 + jnp.arange(mid.shape[a])
            if a == axis
            else jnp.arange(mid.shape[a]) - 1
            for a in range(3)
        ],
    )


def _local_superstep_direct_faces(
    u_local: jax.Array,
    taps: np.ndarray,
    cfg: SolverConfig,
    direct2,
) -> jax.Array:
    """Multi-chip fused two-update superstep without the padded copy:
    width-2 faces-only exchange + BC-fused direct2 bulk kernel + 2-deep
    shard-boundary shell patches.

    The direct2 kernel's local ghost synthesis is wrong only where a
    two-step dependence (distance <= 2) reaches across a sharded axis — the
    outermost TWO planes per side. Those are recomputed from 6-thick
    virtual width-2 padded slabs (faces carry 2-deep neighbor data,
    corners included): apply taps, pin the slab intermediate's domain
    ghosts (storage-dtype round trip like the unfused sequence), apply taps
    again, patch in. One exchange and one HBM sweep per TWO updates."""
    from heat3d_tpu.parallel.halo import exchange_halo_faces

    periodic = cfg.stencil.bc is BoundaryCondition.PERIODIC
    compute_dtype = jnp.dtype(cfg.precision.compute)
    out_dtype = jnp.dtype(cfg.precision.storage)
    faces = exchange_halo_faces(
        u_local, cfg.mesh, cfg.stencil.bc, cfg.stencil.bc_value, width=2
    )
    out = direct2(
        u_local,
        taps,
        periodic=periodic,
        bc_value=cfg.stencil.bc_value,
        compute_dtype=compute_dtype,
        out_dtype=out_dtype,
    )
    for axis, size in enumerate(cfg.mesh.shape):
        if size == 1:
            continue  # kernel's local BC/wrap is already exact on this axis
        n = u_local.shape[axis]
        for start in (0, n - 2):  # width-2 padded coords; final planes
            # env-default route: the direct2 bulk kernel follows the
            # mehrstellen knob (q-ring variant), so patched cells follow
            # it too (cross-kernel ulp-match contract)
            slab = _padded_slab(u_local, faces, axis, start, w=2, thickness=6)
            mid = apply_taps_padded(
                slab, taps, compute_dtype=compute_dtype, out_dtype=out_dtype
            )
            mid = _pin_slab_mid(mid, cfg, axis, start)
            shell = apply_taps_padded(
                mid, taps, compute_dtype=compute_dtype, out_dtype=out_dtype
            )
            idx = [0, 0, 0]
            idx[axis] = start  # local planes [start, start+2)
            out = lax.dynamic_update_slice(out, shell, tuple(idx))
    return out


def _fused_dma_route(cfg: SolverConfig, tb: int):
    """Shared resolver for the fused DMA-overlap routes: the tb=1 step
    kernel or the tb=2 superstep kernel, or None when the config/env/scope
    gates reject. One body so the two routes cannot drift."""
    ok, interpret = _kernel_env_gate(cfg)
    if not ok:
        return None
    try:
        from heat3d_tpu.ops.stencil_dma_fused import (
            apply_step_fused_dma,
            apply_superstep_fused_dma,
            fused_dma2_supported,
            fused_dma_supported,
            reference_fused_step_xla,
            reference_fused_superstep_xla,
        )
    except ImportError:
        return None
    supported, apply_fn, reference_fn = (
        (fused_dma_supported, apply_step_fused_dma, reference_fused_step_xla)
        if tb == 1
        else (
            fused_dma2_supported,
            apply_superstep_fused_dma,
            reference_fused_superstep_xla,
        )
    )
    itemsize = jnp.dtype(cfg.precision.storage).itemsize
    if not supported(
        cfg.local_shape,
        cfg.mesh.shape,
        _solver_taps(cfg),
        itemsize,
        itemsize,
        jnp.dtype(cfg.precision.compute).itemsize,
    ):
        return None
    if interpret:
        # Pallas' interpreter cannot discharge remote DMA on the
        # production 3-named-axis meshes (jax 0.9) — the off-TPU
        # emulation tier dispatches the kernels' pure-XLA reference
        # contracts (certified equal on the 1D ring, where interpret CAN
        # run the real kernels: tests/multidevice_checks.py)
        return reference_fn
    return apply_fn


def _fused_dma_fn(cfg: SolverConfig):
    """Return the fused DMA-overlap kernel entry for this config, or None.

    The route exists for overlap=True on the RDMA transport (SURVEY.md
    §7.1 item 7): one Pallas kernel issues the x-face remote copies, sweeps
    every x-interior output plane while they fly, and waits only for the
    two shard-boundary planes. Scope gates mirror the kernel's
    (ops/stencil_dma_fused.fused_dma_supported): 1D x-slab mesh, unpadded
    shards, either stencil family. HEAT3D_NO_DIRECT does NOT disable this
    route (deliberate asymmetry: that knob A/Bs the direct kernels against
    the exchange path; this route is selected explicitly by
    overlap+halo='dma')."""
    if not (cfg.overlap and cfg.halo == "dma"):
        return None
    return _fused_dma_route(cfg, tb=1)


def _fused_dma_3d_fn(cfg: SolverConfig):
    """Return the fused DMA-overlap kernel entry for an x-sharded 3D/2D
    block mesh, or None. Mutually exclusive with _fused_dma_fn's x-slab
    scope (fused_dma_3d_supported requires a sharded y or z axis); the
    step wrapper is _local_step_fused_dma_3d, which patches the y/z
    shard-boundary shells the kernel's domain-BC synthesis got wrong.
    tb=2 is out of scope — the 3D superstep keeps the faces-direct route
    (make_superstep_fn)."""
    if not (cfg.overlap and cfg.halo == "dma"):
        return None
    ok, interpret = _kernel_env_gate(cfg)
    if not ok:
        return None
    try:
        from heat3d_tpu.ops.stencil_dma_fused import (
            apply_step_fused_dma,
            fused_dma_3d_supported,
            reference_fused_step_xla,
        )
    except ImportError:
        return None
    itemsize = jnp.dtype(cfg.precision.storage).itemsize
    if not fused_dma_3d_supported(
        cfg.local_shape,
        cfg.mesh.shape,
        _solver_taps(cfg),
        itemsize,
        itemsize,
        jnp.dtype(cfg.precision.compute).itemsize,
    ):
        return None
    if interpret:
        # Pallas' interpreter cannot discharge remote DMA on a
        # >1-named-axis mesh (jax 0.9), so the off-TPU emulation tier
        # runs the kernel's pure-XLA reference contract instead — the
        # glue (face seeding + shell patches) stays the production code
        return reference_fused_step_xla
    return apply_step_fused_dma


def _fused_streamk_fn(cfg: SolverConfig):
    """Return the fused k-sweep streaming kernel entry for this config's
    ``time_blocking = k`` (2 <= k <= 4), or None.

    One width-k ghost exchange, then ONE HBM sweep applies the stencil k
    times with shrinking ghost rings resident in VMEM
    (ops/stencil_pallas.apply_taps_pallas_streamk — the k-generalization
    of the stream2 kernel; at k=2 this IS the exchange-path fused
    two-update route, dispatched after the no-padded-copy direct2
    kernel). Gated by the shared ``_kernel_env_gate`` (backend, padding,
    halo_order, platform/emulation env) plus the kernel's own VMEM
    feasibility; off-TPU with no emulation env the route stands down and
    the jnp ring-recompute superstep (_local_stepk) runs instead."""
    k = cfg.time_blocking
    if k not in (2, 3, 4):
        return None
    if cfg.overlap:
        # the overlap branch of make_superstep_fn (fused DMA-overlap tb=2
        # or the mutual-exclusion error) runs before any streamk dispatch
        return None
    ok, interpret = _kernel_env_gate(cfg)
    if not ok:
        return None
    try:
        from heat3d_tpu.ops.stencil_pallas import (
            apply_taps_pallas_streamk,
            streamk_supported,
        )
    except ImportError:
        return None
    itemsize = jnp.dtype(cfg.precision.storage).itemsize
    n_taps = effective_num_taps(STENCILS[cfg.stencil.kind].weights)
    c_item = jnp.dtype(cfg.precision.compute).itemsize
    if not streamk_supported(
        cfg.local_shape, k, itemsize, itemsize, n_taps, c_item
    ):
        return None
    import functools

    if interpret:
        return functools.partial(apply_taps_pallas_streamk, interpret=True)
    return apply_taps_pallas_streamk


def superstep_cell_updates(cfg: SolverConfig) -> tuple:
    """(raw, effective) cell updates ONE superstep call executes per
    device — the honest accounting of deep temporal blocking's redundant
    ring recompute.

    ``effective`` is the k useful sweeps over the local block (what the
    simulation advances); ``raw`` is the recompute trapezoid every
    tb-superstep implementation pays — application j (of k) updates the
    (n + 2r)-extent slab still carrying r = k-1-j ghost rings, whether
    as jnp ring recompute (_local_stepk), the fused streaming kernels'
    in-VMEM stages, or the direct kernels' synthesized-ghost sweeps. At
    k <= 1 raw == effective. Fractions derived from these are
    scale-free per device, so they also describe the whole mesh."""
    k = max(1, cfg.time_blocking)
    nx, ny, nz = cfg.local_shape
    effective = k * nx * ny * nz
    raw = sum(
        (nx + 2 * r) * (ny + 2 * r) * (nz + 2 * r) for r in range(k)
    )
    return raw, effective


def redundant_flops_frac(cfg: SolverConfig) -> float:
    """Fraction of a superstep's executed stencil FLOPs that are
    redundant ghost-ring recompute (0.0 at time_blocking <= 1) — the
    ``cost_redundant_flops_frac`` bench-row field and the roofline
    report's raw-vs-effective discount. A tb=k "win" whose measured
    Gcell/s rides mostly on this recompute is visible as a large frac
    next to a modest effective rate."""
    raw, effective = superstep_cell_updates(cfg)
    return 0.0 if raw <= effective else 1.0 - effective / raw


def _fused_dma2_fn(cfg: SolverConfig):
    """The tb=2 analogue of _fused_dma_fn: the fused two-update superstep
    with the width-2 halo DMA overlapped under the phase-A sweep, for
    overlap=True + halo='dma' + time_blocking=2 on an x-slab mesh."""
    if not (cfg.overlap and cfg.halo == "dma" and cfg.time_blocking == 2):
        return None
    return _fused_dma_route(cfg, tb=2)


def resolve_fused_rdma(cfg: SolverConfig) -> str:
    """The concrete fused-RDMA knob value for ``cfg`` in the current env:
    ``HEAT3D_FUSED_RDMA`` overrides the config field (the A/B escape
    hatch — '1'/'on'/'true' asks for the route, '0'/'off' stands it
    down), and any ``'auto'`` still standing here takes the static
    fallback (off) — same belt-and-braces posture as the other auto
    knobs (tune.cache resolves 'auto' at the entry points)."""
    import os

    env = os.environ.get("HEAT3D_FUSED_RDMA")
    if env is not None:
        return (
            "on"
            if env.strip().lower() in ("1", "on", "true", "yes")
            else "off"
        )
    mode = getattr(cfg, "fused_rdma", "off")
    return "off" if mode == "auto" else mode


def _fused_rdma_route(cfg: SolverConfig, tb: int):
    """Shared resolver for the fused in-kernel RDMA superstep routes
    (ops/stencil_fused_rdma — the plan-scheduled sibling of the fused
    DMA-overlap family): the tb=1 step kernel or the tb=2 superstep
    kernel with ``plan`` bound, or None when the knob/config/env/scope
    gates reject. Unlike the fused-DMA route this one is selected by an
    explicit knob (``fused_rdma='on'`` / HEAT3D_FUSED_RDMA) rather than
    by overlap+halo='dma', and it is the one kernel route that CONSUMES
    ``halo_plan='partitioned'`` (per-sub-block remote-copy descriptors
    ride the plan's schedule), so it passes the gate's
    allow_partitioned_plan carve-out."""
    if resolve_fused_rdma(cfg) != "on":
        return None
    if cfg.overlap or cfg.halo == "dma":
        # those knobs select the fused-DMA family; config validation
        # rejects the combination, and an env-forced 'on' defers the
        # same way rather than fight the explicit transport choice
        return None
    ok, interpret = _kernel_env_gate(cfg, allow_partitioned_plan=True)
    if not ok:
        return None
    try:
        from heat3d_tpu.ops.stencil_fused_rdma import (
            apply_step_fused_rdma,
            apply_superstep_fused_rdma,
            fused_rdma2_supported,
            fused_rdma_supported,
            plan_send_bounds,
            reference_fused_rdma_step_xla,
            reference_fused_rdma_superstep_xla,
        )
    except ImportError:
        return None
    supported, apply_fn, reference_fn = (
        (
            fused_rdma_supported,
            apply_step_fused_rdma,
            reference_fused_rdma_step_xla,
        )
        if tb == 1
        else (
            fused_rdma2_supported,
            apply_superstep_fused_rdma,
            reference_fused_rdma_superstep_xla,
        )
    )
    itemsize = jnp.dtype(cfg.precision.storage).itemsize
    if not supported(
        cfg.local_shape,
        cfg.mesh.shape,
        _solver_taps(cfg),
        itemsize,
        itemsize,
        jnp.dtype(cfg.precision.compute).itemsize,
    ):
        return None
    import functools

    from heat3d_tpu.parallel.plan import _event_once, plan_for

    plan = plan_for(cfg, width=tb)
    _event_once(
        "fused_rdma_dispatch",
        plan.key,
        tb=tb,
        emulated=bool(interpret),
        parts=len(plan_send_bounds(plan, cfg.local_shape, itemsize)),
    )
    if interpret:
        # same posture as the fused-DMA route: Pallas' interpreter
        # cannot discharge remote DMA on the production 3-named-axis
        # meshes (jax 0.9) — the off-TPU emulation tier dispatches the
        # kernel's pure-XLA reference contract, certified bitwise
        # against the real kernel on the 1D ring where interpret CAN
        # run it (tests/multidevice_checks.py fused_rdma)
        return functools.partial(reference_fn, plan=plan)
    return functools.partial(apply_fn, plan=plan)


def _fused_rdma_fn(cfg: SolverConfig):
    """The fused in-kernel RDMA step entry for this config, or None.
    Also serves the remainder single steps of a tb=2 run — the step and
    superstep kernels coexist under distinct collective ids."""
    return _fused_rdma_route(cfg, tb=1)


def _fused_rdma2_fn(cfg: SolverConfig):
    """The tb=2 analogue of _fused_rdma_fn: the plan-scheduled fused
    superstep (k <= 2 is the route's temporal-blocking ceiling)."""
    if cfg.time_blocking != 2:
        return None
    return _fused_rdma_route(cfg, tb=2)


def _local_step_fused_rdma(
    u_local: jax.Array,
    taps: np.ndarray,
    cfg: SolverConfig,
    fused,
) -> jax.Array:
    """The fused in-kernel RDMA step/superstep (ops/stencil_fused_rdma):
    same call surface as the fused-DMA wrapper — the ExchangePlan is
    already bound in the route's partial. The named scope stays
    "fused_dma" (PHASE_FUSED): exchange+stencil are one kernel here
    too, the roofline/profile join keys per-phase cost on that one
    vocabulary, and the bench row's ``fused_rdma_path`` field carries
    the route identity."""
    with named_phase("fused_dma"):
        out = fused(
            u_local,
            taps,
            axis_name=cfg.mesh.axis_names[0],
            axis_size=cfg.mesh.shape[0],
            mesh_axes=cfg.mesh.axis_names,
            periodic=cfg.stencil.bc is BoundaryCondition.PERIODIC,
            bc_value=cfg.stencil.bc_value,
            compute_dtype=jnp.dtype(cfg.precision.compute),
            out_dtype=jnp.dtype(cfg.precision.storage),
        )
        return _pin_padding(out, cfg)


def _local_step_fused_dma(
    u_local: jax.Array,
    taps: np.ndarray,
    cfg: SolverConfig,
    fused,
) -> jax.Array:
    with named_phase("fused_dma"):
        out = fused(
            u_local,
            taps,
            axis_name=cfg.mesh.axis_names[0],
            axis_size=cfg.mesh.shape[0],
            mesh_axes=cfg.mesh.axis_names,
            periodic=cfg.stencil.bc is BoundaryCondition.PERIODIC,
            bc_value=cfg.stencil.bc_value,
            compute_dtype=jnp.dtype(cfg.precision.compute),
            out_dtype=jnp.dtype(cfg.precision.storage),
        )
        return _pin_padding(out, cfg)


def _local_step_fused_dma_3d(
    u_local: jax.Array,
    taps: np.ndarray,
    cfg: SolverConfig,
    fused,
) -> jax.Array:
    """The fused DMA-overlap step on an x-sharded 3D/2D block mesh
    (BASELINE.json configs 3-5; VERDICT r4 item 5's generalization).

    The unchanged x-slab kernel sweeps the bulk with its x-face RDMA in
    flight (y/z frames synthesized as domain boundaries — wrong only in
    the outermost shell of each sharded y/z axis), and ALSO returns the
    two landed ghost planes. Those planes then seed the axis-ordered
    faces-only exchange (``exchange_halo_faces(x_ghosts=...)``) — the y/z
    ppermutes carry the x-ghost corners exactly as the pure-ppermute form
    does, with NO second x transfer — and the y/z shells are recomputed
    and patched like the faces-direct step's.

    Overlap structure: the x faces (the slab worst case of the traffic
    model, BASELINE.md) ride under the sweep in-kernel; the y/z face
    ppermutes serialize after the sweep because their send faces embed the
    RDMA-landed ghosts. At the judged block configs' shard sizes those
    faces are microseconds against a multi-hundred-microsecond sweep; the
    pod A/B against faces-direct (scripts/pod_ab_fused.sh) decides whether
    that trade wins."""
    periodic = cfg.stencil.bc is BoundaryCondition.PERIODIC
    compute_dtype = jnp.dtype(cfg.precision.compute)
    out_dtype = jnp.dtype(cfg.precision.storage)
    with named_phase("fused_dma"):
        out, glo, ghi = fused(
            u_local,
            taps,
            axis_name=cfg.mesh.axis_names[0],
            axis_size=cfg.mesh.shape[0],
            mesh_axes=cfg.mesh.axis_names,
            periodic=periodic,
            bc_value=cfg.stencil.bc_value,
            compute_dtype=compute_dtype,
            out_dtype=out_dtype,
            return_ghosts=True,
        )
    # (ny, nz) -> (1, ny, nz) x-faces; Dirichlet x-edge devices substitute
    # the BC over the landed wrap transfer, exactly as the kernel reads it
    from heat3d_tpu.ops.stencil_dma_fused import substitute_dirichlet_x_edges

    xlo, xhi = substitute_dirichlet_x_edges(
        glo[None], ghi[None],
        axis_name=cfg.mesh.axis_names[0],
        axis_size=cfg.mesh.shape[0],
        periodic=periodic,
        bc_value=cfg.stencil.bc_value,
    )
    from heat3d_tpu.parallel.halo import exchange_halo_faces

    faces = exchange_halo_faces(
        u_local, cfg.mesh, cfg.stencil.bc, cfg.stencil.bc_value,
        x_ghosts=(xlo, xhi),
    )
    out = _patch_boundary_shells(
        out, u_local, faces, taps, cfg, (1, 2), compute_dtype, out_dtype
    )
    return _pin_padding(out, cfg)


def _local_step_overlap(
    u_local: jax.Array,
    taps: np.ndarray,
    cfg: SolverConfig,
    compute_padded: LocalCompute,
) -> jax.Array:
    """Comm/compute-overlapped local step (SURVEY.md §3.2 "optimized variants
    ... interior-update kernel on one CUDA stream while faces exchange on
    another, then boundary-update").

    The interior cells (local indices 1..n-2 per axis) read only local data,
    so their update carries **no data dependence on the ppermutes** — XLA's
    async collectives (collective-permute-start/done) can run the ICI
    transfers concurrently with the interior sweep. Only the 1-cell boundary
    shell waits for ghosts. The assembled result is arithmetically identical
    to the unsplit step (same taps, same op order per cell).
    """
    nx, ny, nz = u_local.shape
    compute_dtype = jnp.dtype(cfg.precision.compute)
    out_dtype = jnp.dtype(cfg.precision.storage)

    # Ghost exchange: the transfers this step overlaps with.
    up = exchange(u_local, cfg)

    # Interior update from the local block alone (u_local acts as its own
    # ghost-padded input for the (nx-2, ny-2, nz-2) interior) — the bulk of
    # the FLOPs, scheduled while faces are in flight.
    with named_phase("stencil"):
        interior = compute_padded(
            u_local, taps, compute_dtype=compute_dtype, out_dtype=out_dtype
        )
    out = jnp.zeros((nx, ny, nz), out_dtype)
    out = lax.dynamic_update_slice(out, interior, (1, 1, 1))

    # Boundary shell: six thickness-1 faces from the ghost-padded block.
    # Edge/corner cells land in two or three face slabs; each computes the
    # identical value, so overlapping writes are benign. Faces are thin VPU
    # work — always the jnp path, even when the interior runs Pallas; the
    # route must then match the interior's (a windowed-kernel interior
    # runs the tap chain, so its faces pin mehrstellen=False).
    face_mehrstellen = None if compute_padded is apply_taps_padded else False
    for axis, n in enumerate((nx, ny, nz)):
        for start, pos in ((0, 0), (n - 1, n - 1)):
            slab = lax.slice_in_dim(up, start, start + 3, axis=axis)
            face = apply_taps_padded(
                slab, taps, compute_dtype=compute_dtype, out_dtype=out_dtype,
                mehrstellen=face_mehrstellen,
            )
            idx = [0, 0, 0]
            idx[axis] = pos
            out = lax.dynamic_update_slice(out, face, tuple(idx))
    return _pin_padding(out, cfg)


def make_step_fn(
    cfg: SolverConfig,
    mesh: Mesh,
    compute_padded: LocalCompute = apply_taps_padded,
    with_residual: bool = False,
):
    """Build the sharded one-step function ``u -> u_new`` (or
    ``u -> (u_new, residual_sumsq)``) over global arrays sharded
    P('x','y','z'). Not jitted — callers compose it under jit."""
    taps = _solver_taps(cfg)
    spec = P(*cfg.mesh.axis_names)
    axes = cfg.mesh.axis_names
    local_step = _local_step
    # fused_rdma='on' wins the route when its gates pass: the knob is an
    # explicit opt-in, so it is dispatched ahead of the direct family
    # (which would otherwise claim the same scope)
    fused_rdma = _fused_rdma_fn(cfg)
    if fused_rdma is not None:
        _log_step_path_once(
            "step path: fused in-kernel RDMA superstep kernel "
            "(plan-scheduled remote face copies under the sweep)"
            + (
                " [XLA reference emulation]"
                if _kernel_env_gate(cfg, allow_partitioned_plan=True)[1]
                else ""
            )
        )

        def local_step(u_local, taps, cfg, compute_padded):
            return _local_step_fused_rdma(u_local, taps, cfg, fused_rdma)

    direct = (
        None
        if fused_rdma is not None
        else _direct_kernel_fn(cfg, halo=1, multichip=True)
    )
    if direct is not None:
        _log_step_path_once(
            "step path: %s direct kernel (no padded copy)"
            % (
                "single-shard"
                if cfg.mesh.shape == (1, 1, 1)
                else "faces-direct multi-chip"
            )
        )
        if cfg.mesh.shape == (1, 1, 1):
            periodic = cfg.stencil.bc is BoundaryCondition.PERIODIC

            def local_step(u_local, taps, cfg, compute_padded):
                with named_phase("stencil"):
                    return direct(
                        u_local,
                        taps,
                        periodic=periodic,
                        bc_value=cfg.stencil.bc_value,
                        compute_dtype=jnp.dtype(cfg.precision.compute),
                        out_dtype=jnp.dtype(cfg.precision.storage),
                    )

        else:

            def local_step(u_local, taps, cfg, compute_padded):
                with named_phase("stencil"):
                    return _local_step_direct_faces(
                        u_local, taps, cfg, direct
                    )

    if cfg.overlap and direct is None:
        fused_dma = _fused_dma_fn(cfg)
        fused_dma_3d = None if fused_dma is not None else _fused_dma_3d_fn(cfg)
        emulated = " [XLA reference emulation]" if _kernel_env_gate(cfg)[1] else ""
        if fused_dma is not None:
            _log_step_path_once(
                "step path: fused DMA-overlap kernel (remote face copies "
                "under the sweep)" + emulated
            )

            def local_step(u_local, taps, cfg, compute_padded):
                return _local_step_fused_dma(u_local, taps, cfg, fused_dma)

        elif fused_dma_3d is not None:
            _log_step_path_once(
                "step path: fused DMA-overlap kernel + y/z shell patches "
                "(x-sharded block mesh)" + emulated
            )

            def local_step(u_local, taps, cfg, compute_padded):
                return _local_step_fused_dma_3d(
                    u_local, taps, cfg, fused_dma_3d
                )

        else:
            # jnp interior/boundary split — the portable overlap form; when
            # the direct kernel dispatched above, the faces-direct step
            # already overlaps the face ppermutes with the bulk sweep
            if min(cfg.local_shape) < 3:
                raise ValueError(
                    f"overlap=True needs local blocks >= 3 per axis to have "
                    f"an interior, got {cfg.local_shape}"
                )
            if cfg.halo == "dma":
                raise ValueError(
                    "overlap=True with halo='dma' needs the fused "
                    "DMA-overlap kernel (a mesh with >= 2 devices along x "
                    "— slab or x-sharded block, unpadded shards, TPU); "
                    "outside "
                    "that scope the side-effecting DMA exchange kernels "
                    "cannot overlap with compute — use halo='ppermute' for "
                    "XLA's async collective-permutes"
                )
            local_step = _local_step_overlap

    # check_vma=False: pallas_call inside shard_map would otherwise require a
    # `vma` annotation on its out_shape (jax 0.9), and the kernel is built
    # mesh-agnostic. The unmapped residual out_spec stays sound: psum over all
    # mesh axes makes it replicated by construction.
    if with_residual:

        def local(u_local):
            u_new = local_step(u_local, taps, cfg, compute_padded)
            with named_phase("residual"):
                r = residual_sumsq(
                    u_new, u_local, jnp.dtype(cfg.precision.residual)
                )
                # MPI_Allreduce analogue (SURVEY.md §3.3)
                r = lax.psum(r, axes)
            return u_new, r

        # scoped(PHASE_STEP, ...): the whole-step heat3d.step named scope
        # (trace-time metadata only) — profiled ops outside the inner
        # stencil/halo/residual scopes (dispatch glue, padding pins)
        # attribute to "step" instead of (unattributed), which is what the
        # profile→roofline join keys on (obs/perf/timeline.py)
        return scoped(
            PHASE_STEP,
            shard_map(
                local, mesh=mesh, in_specs=spec, out_specs=(spec, P()),
                check_vma=False,
            ),
        )

    def local(u_local):
        return local_step(u_local, taps, cfg, compute_padded)

    return scoped(
        PHASE_STEP,
        shard_map(
            local, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
        ),
    )


def make_superstep_fn(
    cfg: SolverConfig,
    mesh: Mesh,
    compute_padded: LocalCompute = apply_taps_padded,
):
    """Build the sharded temporally-blocked superstep ``u -> u_after_k_steps``
    for ``k = cfg.time_blocking`` (see _local_stepk). Composes with either
    halo transport (ppermute or the width-k DMA slab exchange); requires no
    overlap split and local extents >= k."""
    if cfg.overlap:
        # One combination earns its keep: halo='dma' + tb=2 on an x-slab
        # mesh, where the fused two-update kernel overlaps the width-2
        # slab DMA under its phase-A sweep (the tb=2 form of the fused
        # DMA-overlap route).
        fused2 = _fused_dma2_fn(cfg)
        if fused2 is not None:
            _log_step_path_once(
                "superstep path: fused DMA-overlap direct2 kernel "
                "(width-2 slab RDMA under the sweep)"
                + (
                    " [XLA reference emulation]"
                    if _kernel_env_gate(cfg)[1]
                    else ""
                )
            )
            taps2 = _solver_taps(cfg)
            spec2 = P(*cfg.mesh.axis_names)

            def local_fused2(u_local):
                return _local_step_fused_dma(u_local, taps2, cfg, fused2)

            return scoped(
                PHASE_STEP,
                shard_map(
                    local_fused2, mesh=mesh, in_specs=spec2,
                    out_specs=spec2, check_vma=False,
                ),
            )
        raise ValueError(
            f"time_blocking={cfg.time_blocking} and overlap=True are "
            "mutually exclusive — the superstep already restructures the "
            "exchange/compute schedule. The one supported combination is "
            "the fused DMA-overlap superstep: halo='dma' + tb=2 on a 1D "
            "x-slab mesh with >= 2 devices, local nx >= 4, unpadded "
            "shards, on TPU"
        )
    # k ghost layers must fit the local block AND the shrinking-ring
    # intermediates need a genuine interior to recompute into: below 3
    # cells per axis a superstep's first application already consumes the
    # whole block (the same floor the overlap split enforces)
    min_extent = max(3, cfg.time_blocking)
    if min(cfg.local_shape) < min_extent:
        raise ValueError(
            f"time_blocking={cfg.time_blocking} needs local extents >= "
            f"{min_extent} (k ghost layers plus the shrinking recompute "
            f"rings), got {cfg.local_shape}"
        )
    taps = _solver_taps(cfg)
    spec = P(*cfg.mesh.axis_names)

    # fused_rdma='on' at tb=2: the plan-scheduled in-kernel RDMA
    # superstep — both updates AND the width-2 remote copies in ONE
    # kernel. Dispatched ahead of the direct2/streamk families: the knob
    # is an explicit opt-in, so when its gates pass it wins the route.
    if cfg.time_blocking == 2:
        fused_rdma2 = _fused_rdma2_fn(cfg)
        if fused_rdma2 is not None:
            _log_step_path_once(
                "superstep path: fused in-kernel RDMA superstep kernel "
                "(plan-scheduled width-2 remote copies under the sweep)"
                + (
                    " [XLA reference emulation]"
                    if _kernel_env_gate(cfg, allow_partitioned_plan=True)[1]
                    else ""
                )
            )

            def local_fr2(u_local):
                return _local_step_fused_rdma(u_local, taps, cfg, fused_rdma2)

            return scoped(
                PHASE_STEP,
                shard_map(
                    local_fr2, mesh=mesh, in_specs=spec, out_specs=spec,
                    check_vma=False,
                ),
            )

    # k=2 with the BC-fused direct2 kernel: both updates in one sweep of the
    # UNPADDED field — no width-2 ghost copy at all. On multi-chip meshes
    # the faces-direct superstep patches the 2-deep shard-boundary shells.
    if cfg.time_blocking == 2:
        direct2 = _direct_kernel_fn(cfg, halo=2, multichip=True)
        if direct2 is not None:
            if cfg.mesh.shape == (1, 1, 1):
                _log_step_path_once(
                    "superstep path: single-shard fused direct2 kernel"
                )
                periodic2 = cfg.stencil.bc is BoundaryCondition.PERIODIC

                def local2(u_local):
                    with named_phase("stencil"):
                        return direct2(
                            u_local,
                            taps,
                            periodic=periodic2,
                            bc_value=cfg.stencil.bc_value,
                            compute_dtype=jnp.dtype(cfg.precision.compute),
                            out_dtype=jnp.dtype(cfg.precision.storage),
                        )

            else:
                _log_step_path_once(
                    "superstep path: faces-direct fused direct2 kernel "
                    "(multi-chip, no padded copy)"
                )

                def local2(u_local):
                    with named_phase("stencil"):
                        return _local_superstep_direct_faces(
                            u_local, taps, cfg, direct2
                        )

            return scoped(
                PHASE_STEP,
                shard_map(
                    local2, mesh=mesh, in_specs=spec, out_specs=spec,
                    check_vma=False,
                ),
            )

    # The fused k-sweep streaming kernel (k=2..4): keeps the width-k
    # padded slab resident in VMEM and applies the stencil k times with
    # shrinking ghost rings — one exchange AND one HBM sweep per k
    # updates. Composes with either exchange transport (ppermute or the
    # width-k DMA slab kernels); stands down (jnp ring recompute below)
    # off-TPU or when the slab busts the VMEM gate. k=2 reaches here only
    # when the direct2 kernel above didn't dispatch (its no-padded-copy
    # form is strictly better in that scope).
    fusedk = _fused_streamk_fn(cfg)
    if fusedk is not None:
        k = cfg.time_blocking
        _log_step_path_once(
            "superstep path: fused %d-sweep streaming kernel (width-%d "
            "slab resident in VMEM, shrinking-ring recompute)%s"
            % (k, k, " [interpret]" if _kernel_env_gate(cfg)[1] else "")
        )
        periodic_k = cfg.stencil.bc is BoundaryCondition.PERIODIC

        def localk(u_local):
            upk = exchange(u_local, cfg, width=k)
            with named_phase("stencil"):
                return fusedk(
                    upk,
                    taps,
                    k,
                    mesh_axis_names=cfg.mesh.axis_names,
                    periodic=periodic_k,
                    bc_value=cfg.stencil.bc_value,
                    compute_dtype=jnp.dtype(cfg.precision.compute),
                    out_dtype=jnp.dtype(cfg.precision.storage),
                )

        return scoped(
            PHASE_STEP,
            shard_map(
                localk, mesh=mesh, in_specs=spec, out_specs=spec,
                check_vma=False,
            ),
        )

    # Fallback: k compute_padded applications with jnp ring recompute —
    # still cuts the exchanges k-fold, runs anywhere.
    def local(u_local):
        return _local_stepk(u_local, taps, cfg, compute_padded)

    return scoped(
        PHASE_STEP,
        shard_map(
            local, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
        ),
    )


def _pingpong_loop(step_fn, u: jax.Array, count) -> jax.Array:
    """Apply ``step_fn`` ``count`` times via a two-buffer pair carry.

    A single-buffer ``fori_loop(0, n, lambda _, v: step_fn(v), u)`` forces
    XLA to insert a full-volume copy every iteration: the while carry is a
    fixed buffer, the stencil custom-call cannot write its output into the
    buffer it is reading, so copy-insertion clones the carry before each
    call (measured at 38–49% of step time on-chip — see BASELINE.md). With
    a pair carry the two calls per iteration alternate buffers — each
    writes into the buffer whose contents are already dead — and buffer
    assignment elides the copy entirely (verified: the compiled pair-loop
    body is two custom-calls, zero copies). This is the reference's
    ``swap(u_old, u_new)`` pointer swap (SURVEY.md §1 L0) done the XLA way.

    The scratch buffer is zero-initialized once per call (a write-only
    broadcast, amortized over the run); the odd trailing iteration runs in
    a ≤1-trip loop that still pays one copy."""

    def body2(_, uv):
        a, b = uv
        b = step_fn(a)
        a = step_fn(b)
        return (a, b)

    u, _ = lax.fori_loop(0, count // 2, body2, (u, jnp.zeros_like(u)))
    return lax.fori_loop(0, count % 2, lambda _, v: step_fn(v), u)


def make_multistep_fn(
    cfg: SolverConfig,
    mesh: Mesh,
    compute_padded: LocalCompute = apply_taps_padded,
):
    """Build ``(u, num_steps) -> u_after`` with the fori_loop *inside* the
    compiled program. num_steps is a traced scalar so one executable serves
    any step count (the reference recompiles nothing either — its loop is
    host-side; ours is device-side, SURVEY.md §3.2 TPU mapping).

    With cfg.time_blocking == k > 1, the loop advances in k-update
    supersteps (1/k the exchanges) plus trailing single steps for the
    remainder. Both loops use the ping-pong pair carry (_pingpong_loop) so
    the stencil sweeps alternate between two field buffers copy-free."""
    step = make_step_fn(cfg, mesh, compute_padded, with_residual=False)

    if cfg.time_blocking > 1:
        k = cfg.time_blocking
        superstep = make_superstep_fn(cfg, mesh, compute_padded)

        def runk(u, num_steps):
            u = _pingpong_loop(superstep, u, num_steps // k)
            # remainder is <= k-1 trips: a plain loop (one carry copy per
            # trip) beats materializing another full-volume scratch
            return lax.fori_loop(0, num_steps % k, lambda _, v: step(v), u)

        return runk

    def run(u, num_steps):
        return _pingpong_loop(step, u, num_steps)

    return run


def make_converge_fn(
    cfg: SolverConfig,
    mesh: Mesh,
    compute_padded: LocalCompute = apply_taps_padded,
):
    """Build ``(u, max_steps, tol) -> (u, steps_taken, last_residual)``:
    iterate until the global L2 residual of one update drops below tol —
    the convergence-mode path (SURVEY.md §3.3; fixed-step benchmark mode
    never syncs and uses make_multistep_fn instead).

    With ``cfg.run.residual_every = K > 1`` the while body advances K-1
    updates through the fixed-step machinery — the copy-free ping-pong
    pair carry AND temporal-blocking supersteps both apply — then runs one
    residual step, so the psum + its convergence check happen every K
    updates instead of every update. This is exactly the reference class's
    cadence ("every k iters: residual + MPI_Allreduce", SURVEY.md §3.2);
    the run may overshoot the tol crossing by up to K-1 updates but never
    exceeds max_steps, and ``steps_taken`` counts real updates exactly.

    With K <= 1 (the default) the residual is checked after EVERY update:
    single-buffer carry (its per-iteration XLA copy is dominated by the
    per-step psum sync) and no temporal blocking."""
    step_r = make_step_fn(cfg, mesh, compute_padded, with_residual=True)
    every = max(1, cfg.run.residual_every or 1)

    if every > 1:
        multistep = make_multistep_fn(cfg, mesh, compute_padded)

        def run(u, max_steps, tol):
            def cond(state):
                _, i, r2 = state
                return jnp.logical_and(i < max_steps, r2 > tol * tol)

            def body(state):
                u, i, _ = state
                # leave one update for the residual step; never pass
                # max_steps even when it isn't a multiple of K
                n = jnp.minimum(jnp.int32(every - 1), max_steps - 1 - i)
                u = multistep(u, n)
                u_new, r2 = step_r(u)
                return u_new, i + n + 1, r2

            init = (
                u, jnp.zeros((), jnp.int32), jnp.full((), jnp.inf, jnp.float32)
            )
            u, steps, r2 = lax.while_loop(cond, body, init)
            return u, steps, jnp.sqrt(r2)

        return run

    def run(u, max_steps, tol):
        def cond(state):
            _, i, r2 = state
            return jnp.logical_and(i < max_steps, r2 > tol * tol)

        def body(state):
            u, i, _ = state
            u_new, r2 = step_r(u)
            return u_new, i + 1, r2

        init = (u, jnp.zeros((), jnp.int32), jnp.full((), jnp.inf, jnp.float32))
        u, steps, r2 = lax.while_loop(cond, body, init)
        return u, steps, jnp.sqrt(r2)

    return run


# ---- span <-> cost-analysis keying ------------------------------------------

# The named_phase brackets above (obs/trace.py), the profiler trace's
# per-phase table (scripts/summarize_trace.py), the ledger spans, and the
# per-phase compile targets below all share these names — a cost_analysis()
# record joins a measured span on ONE key (obs/perf/roofline.py consumes).
PHASE_STEP = "step"
PHASE_STENCIL = "stencil"
PHASE_HALO = "halo_exchange"
PHASE_FUSED = "fused_dma"
PHASE_RESIDUAL = "residual"

# The canonical phase vocabulary, in roofline-table order — the
# profile→roofline join iterates it and keys per-phase call counts on
# the PHASE_* constants (obs/perf/roofline.profile_join_records /
# _phase_calls); obs/perf/timeline.normalize_phase folds trace scopes
# onto the same names.
PHASES = (PHASE_STEP, PHASE_STENCIL, PHASE_HALO, PHASE_FUSED, PHASE_RESIDUAL)


def phase_programs(
    cfg: SolverConfig,
    mesh: Mesh,
    compute_padded: LocalCompute = apply_taps_padded,
):
    """Un-jitted compile targets per phase, each a callable over the
    sharded global field (storage layout, ``cfg.padded_shape``):

    - ``step``: the full iteration program this config's hot loop runs —
      the single step (exchange + stencil [+ padding pin]) at
      ``time_blocking == 1``, the k-update SUPERSTEP at k > 1 (one
      exchange amortized over k updates, ghost-ring recompute included;
      costing the single step there would describe a program the loop
      never runs). Costs and timings are per CALL — at k > 1 one call is
      k updates; divide by k for per-update numbers
      (``obs.perf.roofline.step_cost_fields`` does).
    - ``halo_exchange``: the ghost exchange alone (whichever transport
      ``cfg.halo`` selects), cropped back to the local block so the
      program has a data-live consumer of every received face.
    - ``stencil``: the local tap application alone on locally-padded
      blocks (no collective) — the compute leg of the roofline.
    - ``residual``: the fp32 reduction + psum alone.
    - ``fused_dma``: only when this config resolves to a fused
      DMA-overlap route OR the fused in-kernel RDMA route (both scope
      under this one phase name), where exchange+stencil are ONE kernel
      and per-leg programs would misattribute: the full step program is
      the honest program for the span of the same name.

    Callers jit + ``.lower(u).compile().cost_analysis()`` each to get the
    FLOPs/bytes the roofline report divides measured span time by.
    """
    taps = _solver_taps(cfg)
    spec = P(*cfg.mesh.axis_names)
    compute_dtype = jnp.dtype(cfg.precision.compute)
    out_dtype = jnp.dtype(cfg.precision.storage)

    def _sharded(f, out_specs=spec):
        return shard_map(
            f, mesh=mesh, in_specs=spec, out_specs=out_specs, check_vma=False
        )

    def _halo_only(u_local):
        # every received ghost face is folded onto the block boundary
        # (face-sized writes, the same keep-alive trick bench_halo uses) so
        # XLA cannot DCE any of the six transports out of the program
        nx, ny, nz = u_local.shape
        p = exchange(u_local, cfg)
        out = u_local
        out = out.at[0].add(p[0, 1 : 1 + ny, 1 : 1 + nz])
        out = out.at[nx - 1].add(p[nx + 1, 1 : 1 + ny, 1 : 1 + nz])
        out = out.at[:, 0].add(p[1 : 1 + nx, 0, 1 : 1 + nz])
        out = out.at[:, ny - 1].add(p[1 : 1 + nx, ny + 1, 1 : 1 + nz])
        out = out.at[:, :, 0].add(p[1 : 1 + nx, 1 : 1 + ny, 0])
        out = out.at[:, :, nz - 1].add(p[1 : 1 + nx, 1 : 1 + ny, nz + 1])
        return out

    def _stencil_only(u_local):
        with named_phase("stencil"):
            return compute_padded(
                jnp.pad(u_local, 1),  # local ghost fill: no collective
                taps,
                compute_dtype=compute_dtype,
                out_dtype=out_dtype,
            )

    def _residual_only(u_local):
        with named_phase("residual"):
            r = residual_sumsq(
                u_local, u_local * 1, jnp.dtype(cfg.precision.residual)
            )
            return lax.psum(r, cfg.mesh.axis_names)

    programs = {
        PHASE_STEP: (
            make_superstep_fn(cfg, mesh, compute_padded)
            if cfg.time_blocking > 1
            else make_step_fn(cfg, mesh, compute_padded)
        ),
        PHASE_HALO: _sharded(_halo_only),
        PHASE_STENCIL: _sharded(_stencil_only),
        PHASE_RESIDUAL: _sharded(_residual_only, out_specs=P()),
    }
    fused = (
        (
            _fused_dma2_fn(cfg) is not None
            or _fused_rdma2_fn(cfg) is not None
        )
        if cfg.time_blocking == 2
        else (
            _fused_dma_fn(cfg) is not None
            or _fused_dma_3d_fn(cfg) is not None
            or _fused_rdma_fn(cfg) is not None
        )
        if cfg.time_blocking == 1
        else False
    )
    if fused:
        programs[PHASE_FUSED] = programs[PHASE_STEP]
    return programs
