"""Checker: fail-soft enforcement for the obs/ telemetry surface.

PR 2's design invariant — "telemetry never kills the run it observes"
(docs/OBSERVABILITY.md, Failure posture) — was stated and hand-enforced:
every ledger write, metrics export, and profiler bracket is supposed to
swallow *environmental* failures (file IO, serialization) instead of
propagating them into the instrumented run. Nothing checked it; a PR
adding one unguarded ``open()`` three calls deep silently converts every
instrumented caller into a crash site the next time a disk fills.

This checker enforces it mechanically. For every function in the
:data:`~heat3d_tpu.analysis.registry.FAIL_SOFT_CONTRACT` surface it
computes, over the intra-``obs/`` call graph, the set of environmental
exception classes that can escape to the caller:

- **risky ops**: ``open``/``os.makedirs``/``os.replace``/``.write``/
  ``.flush``/``.close``/... raise ``OSError``; ``json.dumps``/``dump``
  raise ``TypeError``/``ValueError``; ``json.loads``/``load`` raise
  ``ValueError``.
- **guards**: an ancestor ``try`` whose handlers catch the class or a
  superclass (``Exception``/``BaseException``/bare ``except``) absorbs
  the risk; so does a guard at the *call site* of a helper whose own
  body leaks.
- **propagation**: unguarded risk flows caller-ward through resolvable
  calls (module functions, ``self.`` methods, ``ClassName(...)`` ->
  ``__init__``, names imported from the contract modules).

Deliberate contract raises (``Counter.inc`` rejecting negative
increments) are out of scope: those are caller bugs, not environment.
Unresolvable calls (stdlib, jax) contribute no risk — the checker is a
tripwire for the obs package's own IO, not a theorem prover; its misses
are documented in docs/ANALYSIS.md.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from heat3d_tpu.analysis import astutil
from heat3d_tpu.analysis.findings import ERROR, Finding
from heat3d_tpu.analysis.registry import FAIL_SOFT_CONTRACT

CHECKER = "fail-soft"

# risky-op table: matcher -> exception classes raised
_OS_CALLS = {
    "open",
    "os.makedirs",
    "os.replace",
    "os.rename",
    "os.remove",
    "os.unlink",
    "os.fsync",
    "os.path.getmtime",
}
# file-handle method calls count as IO only on receivers that look like
# file handles (`f`, `self._f`, ...) — `ledger.close()` is not file IO,
# and its own leaks are covered by the contract on `Ledger.close` itself
_OS_METHOD_TAILS = {"write", "flush", "close", "read", "readlines"}
_FILE_RECEIVERS = {"f", "_f", "fh", "fp", "file", "tmp", "out"}
_JSON_DUMP = {"json.dumps", "json.dump"}
_JSON_LOAD = {"json.loads", "json.load"}

# exception-class subsumption for guard matching
_SUPERS: Dict[str, Set[str]] = {
    "OSError": {"OSError", "IOError", "EnvironmentError", "Exception", "BaseException", ""},
    "ValueError": {"ValueError", "Exception", "BaseException", ""},
    "TypeError": {"TypeError", "Exception", "BaseException", ""},
}


def _file_method(call: ast.Call) -> bool:
    if not isinstance(call.func, ast.Attribute):
        return False
    if call.func.attr not in _OS_METHOD_TAILS:
        return False
    recv = astutil.dotted_name(call.func.value)
    return recv is not None and recv.rsplit(".", 1)[-1] in _FILE_RECEIVERS


def _risks_of_call(call: ast.Call) -> Set[str]:
    name = astutil.call_name(call)
    if name in _OS_CALLS or _file_method(call):
        return {"OSError"}
    if name in _JSON_DUMP:
        return {"TypeError", "ValueError"}
    if name in _JSON_LOAD:
        return {"ValueError"}
    return set()


def _unguarded(call: ast.Call, risks: Set[str]) -> Set[str]:
    """The subset of ``risks`` not absorbed by any ancestor try-handler."""
    handler_sets = astutil.guarding_handlers(call)
    out = set()
    for r in risks:
        caught = any(
            any(h.rsplit(".", 1)[-1] in _SUPERS[r] for h in handlers)
            for handlers in handler_sets
        )
        if not caught:
            out.add(r)
    return out


class _Module:
    def __init__(self, relpath: str, tree: ast.Module):
        self.relpath = relpath
        self.tree = tree
        # qualname -> FunctionDef (methods as Class.method)
        self.functions: Dict[str, ast.AST] = {}
        # imported-name -> (module relpath hint, qualname) for
        # `from heat3d_tpu.obs.X import f` style imports
        self.imports: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[astutil.qualname(node)] = node
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )


def _resolve_call(
    call: ast.Call,
    mod: _Module,
    enclosing_class: Optional[str],
    all_functions: Dict[Tuple[str, str], ast.AST],
    module_of: Dict[str, str],
) -> Optional[Tuple[str, str]]:
    """(module relpath, qualname) of the callee when it is one of ours."""
    name = astutil.call_name(call)
    if name is None:
        return None
    # self.method() -> same class
    if name.startswith("self.") and enclosing_class:
        q = f"{enclosing_class}.{name[len('self.'):]}"
        if (mod.relpath, q) in all_functions:
            return (mod.relpath, q)
        return None
    # plain name: same-module function, ClassName() -> __init__, or import
    if "." not in name:
        if (mod.relpath, name) in all_functions:
            return (mod.relpath, name)
        if (mod.relpath, f"{name}.__init__") in all_functions:
            return (mod.relpath, f"{name}.__init__")
        target = mod.imports.get(name)
        if target:
            dotted_mod, _, func = target.rpartition(".")
            rel = module_of.get(dotted_mod)
            if rel and (rel, func) in all_functions:
                return (rel, func)
            if rel and (rel, f"{func}.__init__") in all_functions:
                return (rel, f"{func}.__init__")
        return None
    # module-qualified: `ledger.activate(...)` etc.
    head, _, func = name.rpartition(".")
    target = mod.imports.get(head)
    if target:
        rel = module_of.get(target)
        if rel and (rel, func) in all_functions:
            return (rel, func)
    return None


def check(
    root: str,
    contract: Optional[Dict[str, tuple]] = None,
    files: Optional[Sequence[str]] = None,
) -> List[Finding]:
    contract = contract if contract is not None else FAIL_SOFT_CONTRACT
    paths = (
        list(files)
        if files is not None
        else [os.path.join(root, relp) for relp in contract]
    )
    modules: Dict[str, _Module] = {}
    for p in paths:
        tree = astutil.parse_file(p)
        if tree is None:
            continue
        relp = astutil.rel(root, p)
        modules[relp] = _Module(relp, tree)

    all_functions: Dict[Tuple[str, str], ast.AST] = {
        (relp, q): fn
        for relp, mod in modules.items()
        for q, fn in mod.functions.items()
    }
    # dotted module name -> relpath ("heat3d_tpu.obs.ledger" -> ".../ledger.py")
    module_of = {
        relp[:-3].replace(os.sep, "."): relp for relp in modules
    }

    # escape[(mod, qual)] = {exc: (witness_relpath, line, description)}
    escape: Dict[Tuple[str, str], Dict[str, Tuple[str, int, str]]] = {
        key: {} for key in all_functions
    }

    def _enclosing_class(fn: ast.AST) -> Optional[str]:
        q = astutil.qualname(fn)
        return q.rsplit(".", 1)[0] if "." in q else None

    # seed: direct unguarded risky ops
    for (relp, qual), fn in all_functions.items():
        for call in astutil.calls_in(fn):
            risks = _risks_of_call(call)
            if not risks:
                continue
            for exc in _unguarded(call, risks):
                escape[(relp, qual)].setdefault(
                    exc,
                    (relp, call.lineno, f"unguarded `{ast.unparse(call)[:60]}`"),
                )

    # propagate through resolvable calls until fixpoint
    changed = True
    while changed:
        changed = False
        for (relp, qual), fn in all_functions.items():
            mod = modules[relp]
            cls = _enclosing_class(fn)
            for call in astutil.calls_in(fn):
                callee = _resolve_call(call, mod, cls, all_functions, module_of)
                if callee is None or callee == (relp, qual):
                    continue
                for exc, (wp, wl, wd) in escape[callee].items():
                    if exc in escape[(relp, qual)]:
                        continue
                    if exc in _unguarded(call, {exc}):
                        escape[(relp, qual)][exc] = (
                            wp,
                            wl,
                            f"{wd} via {callee[1]} (called at line {call.lineno})",
                        )
                        changed = True

    findings: List[Finding] = []
    for relp, quals in contract.items():
        mod = modules.get(relp)
        for qual in quals:
            if mod is None or qual not in mod.functions:
                findings.append(
                    Finding(
                        checker=CHECKER,
                        severity=ERROR,
                        path=relp,
                        line=0,
                        code="ANL202",
                        symbol=qual,
                        message=(
                            f"fail-soft contract names '{qual}' but it does "
                            "not exist here — update the contract in "
                            "analysis/registry.py alongside the refactor"
                        ),
                    )
                )
                continue
            esc = escape[(relp, qual)]
            if not esc:
                continue
            details = "; ".join(
                f"{exc} from {wd} at {wp}:{wl}"
                for exc, (wp, wl, wd) in sorted(esc.items())
            )
            findings.append(
                Finding(
                    checker=CHECKER,
                    severity=ERROR,
                    path=relp,
                    line=mod.functions[qual].lineno,
                    code="ANL201",
                    symbol=qual,
                    message=(
                        f"public telemetry function '{qual}' can propagate "
                        f"{details} — the obs fail-soft invariant "
                        "(docs/OBSERVABILITY.md, Failure posture) requires "
                        "environmental failures to be swallowed, not raised "
                        "into the instrumented run"
                    ),
                )
            )
    return findings
