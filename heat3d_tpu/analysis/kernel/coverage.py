"""kernel-coverage (ANL1021-1023) — every output element written exactly
once across the grid.

A Pallas output block is flushed to HBM when the grid moves off its
block index; whatever the VMEM tile holds at that moment is the result.
Three ways that silently corrupts (all invisible to interpret-mode
parity at the shapes where they happen *not* to corrupt, and none
visible in the jnp reference):

- **ANL1021** — an output block no grid step ever maps to: its HBM
  region is never flushed (stale/garbage output).
- **ANL1022** — a block revisited after the pipeline left it: the block
  index sequence is non-contiguous, so the block is fetched/flushed
  twice and the second run's initial tile content is pipeline-dependent.
- **ANL1023** — a visit run in which the kernel never writes the block:
  the flush emits whatever the tile held (the "parked" index trick —
  e.g. the streaming kernels park on block 0 during ring priming — is
  only sound because the park run ends with a real write; this checker
  is what holds that).

Index maps are abstract-interpreted exactly: each output's
``index_map_jaxpr`` is evaluated at every grid point in row-major
pipeline order, runs are segmented, and writes come from the simulated
effect timeline (completed DMA landings count — the exchange kernels'
ghost outputs are written by the remote copy, committed at the recv
wait).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Set, Tuple

from heat3d_tpu.analysis.findings import ERROR, Finding
from heat3d_tpu.analysis.kernel import interp

CHECKER = "kernel-coverage"


def _finding(case, code, invariant, message) -> Finding:
    return Finding(
        checker=CHECKER,
        severity=ERROR,
        path=case.path,
        line=0,
        code=code,
        symbol=f"{case.key}|{invariant}",
        message=f"[{case.key}] {case.entry}: {message}",
    )


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _runs(visits):
    """Segment a visit sequence into per-block contiguous runs:
    ``block -> [[step, ...], ...]``."""
    runs: Dict[Tuple[int, ...], List[List[Tuple[int, ...]]]] = {}
    prev = None
    for step, block in visits:
        if block != prev:
            runs.setdefault(block, []).append([])
            prev = block
        runs[block][-1].append(step)
    return runs


def _write_steps(case, ci, out_ref_idx) -> Set[Tuple[int, ...]]:
    """Grid steps at which ANY simulated device position produces a
    committed write (kernel store or completed DMA landing) to the
    output ref. Union over positions: a write predicated on a device
    coordinate (a Dirichlet edge fill) still counts as covering the
    step; a step NO position writes is a genuine hole."""
    from heat3d_tpu.analysis.kernel.races import replay

    steps: Set[Tuple[int, ...]] = set()
    for rec in case.sims(ci):
        writes, _ = replay(rec)
        for (ref, _plane), log in writes.items():
            if ref == out_ref_idx:
                steps.update(t for t, _o in log)
    return steps


def check_case(case) -> List[Finding]:
    findings: List[Finding] = []
    seen: set = set()

    def emit(code, invariant, message):
        key = (code, invariant)
        if key in seen:
            return
        seen.add(key)
        findings.append(_finding(case, code, invariant, message))

    for ci, eqn in enumerate(case.calls()):
        gm = eqn.params["grid_mapping"]
        n_idx = getattr(gm, "num_index_operands", 0)
        for oi, bm, visits in interp.out_block_visits(eqn):
            ref_idx = n_idx + gm.num_inputs + oi
            writes = _write_steps(case, ci, ref_idx)
            shape = tuple(bm.array_shape_dtype.shape)
            block = tuple(bm.block_shape) if bm.block_shape else ()
            if not visits:
                continue
            runs = _runs(visits)
            if gm.grid and block and len(block) == len(shape):
                want = list(
                    itertools.product(
                        *[range(_ceil_div(s, b)) for s, b in zip(shape, block)]
                    )
                )
            else:  # whole-ref output (no windowed mapping)
                want = [visits[0][1]]
            for b in want:
                if b not in runs:
                    emit(
                        "ANL1021",
                        f"call{ci}|out{oi}|uncovered|{b}",
                        f"call #{ci} output #{oi}: block {b} of "
                        f"{_ceil_div(shape[0], block[0]) if block else 1} "
                        "x ... is never visited by the grid — its HBM "
                        "region is never written",
                    )
            for b, rs in runs.items():
                if len(rs) > 1:
                    emit(
                        "ANL1022",
                        f"call{ci}|out{oi}|revisit|{b}",
                        f"call #{ci} output #{oi}: block {b} is visited "
                        f"in {len(rs)} separate runs (first two end/"
                        f"begin at grid{rs[0][-1]} / grid{rs[1][0]}) — "
                        "the pipeline flushes it twice and the second "
                        "run's initial tile content is undefined",
                    )
                for run in rs[:1] if len(rs) > 1 else rs:
                    if not any(step in writes for step in run):
                        emit(
                            "ANL1023",
                            f"call{ci}|out{oi}|unwritten-run|{b}",
                            f"call #{ci} output #{oi}: the grid visits "
                            f"block {b} over steps grid{run[0]}.."
                            f"grid{run[-1]} but no device position "
                            "writes it during that run — the flush "
                            "emits stale VMEM tile content",
                        )
    return findings


def check(root: str, cases=None) -> List[Finding]:
    from heat3d_tpu.analysis.kernel import programs

    if cases is None:
        cases = programs.judged_kernels()
    findings: List[Finding] = []
    for case in cases:
        findings.extend(check_case(case))
    return findings
