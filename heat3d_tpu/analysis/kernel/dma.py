"""kernel-dma (ANL1001-1005) — in-kernel DMA start/wait discipline.

The invariant the interpret tier cannot see: interpret mode discharges
``dma_start`` synchronously, so a copy that is started and never waited,
a wait with no matching start, or two in-flight copies aliasing one
semaphore cell all *execute correctly* there — and deadlock or corrupt
silently on hardware, where the semaphore counts are real. This checker
replays every judged kernel's full grid at every device position
(:mod:`..interp`) and audits the semaphore ledger exactly:

- **ANL1001** — a started copy's send or recv semaphore cell is still
  armed when the grid ends: the start has no matching wait on the
  control path this device/grid actually takes.
- **ANL1002** — a ``dma_wait`` on a semaphore cell with no copy in
  flight: the wait blocks forever on hardware (or consumes a stray
  signal and desynchronizes the next exchange).
- **ANL1003** — a ``dma_start`` arms a semaphore cell that is already
  armed by a still-in-flight copy: two transfers share one completion
  count, so a single wait can retire the wrong copy.
- **ANL1004** — barrier-semaphore imbalance: the neighbor signals a
  device issues (SPMD-mirrored: every peer runs the same program, so my
  expected arrivals equal the incs I send) do not cover its waits.
- **ANL1005** (warning) — the simulator could not resolve part of the
  kernel's control flow, so the discipline is NOT certified; an
  unanalyzable kernel must never read as clean.

Remote-copy accounting is SPMD-mirrored: my ``dma_start`` into a
neighbor's ghost ref arms my *own* recv cell, because the symmetric peer
program starts the copy that lands in mine — the same reasoning the
kernels' comments pin ("my recv_sem[0] = lo nb's push into lo_ref").
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from heat3d_tpu.analysis.findings import ERROR, WARNING, Finding

CHECKER = "kernel-dma"


def _cell_name(cell: Tuple[int, object]) -> str:
    idx, plane = cell
    base = "barrier" if idx < 0 else f"sem{idx}"
    return base if plane is None else f"{base}[{plane}]"


def _finding(case, severity, code, invariant, message) -> Finding:
    return Finding(
        checker=CHECKER,
        severity=severity,
        path=case.path,
        line=0,
        code=code,
        symbol=f"{case.key}|{invariant}",
        message=f"[{case.key}] {case.entry}: {message}",
    )


def check_case(case) -> List[Finding]:
    findings: List[Finding] = []
    seen: set = set()

    def emit(severity, code, invariant, message):
        key = (code, invariant)
        if key in seen:
            return
        seen.add(key)
        findings.append(_finding(case, severity, code, invariant, message))

    for ci in range(len(case.calls())):
        for rec in case.sims(ci):
            if rec.incomplete:
                emit(
                    WARNING,
                    "ANL1005",
                    f"call{ci}|unanalyzable",
                    f"call #{ci}: control flow not fully analyzable "
                    f"({'; '.join(rec.incomplete)}) — DMA discipline NOT "
                    "certified for this kernel",
                )
            armed: Dict[Tuple[int, object], object] = {}
            barrier_balance = 0
            saw_barrier = False
            for ev in rec.events:
                if ev.kind == "dma_start":
                    for side, cell in (
                        ("send", ev.info.get("send_cell")),
                        ("recv", ev.info.get("recv_cell")),
                    ):
                        if cell is None:
                            continue
                        if cell in armed:
                            emit(
                                ERROR,
                                "ANL1003",
                                f"call{ci}|alias|{_cell_name(cell)}",
                                f"call #{ci} at grid{ev.time} "
                                f"(device {rec.ctx or 'solo'}): dma_start "
                                f"arms {side} semaphore "
                                f"{_cell_name(cell)} while a copy started "
                                f"at grid{armed[cell]} is still in flight "
                                "— two transfers share one completion "
                                "count",
                            )
                        armed[cell] = ev.time
                elif ev.kind == "dma_wait":
                    cell = ev.info.get("recv_cell")
                    if cell in armed:
                        del armed[cell]
                    else:
                        emit(
                            ERROR,
                            "ANL1002",
                            f"call{ci}|wait-without-start|{_cell_name(cell)}",
                            f"call #{ci} at grid{ev.time} "
                            f"(device {rec.ctx or 'solo'}): dma_wait on "
                            f"{_cell_name(cell)} with no copy in flight — "
                            "blocks forever on hardware",
                        )
                elif ev.kind == "sem_signal" and ev.ref < 0:
                    saw_barrier = True
                    inc = ev.info.get("inc")
                    if not isinstance(inc, int):
                        # a data-dependent increment is "not certified",
                        # not a checker crash
                        emit(
                            WARNING,
                            "ANL1005",
                            f"call{ci}|opaque-barrier",
                            f"call #{ci}: barrier signal increment is not "
                            "concretely evaluable — barrier discipline "
                            "NOT certified for this kernel",
                        )
                        continue
                    # SPMD mirror: a signal sent to any neighbor arrives
                    # at my own barrier cell from the symmetric peer
                    barrier_balance += inc
                elif ev.kind == "sem_wait" and ev.ref < 0:
                    saw_barrier = True
                    value = ev.info.get("value")
                    if not isinstance(value, int):
                        emit(
                            WARNING,
                            "ANL1005",
                            f"call{ci}|opaque-barrier",
                            f"call #{ci}: barrier wait value is not "
                            "concretely evaluable — barrier discipline "
                            "NOT certified for this kernel",
                        )
                        continue
                    barrier_balance -= value
            for cell, started in armed.items():
                emit(
                    ERROR,
                    "ANL1001",
                    f"call{ci}|start-without-wait|{_cell_name(cell)}",
                    f"call #{ci}: copy started at grid{started} "
                    f"(device {rec.ctx or 'solo'}) on "
                    f"{_cell_name(cell)} is never waited on this control "
                    "path — the semaphore stays armed into the next "
                    "kernel invocation",
                )
            if saw_barrier and barrier_balance != 0:
                emit(
                    ERROR,
                    "ANL1004",
                    f"call{ci}|barrier-imbalance",
                    f"call #{ci} (device {rec.ctx or 'solo'}): barrier "
                    f"semaphore signals and waits do not balance "
                    f"(residue {barrier_balance:+d} under the SPMD "
                    "mirror) — a desynchronized neighbor barrier",
                )
    return findings


def check(root: str, cases=None) -> List[Finding]:
    from heat3d_tpu.analysis.kernel import programs

    if cases is None:
        cases = programs.judged_kernels()
    findings: List[Finding] = []
    for case in cases:
        findings.extend(check_case(case))
    return findings
