"""kernel-races (ANL1011-1013) — happens-before over ring slots and DMA
edges.

The %3 VMEM ring discipline every streaming kernel shares (``_stream``,
``_stream2``, ``_streamk`` k=2..4, the direct kernels' rings, the fused
DMA kernels' input/mid rings): plane ``p`` lives in slot ``p % 3``,
written once per grid step, and every consumer stage at step ``i`` reads
three *consecutively produced* planes — the writes from steps ``i-2``,
``i-1``, ``i`` of the same chunk column. This checker rebuilds the
happens-before graph from the simulated effect timeline
(:mod:`..interp`) and proves it:

- **ANL1011** — a read of a scratch plane no write (kernel store or
  completed DMA) ever produced on this control path: the stage fired
  before its ring primed (the classic off-by-one in a ``pl.when`` fire
  predicate).
- **ANL1012** — a read (or kernel write) of a buffer a still-in-flight
  DMA copy may write: the write-before-read hazard. THE interpret-tier
  blind spot — interpret mode completes copies synchronously at
  ``start()``, so value-parity tests pass while hardware races (the
  blindness-proof test pins this).
- **ANL1013** — a ring read observing a *stale or colliding* slot: the
  producing write is more than the ring's 3-step window behind the read
  (or in another chunk column), or two planes of one firing stage
  observe writes from the same step — the slot was reused before its
  last consumer, i.e. a later stage may overwrite data still needed
  (loop-order and ring-size bugs).

The lag rule is deliberately semantic-free: it never re-derives what
plane a read *should* see (that is the parity tests' job) — it proves
the schedule shape every 3-slot ring must have, which is exactly what
parity cannot prove.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

from heat3d_tpu.analysis.findings import ERROR, Finding

CHECKER = "kernel-races"

# reads within this many trailing-grid-dim steps of the producing write
# are ring-consistent (3-slot ring: the value written at step i is
# legitimately consumed at steps i, i+1, i+2)
RING_WINDOW = 2


def _is_ring(info) -> bool:
    return (
        info.role == "scratch"
        and info.sem_kind is None
        and len(info.shape) == 3
        and info.shape[0] == 3
    )


def _overlaps(a, b) -> bool:
    """Do two plane ids possibly alias? Unknown/whole (None) aliases
    everything; slices by range; ints exactly."""
    if a is None or b is None:
        return True
    ar = (a, a + 1) if isinstance(a, int) else (a[1], a[1] + a[2])
    br = (b, b + 1) if isinstance(b, int) else (b[1], b[1] + b[2])
    return ar[0] < br[1] and br[0] < ar[1]


def replay(rec):
    """Walk one simulation's timeline once, classifying every effect.

    Returns ``(write_log, hazards)``: ``write_log`` maps
    ``(ref, plane)`` -> ordered list of write times (kernel stores AND
    completed DMA landings — a landing commits at its recv wait, which
    is when its content is safe to read); ``hazards`` is a list of
    ``(kind, ev, detail)`` in-flight violations found along the way.
    Memoized per record: the coverage checker re-reads the same
    timelines (once per output), and the chunked fused cases' event
    streams are the dominant cost of the whole ``--kernel`` run."""
    cached = getattr(rec, "_replay_cache", None)
    if cached is not None:
        return cached
    writes: Dict[Tuple[int, Any], List[Tuple[Tuple[int, ...], int]]] = {}
    # recv-cell -> (dst ref, dst plane, start event); send-cell -> src ref
    in_flight: Dict[Any, Tuple[int, Any, Any]] = {}
    fragile_src: Dict[Any, Tuple[int, Any, Any]] = {}
    hazards: List[Tuple[str, Any, str]] = []

    def in_flight_on(ref, plane):
        for cell, (dref, dplane, sev) in in_flight.items():
            if dref == ref and _overlaps(dplane, plane):
                return cell, sev
        return None

    def fragile_on(ref, plane):
        for cell, (sref, splane, sev) in fragile_src.items():
            if sref == ref and _overlaps(splane, plane):
                return cell, sev
        return None

    def log_write(ref, plane, time, order):
        writes.setdefault((ref, plane), []).append((time, order))

    for ev in rec.events:
        if ev.kind == "write":
            hit = in_flight_on(ev.ref, ev.plane)
            if hit is not None:
                hazards.append(
                    (
                        "write-in-flight-dst",
                        ev,
                        f"kernel write at grid{ev.time} lands in a buffer "
                        f"a DMA started at grid{hit[1].time} is still "
                        "writing",
                    )
                )
            frag = fragile_on(ev.ref, ev.plane)
            if frag is not None:
                hazards.append(
                    (
                        "write-in-flight-src",
                        ev,
                        f"kernel write at grid{ev.time} mutates the "
                        f"source of a DMA started at grid{frag[1].time} "
                        "before its send wait — the transfer may ship "
                        "either value",
                    )
                )
            log_write(ev.ref, ev.plane, ev.time, ev.order)
        elif ev.kind == "read":
            hit = in_flight_on(ev.ref, ev.plane)
            if hit is not None:
                hazards.append(
                    (
                        "read-in-flight-dst",
                        ev,
                        f"read at grid{ev.time} of a buffer a DMA started "
                        f"at grid{hit[1].time} is still writing (no recv "
                        "wait between them)",
                    )
                )
        elif ev.kind == "dma_start":
            recv = ev.info.get("recv_cell")
            if recv is not None:
                in_flight[recv] = (ev.ref, ev.plane, ev)
            send = ev.info.get("send_cell")
            src = ev.info.get("src")
            if src is not None:
                # local copies have no send sem: the src stays fragile
                # until the recv wait retires the transfer
                fragile_src[send if send is not None else recv] = (
                    src,
                    ev.info.get("src_plane"),
                    ev,
                )
        elif ev.kind == "dma_wait":
            cell = ev.info.get("recv_cell")
            if cell in in_flight:
                dref, dplane, _sev = in_flight.pop(cell)
                log_write(dref, dplane, ev.time, ev.order)
                # a local copy's recv wait releases its source too
                fragile_src.pop(cell, None)
            else:
                fragile_src.pop(cell, None)
    rec._replay_cache = (writes, hazards)
    return writes, hazards


def _last_write_before(writes, ref, plane, order):
    """(time, order) of the newest write to (ref, plane) — exact plane,
    whole-ref, or overlapping slice — before program order ``order``."""
    best = None
    for (wref, wplane), log in writes.items():
        if wref != ref or not _overlaps(wplane, plane):
            continue
        for t, o in log:
            if o < order and (best is None or o > best[1]):
                best = (t, o)
    return best


def _finding(case, code, invariant, message) -> Finding:
    return Finding(
        checker=CHECKER,
        severity=ERROR,
        path=case.path,
        line=0,
        code=code,
        symbol=f"{case.key}|{invariant}",
        message=f"[{case.key}] {case.entry}: {message}",
    )


def check_case(case) -> List[Finding]:
    findings: List[Finding] = []
    seen: set = set()

    def emit(code, invariant, message):
        key = (code, invariant)
        if key in seen:
            return
        seen.add(key)
        findings.append(_finding(case, code, invariant, message))

    for ci in range(len(case.calls())):
        for rec in case.sims(ci):
            writes, hazards = replay(rec)
            for kind, ev, detail in hazards:
                emit(
                    "ANL1012",
                    f"call{ci}|{kind}|ref{ev.ref}",
                    f"call #{ci} (device {rec.ctx or 'solo'}): {detail} — "
                    "interpret-mode parity cannot see this (its DMA "
                    "completes synchronously); hardware races",
                )
            # ring-slot lag discipline
            groups: Dict[Tuple, List[Tuple[int, int]]] = {}
            for ev in rec.events:
                if ev.kind != "read":
                    continue
                info = rec.refs[ev.ref]
                if info.role == "scratch" and info.sem_kind is None:
                    w = _last_write_before(writes, ev.ref, ev.plane, ev.order)
                    if w is None:
                        emit(
                            "ANL1011",
                            f"call{ci}|uninitialized|ref{ev.ref}|"
                            f"plane{ev.plane}",
                            f"call #{ci} at grid{ev.time} (device "
                            f"{rec.ctx or 'solo'}): read of scratch "
                            f"ref{ev.ref} plane {ev.plane} that no write "
                            "ever produced on this control path — the "
                            "stage fires before its ring primes",
                        )
                        continue
                    if not _is_ring(info) or not isinstance(ev.plane, int):
                        continue
                    wt, _wo = w
                    same_col = wt[:-1] == ev.time[:-1]
                    lag = ev.time[-1] - wt[-1] if same_col else None
                    if lag is None or lag < 0 or lag > RING_WINDOW:
                        lag_desc = "cross-column" if lag is None else str(lag)
                        emit(
                            "ANL1013",
                            f"call{ci}|stale-slot|ref{ev.ref}",
                            f"call #{ci} at grid{ev.time} (device "
                            f"{rec.ctx or 'solo'}): ring ref{ev.ref} slot "
                            f"{ev.plane} observes the write from "
                            f"grid{wt} — outside the 3-slot window "
                            f"(lag {lag_desc}), so the consumer reads a "
                            "plane the ring already recycled (or a later "
                            "stage's overwrite)",
                        )
                        continue
                    groups.setdefault(
                        (ci, ev.ref, ev.time, ev.branch), []
                    ).append((int(ev.plane), int(lag)))
            for (gci, ref, time, _branch), pairs in groups.items():
                by_plane = dict(pairs)
                if len(by_plane) < 2:
                    continue
                lags = list(by_plane.values())
                if len(set(lags)) != len(lags):
                    emit(
                        "ANL1013",
                        f"call{gci}|slot-collision|ref{ref}",
                        f"call #{gci} at grid{time} (device "
                        f"{rec.ctx or 'solo'}): one stage reads ring "
                        f"ref{ref} planes {sorted(by_plane)} that observe "
                        f"writes from the same step (lags {lags}) — "
                        "distinct planes of a 3-slot ring must carry "
                        "distinct steps; a slot was recycled under a "
                        "still-pending consumer",
                    )
    return findings


def check(root: str, cases=None) -> List[Finding]:
    from heat3d_tpu.analysis.kernel import programs

    if cases is None:
        cases = programs.judged_kernels()
    findings: List[Finding] = []
    for case in cases:
        findings.extend(check_case(case))
    return findings
