"""Concrete kernel-body interpreter — the engine under every kernel-tier
checker.

A Pallas kernel body is a jaxpr over ``Ref``s whose *scheduling* skeleton
(``pl.when`` predicates, ring-slot arithmetic, DMA starts/waits, semaphore
choreography) is a pure function of ``program_id`` and ``lax.axis_index``
— both concrete once a grid step and a device position are fixed. This
module walks that skeleton exactly: it iterates the full grid in the
row-major order the Mosaic pipeline executes (``dimension_semantics`` is
unset/arbitrary on every repo kernel), evaluates every scalar expression
concretely, resolves every ``cond`` branch, and records the effect
stream — ``Ref`` reads/writes with their plane indices, DMA
starts/waits with their semaphore cells and device targets, semaphore
signals/waits — as a timeline of :class:`Event` records the checkers
turn into happens-before verdicts.

Vector *values* are deliberately opaque (class :data:`OPAQUE`): the
interpret-tier parity tests already prove values; this tier proves
*schedules*, which is exactly what those tests cannot see (interpret
mode discharges DMA synchronously, so an unwaited copy or an in-flight
read still produces correct values there).

If a predicate ever fails to resolve concretely (none does today — the
repo kernels branch only on ``program_id``/``axis_index`` arithmetic),
the simulation records the spot in ``ExecRecord.incomplete`` instead of
guessing, and the DMA-discipline checker surfaces it as a warning: an
unanalyzable kernel must read as "not certified", never as clean.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Tuple

import jax.core as jcore
from jax import tree_util


class _Opaque:
    """Marker for values the scalar interpreter does not track."""

    __slots__ = ()

    def __repr__(self):  # pragma: no cover - debug aid
        return "OPAQUE"


OPAQUE = _Opaque()


@dataclasses.dataclass(frozen=True)
class RefToken:
    """Identity of a kernel ``Ref`` operand: its position in the kernel
    jaxpr's invars (stable across retraces of the same source)."""

    idx: int


# the synthetic ref index get_barrier_semaphore yields (kernel invars are
# nonnegative positions; the barrier ref is not an operand)
BARRIER_REF = -1


@dataclasses.dataclass(frozen=True)
class RefInfo:
    idx: int
    role: str  # "in" | "out" | "scratch" | "sem"
    shape: Tuple[int, ...]
    space: str  # memory-space string: "vmem" | "any" | "semaphore_mem" | ...
    sem_kind: Optional[str] = None  # dma_sem | barrier_sem | sem


@dataclasses.dataclass
class Event:
    """One effect at one grid step of one simulated device.

    ``time`` is the grid index tuple, ``order`` a global program-order
    counter (happens-before within and across steps), ``pt`` the static
    program point (eqn-index path through the cond tree — stable across
    retraces), and ``branch`` the enclosing branch path (``pt[:-1]``),
    which groups the reads of one firing stage."""

    kind: str  # read | write | dma_start | dma_wait | sem_signal | sem_wait
    ref: int
    plane: Any  # int plane index | ("s", start, size) | None (whole/unknown)
    time: Tuple[int, ...]
    order: int
    pt: Tuple[int, ...]
    info: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def branch(self) -> Tuple[int, ...]:
        return self.pt[:-1]


@dataclasses.dataclass
class ExecRecord:
    """One full-grid simulation of one kernel on one device position."""

    ctx: Dict[str, Tuple[int, int]]  # axis name -> (index, size)
    grid: Tuple[int, ...]
    refs: List[RefInfo]
    events: List[Event]
    incomplete: List[str]  # reasons analysis is partial (empty = complete)


_SEM_DTYPES = ("dma_sem", "barrier_sem", "sem")


def classify_refs(call_eqn) -> List[RefInfo]:
    """Roles of the kernel jaxpr invars from the grid mapping: scalar
    index operands (none in this repo), then inputs, outputs, scratch
    (semaphores included)."""
    gm = call_eqn.params["grid_mapping"]
    jaxpr = call_eqn.params["jaxpr"]
    n_idx = getattr(gm, "num_index_operands", 0)
    n_in = gm.num_inputs
    n_out = gm.num_outputs
    infos: List[RefInfo] = []
    for i, v in enumerate(jaxpr.invars):
        aval = v.aval
        inner = getattr(aval, "inner_aval", aval)
        dt = str(getattr(inner, "dtype", ""))
        shape = tuple(getattr(inner, "shape", ()))
        space = str(getattr(aval, "memory_space", "") or "")
        if i < n_idx:
            role = "in"
        elif i < n_idx + n_in:
            role = "in"
        elif i < n_idx + n_in + n_out:
            role = "out"
        else:
            role = "sem" if dt in _SEM_DTYPES else "scratch"
        infos.append(
            RefInfo(
                idx=i,
                role=role,
                shape=shape,
                space=space,
                sem_kind=dt if dt in _SEM_DTYPES else None,
            )
        )
    return infos


def _py(x):
    """Concrete python scalar from a numpy/jax 0-d value, else OPAQUE."""
    if isinstance(x, (bool, int, float)):
        return x
    if isinstance(x, _Opaque) or x is None:
        return x
    try:
        if getattr(x, "shape", None) == () or getattr(x, "ndim", None) == 0:
            return x.item()
    except Exception:  # noqa: BLE001 - anything weird stays opaque
        return OPAQUE
    return OPAQUE


def _trunc_rem(a, b):
    # lax.rem is C-style (truncated) remainder, not python's floor mod
    q = int(a / b) if b else 0
    return a - b * q


def _trunc_div(a, b):
    # lax.div on integers truncates toward zero, not python's floor
    if isinstance(a, int) and isinstance(b, int):
        return int(a / b) if b else 0
    return a / b


_BINOPS = {
    "add": lambda a, b: a + b,
    "sub": lambda a, b: a - b,
    "mul": lambda a, b: a * b,
    "max": max,
    "min": min,
    "rem": _trunc_rem,
    "div": _trunc_div,
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "and": lambda a, b: bool(a) and bool(b),
    "or": lambda a, b: bool(a) or bool(b),
    "xor": lambda a, b: bool(a) ^ bool(b),
}

_UNOPS = {
    "not": lambda a: not a,
    "neg": lambda a: -a,
    "sign": lambda a: (a > 0) - (a < 0),
    "abs": abs,
    "floor": int,
    "ceil": lambda a: -int(-a),
}


def _decode_plane(transforms) -> Any:
    """Normalize an indexer-transform chain to a plane id on the leading
    dim: an int for a scalar index, ("s", start, size) for a leading
    slice, None for whole-ref / undecodable access."""
    if not transforms:
        return None
    t = transforms[0]
    indices = getattr(t, "indices", None)
    if indices is None or not len(indices):
        return None
    lead = indices[0]
    lead = _py(lead) if not hasattr(lead, "start") else lead
    if isinstance(lead, (int, bool)):
        return int(lead)
    if hasattr(lead, "start"):
        start = _py(lead.start)
        size = _py(lead.size)
        if isinstance(start, int) and isinstance(size, int):
            return ("s", start, size)
        return None
    return None


class _KernelSim:
    def __init__(self, call_eqn, ctx: Dict[str, Tuple[int, int]]):
        self.eqn = call_eqn
        self.gm = call_eqn.params["grid_mapping"]
        self.jaxpr = call_eqn.params["jaxpr"]
        self.ctx = ctx
        self.refs = classify_refs(call_eqn)
        self.events: List[Event] = []
        self.incomplete: List[str] = []
        self.order = 0
        self.step: Tuple[int, ...] = ()

    # -- value resolution ---------------------------------------------------

    def _val(self, env, v):
        if isinstance(v, jcore.Literal):
            return _py(v.val)
        return env.get(v, OPAQUE)

    def _emit(self, kind, ref, plane, pt, **info):
        self.order += 1
        self.events.append(
            Event(
                kind=kind,
                ref=ref,
                plane=plane,
                time=self.step,
                order=self.order,
                pt=pt,
                info=info,
            )
        )

    def _ref_idx(self, tok) -> Optional[int]:
        if isinstance(tok, RefToken):
            return tok.idx
        return None

    # -- effect primitives --------------------------------------------------

    def _sem_cell(self, ref_tok, transforms):
        idx = self._ref_idx(ref_tok)
        plane = _decode_plane(transforms or ())
        return (idx, plane if isinstance(plane, int) else None)

    def _handle_get(self, eqn, env, pt):
        ref = self._val(env, eqn.invars[0])
        leaves = [self._val(env, v) for v in eqn.invars[1:]]
        transforms = tree_util.tree_unflatten(eqn.params["tree"], leaves)
        ridx = self._ref_idx(ref)
        if ridx is not None:
            self._emit("read", ridx, _decode_plane(transforms), pt)
        for ov in eqn.outvars:
            env[ov] = OPAQUE

    def _handle_swap(self, eqn, env, pt):
        ref = self._val(env, eqn.invars[0])
        leaves = [self._val(env, v) for v in eqn.invars[2:]]
        transforms = tree_util.tree_unflatten(eqn.params["tree"], leaves)
        ridx = self._ref_idx(ref)
        if ridx is not None:
            self._emit("write", ridx, _decode_plane(transforms), pt)
        for ov in eqn.outvars:
            env[ov] = OPAQUE

    def _handle_dma_start(self, eqn, env, pt, *, wait: bool):
        leaves = [self._val(env, v) for v in eqn.invars]
        (
            src,
            src_t,
            dst,
            dst_t,
            dst_sem,
            dst_sem_t,
            src_sem,
            src_sem_t,
            device_id,
        ) = tree_util.tree_unflatten(eqn.params["tree"], leaves)
        src_i = self._ref_idx(src)
        dst_i = self._ref_idx(dst)
        recv_cell = self._sem_cell(dst_sem, dst_sem_t)
        send_cell = (
            self._sem_cell(src_sem, src_sem_t) if src_sem is not None else None
        )
        if isinstance(device_id, dict):
            device_id = {k: _py(v) for k, v in device_id.items()}
        else:
            device_id = _py(device_id)
        kind = "dma_wait" if wait else "dma_start"
        self._emit(
            kind,
            dst_i if dst_i is not None else -2,
            _decode_plane(dst_t),
            pt,
            src=src_i,
            src_plane=_decode_plane(src_t),
            recv_cell=recv_cell,
            send_cell=send_cell,
            device_id=device_id,
            remote=src_sem is not None,
        )

    def _handle_sem(self, eqn, env, pt, name):
        leaves = [self._val(env, v) for v in eqn.invars]
        parts = tree_util.tree_unflatten(eqn.params["args_tree"], leaves)
        sem, sem_t = parts[0], parts[1]
        cell = self._sem_cell(sem, sem_t)
        if name == "semaphore_signal":
            inc = _py(parts[2]) if len(parts) > 2 else 1
            device_id = parts[3] if len(parts) > 3 else None
            if isinstance(device_id, dict):
                device_id = {k: _py(v) for k, v in device_id.items()}
            else:
                device_id = _py(device_id) if device_id is not None else None
            self._emit(
                "sem_signal", cell[0], cell[1], pt, cell=cell, inc=inc,
                device_id=device_id,
            )
        else:
            value = _py(parts[2]) if len(parts) > 2 else 1
            self._emit("sem_wait", cell[0], cell[1], pt, cell=cell, value=value)

    # -- the walk ----------------------------------------------------------

    def _eval_jaxpr(self, jaxpr, env, pt_prefix):
        for ei, eqn in enumerate(jaxpr.eqns):
            pt = pt_prefix + (ei,)
            name = eqn.primitive.name
            if name == "cond":
                pred = self._val(env, eqn.invars[0])
                if isinstance(pred, _Opaque):
                    spot = f"opaque cond predicate at pt={pt}"
                    if spot not in self.incomplete:
                        self.incomplete.append(spot)
                    for ov in eqn.outvars:
                        env[ov] = OPAQUE
                    continue
                branches = eqn.params["branches"]
                bi = min(max(int(pred), 0), len(branches) - 1)
                closed = branches[bi]
                benv = {}
                for cv, c in zip(closed.jaxpr.constvars, closed.consts):
                    benv[cv] = _py(c)
                for bv, opnd in zip(closed.jaxpr.invars, eqn.invars[1:]):
                    benv[bv] = self._val(env, opnd)
                self._eval_jaxpr(closed.jaxpr, benv, pt + (bi,))
                for ov, bo in zip(eqn.outvars, closed.jaxpr.outvars):
                    env[ov] = self._val(benv, bo)
                continue
            if name == "get":
                self._handle_get(eqn, env, pt)
                continue
            if name == "swap":
                self._handle_swap(eqn, env, pt)
                continue
            if name == "dma_start":
                self._handle_dma_start(eqn, env, pt, wait=False)
                continue
            if name == "dma_wait":
                self._handle_dma_start(eqn, env, pt, wait=True)
                continue
            if name in ("semaphore_signal", "semaphore_wait"):
                self._handle_sem(eqn, env, pt, name)
                continue
            if name == "get_barrier_semaphore":
                env[eqn.outvars[0]] = RefToken(BARRIER_REF)
                continue
            if name == "program_id":
                env[eqn.outvars[0]] = int(self.step[eqn.params["axis"]])
                continue
            if name == "num_programs":
                env[eqn.outvars[0]] = int(self.gm.grid[eqn.params["axis"]])
                continue
            if name == "axis_index":
                ax = eqn.params["axis_name"]
                if isinstance(ax, (tuple, list)):
                    ax = ax[0] if len(ax) == 1 else ax
                pos = self.ctx.get(ax)
                env[eqn.outvars[0]] = pos[0] if pos else OPAQUE
                continue
            if name in ("convert_element_type", "copy", "stop_gradient"):
                v = self._val(env, eqn.invars[0])
                if isinstance(v, bool) and "int" in str(
                    eqn.params.get("new_dtype", "")
                ):
                    v = int(v)
                env[eqn.outvars[0]] = v
                continue
            if name == "select_n":
                which = self._val(env, eqn.invars[0])
                if isinstance(which, (bool, int)):
                    env[eqn.outvars[0]] = self._val(
                        env, eqn.invars[1 + int(which)]
                    )
                else:
                    env[eqn.outvars[0]] = OPAQUE
                continue
            if name == "clamp":
                lo, x, hi = (self._val(env, v) for v in eqn.invars)
                if all(isinstance(v, (int, float, bool)) for v in (lo, x, hi)):
                    env[eqn.outvars[0]] = min(max(x, lo), hi)
                else:
                    env[eqn.outvars[0]] = OPAQUE
                continue
            if name in _BINOPS and len(eqn.invars) == 2:
                a = self._val(env, eqn.invars[0])
                b = self._val(env, eqn.invars[1])
                if isinstance(a, (bool, int, float)) and isinstance(
                    b, (bool, int, float)
                ):
                    env[eqn.outvars[0]] = _BINOPS[name](a, b)
                else:
                    env[eqn.outvars[0]] = OPAQUE
                continue
            if name in _UNOPS and len(eqn.invars) == 1:
                a = self._val(env, eqn.invars[0])
                if isinstance(a, (bool, int, float)):
                    env[eqn.outvars[0]] = _UNOPS[name](a)
                else:
                    env[eqn.outvars[0]] = OPAQUE
                continue
            sub = eqn.params.get("jaxpr") or eqn.params.get("call_jaxpr")
            if (
                isinstance(sub, jcore.ClosedJaxpr)
                and name not in ("scan", "while")
                and len(sub.jaxpr.invars) == len(eqn.invars)
            ):
                # inline call-like eqns (pjit from jnp.where etc.): the
                # scheduling scalars often route through them
                benv = {}
                for cv, c in zip(sub.jaxpr.constvars, sub.consts):
                    benv[cv] = _py(c)
                for bv, opnd in zip(sub.jaxpr.invars, eqn.invars):
                    benv[bv] = self._val(env, opnd)
                self._eval_jaxpr(sub.jaxpr, benv, pt + (0,))
                for ov, bo in zip(eqn.outvars, sub.jaxpr.outvars):
                    env[ov] = self._val(benv, bo)
                continue
            if name in ("scan", "while"):
                spot = (
                    f"{name} loop at pt={pt} — kernel control flow the "
                    "simulator does not model"
                )
                if spot not in self.incomplete:
                    self.incomplete.append(spot)
            # vector compute and anything else: opaque outputs
            for ov in eqn.outvars:
                env[ov] = OPAQUE

    def run(self) -> ExecRecord:
        grid = tuple(int(g) for g in self.gm.grid)
        steps = itertools.product(*[range(g) for g in grid]) if grid else [()]
        base_env = {}
        for i, v in enumerate(self.jaxpr.invars):
            base_env[v] = RefToken(i)
        for cv in getattr(self.jaxpr, "constvars", ()):
            base_env[cv] = OPAQUE
        for step in steps:
            self.step = tuple(step)
            self._eval_jaxpr(self.jaxpr, dict(base_env), ())
        return ExecRecord(
            ctx=dict(self.ctx),
            grid=grid,
            refs=self.refs,
            events=self.events,
            incomplete=list(self.incomplete),
        )


def simulate(call_eqn, ctx: Dict[str, Tuple[int, int]]) -> ExecRecord:
    """Run one kernel ``pallas_call`` eqn over its full grid at one device
    position; returns the effect timeline."""
    return _KernelSim(call_eqn, ctx).run()


def out_block_visits(call_eqn):
    """Per-output block-index visit sequences, in row-major grid order:
    ``[(out_index, [(step, block_tuple), ...]), ...]`` — the grid/output
    coverage checker's raw material. Outputs without a windowed block
    mapping (whole-ref VMEM/ANY outputs) yield block ``()`` every step."""
    gm = call_eqn.params["grid_mapping"]
    grid = tuple(int(g) for g in gm.grid)
    steps = (
        list(itertools.product(*[range(g) for g in grid])) if grid else [()]
    )
    out = []
    for oi in range(gm.num_outputs):
        bm = gm.block_mappings[gm.num_inputs + oi]
        cj = bm.index_map_jaxpr
        visits = []
        for step in steps:
            if grid:
                idx = jcore.eval_jaxpr(cj.jaxpr, cj.consts, *step)
                visits.append((tuple(step), tuple(int(i) for i in idx)))
            else:
                visits.append(((), ()))
        out.append((oi, bm, visits))
    return out
