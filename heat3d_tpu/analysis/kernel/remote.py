"""kernel-remote (ANL1031-1033) — remote-copy targets certified against
the mesh neighbor graph and the ExchangePlan schedule.

``make_async_remote_copy`` takes a raw ``device_id`` — nothing stops a
kernel from shipping a face to the wrong chip, and no parity test run
on a synchronous emulator will catch a target that is merely *shifted*
(every device still receives exactly one face; the values are wrong
only at scale, on hardware, under a real mesh). This checker evaluates
each copy's ``device_id`` expression concretely at EVERY device
position of the case's ring and proves:

- **ANL1031** — each remote-copy program point realizes a bijection
  equal to one of the two ±1 ring shifts
  :func:`heat3d_tpu.parallel.halo.shift_perm` builds — the SAME
  neighbor-graph source the ppermute exchange and the IR tier's ANL601
  certify against, so all three tiers answer to one oracle. (The
  kernels always run the torus-symmetric transfer — Dirichlet edges
  substitute values after the wait — so the kernel-side contract is the
  periodic shift.)
- **ANL1032** — a plan-driven exchange must realize the
  ``ExchangePlan``'s axis schedule: one kernel per sharded axis, in the
  plan's corner-propagation order, each moving data along exactly that
  axis (a dict ``device_id`` touching any other mesh axis fires). This
  is the standing gate the fused in-kernel-RDMA superstep arc lands
  against (ROADMAP): a superstep that consumes the plan out of order or
  ships a sub-block off-axis reds this lint on CPU.
- **ANL1033** — direction completeness: every exchange kernel must
  carry BOTH ring directions (on a size-2 ring the two shifts coincide
  — the self-inverse case ANL604 pinned at the IR tier — and one class
  suffices).
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

from heat3d_tpu.analysis.findings import ERROR, Finding

CHECKER = "kernel-remote"


def _finding(case, code, invariant, message) -> Finding:
    return Finding(
        checker=CHECKER,
        severity=ERROR,
        path=case.path,
        line=0,
        code=code,
        symbol=f"{case.key}|{invariant}",
        message=f"[{case.key}] {case.entry}: {message}",
    )


def _target_on_axis(device_id, axis_name):
    """The target coordinate along ``axis_name``, or a reason string.

    Scalar device ids address the (single) shard_map mesh axis; dict
    (MESH partial) ids must move ONLY the exchange axis."""
    if isinstance(device_id, dict):
        if set(device_id) != {axis_name}:
            return None, (
                f"device_id moves mesh axes {sorted(device_id)} — the "
                f"exchange axis is {axis_name!r}"
            )
        v = device_id[axis_name]
        if not isinstance(v, int):
            return None, "device_id not concretely evaluable"
        return v, None
    if isinstance(device_id, int):
        return device_id, None
    return None, "device_id not concretely evaluable"


def check_case(case) -> List[Finding]:
    from heat3d_tpu.parallel.halo import shift_perm

    findings: List[Finding] = []
    seen: set = set()

    def emit(code, invariant, message):
        key = (code, invariant)
        if key in seen:
            return
        seen.add(key)
        findings.append(_finding(case, code, invariant, message))

    if not case.comm:
        return findings
    calls = case.calls()
    if len(calls) != len(case.comm):
        sched = (
            f"plan {case.plan_key}" if case.plan_key else "expected schedule"
        )
        emit(
            "ANL1032",
            "schedule|call-count",
            f"traced program has {len(calls)} exchange kernel(s) but the "
            f"{sched} wants {len(case.comm)} — an axis exchange is "
            "missing or duplicated",
        )
        return findings
    for ci, axis in enumerate(case.comm):
        # pairs per remote-copy program point, aggregated over every
        # device position of the ring
        by_pt: Dict[Tuple, Set[Tuple[int, int]]] = {}
        for rec in case.sims(ci):
            my = rec.ctx.get(axis.name, (None, None))[0]
            for ev in rec.events:
                if ev.kind != "dma_start" or not ev.info.get("remote"):
                    continue
                tgt, reason = _target_on_axis(
                    ev.info.get("device_id"), axis.name
                )
                if tgt is None:
                    emit(
                        "ANL1032" if "axes" in (reason or "") else "ANL1031",
                        f"call{ci}|offaxis|pt{ev.pt}",
                        f"call #{ci} (device {rec.ctx}): {reason}",
                    )
                    continue
                if my is None:
                    emit(
                        "ANL1031",
                        f"call{ci}|noctx",
                        f"call #{ci}: device context lacks the exchange "
                        f"axis {axis.name!r} — matrix entry is stale",
                    )
                    continue
                by_pt.setdefault(ev.pt, set()).add((my, int(tgt)))
        if not by_pt:
            emit(
                "ANL1033",
                f"call{ci}|no-remote-copies",
                f"call #{ci}: exchange kernel issues no remote copies at "
                "all on any device position",
            )
            continue
        # the kernel-side contract is the torus shift (Dirichlet edges
        # substitute values after the wait; the transfer always runs)
        shifts = {
            +1: frozenset(shift_perm(axis.size, +1, True)),
            -1: frozenset(shift_perm(axis.size, -1, True)),
        }
        dirs_found: Set[int] = set()
        for pt, pairs in sorted(by_pt.items()):
            fp = frozenset(pairs)
            matched = [d for d, s in shifts.items() if fp == s]
            if not matched:
                emit(
                    "ANL1031",
                    f"call{ci}|non-neighbor|pt{pt}",
                    f"call #{ci} remote copy at pt{pt}: device targets "
                    f"{sorted(pairs)} are not the ±1 neighbor bijection "
                    f"shift_perm({axis.size}, ±1) on axis "
                    f"{axis.name!r} — the face lands on the wrong chip",
                )
                continue
            dirs_found.update(matched)
        # size-2 rings are exempt: the +1 and -1 shifts coincide
        # (self-inverse), so one matched class covers both directions
        if axis.size > 2 and dirs_found != {+1, -1}:
            emit(
                "ANL1033",
                f"call{ci}|one-way",
                f"call #{ci}: only direction(s) {sorted(dirs_found)} are "
                "exchanged — a halo exchange must push both ring "
                "directions or one face of every shard stays stale",
            )
    return findings


def check(root: str, cases=None) -> List[Finding]:
    from heat3d_tpu.analysis.kernel import programs

    if cases is None:
        cases = programs.judged_kernels()
    findings: List[Finding] = []
    for case in cases:
        findings.extend(check_case(case))
    return findings
