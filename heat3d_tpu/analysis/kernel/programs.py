"""The judged kernel matrix the kernel-tier checkers certify.

Every case is a REAL ``pallas_call`` the production dispatch can build —
the streaming stencil ring (``_stream``), the fused two-update and
k-update supersteps (``_stream2`` / ``_streamk`` at k = 2..4), the
direct in-kernel-BC kernels (single- and multi-chunk, mehrstellen q-ring
included), both DMA halo-exchange kernels (width-1 zero-staging and the
width-k slab path, plus the plan-driven multi-axis composition), and the
fused DMA-overlap step/superstep — traced to a closed jaxpr on CPU
(kernel bodies over ``Ref``s trace without a TPU; shapes mirror the
interpret-tier parity matrix in tests/multidevice_checks.py) and handed
to the checkers as :class:`KernelCase` records.

Tracing uses ``interpret=False`` deliberately: the interpret flag elides
the neighbor-barrier choreography (``use_barrier``), and the kernel tier
exists precisely to certify the schedule the HARDWARE runs, not the one
the emulator runs.

Device posture mirrors the IR tier: the DMA cases want a >= 4-device CPU
backend for their judged ring meshes (``HEAT3D_KERNEL_LINT_DEVICES``,
default 4, forced only while jax is uninitialized); a session that
already booted smaller degrades the matrix and the runner surfaces that
as a warning finding (ANL1040), never a silent green.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
import os
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

ENV_DEVICES = "HEAT3D_KERNEL_LINT_DEVICES"


def wanted_devices() -> int:
    """Device count the full kernel matrix needs (the size-4 rings and
    the (2,2,1) planned-exchange mesh both factor into 4)."""
    return int(os.environ.get(ENV_DEVICES, "4") or 4)


def ensure_devices() -> int:
    """Force a multi-device CPU backend for the judged ring meshes when
    still possible; returns the visible device count either way."""
    from heat3d_tpu.analysis.hostdev import ensure_host_devices

    return ensure_host_devices(wanted_devices())


@dataclasses.dataclass(frozen=True)
class CommAxis:
    """Expected remote-exchange schedule of ONE pallas call: the mesh
    axis its DMAs must move along (±1 ring shifts only)."""

    name: str
    size: int


@dataclasses.dataclass
class KernelCase:
    """One traced kernel program under certification.

    ``key`` is the kernel half of every finding fingerprint — checkers
    anchor findings on ``(checker, key, invariant)``, never on jaxpr
    pretty-printer text, so baselines survive jax upgrades (the same
    contract the IR tier pinned)."""

    key: str
    path: str  # repo-relative module of the kernel body
    entry: str  # public entry symbol (docs/messages)
    build: Callable[[], Tuple[Any, Tuple[Any, ...]]]  # () -> (fn, avals)
    ctxs: Tuple[Dict[str, Tuple[int, int]], ...] = ({},)
    comm: Tuple[CommAxis, ...] = ()  # per-pallas-call expected axis, in order
    plan_key: Optional[str] = None  # ExchangePlan key when plan-driven
    _calls: Any = None
    _sims: Any = None

    def calls(self) -> List[Any]:
        """The case's ``pallas_call`` eqns, in trace order."""
        if self._calls is None:
            import jax

            fn, avals = self.build()
            jaxpr = jax.make_jaxpr(fn)(*avals)
            self._calls = collect_pallas_calls(jaxpr.jaxpr)
            if not self._calls:
                raise ValueError(
                    f"kernel case {self.key}: traced program contains no "
                    "pallas_call — the matrix entry is stale"
                )
        return self._calls

    def sims(self, call_index: int) -> List[Any]:
        """All-device-position simulations of one pallas call (memoized)."""
        from heat3d_tpu.analysis.kernel import interp

        if self._sims is None:
            self._sims = {}
        if call_index not in self._sims:
            eqn = self.calls()[call_index]
            self._sims[call_index] = [
                interp.simulate(eqn, ctx) for ctx in self.ctxs
            ]
        return self._sims[call_index]


def collect_pallas_calls(jaxpr) -> List[Any]:
    """Every pallas_call eqn under ``jaxpr``, depth-first in program
    order (shard_map/jit/cond bodies included)."""
    import jax.core as jcore

    out: List[Any] = []

    def sub(params):
        for v in params.values():
            if isinstance(v, jcore.ClosedJaxpr):
                yield v.jaxpr
            elif isinstance(v, jcore.Jaxpr):
                yield v
            elif isinstance(v, (tuple, list)):
                for x in v:
                    if isinstance(x, jcore.ClosedJaxpr):
                        yield x.jaxpr
                    elif isinstance(x, jcore.Jaxpr):
                        yield x

    def walk(j):
        for eqn in j.eqns:
            if eqn.primitive.name == "pallas_call":
                out.append(eqn)
            for sj in sub(eqn.params):
                walk(sj)

    walk(jaxpr)
    return out


def ring_ctxs(axes: Sequence[Tuple[str, int]]) -> Tuple[Dict, ...]:
    """Every device position of a (small) mesh: the remote checker needs
    the full ring to prove the neighbor bijection, and the race/DMA
    checkers get every edge/interior control path for free."""
    names = [n for n, _ in axes]
    return tuple(
        {n: (i, s) for (n, s), i in zip(axes, pos)}
        for pos in itertools.product(*[range(s) for _, s in axes])
    )


# local shapes: small enough to simulate in milliseconds, large enough
# that every ring primes fully and the deep-tb epilogues are distinct
# phases (nx >= 2k + 2 for streamk, nx >= 4 for the fused superstep)
_SHAPE = (8, 8, 128)


def _taps(kind: str):
    from heat3d_tpu.core.stencils import STENCILS, stencil_taps

    return stencil_taps(STENCILS[kind], 0.1, 0.05, (1.0, 1.0, 1.0))


def _mesh(shape: Tuple[int, ...], names: Tuple[str, ...]):
    import jax
    import numpy as np
    from jax.sharding import Mesh

    n = 1
    for s in shape:
        n *= s
    return Mesh(np.array(jax.devices()[:n]).reshape(shape), names)


def _sharded(fn, mesh, spec):
    from heat3d_tpu.utils.compat import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )


def _stream_case(kind: str) -> KernelCase:
    def build():
        import jax
        import jax.numpy as jnp

        from heat3d_tpu.ops.stencil_pallas import apply_taps_pallas_stream

        taps = _taps(kind)
        nx, ny, nz = _SHAPE
        aval = jax.ShapeDtypeStruct((nx + 2, ny + 2, nz + 2), jnp.float32)
        return (lambda u: apply_taps_pallas_stream(u, taps)), (aval,)

    return KernelCase(
        key=f"stream/{kind}",
        path="heat3d_tpu/ops/stencil_pallas.py",
        entry="apply_taps_pallas_stream",
        build=build,
    )


def _stream2_case() -> KernelCase:
    axes = (("x", 2), ("y", 1), ("z", 1))

    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from heat3d_tpu.ops.stencil_pallas import apply_taps_pallas_stream2

        taps = _taps("7pt")
        mesh = _mesh((2, 1, 1), ("x", "y", "z"))
        nx, ny, nz = _SHAPE
        aval = jax.ShapeDtypeStruct(
            (2 * (nx + 4), ny + 4, nz + 4), jnp.float32
        )
        fn = _sharded(
            lambda u: apply_taps_pallas_stream2(
                u, taps, ("x", "y", "z"), periodic=False, bc_value=1.5
            ),
            mesh,
            P("x", None, None),
        )
        return fn, (aval,)

    return KernelCase(
        key="stream2/7pt",
        path="heat3d_tpu/ops/stencil_pallas.py",
        entry="apply_taps_pallas_stream2",
        build=build,
        ctxs=ring_ctxs(axes),
    )


def _streamk_case(kind: str, k: int, periodic: bool) -> KernelCase:
    axes = (("x", 2), ("y", 1), ("z", 1))

    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from heat3d_tpu.ops.stencil_pallas import apply_taps_pallas_streamk

        taps = _taps(kind)
        mesh = _mesh((2, 1, 1), ("x", "y", "z"))
        nx, ny, nz = _SHAPE
        aval = jax.ShapeDtypeStruct(
            (2 * (nx + 2 * k), ny + 2 * k, nz + 2 * k), jnp.float32
        )
        fn = _sharded(
            lambda u: apply_taps_pallas_streamk(
                u, taps, k, ("x", "y", "z"), periodic=periodic, bc_value=1.5
            ),
            mesh,
            P("x", None, None),
        )
        return fn, (aval,)

    tag = "/periodic" if periodic else ""
    return KernelCase(
        key=f"streamk{k}/{kind}{tag}",
        path="heat3d_tpu/ops/stencil_pallas.py",
        entry="apply_taps_pallas_streamk",
        build=build,
        ctxs=ring_ctxs(axes),
    )


def _direct_case(kind: str, periodic: bool, shape=None, tag="") -> KernelCase:
    shape = shape or _SHAPE

    def build():
        import jax
        import jax.numpy as jnp

        from heat3d_tpu.ops.stencil_pallas_direct import apply_taps_direct

        taps = _taps(kind)
        aval = jax.ShapeDtypeStruct(shape, jnp.float32)
        return (
            lambda u: apply_taps_direct(
                u, taps, periodic=periodic, bc_value=1.5
            )
        ), (aval,)

    ptag = "/periodic" if periodic else ""
    return KernelCase(
        key=f"direct/{kind}{ptag}{tag}",
        path="heat3d_tpu/ops/stencil_pallas_direct.py",
        entry="apply_taps_direct",
        build=build,
    )


def _direct2_case(kind: str) -> KernelCase:
    def build():
        import jax
        import jax.numpy as jnp

        from heat3d_tpu.ops.stencil_pallas_direct import apply_taps_direct2

        taps = _taps(kind)
        aval = jax.ShapeDtypeStruct(_SHAPE, jnp.float32)
        return (
            lambda u: apply_taps_direct2(
                u, taps, periodic=False, bc_value=1.5
            )
        ), (aval,)

    return KernelCase(
        key=f"direct2/{kind}",
        path="heat3d_tpu/ops/stencil_pallas_direct.py",
        entry="apply_taps_direct2",
        build=build,
    )


def _dma_axis_case(width: int, size: int, periodic: bool) -> KernelCase:
    axes = (("x", size),)

    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from heat3d_tpu.ops.halo_pallas import exchange_axis_dma

        mesh = _mesh((size,), ("x",))
        nx, ny, nz = _SHAPE
        aval = jax.ShapeDtypeStruct((size * nx, ny, nz), jnp.float32)
        fn = _sharded(
            lambda u: exchange_axis_dma(
                u, 0, "x", size, ("x",), periodic, 1.5, width=width
            ),
            mesh,
            P("x", None, None),
        )
        return fn, (aval,)

    tag = "/periodic" if periodic else ""
    name = "dma-face" if width == 1 else "dma-slab"
    return KernelCase(
        key=f"{name}/w{width}/x{size}{tag}",
        path="heat3d_tpu/ops/halo_pallas.py",
        entry=(
            "_face_exchange_kernel" if width == 1 else "_slab_exchange_kernel"
        ),
        build=build,
        ctxs=ring_ctxs(axes),
        comm=(CommAxis("x", size),),
    )


def _dma_planned_case() -> Tuple[KernelCase, Any]:
    """The plan-driven multi-axis DMA composition on a (2,2,1) block
    mesh: the traced per-axis kernel sequence must realize the
    ``ExchangePlan``'s axis schedule (the corner-propagation order) —
    this is the standing gate the fused in-kernel-RDMA superstep arc
    lands against (ROADMAP)."""
    from heat3d_tpu.core.config import BoundaryCondition, MeshConfig
    from heat3d_tpu.parallel.plan import build_plan

    mesh_cfg = MeshConfig(shape=(2, 2, 1))
    plan = build_plan(
        mesh_cfg, BoundaryCondition.DIRICHLET, width=1, transport="dma"
    )
    axes = tuple(zip(mesh_cfg.axis_names, mesh_cfg.shape))

    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from heat3d_tpu.ops.halo_pallas import exchange_halo_dma_planned

        mesh = _mesh(mesh_cfg.shape, mesh_cfg.axis_names)
        nx, ny, nz = _SHAPE
        aval = jax.ShapeDtypeStruct((2 * nx, 2 * ny, nz), jnp.float32)
        fn = _sharded(
            lambda u: exchange_halo_dma_planned(u, plan, bc_value=1.5),
            mesh,
            P("x", "y", None),
        )
        return fn, (aval,)

    case = KernelCase(
        key="dma-plan/m2x2x1/w1",
        path="heat3d_tpu/ops/halo_pallas.py",
        entry="exchange_halo_dma_planned",
        build=build,
        ctxs=ring_ctxs(axes),
        comm=tuple(
            CommAxis(spec.name, spec.size)
            for spec in plan.axis_specs
            if spec.size > 1
        ),
        plan_key=plan.key,
    )
    return case, plan


def _fused_case(
    kind: str, periodic: bool, superstep: bool, mesh_axes=("x",), tag="",
    shape=None,
) -> KernelCase:
    size = 4
    names = tuple(mesh_axes)
    mesh_shape = (size,) + (1,) * (len(names) - 1)
    axes = tuple(zip(names, mesh_shape))
    shape = shape or _SHAPE

    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from heat3d_tpu.ops.stencil_dma_fused import (
            apply_step_fused_dma,
            apply_superstep_fused_dma,
        )

        taps = _taps(kind)
        mesh = _mesh(mesh_shape, names)
        nx, ny, nz = shape
        aval = jax.ShapeDtypeStruct((size * nx, ny, nz), jnp.float32)
        apply = apply_superstep_fused_dma if superstep else apply_step_fused_dma
        fn = _sharded(
            lambda u: apply(
                u,
                taps,
                axis_name=names[0],
                axis_size=size,
                mesh_axes=names,
                periodic=periodic,
                bc_value=1.5,
            ),
            mesh,
            P(*([names[0]] + [None] * 2)),
        )
        return fn, (aval,)

    ptag = "/periodic" if periodic else ""
    name = "fused2" if superstep else "fused"
    return KernelCase(
        key=f"{name}/{kind}/x{size}{ptag}{tag}",
        path="heat3d_tpu/ops/stencil_dma_fused.py",
        entry=(
            "apply_superstep_fused_dma" if superstep else "apply_step_fused_dma"
        ),
        build=build,
        ctxs=ring_ctxs(axes),
        comm=(CommAxis(names[0], size),),
    )


def _fused_rdma_case(
    kind: str, periodic: bool, superstep: bool, plan_mode: str,
    tag="", shape=None,
) -> KernelCase:
    """The fused in-kernel RDMA superstep (ops/stencil_fused_rdma): the
    template sweep bodies with the plan-scheduled transport — one
    remote-copy descriptor per (direction, sub-block) of the
    ``ExchangePlan``'s decomposition, each owning its own flat semaphore
    cell. ``plan_mode='partitioned'`` builds the plan with the
    granularity floor off so the certified program genuinely ships
    sub-blocks (the judged discipline: per-descriptor start/wait
    pairing, no semaphore-cell aliasing, remote targets still the ±1
    ring bijection)."""
    size = 4
    axes = (("x", size),)
    shape = shape or _SHAPE
    width = 2 if superstep else 1

    from heat3d_tpu.core.config import BoundaryCondition, MeshConfig
    from heat3d_tpu.parallel.plan import build_plan

    plan = build_plan(
        MeshConfig(shape=(size, 1, 1)),
        BoundaryCondition.PERIODIC if periodic else BoundaryCondition.DIRICHLET,
        width=width,
        transport="ppermute",
        mode=plan_mode,
        min_part_bytes=0,
    )

    def build():
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from heat3d_tpu.ops.stencil_fused_rdma import (
            apply_step_fused_rdma,
            apply_superstep_fused_rdma,
        )

        taps = _taps(kind)
        mesh = _mesh((size,), ("x",))
        nx, ny, nz = shape
        aval = jax.ShapeDtypeStruct((size * nx, ny, nz), jnp.float32)
        apply = (
            apply_superstep_fused_rdma if superstep else apply_step_fused_rdma
        )
        fn = _sharded(
            lambda u: apply(
                u,
                taps,
                plan=plan,
                axis_name="x",
                axis_size=size,
                mesh_axes=("x",),
                periodic=periodic,
                bc_value=1.5,
            ),
            mesh,
            P("x", None, None),
        )
        return fn, (aval,)

    ptag = "/periodic" if periodic else ""
    mtag = "/planned" if plan_mode == "partitioned" else ""
    name = "fused-rdma2" if superstep else "fused-rdma"
    return KernelCase(
        key=f"{name}/{kind}/x{size}{ptag}{mtag}{tag}",
        path="heat3d_tpu/ops/stencil_fused_rdma.py",
        entry=(
            "apply_superstep_fused_rdma"
            if superstep
            else "apply_step_fused_rdma"
        ),
        build=build,
        ctxs=ring_ctxs(axes),
        comm=(CommAxis("x", size),),
        plan_key=plan.key,
    )


@functools.lru_cache(maxsize=1)
def _cached_matrix() -> Tuple[KernelCase, ...]:
    import jax

    n = len(jax.devices())
    cases: List[KernelCase] = [
        _stream_case("7pt"),
        _stream_case("27pt"),
        _direct_case("7pt", periodic=False),
        _direct_case("7pt", periodic=True),
        _direct_case("27pt", periodic=False),
        _direct2_case("7pt"),
    ]
    if n >= 2:
        cases += [
            _stream2_case(),
            _streamk_case("27pt", 2, periodic=False),
            _streamk_case("7pt", 3, periodic=True),
            _streamk_case("7pt", 4, periodic=False),
        ]
    if n >= 4:
        cases += [
            _dma_axis_case(width=1, size=4, periodic=False),
            _dma_axis_case(width=2, size=4, periodic=True),
            _dma_axis_case(width=4, size=4, periodic=False),
            _dma_planned_case()[0],
            _fused_case("7pt", periodic=False, superstep=False),
            _fused_case("27pt", periodic=True, superstep=False),
            _fused_case(
                "7pt", periodic=False, superstep=False,
                mesh_axes=("x", "y", "z"), tag="/mesh3",
            ),
            _fused_case("7pt", periodic=False, superstep=True),
            _fused_case("27pt", periodic=True, superstep=True),
            # multi-chunk-column variants: the 2D grid re-primes the
            # rings per column and derives j-dependent ghost rows —
            # the cross-column happens-before discipline is its own
            # control-flow family (the (8,1024,512) local block chunks
            # at by=512 / by=256 under the default VMEM budget)
            _fused_case(
                "7pt", periodic=False, superstep=False,
                shape=(8, 1024, 512), tag="/chunked",
            ),
            _fused_case(
                "7pt", periodic=False, superstep=True,
                shape=(8, 1024, 512), tag="/chunked",
            ),
            # the plan-scheduled fused RDMA superstep: monolithic (one
            # descriptor per direction — the degenerate plan) and
            # partitioned (per-sub-block descriptors, flat semaphore
            # cells) arms, step and tb=2 forms, at every ring position
            _fused_rdma_case(
                "7pt", periodic=False, superstep=False,
                plan_mode="monolithic",
            ),
            _fused_rdma_case(
                "27pt", periodic=True, superstep=False,
                plan_mode="partitioned",
            ),
            _fused_rdma_case(
                "7pt", periodic=False, superstep=True,
                plan_mode="partitioned",
            ),
            _fused_rdma_case(
                "27pt", periodic=True, superstep=True,
                plan_mode="monolithic",
            ),
            # multi-chunk + partitioned sends: the cross-column ring
            # re-prime composed with per-sub-block descriptor waits
            _fused_rdma_case(
                "7pt", periodic=False, superstep=False,
                plan_mode="partitioned", shape=(8, 1024, 512),
                tag="/chunked",
            ),
        ]
        cases.append(
            _direct_case(
                "7pt", periodic=False, shape=(8, 1024, 512), tag="/chunked"
            )
        )
    return tuple(cases)


def judged_kernels() -> List[KernelCase]:
    """The full kernel certification matrix for the current device
    posture (degraded below 4 devices — the runner warns)."""
    return list(_cached_matrix())
