"""Kernel-tier certification — the Pallas DMA/race verifier behind
``heat3d lint --kernel``.

The AST tier (PR 6) audits source, the IR tier (PR 9) audits the traced
programs — and both documented the same blind spot: ``pallas_call``
bodies were opaque, so every in-kernel DMA (the slab exchanges, the
fused streaming overlap, the upcoming in-kernel RDMA superstep) was
certified only by interpret-tier *value* parity, which proves values
but not schedules. This package closes that: every repo Pallas kernel
body is traced to its jaxpr on CPU (:mod:`.programs` — kernel functions
over ``Ref``s trace without a TPU), a concrete interpreter replays the
full grid at every judged device position (:mod:`.interp`), and four
checker families certify the schedule —

- :mod:`.dma` (ANL1001-1005): every DMA start has exactly one matching
  wait on every control path, no wait-without-start, no semaphore
  aliasing across in-flight copies, balanced neighbor barriers;
- :mod:`.races` (ANL1011-1013): a happens-before graph over ``Ref``
  reads/writes and DMA edges proving the %3 VMEM rings never read a
  slot a still-in-flight copy or a recycled-slot write may clobber;
- :mod:`.coverage` (ANL1021-1023): each output element written exactly
  once across the grid, via index-map abstract interpretation;
- :mod:`.remote` (ANL1031-1033): every ``make_async_remote_copy``
  device target realizes the ±1 neighbor bijection
  ``parallel.halo.shift_perm`` builds, and plan-driven exchanges
  realize the ``ExchangePlan`` axis schedule — the standing gate the
  fused in-kernel-RDMA arc lands against.

Findings report through the shared framework (severity policy, inline +
baseline suppression, ``--json``) and fingerprint on
``(checker, kernel-case key, invariant)`` — never jaxpr text, the same
stability contract the IR tier pinned.
"""

from __future__ import annotations

from typing import List

from heat3d_tpu.analysis.findings import Finding

# checker name -> module path, mirroring analysis.CHECKERS / IR_CHECKERS
KERNEL_CHECKERS = {
    "kernel-dma": "heat3d_tpu.analysis.kernel.dma",
    "kernel-races": "heat3d_tpu.analysis.kernel.races",
    "kernel-coverage": "heat3d_tpu.analysis.kernel.coverage",
    "kernel-remote": "heat3d_tpu.analysis.kernel.remote",
}


def run_kernel_checkers(root: str, names: List[str]) -> List[Finding]:
    """Trace the judged kernel matrix ONCE, run every named family over
    it. Mirrors the AST/IR runners: a crashed family or a broken matrix
    is an ANL000 error finding, never a silent green. Emits the
    ``kernel_lint_start`` / ``kernel_lint_verdict`` ledger events
    (fail-soft NullLedger when no ledger is active)."""
    import importlib

    from heat3d_tpu import obs
    from heat3d_tpu.analysis.kernel import programs

    findings: List[Finding] = []
    devices = None
    cases = None
    try:
        devices = programs.ensure_devices()
        cases = programs.judged_kernels()
    except Exception as e:  # noqa: BLE001 - surfaced as a finding
        findings.append(
            Finding(
                checker="kernel-matrix",
                severity="error",
                path="heat3d_tpu/analysis/kernel",
                line=0,
                code="ANL000",
                symbol="judged_kernels",
                message=(
                    f"kernel-matrix build crashed: {type(e).__name__}: "
                    f"{e} — no kernel was certified (a broken matrix is "
                    "a silent green)"
                ),
            )
        )
        cases = []
    obs.get().event(
        "kernel_lint_start",
        families=list(names),
        cases=len(cases),
        devices=devices,
    )
    want = programs.wanted_devices()
    if cases and devices is not None and devices < want:
        findings.append(
            Finding(
                checker="kernel-matrix",
                severity="warning",
                path="heat3d_tpu/analysis/kernel",
                line=0,
                code="ANL1040",
                symbol="degraded-matrix",
                message=(
                    f"jax initialized with {devices} device(s) before the "
                    f"kernel lint could force its {want}-device CPU mesh "
                    "(HEAT3D_KERNEL_LINT_DEVICES): the judged matrix lost "
                    "its DMA exchange rings and fused-overlap kernels, so "
                    "the DMA/remote families certified almost nothing "
                    "this run — run `heat3d lint --kernel` in a fresh "
                    "process"
                ),
            )
        )
    for name in names:
        try:
            mod = importlib.import_module(KERNEL_CHECKERS[name])
            findings.extend(mod.check(root, cases=cases))
        except Exception as e:  # noqa: BLE001 - surfaced as a finding
            findings.append(
                Finding(
                    checker=name,
                    severity="error",
                    path="heat3d_tpu/analysis/kernel",
                    line=0,
                    code="ANL000",
                    symbol=name,
                    message=(
                        f"checker crashed: {type(e).__name__}: {e} — fix "
                        "the checker (a broken lint is a silent green)"
                    ),
                )
            )
    counts = {"error": 0, "warning": 0, "info": 0}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    obs.get().event("kernel_lint_verdict", families=list(names), **counts)
    return findings
