"""Checker: config-knob drift across the five surfaces that must agree.

A performance knob is only real when five layers agree on it:
``SolverConfig`` carries it, the tuner's lattice searches it, the CLI
exposes it, the bench rows record it (so the regression gate and the
provenance lint can key on it), and docs/TUNING.md teaches it. PR 4's
``halo_order`` landed all five by hand; nothing would have caught a PR
that landed four. This checker loads the five surfaces LIVE (the real
``CONFIG_KNOBS``/``DEFAULT_KNOBS``/parser — a registry copy would just
be a sixth thing to drift) and cross-checks:

- ANL501: ``tune.cache.CONFIG_KNOBS`` (the canonical knob tuple — the
  cache entry schema) must all be ``SolverConfig`` fields;
- ANL502: every ``tune.space.DEFAULT_KNOBS`` key must be a config knob
  (or ``mesh``, the opt-in topology axis);
- ANL503: every config knob must be searched by the default lattice;
- ANL504: every config knob must have its ``--flag`` on the solver CLI
  (the bench CLI inherits that parser);
- ANL505: every config knob must be recorded on bench throughput rows
  (``bench/harness.py``);
- ANL506: every provenance route field the lint requires
  (``analysis.provenance.ROUTE_FIELDS``) must be recorded on rows;
- ANL507: every config knob must be documented in docs/TUNING.md.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set

from heat3d_tpu.analysis import astutil
from heat3d_tpu.analysis.findings import ERROR, Finding

CHECKER = "knob-drift"

_CACHE_PY = "heat3d_tpu/tune/cache.py"
_SPACE_PY = "heat3d_tpu/tune/space.py"
_CLI_PY = "heat3d_tpu/cli.py"
_HARNESS_PY = "heat3d_tpu/bench/harness.py"
_TUNING_MD = "docs/TUNING.md"


def _harness_row_keys(root: str, harness_path: str) -> Set[str]:
    """String keys of dict literals (plus string subscript-assignment
    targets, ``row["x"] = ...``) in the bench harness — the row field
    names. Deliberately NOT every string literal: a knob named in a
    docstring or log message must not count as 'recorded on rows'."""
    tree = astutil.parse_file(os.path.join(root, harness_path))
    if tree is None:
        return set()
    keys: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Dict):
            for k in n.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
        elif isinstance(n, ast.Assign):
            for t in n.targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)
                ):
                    keys.add(t.slice.value)
    return keys


def check(
    root: str,
    knobs: Optional[Sequence[str]] = None,
    space_keys: Optional[Sequence[str]] = None,
    cli_flags: Optional[Sequence[str]] = None,
    row_strings: Optional[Set[str]] = None,
    route_fields: Optional[Sequence[str]] = None,
    tuning_doc: Optional[str] = None,
) -> List[Finding]:
    """All sources are injectable for fixture tests; by default the LIVE
    surfaces are loaded."""
    import dataclasses

    from heat3d_tpu.core.config import SolverConfig

    if knobs is None:
        from heat3d_tpu.tune.cache import CONFIG_KNOBS as knobs  # type: ignore[no-redef]
    if space_keys is None:
        from heat3d_tpu.tune.space import DEFAULT_KNOBS

        space_keys = list(DEFAULT_KNOBS)
    if cli_flags is None:
        from heat3d_tpu.cli import build_parser

        cli_flags = [
            s for a in build_parser()._actions for s in a.option_strings
        ]
    if row_strings is None:
        row_strings = _harness_row_keys(root, _HARNESS_PY)
    if route_fields is None:
        from heat3d_tpu.analysis.provenance import ROUTE_FIELDS as route_fields  # type: ignore[no-redef]
    if tuning_doc is None:
        try:
            with open(os.path.join(root, _TUNING_MD)) as f:
                tuning_doc = f.read()
        except OSError:
            tuning_doc = ""

    cfg_fields = {f.name for f in dataclasses.fields(SolverConfig)}
    findings: List[Finding] = []

    def add(code: str, path: str, symbol: str, message: str) -> None:
        findings.append(
            Finding(
                checker=CHECKER,
                severity=ERROR,
                path=path,
                line=0,
                code=code,
                symbol=symbol,
                message=message,
            )
        )

    for k in knobs:
        if k not in cfg_fields:
            add(
                "ANL501", _CACHE_PY, k,
                f"CONFIG_KNOBS lists '{k}' but SolverConfig has no such "
                "field — the cache entry schema and the config surface "
                "disagree",
            )
    for k in space_keys:
        if k not in knobs and k != "mesh":
            add(
                "ANL502", _SPACE_PY, k,
                f"DEFAULT_KNOBS searches '{k}' which is not a config knob "
                "(tune.cache.CONFIG_KNOBS) — the tuner would measure a "
                "knob the cache cannot store or resolve",
            )
    for k in knobs:
        if k not in space_keys:
            add(
                "ANL503", _SPACE_PY, k,
                f"config knob '{k}' is absent from the default search "
                "lattice (DEFAULT_KNOBS) — auto resolution can serve a "
                "knob the search never measures",
            )
        flag = "--" + k.replace("_", "-")
        if flag not in cli_flags:
            add(
                "ANL504", _CLI_PY, k,
                f"config knob '{k}' has no CLI flag {flag} — a tuned "
                "winner cannot be applied from the command line "
                "(tune apply emits flag lines)",
            )
        if k not in row_strings:
            add(
                "ANL505", _HARNESS_PY, k,
                f"config knob '{k}' is not recorded on bench rows — the "
                "regression gate and sweep journals cannot key on it, so "
                "A/Bs of this knob are unprovenanced",
            )
        if tuning_doc and k not in tuning_doc:
            add(
                "ANL507", _TUNING_MD, k,
                f"config knob '{k}' is undocumented in docs/TUNING.md — "
                "add it to the knob table",
            )
    for rf in route_fields:
        if rf not in row_strings:
            add(
                "ANL506", _HARNESS_PY, rf,
                f"provenance route field '{rf}' (required by "
                "check_provenance on throughput rows) is not recorded by "
                "the bench harness — every new row would fail the "
                "provenance lint",
            )
    return findings
