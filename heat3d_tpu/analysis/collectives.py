"""Checker: collective-divergence — the deadlock-by-construction hazard.

The MPI ancestry of this codebase makes one defect class fatal in a way
no unit test can catch: a collective (``ppermute``/``psum``/remote DMA)
that *some* participants execute and others skip. On a pod that is not a
failing test — it is a hung slice. The classic way to write one is a
Python-level conditional around a collective whose truth value differs
across participants at trace time:

- **process-divergent** (``jax.process_index() == 0 and ...``): each host
  traces its own program, so the guard compiles the collective into some
  programs and not others — the TPU analog of an ``MPI_Isend`` with no
  matching ``MPI_Irecv``.
- **device-divergent** (``if lax.axis_index(..)``-derived values): a
  traced per-device value in Python control flow — a trace-time error at
  best, divergence if it ever concretizes.
- **data-dependent** (``if float(jnp.max(u)) > t:``): host-materialized
  array data steering whether a collective is traced; processes seeing
  different shards take different branches.

The checker flags collectives (and calls to *collective-bearing* repo
functions — a call-graph fixpoint over the scanned files, so wrapping
``ppermute`` in ``axis_ghosts`` in ``exchange_axis`` hides nothing)
guarded by such conditionals. Uniform guards — static config flags,
``periodic``, axis sizes, ``pl.when`` (traced, all devices evaluate it) —
are not flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from heat3d_tpu.analysis import astutil
from heat3d_tpu.analysis.findings import ERROR, Finding

CHECKER = "collective-divergence"

# jax collective primitives (dotted-name tails)
COLLECTIVE_CALLS = {
    "ppermute",
    "psum",
    "psum_scatter",
    "pmean",
    "pmax",
    "pmin",
    "pbroadcast",
    "all_gather",
    "all_to_all",
    "make_async_remote_copy",
}

# host-level process identity: different VALUES on different hosts at
# trace time -> divergent programs
PROCESS_DIVERGENT_CALLS = {
    "process_index",
    "process_count",
    "is_coordinator",
    "host_id",
    "gethostname",
    "getpid",
}

# traced per-device identity: a Python branch on it is device-divergent
DEVICE_DIVERGENT_CALLS = {"axis_index"}

# host materialization of traced data: float()/int()/bool()/.item() over
# a jnp/lax-derived value inside a conditional
_MATERIALIZERS = {"float", "int", "bool"}
_ARRAY_MODULES = ("jnp", "jax.numpy", "lax", "jax.lax")


def _tail(name: Optional[str]) -> Optional[str]:
    return name.rsplit(".", 1)[-1] if name else None


def _collect_taint(func: ast.AST) -> Tuple[Set[str], Set[str], Set[str]]:
    """(process_tainted, device_tainted, data_tainted) local names in
    ``func``: simple one-level flow from ``x = <divergent call>`` /
    ``x = jnp.<op>(...)`` assignments."""
    process: Set[str] = set()
    device: Set[str] = set()
    data: Set[str] = set()
    for node in ast.walk(func):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        tail = _tail(astutil.call_name(node.value))
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not targets or tail is None:
            continue
        dn = astutil.call_name(node.value) or ""
        if tail in PROCESS_DIVERGENT_CALLS:
            process.update(targets)
        elif tail in DEVICE_DIVERGENT_CALLS:
            device.update(targets)
        elif any(dn.startswith(m + ".") for m in _ARRAY_MODULES):
            data.update(targets)
    return process, device, data


def _classify_test(
    test: ast.AST,
    process_taint: Set[str],
    device_taint: Set[str],
    data_taint: Set[str],
) -> Optional[Tuple[str, str, str]]:
    """(kind, code, witness) when the conditional can diverge across
    participants, else None."""
    for call in astutil.calls_in(test):
        tail = _tail(astutil.call_name(call))
        if tail in PROCESS_DIVERGENT_CALLS:
            return ("process-dependent", "ANL101", ast.unparse(test))
        if tail in DEVICE_DIVERGENT_CALLS:
            return ("device-dependent", "ANL102", ast.unparse(test))
        if tail in _MATERIALIZERS or tail == "item":
            inner = call.args[0] if call.args else call.func
            inner_names = set(astutil.names_in(inner))
            if tail == "item" or inner_names & data_taint or any(
                (astutil.call_name(c) or "").startswith(m + ".")
                for c in astutil.calls_in(inner)
                for m in _ARRAY_MODULES
            ):
                return ("data-dependent", "ANL103", ast.unparse(test))
    names = set(astutil.names_in(test))
    if names & process_taint:
        return ("process-dependent", "ANL101", ast.unparse(test))
    if names & device_taint:
        return ("device-dependent", "ANL102", ast.unparse(test))
    if names & data_taint:
        return ("data-dependent", "ANL103", ast.unparse(test))
    return None


def _collective_bearing_fixpoint(
    trees: Dict[str, ast.Module]
) -> Set[str]:
    """Names of functions (across the scanned files) that transitively
    contain a direct collective call — matched by simple name, which is
    deliberately conservative for a lint."""
    contains: Set[str] = set()
    calls: Dict[str, Set[str]] = {}
    for tree in trees.values():
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            name = node.name
            callees = calls.setdefault(name, set())
            for call in astutil.calls_in(node):
                tail = _tail(astutil.call_name(call))
                if tail in COLLECTIVE_CALLS:
                    contains.add(name)
                elif tail:
                    callees.add(tail)
    changed = True
    while changed:
        changed = False
        for name, callees in calls.items():
            if name not in contains and callees & contains:
                contains.add(name)
                changed = True
    return contains


def check(
    root: str,
    files: Optional[Sequence[str]] = None,
) -> List[Finding]:
    paths = list(
        files
        if files is not None
        else astutil.iter_py_files(root, subdirs=("heat3d_tpu",))
    )
    trees: Dict[str, ast.Module] = {}
    for p in paths:
        t = astutil.parse_file(p)
        if t is not None:
            trees[p] = t
    bearing = _collective_bearing_fixpoint(trees)

    findings: List[Finding] = []
    for path, tree in trees.items():
        relpath = astutil.rel(root, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            tail = _tail(astutil.call_name(node))
            if tail in COLLECTIVE_CALLS:
                direct = True
            elif tail in bearing:
                direct = False
            else:
                continue
            func = astutil.enclosing_function(node)
            if func is None:
                continue
            guards = astutil.guarding_conditionals(node)
            if not guards:
                continue
            taints = _collect_taint(func)
            for test, _stmt in guards:
                verdict = _classify_test(test, *taints)
                if verdict is None:
                    continue
                kind, code, witness = verdict
                what = (
                    f"collective '{astutil.call_name(node)}'"
                    if direct
                    else f"call to collective-bearing '{tail}'"
                )
                findings.append(
                    Finding(
                        checker=CHECKER,
                        # all three divergence classes are deadlock
                        # hazards — data-dependent (ANL103) included
                        severity=ERROR,
                        path=relpath,
                        line=node.lineno,
                        code=code,
                        symbol=astutil.qualname(func),
                        message=(
                            f"{what} is guarded by a {kind} conditional "
                            f"`{witness}` (line {test.lineno}): participants "
                            "may disagree about executing the collective — "
                            "a pod-deadlock hazard (conditionally-skipped "
                            "collective). Hoist the collective out of the "
                            "branch or make the guard uniform (static "
                            "config / pl.when / jnp.where)."
                        ),
                    )
                )
                break  # one finding per collective site is enough
    return findings
