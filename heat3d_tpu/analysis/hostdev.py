"""Shared CPU host-device forcing for the program-tracing lint tiers.

Both the IR tier (``HEAT3D_IR_DEVICES``) and the kernel tier
(``HEAT3D_KERNEL_LINT_DEVICES``) want a multi-device CPU backend for
their judged meshes/rings, and both can only get one BEFORE jax
initializes. This is the single implementation of that dance (it leans
on a private jax API — ``xla_bridge.backends_are_initialized`` — which
must not be duplicated per tier), and the place ``lint --all`` resolves
ONE posture for the whole process: the max of every tier's wanted
count, so one tier's default cannot silently degrade another's
configured matrix.
"""

from __future__ import annotations

import os


def ensure_host_devices(want: int) -> int:
    """Force ``want`` CPU host devices when jax is still uninitialized
    (no-op otherwise — callers surface a degraded posture themselves);
    returns the visible device count either way."""
    import jax

    try:
        from jax._src import xla_bridge

        initialized = xla_bridge.backends_are_initialized()
    except Exception:  # noqa: BLE001 - private API; assume the worst
        initialized = True
    if not initialized and want > 1:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={want}"
            ).strip()
    return len(jax.devices())
