"""Shared AST plumbing for the static checkers.

All checkers *parse* the files they audit (they never import them — a
fixture module full of seeded deadlocks must be analyzable without being
executable), so the common needs live here: file discovery, a parse
cache, parent links, dotted call-name resolution, and ancestor walks
(enclosing function, guarding conditionals, guarding ``try`` blocks).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Tuple

_PARSE_CACHE: Dict[str, ast.Module] = {}


def repo_root() -> str:
    """The checkout root: the directory holding the ``heat3d_tpu``
    package (works from an installed location too, as long as the layout
    is a source checkout)."""
    import heat3d_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(heat3d_tpu.__file__)))


def rel(root: str, path: str) -> str:
    return os.path.relpath(os.path.abspath(path), os.path.abspath(root))


def iter_py_files(
    root: str,
    subdirs: Tuple[str, ...] = ("heat3d_tpu",),
    extras: Tuple[str, ...] = (),
    exclude_dirs: Tuple[str, ...] = ("__pycache__",),
) -> Iterator[str]:
    """Absolute paths of the .py files under ``root/subdirs`` plus the
    ``extras`` (root-relative), sorted for deterministic reports."""
    out: List[str] = []
    for sub in subdirs:
        base = os.path.join(root, sub)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames if d not in exclude_dirs]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    for extra in extras:
        p = os.path.join(root, extra)
        if os.path.isfile(p):
            out.append(p)
    return iter(sorted(out))


def parse_file(path: str) -> Optional[ast.Module]:
    """Parse (cached, parent-linked); None on unreadable/unparseable —
    the caller decides whether that itself is a finding."""
    path = os.path.abspath(path)
    if path in _PARSE_CACHE:
        return _PARSE_CACHE[path]
    try:
        with open(path) as f:
            tree = ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None
    add_parents(tree)
    _PARSE_CACHE[path] = tree
    return tree


def clear_cache() -> None:
    _PARSE_CACHE.clear()


def add_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.parent = node  # type: ignore[attr-defined]


def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None (calls/subscripts in
    the chain break it — ``obs.get().event`` resolves to None here and is
    handled by the taxonomy checker's method-name fallback)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def method_name(call: ast.Call) -> Optional[str]:
    """The trailing attribute of a call (``anything.event(...)`` ->
    ``event``), regardless of whether the receiver chain is resolvable."""
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def enclosing_function(node: ast.AST) -> Optional[ast.AST]:
    cur = getattr(node, "parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = getattr(cur, "parent", None)
    return None


def qualname(func: ast.AST) -> str:
    """``Class.method`` / ``outer.inner`` / ``func`` for a FunctionDef,
    from the parent chain."""
    parts = [func.name]  # type: ignore[union-attr]
    cur = getattr(func, "parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            parts.append(cur.name)
        cur = getattr(cur, "parent", None)
    return ".".join(reversed(parts))


def guarding_conditionals(node: ast.AST) -> List[Tuple[ast.AST, ast.AST]]:
    """(test, statement) for every ``if``/``while``/ternary ancestor whose
    body-or-orelse contains ``node`` — the Python-level control flow that
    decides whether ``node`` executes at trace time."""
    out: List[Tuple[ast.AST, ast.AST]] = []
    cur = node
    parent = getattr(node, "parent", None)
    while parent is not None:
        if isinstance(parent, (ast.If, ast.While)) and cur is not parent.test:
            out.append((parent.test, parent))
        elif isinstance(parent, ast.IfExp) and cur is not parent.test:
            out.append((parent.test, parent))
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break  # conditionals outside the enclosing function don't count
        cur, parent = parent, getattr(parent, "parent", None)
    return out


def guarding_handlers(node: ast.AST) -> List[List[str]]:
    """For each ``try`` ancestor that ``node`` sits in the *body* of (not
    a handler/finally), the list of caught exception-name strings of its
    handlers (``[]`` entry = bare ``except``, catches everything)."""
    out: List[List[str]] = []
    cur = node
    parent = getattr(node, "parent", None)
    while parent is not None:
        if isinstance(parent, ast.Try) and _in_try_body(parent, cur):
            names: List[str] = []
            for h in parent.handlers:
                names.extend(_handler_names(h))
            out.append(names)
        if isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        cur, parent = parent, getattr(parent, "parent", None)
    return out


def _in_try_body(try_node: ast.Try, child: ast.AST) -> bool:
    return any(child is stmt for stmt in try_node.body)


def _handler_names(h: ast.ExceptHandler) -> List[str]:
    if h.type is None:
        return [""]  # bare except
    if isinstance(h.type, ast.Tuple):
        return [dotted_name(e) or "?" for e in h.type.elts]
    return [dotted_name(h.type) or "?"]


def names_in(node: ast.AST) -> List[str]:
    return [n.id for n in ast.walk(node) if isinstance(n, ast.Name)]


def calls_in(node: ast.AST) -> List[ast.Call]:
    return [n for n in ast.walk(node) if isinstance(n, ast.Call)]


def literal_str_arg(call: ast.Call, index: int = 0) -> Optional[str]:
    if len(call.args) > index:
        a = call.args[index]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            return a.value
    return None
