"""Checker: equation-registry drift across the surfaces that must agree.

An equation family is only real when four layers agree on it: the
``heat3d_tpu.eqn`` registry defines it, the solver CLI exposes it
(``--equation``), docs/EQUATIONS.md teaches it (the family table), and
the test suite exercises it against its fp64 golden/MMS reference. The
knob-drift checker (ANL501-507) guards the tuner-knob pentagon the same
way; this one guards the family square — an undocumented or untested
family is a finding, not a feature. Live surfaces are loaded (the real
registry, the real parser), the docs leg is row-anchored like the
taxonomy checker's (``| `name` |`` — a deleted row cannot ride on a
longer name's row).

- ANL521: registry vs CLI ``--equation`` choices drift (either
  direction — a family the CLI cannot select, or a CLI choice the
  registry does not define);
- ANL522: registry vs docs/EQUATIONS.md family-table drift (either
  direction);
- ANL523: a family without a manufactured-solution reference
  (``mms_rates``) — its convergence can never be certified;
- ANL524: a family no test file ever names — registered and documented
  but unexercised.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Set

from heat3d_tpu.analysis.findings import ERROR, Finding

CHECKER = "eqn-registry"

_EQN_INIT = "heat3d_tpu/eqn/__init__.py"
_CLI_PY = "heat3d_tpu/cli.py"
_EQN_MD = "docs/EQUATIONS.md"
_TESTS_DIR = "tests"


def _docs_families(doc_text: str) -> Set[str]:
    """Family names with a row in the docs table — anchored on the
    backticked row start (``| `name` |``), the ANL404 discipline."""
    out: Set[str] = set()
    for line in doc_text.splitlines():
        if line.startswith("| `"):
            name = line[3:].split("`", 1)[0]
            if name:
                out.add(name)
    return out


def _tests_text(root: str) -> str:
    chunks = []
    tdir = os.path.join(root, _TESTS_DIR)
    if os.path.isdir(tdir):
        for fn in sorted(os.listdir(tdir)):
            if fn.endswith(".py"):
                try:
                    with open(os.path.join(tdir, fn)) as f:
                        chunks.append(f.read())
                except OSError:
                    pass
    return "\n".join(chunks)


def check(
    root: str,
    families: Optional[Dict[str, object]] = None,
    cli_choices: Optional[Sequence[str]] = None,
    doc_text: Optional[str] = None,
    tests_text: Optional[str] = None,
) -> List[Finding]:
    """All sources injectable for fixture tests; by default the LIVE
    surfaces are loaded (the same posture as the knob-drift checker)."""
    if families is None:
        from heat3d_tpu.eqn import FAMILIES as families  # type: ignore[no-redef]
    if cli_choices is None:
        from heat3d_tpu.cli import build_parser

        cli_choices = []
        for a in build_parser()._actions:
            if "--equation" in a.option_strings:
                cli_choices = list(a.choices or [])
    if doc_text is None:
        try:
            with open(os.path.join(root, _EQN_MD)) as f:
                doc_text = f.read()
        except OSError:
            doc_text = ""
    if tests_text is None:
        tests_text = _tests_text(root)

    findings: List[Finding] = []

    def add(code: str, path: str, symbol: str, message: str) -> None:
        findings.append(
            Finding(
                checker=CHECKER,
                severity=ERROR,
                path=path,
                line=0,
                code=code,
                symbol=symbol,
                message=message,
            )
        )

    reg = set(families)
    cli = set(cli_choices)
    for name in sorted(reg - cli):
        add(
            "ANL521", _CLI_PY, name,
            f"equation family '{name}' is registered but not a CLI "
            "--equation choice — operators cannot select it "
            "(the choices must come from the live registry)",
        )
    for name in sorted(cli - reg):
        add(
            "ANL521", _CLI_PY, name,
            f"CLI --equation choice '{name}' is not a registered family "
            "— selecting it fails at config validation",
        )

    documented = _docs_families(doc_text)
    for name in sorted(reg - documented):
        add(
            "ANL522", _EQN_MD, name,
            f"equation family '{name}' has no row in the "
            "docs/EQUATIONS.md family table — an undocumented family "
            "is invisible to operators",
        )
    for name in sorted(documented - reg):
        add(
            "ANL522", _EQN_MD, name,
            f"docs/EQUATIONS.md documents family '{name}' which the "
            "registry does not define — stale docs row",
        )

    for name in sorted(reg):
        fam = families[name]
        if not callable(getattr(fam, "mms_rates", None)):
            add(
                "ANL523", _EQN_INIT, name,
                f"equation family '{name}' carries no manufactured-"
                "solution reference (mms_rates) — its convergence order "
                "can never be certified against an analytic solution",
            )
        if f'"{name}"' not in tests_text and f"'{name}'" not in tests_text:
            add(
                "ANL524", _EQN_INIT, name,
                f"equation family '{name}' is never named by any test "
                "file — registered and documented but unexercised "
                "(add an MMS/golden test; tests/test_eqn.py is the home)",
            )
    return findings
