"""``heat3d lint`` — run the static-analysis checkers over the repo.

Usage::

    heat3d lint                          # all five checkers, human table
    heat3d lint --json                   # machine verdict (CI gate)
    heat3d lint --checker vmem-budget    # one checker (repeatable / CSV)
    heat3d lint --write-baseline         # grandfather current findings
    heat3d lint --list                   # checker catalog
    heat3d lint --ir [--json]            # IR-tier program verifier
    heat3d lint --ir --checker ir-dtype  # one IR family
    heat3d lint --kernel [--json]        # kernel-tier Pallas verifier
    heat3d lint --all [--json]           # every tier, one merged verdict

``--ir`` switches to the IR-tier catalog (:mod:`heat3d_tpu.analysis.ir`):
instead of parsing source, it traces the judged config matrix through
the real step/superstep/ensemble builders and certifies the closed
jaxprs (collective topology, halo footprint, dtype flow, compiled
memory contract). Same severity/suppression/baseline machinery; IR
findings fingerprint on (checker, config-key, invariant), so baselines
survive jaxpr pretty-printer drift across jax versions.

``--kernel`` switches to the kernel-tier catalog
(:mod:`heat3d_tpu.analysis.kernel`): every repo Pallas kernel body is
traced to its jaxpr and a concrete interpreter replays the full grid,
certifying DMA start/wait discipline, ring-slot happens-before, output
coverage and remote-copy neighbor targets — the schedules the
interpret-tier value-parity tests cannot see. Fingerprints anchor on
(checker, kernel-case key, invariant), same stability contract.

``--all`` runs the AST, IR and kernel tiers in ONE process and merges
everything into a single verdict (one JSON document, one rc) — the
pre-merge sweep ``scripts/lint_all.sh`` uses.

Severity policy (docs/ANALYSIS.md): rc 1 **only** on unsuppressed
error-severity findings — warnings are drift that needs a decision, info
is headroom context; neither reds a build. Suppression is two-layer:
inline ``# heat3d-lint: ok=<checker>`` comments on the flagged line, and
the repo-root baseline file (``.heat3d-lint-baseline.json``) holding
line-number-free fingerprints of grandfathered findings. Regenerate the
baseline with ``--write-baseline`` only after reviewing that every entry
is genuinely grandfathered, not new.
"""

from __future__ import annotations

import argparse
import importlib
import os
import sys
from typing import List, Optional

from heat3d_tpu.analysis import CHECKERS
from heat3d_tpu.analysis import astutil
from heat3d_tpu.analysis.findings import (
    BASELINE_NAME,
    Finding,
    apply_suppressions,
    exit_code,
    load_baseline,
    render_json,
    render_table,
    write_baseline,
)


def run_checkers(root: str, names: List[str]) -> List[Finding]:
    """All findings from the named checkers, in catalog order. A checker
    that crashes is itself an error finding — a broken lint must never
    read as a clean repo."""
    astutil.clear_cache()
    findings: List[Finding] = []
    for name in names:
        try:
            mod = importlib.import_module(CHECKERS[name])
            findings.extend(mod.check(root))
        except Exception as e:  # noqa: BLE001 - surfaced as a finding
            findings.append(
                Finding(
                    checker=name,
                    severity="error",
                    path="heat3d_tpu/analysis",
                    line=0,
                    code="ANL000",
                    symbol=name,
                    message=(
                        f"checker crashed: {type(e).__name__}: {e} — fix "
                        "the checker (a broken lint is a silent green)"
                    ),
                )
            )
    return findings


def _resolve_checkers(raw: List[str], catalog=None) -> List[str]:
    catalog = CHECKERS if catalog is None else catalog
    if not raw:
        return list(catalog)
    names: List[str] = []
    for item in raw:
        for name in item.split(","):
            name = name.strip()
            if name not in catalog:
                raise SystemExit(
                    f"heat3d lint: unknown checker {name!r} "
                    f"(known: {', '.join(catalog)})"
                )
            if name not in names:
                names.append(name)
    return names


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="heat3d lint",
        description="SPMD-safety and invariant lints over the repo "
        "(docs/ANALYSIS.md). rc 1 only on unsuppressed error-severity "
        "findings.",
    )
    p.add_argument("--json", action="store_true", help="machine verdict")
    p.add_argument(
        "--ir", action="store_true",
        help="run the IR-tier program verifier (trace the judged config "
        "matrix and certify the closed jaxprs) instead of the source "
        "checkers",
    )
    p.add_argument(
        "--kernel", action="store_true",
        help="run the kernel-tier Pallas verifier (trace every repo "
        "kernel body and certify DMA discipline, ring races, output "
        "coverage and remote targets) instead of the source checkers",
    )
    p.add_argument(
        "--all", action="store_true",
        help="run every tier (AST + IR + kernel) in one process with a "
        "single merged verdict and rc",
    )
    p.add_argument(
        "--checker", action="append", default=[],
        help="run only this checker (repeatable, or comma-separated)",
    )
    p.add_argument(
        "--root", default=None,
        help="checkout root to lint (default: the root of the installed "
        "source tree)",
    )
    p.add_argument(
        "--baseline", default=None,
        help=f"baseline suppressions file (default: <root>/{BASELINE_NAME})",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="regenerate the baseline from the current unsuppressed "
        "findings and exit 0 (review the diff before committing)",
    )
    p.add_argument(
        "--no-suppress", action="store_true",
        help="report everything, ignoring the baseline and inline "
        "suppressions (audit view)",
    )
    p.add_argument(
        "--list", action="store_true", help="print the checker catalog"
    )
    args = p.parse_args(argv)

    if sum(map(bool, (args.ir, args.kernel, args.all))) > 1:
        raise SystemExit(
            "heat3d lint: --ir, --kernel and --all are mutually exclusive"
        )
    if args.ir:
        from heat3d_tpu.analysis.ir import IR_CHECKERS as catalog
    elif args.kernel:
        from heat3d_tpu.analysis.kernel import KERNEL_CHECKERS as catalog
    elif args.all:
        from heat3d_tpu.analysis.ir import IR_CHECKERS
        from heat3d_tpu.analysis.kernel import KERNEL_CHECKERS

        catalog = {**CHECKERS, **IR_CHECKERS, **KERNEL_CHECKERS}
    else:
        catalog = CHECKERS

    if args.list:
        for name, modpath in catalog.items():
            doc = (importlib.import_module(modpath).__doc__ or "").strip()
            print(f"{name}: {doc.splitlines()[0]}")
        return 0

    root = os.path.abspath(args.root) if args.root else astutil.repo_root()
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    names = _resolve_checkers(args.checker, catalog)

    if args.ir:
        from heat3d_tpu.analysis.ir import run_ir_checkers

        findings = run_ir_checkers(root, names)
    elif args.kernel:
        from heat3d_tpu.analysis.kernel import run_kernel_checkers

        findings = run_kernel_checkers(root, names)
    elif args.all:
        from heat3d_tpu.analysis.hostdev import ensure_host_devices
        from heat3d_tpu.analysis.ir import run_ir_checkers
        from heat3d_tpu.analysis.ir import programs as ir_programs
        from heat3d_tpu.analysis.kernel import run_kernel_checkers
        from heat3d_tpu.analysis.kernel import programs as kernel_programs

        # one process, three tiers, one merged verdict: AST first (no
        # jax), then the device-posture-sensitive tiers. ONE posture is
        # resolved up front — the max of every tier's wanted count — so
        # whichever tier initializes jax first cannot silently degrade
        # the other's configured matrix (e.g. HEAT3D_IR_DEVICES=8 with
        # the kernel tier's default 4)
        ast_names = [n for n in names if n in CHECKERS]
        ir_names = [n for n in names if n in IR_CHECKERS]
        kernel_names = [n for n in names if n in KERNEL_CHECKERS]
        if ir_names or kernel_names:
            ensure_host_devices(
                max(
                    ir_programs.wanted_devices(),
                    kernel_programs.wanted_devices(),
                )
            )
        findings = list(run_checkers(root, ast_names))
        if kernel_names:
            findings.extend(run_kernel_checkers(root, kernel_names))
        if ir_names:
            findings.extend(run_ir_checkers(root, ir_names))
    else:
        findings = run_checkers(root, names)
    baseline = load_baseline(baseline_path)
    if args.no_suppress:
        kept, suppressed = findings, []
    else:
        kept, suppressed = apply_suppressions(root, findings, baseline)

    if args.write_baseline:
        # Regenerate from the current findings with only INLINE
        # suppressions applied — a still-firing grandfathered finding
        # must stay in the baseline, not silently drop out and red the
        # next run. Entries owned by checkers not run this invocation
        # are carried over verbatim.
        kept_inline, _ = apply_suppressions(root, findings, {})
        # never grandfather a checker crash: its fingerprint is anchored
        # on the checker name alone, so one baselined ANL000 would
        # suppress EVERY future crash of that checker — the exact silent
        # green the ANL000 tripwire exists to prevent
        kept_inline = [f for f in kept_inline if f.code != "ANL000"]
        carried = [
            e for e in baseline.values() if e.get("checker") not in names
        ]
        n = write_baseline(baseline_path, kept_inline, carry=carried)
        print(
            f"heat3d lint: baseline written to {baseline_path} "
            f"({n} suppression(s))"
        )
        return 0

    if args.json:
        render_json(kept, suppressed, names)
    else:
        render_table(kept, suppressed)
    return exit_code(kept)


if __name__ == "__main__":
    sys.exit(main())
