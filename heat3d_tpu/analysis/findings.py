"""The shared finding/severity/suppression framework every checker emits
through.

A checker is a function ``check(root, ...) -> List[Finding]``; the CLI
(``heat3d lint`` — :mod:`heat3d_tpu.analysis.cli`) runs them, applies the
two suppression layers, and renders a human table or ``--json``. The
contract downstream tooling relies on:

- **Severity policy**: ``error`` findings are invariant violations that
  would (or will, on the next pod session) break production — rc 1;
  ``warning`` is drift that needs a decision but not a red build;
  ``info`` is headroom/attribution context. Only unsuppressed *errors*
  fail the lint.
- **Suppression**: (a) an inline ``# heat3d-lint: ok=<checker>[,..]``
  comment on the flagged line (self-documenting, for single sites whose
  justification belongs next to the code); (b) the baseline file
  (``.heat3d-lint-baseline.json`` at the repo root) holding fingerprints
  of grandfathered findings — regenerate with ``heat3d lint
  --write-baseline`` after reviewing that every entry is genuinely
  grandfathered, not new. Fingerprints are line-number-free (checker |
  code | path | symbol-or-normalized-message), so routine edits don't
  invalidate the baseline.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

ERROR = "error"
WARNING = "warning"
INFO = "info"
SEVERITIES = (ERROR, WARNING, INFO)

BASELINE_NAME = ".heat3d-lint-baseline.json"
BASELINE_VERSION = 1

# inline suppression: `# heat3d-lint: ok` (all checkers) or
# `# heat3d-lint: ok=checker-a,checker-b` on the flagged line
_INLINE_RE = re.compile(r"#\s*heat3d-lint:\s*ok(?:=([\w,-]+))?")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect: which checker, how bad, where, what."""

    checker: str  # checker name, e.g. "collective-divergence"
    severity: str  # error | warning | info
    path: str  # repo-relative file path
    line: int  # 1-based; 0 = file/project-level finding
    code: str  # stable short code, e.g. "ANL101"
    message: str
    symbol: Optional[str] = None  # enclosing function/registry key, if any

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"severity {self.severity!r} not in {SEVERITIES}")

    def fingerprint(self) -> str:
        """Line-number-free identity for baseline suppression: stable
        across unrelated edits to the file (numbers in the message are
        normalized away so shape/byte counts don't churn the baseline)."""
        anchor = self.symbol or re.sub(r"\d+", "N", self.message)
        base = f"{self.checker}|{self.code}|{self.path}|{anchor}"
        return hashlib.sha1(base.encode()).hexdigest()[:16]

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d


# ---- suppression ----------------------------------------------------------


def load_baseline(path: str) -> Dict[str, Dict[str, Any]]:
    """fingerprint -> suppression entry from the baseline file (empty when
    the file is absent or unreadable — a broken baseline must surface the
    findings it hid, never hide them harder)."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    if not isinstance(data, dict):
        return {}
    out: Dict[str, Dict[str, Any]] = {}
    for entry in data.get("suppressions") or []:
        if isinstance(entry, dict) and isinstance(entry.get("fingerprint"), str):
            out[entry["fingerprint"]] = entry
    return out


_ENTRY_KEYS = (
    "fingerprint", "checker", "code", "path", "symbol", "severity", "message"
)


def write_baseline(
    path: str,
    findings: Iterable[Finding],
    carry: Iterable[Dict[str, Any]] = (),
) -> int:
    """Regenerate the baseline from the given findings; returns the entry
    count. Entries carry enough context to review the file without
    re-running the lint. ``carry`` preserves prior entries verbatim —
    the CLI passes the entries owned by checkers NOT run this
    invocation, so ``--checker X --write-baseline`` cannot wipe every
    other checker's grandfathered sites."""
    entries = [
        {
            "fingerprint": f.fingerprint(),
            "checker": f.checker,
            "code": f.code,
            "path": f.path,
            "symbol": f.symbol,
            "severity": f.severity,
            "message": f.message,
        }
        for f in findings
    ]
    seen = {e["fingerprint"] for e in entries}
    for e in carry:
        if isinstance(e, dict) and e.get("fingerprint") not in seen:
            entries.append({k: e.get(k) for k in _ENTRY_KEYS})
            seen.add(e["fingerprint"])
    entries.sort(key=lambda e: (e["checker"], e["path"], e["fingerprint"]))
    payload = {"version": BASELINE_VERSION, "suppressions": entries}
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)
        f.write("\n")
    return len(entries)


def _inline_suppressed(root: str, finding: Finding, cache: Dict[str, List[str]]) -> bool:
    if finding.line <= 0:
        return False
    path = os.path.join(root, finding.path)
    if path not in cache:
        try:
            with open(path) as f:
                cache[path] = f.readlines()
        except OSError:
            cache[path] = []
    lines = cache[path]
    if finding.line > len(lines):
        return False
    m = _INLINE_RE.search(lines[finding.line - 1])
    if not m:
        return False
    which = m.group(1)
    return which is None or finding.checker in which.split(",")


def apply_suppressions(
    root: str,
    findings: List[Finding],
    baseline: Dict[str, Dict[str, Any]],
) -> Tuple[List[Finding], List[Finding]]:
    """(kept, suppressed): baseline fingerprints and inline ``heat3d-lint:
    ok`` comments both suppress."""
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    cache: Dict[str, List[str]] = {}
    for f in findings:
        if f.fingerprint() in baseline or _inline_suppressed(root, f, cache):
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed


# ---- reporting ------------------------------------------------------------


def counts(findings: Iterable[Finding]) -> Dict[str, int]:
    c = {s: 0 for s in SEVERITIES}
    for f in findings:
        c[f.severity] += 1
    return c


def exit_code(findings: Iterable[Finding]) -> int:
    """rc 1 only on unsuppressed error-severity findings."""
    return 1 if any(f.severity == ERROR for f in findings) else 0


def render_table(
    findings: List[Finding], suppressed: List[Finding], out=None
) -> None:
    import sys

    out = out or sys.stdout
    by_checker: Dict[str, List[Finding]] = {}
    for f in findings:
        by_checker.setdefault(f.checker, []).append(f)
    sev_order = {ERROR: 0, WARNING: 1, INFO: 2}
    for checker in sorted(by_checker):
        print(f"\n[{checker}]", file=out)
        for f in sorted(
            by_checker[checker], key=lambda f: (sev_order[f.severity], f.path, f.line)
        ):
            loc = f"{f.path}:{f.line}" if f.line else f.path
            sym = f" ({f.symbol})" if f.symbol else ""
            print(f"  {f.severity.upper():<7} {f.code} {loc}{sym}: {f.message}", file=out)
    c = counts(findings)
    tail = f"{len(findings)} finding(s): {c[ERROR]} error, {c[WARNING]} warning, {c[INFO]} info"
    if suppressed:
        tail += f"; {len(suppressed)} suppressed"
    print(("\n" if findings else "") + tail, file=out)


def data_lint_main(
    argv,
    label: str,
    check_file,
    doc: Optional[str],
    taxonomy_flag: bool = False,
    max_report: int = 20,
) -> int:
    """Shared CLI driver for the promoted data lints (ledger,
    provenance): one flag surface and report shape, so the two
    thin-wrapper scripts cannot drift. ``check_file(path, start_line
    [, taxonomy=...]) -> [(line, description), ...]``; rc 1 on any
    defect, 2 on usage errors."""
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    start_line = 1
    taxonomy = False
    flags = ("--start-line", "--taxonomy") if taxonomy_flag else ("--start-line",)
    while argv and argv[0] in flags:
        if argv[0] == "--taxonomy":
            taxonomy = True
            argv = argv[1:]
            continue
        if len(argv) < 2:
            print("--start-line needs a value", file=sys.stderr)
            return 2
        start_line = int(argv[1])
        argv = argv[2:]
    if not argv:
        print(doc, file=sys.stderr)
        return 2
    kwargs = {"taxonomy": taxonomy} if taxonomy_flag else {}
    failed = False
    for path in argv:
        bad = check_file(path, start_line, **kwargs)
        if not bad:
            print(f"{label} ok: {path}")
            continue
        failed = True
        print(
            f"{label} FAIL: {path}: {len(bad)} defect(s)", file=sys.stderr
        )
        for line_no, desc in bad[:max_report]:
            print(f"  {path}:{line_no}: {desc}", file=sys.stderr)
        if len(bad) > max_report:
            print(f"  ... and {len(bad) - max_report} more", file=sys.stderr)
    return 1 if failed else 0


def render_json(
    findings: List[Finding],
    suppressed: List[Finding],
    checkers_run: List[str],
    out=None,
) -> None:
    import sys

    out = out or sys.stdout
    payload = {
        "version": 1,
        "checkers": checkers_run,
        "counts": counts(findings),
        "suppressed": len(suppressed),
        "rc": exit_code(findings),
        "findings": [f.to_dict() for f in findings],
    }
    json.dump(payload, out, indent=2, default=repr)
    out.write("\n")
