"""Checker: Pallas VMEM budget estimator — over-budget kernels fail lint,
not a pod session.

Every Pallas kernel family in ``ops/`` gates itself on an empirically
tuned explicit-buffer budget (``stream_supported``/``streamk_supported``/
``direct_supported``'s ring + pipeline arithmetic) plus the shared Mosaic
scoped-stack budget for the tap chain. Those budgets are plain module
constants; nothing related them to what a chip actually *has* — a PR
nudging one past a generation's VMEM capacity would compile fine, pass
every CPU test, and first fail as a Mosaic allocation error on the pod.

Three audits:

1. **AST, ring-slot invariant** (ANL302): every ``pltpu.VMEM`` scratch
   ring in ``ops/`` whose leading dim is a literal must be the 3-slot
   ring the streaming schedule assumes (slot ``p % 3``; a 4-slot ring
   silently breaks the slot arithmetic, a 2-slot ring corrupts planes).
2. **AST, cost provenance** (ANL301): every ``pl.pallas_call`` carries a
   ``cost_estimate`` — the roofline/attribution path treats Mosaic calls
   as opaque without one.
3. **Arithmetic, budget-vs-capacity** (ANL303/304/305 + headroom info):
   drives the repo's OWN estimators (``_stream_vmem_bytes``,
   ``_stream2_vmem_bytes``, ``_streamk_vmem_bytes`` with its 3-slot rings
   at k ≤ 4, ``stencil_pallas_direct._vmem_bytes``) over the judged-config
   local shapes and checks each family's admit budget and the admitted
   worst-case footprints against per-chip-generation VMEM capacities
   (margin-adjusted: Mosaic needs headroom for spills and semaphores).
   The scoped tap-stack budget is checked against the compiler's 16 MiB
   scoped-vmem pool separately (it is a separate pool from the explicit
   buffers — see ops/stencil_pallas.py).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from heat3d_tpu.analysis import astutil
from heat3d_tpu.analysis.findings import ERROR, INFO, Finding

CHECKER = "vmem-budget"

MIB = 1024 * 1024


def __getattr__(name: str):
    """Per-generation VMEM capacity (bytes/core), keys spelled as the
    tuning cache normalizes them. The table itself lives in
    ops/stencil_dma_fused.py since PR 9: the fused-DMA gate resolves its
    whole-chip budget from it per live generation, so the checker audits
    the SAME numbers the production gate uses (one source, no drift).
    Resolved lazily (PEP 562) because importing ops pulls jax — this
    module must stay cheap to import for `heat3d lint --list` and the
    pure-AST leg."""
    if name == "CHIP_VMEM_BYTES":
        from heat3d_tpu.ops.stencil_dma_fused import CHIP_VMEM_BYTES

        return CHIP_VMEM_BYTES
    raise AttributeError(name)

# Mosaic's default scoped-vmem pool (the tap-chain stack lives here — a
# separate pool from the explicit ring/pipeline buffers).
SCOPED_STACK_CAP = 16 * MIB

# fraction of capacity the explicit buffers may claim (spill/semaphore
# headroom)
MARGIN = 0.85

# judged-config local blocks (BASELINE.json ladder): single-chip rows,
# the 1024^3 x-slab shard, and the pod-scale 3D-block shard
JUDGED_LOCAL_SHAPES: Tuple[Tuple[int, int, int], ...] = (
    (128, 128, 128),
    (256, 256, 256),
    (512, 512, 512),
    (1024, 1024, 1024),
    (128, 1024, 1024),
)
_ITEMSIZES = (4, 2)  # fp32, bf16 storage


def _ast_findings(root: str, files: Sequence[str]) -> List[Finding]:
    findings: List[Finding] = []
    for path in files:
        tree = astutil.parse_file(path)
        if tree is None:
            continue
        relpath = astutil.rel(root, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node) or ""
            tail = name.rsplit(".", 1)[-1]
            if tail == "pallas_call":
                kwargs = {kw.arg for kw in node.keywords}
                if "cost_estimate" not in kwargs:
                    findings.append(
                        Finding(
                            checker=CHECKER,
                            severity=ERROR,
                            path=relpath,
                            line=node.lineno,
                            code="ANL301",
                            symbol=_sym(node),
                            message=(
                                "pl.pallas_call without a cost_estimate: "
                                "XLA sees Mosaic calls as opaque, so this "
                                "kernel's flops/bytes vanish from roofline "
                                "attribution and step_cost provenance — "
                                "attach pl.CostEstimate(...)"
                            ),
                        )
                    )
            elif tail == "VMEM" and name.endswith("pltpu.VMEM"):
                slots = _leading_literal(node)
                if slots is not None and slots != 3:
                    findings.append(
                        Finding(
                            checker=CHECKER,
                            severity=ERROR,
                            path=relpath,
                            line=node.lineno,
                            code="ANL302",
                            symbol=_sym(node),
                            message=(
                                f"VMEM scratch ring has {slots} slots; the "
                                "streaming schedule's slot arithmetic "
                                "(plane p lives in slot p % 3) requires "
                                "exactly 3 — a different ring size breaks "
                                "plane residency silently"
                            ),
                        )
                    )
    return findings


def _sym(node: ast.AST) -> Optional[str]:
    fn = astutil.enclosing_function(node)
    return astutil.qualname(fn) if fn is not None else None


def _leading_literal(vmem_call: ast.Call) -> Optional[int]:
    """The first element of ``pltpu.VMEM((N, ...), dtype)`` when it is a
    literal int, else None (dynamic ring extents are shape math, not slot
    counts)."""
    if not vmem_call.args:
        return None
    shape = vmem_call.args[0]
    if isinstance(shape, ast.Tuple) and shape.elts:
        first = shape.elts[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, int):
            return first.value
    return None


def _budget_findings(
    chip_table: Dict[str, int], margin: float
) -> List[Finding]:
    """Drive the real estimator modules (imported, not parsed — the
    arithmetic IS the artifact under audit)."""
    from heat3d_tpu.ops import stencil_pallas as sp
    from heat3d_tpu.ops import stencil_pallas_direct as spd

    findings: List[Finding] = []
    budgets = [
        ("windowed per-step budget (_VMEM_STEP_BUDGET)",
         "heat3d_tpu/ops/stencil_pallas.py", sp._VMEM_STEP_BUDGET),
        ("streaming ring budget (_STREAM_VMEM_BUDGET)",
         "heat3d_tpu/ops/stencil_pallas.py", sp._STREAM_VMEM_BUDGET),
        ("fused stream2/streamk budget (_FUSED_STREAM_VMEM_BUDGET)",
         "heat3d_tpu/ops/stencil_pallas.py", sp._FUSED_STREAM_VMEM_BUDGET),
        ("direct-kernel ring budget (_VMEM_BUDGET)",
         "heat3d_tpu/ops/stencil_pallas_direct.py", spd._VMEM_BUDGET),
    ]
    floor_gen = min(chip_table, key=chip_table.get)
    for label, path, budget in budgets:
        for gen, cap in sorted(chip_table.items()):
            if budget > cap * margin:
                findings.append(
                    Finding(
                        checker=CHECKER,
                        severity=ERROR,
                        path=path,
                        line=0,
                        code="ANL303",
                        symbol=label.split("(")[-1].rstrip(")"),
                        message=(
                            f"{label} = {budget / MIB:.1f} MiB exceeds "
                            f"{margin:.0%} of {gen}'s {cap / MIB:.0f} MiB "
                            "VMEM: the gate would admit a kernel Mosaic "
                            "cannot allocate on that generation"
                        ),
                    )
                )
    if sp._TAP_STACK_BUDGET > SCOPED_STACK_CAP:
        findings.append(
            Finding(
                checker=CHECKER,
                severity=ERROR,
                path="heat3d_tpu/ops/stencil_pallas.py",
                line=0,
                code="ANL304",
                symbol="_TAP_STACK_BUDGET",
                message=(
                    f"tap-stack budget {sp._TAP_STACK_BUDGET / MIB:.1f} MiB "
                    f"exceeds Mosaic's {SCOPED_STACK_CAP / MIB:.0f} MiB "
                    "scoped-vmem pool — chains admitted by the gate would "
                    "fail scoped-stack reservation at compile"
                ),
            )
        )
    # The old standing ANL305 warning (fused-DMA 32 MiB default vs
    # 16 MiB parts) is resolved since PR 9: the gate resolves its
    # whole-chip ceiling per generation from THIS table
    # (ops/stencil_dma_fused.chip_vmem_budget_for). The gate-side
    # adjudication — resolved budget vs capacity, including the live
    # HEAT3D_VMEM_BYTES override — now lives in the IR memory-contract
    # checker (ANL905, analysis/ir/memcontract.py), not here.

    # admitted worst-case footprints over the judged shapes: anything the
    # gates admit must fit the floor generation, with headroom reported
    floor_cap = chip_table[floor_gen]
    for shape in JUDGED_LOCAL_SHAPES:
        for item in _ITEMSIZES:
            families = []
            if sp.stream_supported(shape, item, item):
                families.append(
                    ("stream", sp._stream_vmem_bytes(shape, item, item))
                )
            if sp.stream2_supported(shape, item, item):
                families.append(
                    ("stream2", sp._stream2_vmem_bytes(shape, item, item))
                )
            for k in (2, 3, 4):
                if sp.streamk_supported(shape, k, item, item):
                    families.append(
                        (
                            f"streamk k={k}",
                            sp._streamk_vmem_bytes(shape, k, item, item),
                        )
                    )
            for family, footprint in families:
                if footprint > floor_cap * margin:
                    findings.append(
                        Finding(
                            checker=CHECKER,
                            severity=ERROR,
                            path="heat3d_tpu/ops/stencil_pallas.py",
                            line=0,
                            code="ANL306",
                            symbol=family,
                            message=(
                                f"{family} admits local shape {shape} "
                                f"itemsize {item} at "
                                f"{footprint / MIB:.1f} MiB — over "
                                f"{margin:.0%} of {floor_gen}'s "
                                f"{floor_cap / MIB:.0f} MiB VMEM"
                            ),
                        )
                    )
                elif footprint > floor_cap * margin * 0.95:
                    findings.append(
                        Finding(
                            checker=CHECKER,
                            severity=INFO,
                            path="heat3d_tpu/ops/stencil_pallas.py",
                            line=0,
                            code="ANL307",
                            symbol=family,
                            message=(
                                f"{family} at local shape {shape} itemsize "
                                f"{item} uses {footprint / MIB:.1f} MiB — "
                                f"within 5% of the {floor_gen} admit "
                                "ceiling (headroom watch)"
                            ),
                        )
                    )
    return findings


def check(
    root: str,
    files: Optional[Sequence[str]] = None,
    chip_table: Optional[Dict[str, int]] = None,
    margin: float = MARGIN,
    arithmetic: bool = True,
) -> List[Finding]:
    import os

    paths = list(
        files
        if files is not None
        else (
            p
            for p in astutil.iter_py_files(root, subdirs=("heat3d_tpu",))
            if os.sep + "ops" + os.sep in p
        )
    )
    findings = _ast_findings(root, paths)
    if arithmetic and files is None:
        if chip_table is None:
            # module __getattr__ resolves the canonical ops-owned table
            # lazily; plain global lookup would bypass it
            from heat3d_tpu.analysis import vmem as _self

            chip_table = _self.CHIP_VMEM_BYTES
        findings.extend(_budget_findings(chip_table, margin))
    elif arithmetic and chip_table is not None:
        findings.extend(_budget_findings(chip_table, margin))
    return findings
