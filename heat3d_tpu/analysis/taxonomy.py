"""Checker: ledger-event taxonomy + ``HEAT3D_*`` env-knob registry drift.

The ledger's event vocabulary and the env-knob surface are contracts
consumed far from where they are produced: ``obs summary`` pattern-
matches event names, operators grep docs/OBSERVABILITY.md for knobs,
``check_ledger`` audits streams. Five PRs in, both had drifted — spans
emitted nowhere in the docs (``init_state``, ``tune_probe``), env knobs
documented nowhere at all (``HEAT3D_PROBE_TIMEOUT`` and the whole
``HEAT3D_BENCH_*`` family). This checker pins all three legs together
through :mod:`heat3d_tpu.analysis.registry`:

- every ``.event("name")`` / ``.span("name")`` literal (plus registered
  wrapper calls like ``_event_once`` and the ledger's internal
  ``_write(name, kind)``) must name a registered event, with the
  registered *kind* (point vs span) matching the emission form;
- every registered event must appear in docs/OBSERVABILITY.md (the
  taxonomy table) — and registry entries nothing emits anymore are
  flagged stale (``external`` entries, emitted by generated child code
  the AST cannot see, are exempt from the emission check only);
- every ``HEAT3D_*`` token referenced in ``heat3d_tpu/``, ``bench.py``
  or ``scripts/`` must be a registered env var, every registered var
  must be documented, and registered-but-unreferenced vars are stale.
  Prefix references (``HEAT3D_BENCH_*`` in prose) match any registered
  var that extends them.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from heat3d_tpu.analysis import astutil
from heat3d_tpu.analysis.findings import ERROR, WARNING, Finding
from heat3d_tpu.analysis.registry import ENV_VARS, EVENT_WRAPPERS, LEDGER_EVENTS

CHECKER = "ledger-taxonomy"

_ENV_TOKEN = re.compile(r"HEAT3D_[A-Z0-9_]+")
_DOCS = "docs/OBSERVABILITY.md"


def _emissions(
    root: str, files: Sequence[str]
) -> List[Tuple[str, str, str, int]]:
    """(name, kind, relpath, line) for every literal event/span emission."""
    out: List[Tuple[str, str, str, int]] = []
    for path in files:
        tree = astutil.parse_file(path)
        if tree is None:
            continue
        relpath = astutil.rel(root, path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            m = astutil.method_name(node)
            if m in ("event", "span"):
                name = astutil.literal_str_arg(node, 0)
                if name is not None:
                    kind = "point" if m == "event" else "span"
                    out.append((name, kind, relpath, node.lineno))
            elif m in EVENT_WRAPPERS:
                name = astutil.literal_str_arg(node, 0)
                if name is not None:
                    out.append((name, "point", relpath, node.lineno))
            elif m == "_write":
                name = astutil.literal_str_arg(node, 0)
                kind = astutil.literal_str_arg(node, 1)
                if name is not None and kind in ("point", "span"):
                    out.append((name, kind, relpath, node.lineno))
    return out


def _env_tokens(
    root: str, files: Sequence[str]
) -> Dict[str, Tuple[str, int]]:
    """token -> first (relpath, line) reference, from code + scripts."""
    out: Dict[str, Tuple[str, int]] = {}
    for path in files:
        relpath = astutil.rel(root, path)
        try:
            with open(path) as f:
                lines = f.readlines()
        except OSError:
            continue
        for i, line in enumerate(lines, start=1):
            for m in _ENV_TOKEN.finditer(line):
                tok = m.group(0).rstrip("_")
                if tok == "HEAT3D" or tok in out:
                    continue
                out[tok] = (relpath, i)
    return out


def _docs_has_row(
    docs_text: str, name: str, kind: Optional[str] = None
) -> bool:
    """True when the docs carry a rendered taxonomy-table row for
    ``name`` (``| `name` | kind | ...``). Anchored to the backticked
    row start — a bare substring test would let `bench_row` ride on
    `bench_row_measure`'s row after its own was deleted — and, for
    events, the row's kind column must match the registry."""
    for line in docs_text.splitlines():
        if line.startswith(f"| `{name}` |"):
            if kind is None or f"| {kind} |" in line:
                return True
    return False


def check(
    root: str,
    files: Optional[Sequence[str]] = None,
    events_registry: Optional[Dict[str, Dict]] = None,
    env_registry: Optional[Dict[str, Dict]] = None,
    docs_path: str = _DOCS,
) -> List[Finding]:
    events_registry = (
        events_registry if events_registry is not None else LEDGER_EVENTS
    )
    env_registry = env_registry if env_registry is not None else ENV_VARS

    if files is None:
        code_files = [
            p
            for p in astutil.iter_py_files(
                root, subdirs=("heat3d_tpu",), extras=("bench.py",)
            )
            # the analysis package and registry NAME events/vars without
            # emitting them; scanning them would count every registry
            # entry as emitted. The IR and kernel verifier subpackages
            # are the exception: they genuinely emit ir_lint_* /
            # kernel_lint_* and read HEAT3D_IR_* /
            # HEAT3D_KERNEL_LINT_* (production tooling, not
            # checkers-of-names), so they stay in the scan.
            if os.sep + "analysis" + os.sep not in p
            or os.sep + os.path.join("analysis", "ir") + os.sep in p
            or os.sep + os.path.join("analysis", "kernel") + os.sep in p
        ]
        script_files = [
            os.path.join(root, "scripts", fn)
            for fn in sorted(os.listdir(os.path.join(root, "scripts")))
            if fn.endswith((".sh", ".py"))
        ] if os.path.isdir(os.path.join(root, "scripts")) else []
    else:
        code_files = list(files)
        script_files = []

    findings: List[Finding] = []

    # ---- ledger events -----------------------------------------------------
    emitted: Dict[str, List[Tuple[str, str, int]]] = {}
    for name, kind, relpath, line in _emissions(root, code_files):
        emitted.setdefault(name, []).append((kind, relpath, line))
        reg = events_registry.get(name)
        if reg is None:
            findings.append(
                Finding(
                    checker=CHECKER,
                    severity=ERROR,
                    path=relpath,
                    line=line,
                    code="ANL401",
                    symbol=name,
                    message=(
                        f"ledger event '{name}' is emitted but not in the "
                        "canonical registry "
                        "(heat3d_tpu/analysis/registry.LEDGER_EVENTS) — "
                        "register it and add its docs/OBSERVABILITY.md "
                        "taxonomy row"
                    ),
                )
            )
        elif reg.get("kind") != kind:
            findings.append(
                Finding(
                    checker=CHECKER,
                    severity=ERROR,
                    path=relpath,
                    line=line,
                    code="ANL402",
                    symbol=name,
                    message=(
                        f"ledger event '{name}' emitted as {kind} but "
                        f"registered as {reg.get('kind')} — obs summary's "
                        "span tables and the data lint key on the kind"
                    ),
                )
            )

    docs_file = os.path.join(root, docs_path)
    try:
        with open(docs_file) as f:
            docs_text = f.read()
    except OSError as e:
        # an unreadable docs file must not silently disable the whole
        # documentation leg (ANL404/412) — that's a finding, not a skip
        docs_text = None
        findings.append(
            Finding(
                checker=CHECKER,
                severity=ERROR,
                path=docs_path,
                line=0,
                code="ANL405",
                message=(
                    f"taxonomy docs file unreadable ({e}) — the "
                    "registered-must-be-documented checks cannot run"
                ),
            )
        )

    for name, reg in sorted(events_registry.items()):
        if name not in emitted and not reg.get("external"):
            findings.append(
                Finding(
                    checker=CHECKER,
                    severity=WARNING,
                    path="heat3d_tpu/analysis/registry.py",
                    line=0,
                    code="ANL403",
                    symbol=name,
                    message=(
                        f"registered ledger event '{name}' is never "
                        "emitted — stale registry entry (or the emitter "
                        "moved behind a dynamic name; mark it external)"
                    ),
                )
            )
        if docs_text is not None and not _docs_has_row(
            docs_text, name, reg.get("kind")
        ):
            findings.append(
                Finding(
                    checker=CHECKER,
                    severity=ERROR,
                    path=docs_path,
                    line=0,
                    code="ANL404",
                    symbol=name,
                    message=(
                        f"registered ledger event '{name}' has no "
                        f"taxonomy-table row in {docs_path} with its "
                        f"registered kind ({reg.get('kind')}) — add/fix "
                        "the row"
                    ),
                )
            )

    # ---- env vars ----------------------------------------------------------
    referenced = _env_tokens(root, list(code_files) + script_files)

    def _covers(tok: str) -> bool:
        return tok in env_registry or any(
            v.startswith(tok + "_") for v in env_registry
        )

    for tok, (relpath, line) in sorted(referenced.items()):
        if not _covers(tok):
            findings.append(
                Finding(
                    checker=CHECKER,
                    severity=ERROR,
                    path=relpath,
                    line=line,
                    code="ANL411",
                    symbol=tok,
                    message=(
                        f"env knob '{tok}' is referenced but not in the "
                        "canonical registry "
                        "(heat3d_tpu/analysis/registry.ENV_VARS) — register "
                        "it and add its docs/OBSERVABILITY.md taxonomy row"
                    ),
                )
            )
    for var in sorted(env_registry):
        if docs_text is not None and not _docs_has_row(docs_text, var):
            findings.append(
                Finding(
                    checker=CHECKER,
                    severity=ERROR,
                    path=docs_path,
                    line=0,
                    code="ANL412",
                    symbol=var,
                    message=(
                        f"registered env knob '{var}' has no "
                        f"taxonomy-table row in {docs_path} — add it"
                    ),
                )
            )
        if var not in referenced:
            findings.append(
                Finding(
                    checker=CHECKER,
                    severity=WARNING,
                    path="heat3d_tpu/analysis/registry.py",
                    line=0,
                    code="ANL413",
                    symbol=var,
                    message=(
                        f"registered env knob '{var}' is referenced "
                        "nowhere in code or scripts — stale registry entry"
                    ),
                )
            )
    return findings
