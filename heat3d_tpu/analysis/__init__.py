"""Static-analysis subsystem: SPMD-safety and invariant lints.

Six AST/arithmetic checkers over the repo's own source (docs/ANALYSIS.md
is the catalog), one shared finding/severity/suppression framework
(:mod:`~heat3d_tpu.analysis.findings`), the promoted data-lint cores
behind ``scripts/check_ledger.py`` / ``scripts/check_provenance.py``,
and the IR tier (:mod:`~heat3d_tpu.analysis.ir`, ``heat3d lint --ir``)
that traces the judged config matrix and certifies the closed jaxprs.
``heat3d lint`` (:mod:`~heat3d_tpu.analysis.cli`) is the operator/CI
entry point: rc 1 only on unsuppressed error-severity findings.

The source checkers parse, they do not import, the code they audit —
except where the arithmetic itself is the artifact under audit (VMEM
budget estimators, the live knob surfaces), which is loaded
deliberately. The IR tier goes one step further and audits the
*programs* the code builds, not the code.
"""

from __future__ import annotations

from heat3d_tpu.analysis.findings import (  # noqa: F401
    ERROR,
    INFO,
    WARNING,
    Finding,
)

# checker name -> module path (the CLI resolves lazily so `heat3d lint
# --checker vmem-budget` does not import jax-heavy modules it won't run)
CHECKERS = {
    "collective-divergence": "heat3d_tpu.analysis.collectives",
    "fail-soft": "heat3d_tpu.analysis.failsoft",
    "vmem-budget": "heat3d_tpu.analysis.vmem",
    "ledger-taxonomy": "heat3d_tpu.analysis.taxonomy",
    "knob-drift": "heat3d_tpu.analysis.knobs",
    "eqn-registry": "heat3d_tpu.analysis.eqnlint",
}
