"""Static-analysis subsystem: SPMD-safety and invariant lints.

Six AST/arithmetic checkers over the repo's own source (docs/ANALYSIS.md
is the catalog), one shared finding/severity/suppression framework
(:mod:`~heat3d_tpu.analysis.findings`), the promoted data-lint cores
behind ``scripts/check_ledger.py`` / ``scripts/check_provenance.py``,
the IR tier (:mod:`~heat3d_tpu.analysis.ir`, ``heat3d lint --ir``)
that traces the judged config matrix and certifies the closed jaxprs,
and the kernel tier (:mod:`~heat3d_tpu.analysis.kernel`,
``heat3d lint --kernel``) that traces every Pallas kernel body and
certifies the in-kernel DMA/ring schedules the interpret-tier parity
tests cannot see. ``heat3d lint`` (:mod:`~heat3d_tpu.analysis.cli`) is
the operator/CI entry point (``--all`` = every tier, one merged
verdict): rc 1 only on unsuppressed error-severity findings.

The source checkers parse, they do not import, the code they audit —
except where the arithmetic itself is the artifact under audit (VMEM
budget estimators, the live knob surfaces), which is loaded
deliberately. The IR tier goes one step further and audits the
*programs* the code builds; the kernel tier goes inside the one opaque
box the IR tier left — ``pallas_call`` bodies.
"""

from __future__ import annotations

from heat3d_tpu.analysis.findings import (  # noqa: F401
    ERROR,
    INFO,
    WARNING,
    Finding,
)

# checker name -> module path (the CLI resolves lazily so `heat3d lint
# --checker vmem-budget` does not import jax-heavy modules it won't run)
CHECKERS = {
    "collective-divergence": "heat3d_tpu.analysis.collectives",
    "fail-soft": "heat3d_tpu.analysis.failsoft",
    "vmem-budget": "heat3d_tpu.analysis.vmem",
    "ledger-taxonomy": "heat3d_tpu.analysis.taxonomy",
    "knob-drift": "heat3d_tpu.analysis.knobs",
    "eqn-registry": "heat3d_tpu.analysis.eqnlint",
}
